# Developer entry points (the reference ships the same targets).

PYTHON ?= python

.PHONY: test test-fast bench smoke multichip lint lintcheck dev clean faultcheck chaoscheck nosleep perfcheck nofoldin obscheck noperf nostager ledgercheck noartifacts watchcheck costcheck nocost plancheck noknobs kernelcheck nopallas servecheck noserve fusecheck fusionmask sketchcheck nosketchhash veccheck sweepcheck metricscheck topocheck

test:
	$(PYTHON) -m pytest tests/ -q

test-fast:
	$(PYTHON) -m pytest tests/ -q -x

bench:
	$(PYTHON) bench.py

smoke:
	$(PYTHON) bench.py --smoke

multichip:
	# dryrun_multichip self-bootstraps a virtual 8-device CPU mesh when
	# fewer real devices exist; it owns the platform selection (the env
	# var alone loses to auto-registered TPU plugins).
	$(PYTHON) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

# Fault-injection suite (includes the end-to-end degraded-bench run)
# + the no-direct-sleep invariant + the seeded chaos campaign.
faultcheck: nosleep chaoscheck
	$(PYTHON) -m pytest tests/test_resilience.py tests/test_faults.py \
	  tests/test_chaos.py -q

# Seeded chaos campaign: 20 deterministic episodes across EVERY
# FaultPlan seam (stream/pass-B/sketch kills, device loss with elastic
# mesh re-form, wedged probe on a FakeClock, serve kill with
# exactly-once lease replay, torn ledger + fsck), with per-episode
# recovery invariants. CPU mesh, zero real sleeps — tier-1-safe. Set
# PIPELINEDP_TPU_CHAOS_SEED to replay a specific campaign; a failing
# episode prints its exact reproduction command.
chaoscheck:
	$(PYTHON) -m pipelinedp_tpu.resilience.chaos --schedules 20

# Performance-path acceptance suite: overlapped-ingest bit-parity,
# fault-kill drain (no orphan threads), O(n) assignment, id-narrowing
# tiers, sweep checkpoint/resume, the kill/resume fault tests — plus
# the quantile-walk suite (counter-noise generator, three-way walk
# bit-parity, partition-block chunking, guard-cliff boundaries), the
# pass-B sweep suite (planner invariants, multi-tile-vs-per-tile
# bit-parity, hybrid prefix cache, pass-B fault drain) and the
# sketch-first suite (sketchcheck: the ingest ring's third consumer,
# with its own kill-mid-stream drain proof) and the topology suite
# (topocheck: hier-vs-flat bit-parity + the collective-confinement
# lint).
perfcheck: sketchcheck veccheck sweepcheck topocheck
	$(PYTHON) -m pipelinedp_tpu.lint --rule nosleep --rule nofoldin \
	  --rule nostager --rule nopallas
	$(PYTHON) -m pytest tests/test_ingest.py tests/test_faults.py \
	  tests/test_walk.py tests/test_pass_b.py -q

# Utility-analysis megasweep acceptance suite (ISSUE 18): configs as a
# device axis — walked-vs-batched bit parity at every config-batch
# width incl. non-dividing widths and the 8-device mesh (PARITY row
# 41), kill-mid-megasweep resume from the .sweep sibling checkpoint,
# serve `tune` requests (admitted, quota'd, books-stamped, zero (eps,
# delta) debited, warm second tune compiles nothing), the configs/s
# compare-gate refusal across batch widths — plus the jit-staticness
# lint over the batched kernels: config values (bounds, eps-splits,
# noise tables, knob reads) must arrive as RUNTIME inputs, never
# freeze into the traced program.
sweepcheck:
	$(PYTHON) -m pipelinedp_tpu.lint --rule jit-staticness
	$(PYTHON) -m pytest tests/test_analysis.py tests/test_serve.py \
	  tests/test_ledger.py tests/test_lint.py -q

# Wide-D vector aggregation acceptance suite: the Pallas wide-D
# segment-sum parity matrix (random shapes, max-lane values past f32
# exactness, every d_block bit-identical, envelope geometry + visible
# fallbacks), the fx fixed-point accumulator's bit-identity across
# backends / 8-device mesh / streamed pass-A (PARITY row 39), knob
# precedence (vector_accumulator dp-UNSAFE, segsum_wide_d_block
# dp-safe), device vector noise keyed by (partition, coordinate) with
# distribution + key-determinism checks (PARITY row 40), fusion bucket
# compatibility + vector padding invariance, the VECTOR_SUM elastic
# 8->4 reshard (fx bit-identical where f32 cannot be), and the
# pallas-confinement + rng-purity lints over the new surfaces.
veccheck: nopallas
	$(PYTHON) -m pipelinedp_tpu.lint --rule rng-purity
	$(PYTHON) -m pytest tests/test_vector_fx.py tests/test_kernels.py \
	  tests/test_fusion.py -q
	$(PYTHON) -m pytest tests/test_faults.py -q \
	  -k "vector_sum_survives_mid_stream_shrink"

# Pallas-kernel acceptance suite: kernel-level bit-parity vs the XLA
# scatter paths (including the lane-plan boundary widths in interpret
# mode), the four-way pass-B parity (multi-tile XLA = per-tile =
# unchunked = Pallas, single device + 8-device mesh — in
# tests/test_pass_b.py), out-of-envelope + pallas-unavailable
# fallbacks with their kernel.fallback events, kernel_backend knob
# precedence (env > seam > plan > default), the interpret-mode CPU
# roofline peak row, and the in-tree nopallas AST twin.
kernelcheck: nopallas
	$(PYTHON) -m pytest tests/test_kernels.py tests/test_pass_b.py -q

# Resident-service acceptance suite: durable per-tenant budget
# ledgers (exactly-once debits, overdraw refused before compute,
# kill-and-restart replay), admission control (malformed / queue-full
# / per-tenant in-flight / quota refusals as structured responses,
# graceful drain with zero orphan pdp-serve threads), warm
# engine/program reuse (second same-signature request captures no new
# compile.program span), serve-vs-direct bit-parity (PARITY row 34),
# per-tenant books, the run-namespaced multi-request heartbeat, and
# the per-directory report-cursor regression — plus the request-fusion
# suite (fusecheck).
servecheck: noserve fusecheck
	$(PYTHON) -m pytest tests/test_serve.py tests/test_ledger.py -q

# Request-fusion acceptance suite: fused-vs-solo bit-parity across a
# pow2 bucket boundary (PARITY row 35 — released values AND kept
# sets, budget debits/audit records unchanged), padding invariance of
# the solo kernel (the pad-mask contract), kill-mid-batch lease
# resolution (every fused request resolves exactly once), zero new
# compile.program captures on the second same-bucket batch, quota
# refusals, and heartbeat bucket occupancy — plus the fusion-masking
# confinement lint.
fusecheck: fusionmask
	$(PYTHON) -m pytest tests/test_fusion.py -q

fusionmask:
	$(PYTHON) -m pipelinedp_tpu.lint --rule fusion-masking

# Sketch-first / DP heavy-hitters acceptance suite: seeded stable-hash
# round-trips at collision-prone widths, matmul-vs-scatter sketch
# bit-parity (PARITY row 36), per-user pre-sketch bounding invariance,
# sketch-vs-exact candidate recall on a power-law key space, the
# cap>=universe bit-parity with the dense path (PARITY row 37, single
# device + 8-device mesh), the phase-1 budget audit record, the
# schema-v5 report sketch section, kill-mid-sketch drain (zero orphan
# pdp-* threads) — plus the sketch-confinement lint (hashing +
# candidate tables confined to sketch/, raw hash() banned on keys).
sketchcheck: nosketchhash
	$(PYTHON) -m pytest tests/test_sketch.py -q

nosketchhash:
	$(PYTHON) -m pipelinedp_tpu.lint --rule sketch-confinement

# Topology-aware collectives suite (ISSUE 20): hier-vs-flat release
# bit-parity (single device, 8-device mesh, simulated hosts), the
# sharded-vs-single-device sketch parity, elastic shrink under hier,
# the comms byte counters — plus the collective-confinement lint
# (raw psum/psum_scatter/all_gather confined to parallel/sharded.py,
# the one seam carrying the parity contract and the byte meter).
topocheck:
	$(PYTHON) -m pipelinedp_tpu.lint --rule collective-confinement
	$(PYTHON) -m pytest tests/test_topology.py -q

# Observability acceptance suite: tracer thread-safety under a live
# overlapped-ingest run, no-op-mode zero emission, bench-field parity
# (names/semantics unchanged, DP outputs bit-identical trace on/off),
# Chrome-trace round-trip, run-report schema, resilience/fault event
# coverage — plus the no-raw-perf-counter and no-ad-hoc-artifact lints
# and the metrics-plane suite (metricscheck).
obscheck: metricscheck
	$(PYTHON) -m pipelinedp_tpu.lint --rule noperf --rule noartifacts
	$(PYTHON) -m pytest tests/test_obs.py -q

# Metrics-plane + wire-surface acceptance suite: request-scoped trace
# propagation across the serve thread handoffs (fused batches included,
# concurrent tenants isolated), histogram bucket-boundary exactness,
# the Prometheus exposition round-trip through a LIVE /metrics scrape,
# endpoint lifecycle (off-by-default zero threads, clean drain under
# ServeKill), and the trace-context on/off DP bit-parity — plus the
# socket-confinement lint (wire machinery confined to obs/http.py).
metricscheck:
	$(PYTHON) -m pipelinedp_tpu.lint --rule socket-confinement
	$(PYTHON) -m pytest tests/test_metrics.py -q

# Audit-record + run-ledger acceptance suite: schema-v2 privacy section
# (per-mechanism eps/delta + noise stddevs, selection pre/post counts,
# expected errors), audit on/off DP bit-parity, durable store semantics
# (fsync'd appends, v1->v2 reader tolerance, truncated-trailing-line
# recovery, concurrent appends, degraded-baseline exclusion) and the
# bench --compare regression gate (two in-process runs).
ledgercheck: noartifacts
	$(PYTHON) -m pytest tests/test_ledger.py tests/test_obs.py -q

# Live-telemetry acceptance suite: heartbeat atomic-replace under a
# concurrent reader, progress/pace fields, stall watchdog firing at
# the exact FakeClock deadline on a wedged staged fetch (zero orphan
# threads on drain), flight-record ring + thread-stack round-trip,
# heartbeat on/off DP bit-parity, the --summarize ledger analytics
# CLI, and the wedged-probe watchdog-cancel path.
watchcheck:
	$(PYTHON) -m pipelinedp_tpu.lint --rule noperf --rule nosleep
	$(PYTHON) -m pytest tests/test_monitor.py tests/test_obs.py -q

# Device-cost observatory acceptance suite: roofline verdict math,
# instrumented_jit capture-once semantics (the compile-count
# assertion), analysis tolerance across jax versions, HBM watermark
# sampling, store schema tolerance v1->v2->v3 (last_known_good /
# --summarize / bench --compare on a mixed-schema ledger), --csv
# output, Chrome-trace counter tracks, the e2e device_costs report
# shape, and the costs on/off DP bit-parity (PARITY row 31, in
# tests/test_obs.py) — plus the no-direct-analysis-call lint.
costcheck: nocost
	$(PYTHON) -m pytest tests/test_costs.py tests/test_obs.py -q

# Execution-planner acceptance suite: cold-start byte-identity to the
# hardcoded defaults, env > seam > plan > default precedence, dp-unsafe
# knobs never applied from a plan, stale-fingerprint plan rejection
# (plan.stale), cost-model fit/predict/serialize + the static roofline
# fallback, the pass-B q_chunk pin, planner on/off DP bit-parity
# (PARITY row 32), the store's --since-run-id window, bench plan
# provenance + the --compare plan-mismatch refusal, and the in-process
# autotune→plan-file→plain-run acceptance flow — plus the
# no-direct-knob-read lint.
plancheck: noknobs
	$(PYTHON) -m pytest tests/test_plan.py -q

# ---------------------------------------------------------------------
# Static analysis: ONE AST rule engine (pipelinedp_tpu/lint/) replaced
# the former grep forest. Every legacy target below is now a thin
# alias over `python -m pipelinedp_tpu.lint --rule <id>`; `lintcheck`
# runs the full registry (9 ported rules + rng-purity,
# blocking-under-lock, jit-staticness). Findings are `file:line
# rule-id message`; deliberate exceptions are inline
# `# lint: disable=<rule>(reason)` suppressions, counted and reported.
# See README "Static analysis" for the rule table.
# ---------------------------------------------------------------------

lintcheck:
	$(PYTHON) -m pipelinedp_tpu.lint

noserve:
	$(PYTHON) -m pipelinedp_tpu.lint --rule noserve

nopallas:
	$(PYTHON) -m pipelinedp_tpu.lint --rule nopallas

noknobs:
	$(PYTHON) -m pipelinedp_tpu.lint --rule noknobs

nocost:
	$(PYTHON) -m pipelinedp_tpu.lint --rule nocost

noartifacts:
	$(PYTHON) -m pipelinedp_tpu.lint --rule noartifacts

noperf:
	$(PYTHON) -m pipelinedp_tpu.lint --rule noperf

nofoldin:
	$(PYTHON) -m pipelinedp_tpu.lint --rule nofoldin

nostager:
	$(PYTHON) -m pipelinedp_tpu.lint --rule nostager

nosleep:
	$(PYTHON) -m pipelinedp_tpu.lint --rule nosleep

lint: lintcheck
	@if $(PYTHON) -c "import pyflakes" 2>/dev/null; then \
	  $(PYTHON) -m pyflakes pipelinedp_tpu tests; \
	else \
	  $(PYTHON) -m py_compile $$(git ls-files '*.py'); \
	fi

dev:
	$(PYTHON) -m pip install -e . --no-deps --no-build-isolation

clean:
	rm -rf build *.egg-info .pytest_cache
	find . -name __pycache__ -type d -exec rm -rf {} +
	rm -f pipelinedp_tpu/native/_secure_noise.so pipelinedp_tpu/native/_encode.so
