# Developer entry points (the reference ships the same targets).

PYTHON ?= python

.PHONY: test test-fast bench smoke multichip lint dev clean faultcheck nosleep perfcheck nofoldin obscheck noperf nostager ledgercheck noartifacts watchcheck costcheck nocost plancheck noknobs kernelcheck nopallas servecheck noserve

test:
	$(PYTHON) -m pytest tests/ -q

test-fast:
	$(PYTHON) -m pytest tests/ -q -x

bench:
	$(PYTHON) bench.py

smoke:
	$(PYTHON) bench.py --smoke

multichip:
	# dryrun_multichip self-bootstraps a virtual 8-device CPU mesh when
	# fewer real devices exist; it owns the platform selection (the env
	# var alone loses to auto-registered TPU plugins).
	$(PYTHON) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

# Fault-injection suite (includes the end-to-end degraded-bench run)
# + the no-direct-sleep invariant.
faultcheck: nosleep
	$(PYTHON) -m pytest tests/test_resilience.py tests/test_faults.py -q

# Performance-path acceptance suite: overlapped-ingest bit-parity,
# fault-kill drain (no orphan threads), O(n) assignment, id-narrowing
# tiers, sweep checkpoint/resume, the kill/resume fault tests — plus
# the quantile-walk suite (counter-noise generator, three-way walk
# bit-parity, partition-block chunking, guard-cliff boundaries) and
# the pass-B sweep suite (planner invariants, multi-tile-vs-per-tile
# bit-parity, hybrid prefix cache, pass-B fault drain).
perfcheck: nosleep nofoldin nostager nopallas
	$(PYTHON) -m pytest tests/test_ingest.py tests/test_faults.py \
	  tests/test_walk.py tests/test_pass_b.py -q

# Pallas-kernel acceptance suite: kernel-level bit-parity vs the XLA
# scatter paths (including the lane-plan boundary widths in interpret
# mode), the four-way pass-B parity (multi-tile XLA = per-tile =
# unchunked = Pallas, single device + 8-device mesh — in
# tests/test_pass_b.py), out-of-envelope + pallas-unavailable
# fallbacks with their kernel.fallback events, kernel_backend knob
# precedence (env > seam > plan > default), the interpret-mode CPU
# roofline peak row, and the in-tree nopallas AST twin.
kernelcheck: nopallas
	$(PYTHON) -m pytest tests/test_kernels.py tests/test_pass_b.py -q

# Resident-service acceptance suite: durable per-tenant budget
# ledgers (exactly-once debits, overdraw refused before compute,
# kill-and-restart replay), admission control (malformed / queue-full
# / per-tenant in-flight refusals as structured responses, graceful
# drain with zero orphan pdp-serve threads), warm engine/program
# reuse (second same-signature request captures no new
# compile.program span), serve-vs-direct bit-parity (PARITY row 34),
# per-tenant books, the run-namespaced multi-request heartbeat, and
# the per-directory report-cursor regression.
servecheck: noserve
	$(PYTHON) -m pytest tests/test_serve.py tests/test_ledger.py -q

# Lint-style check: durable budget-ledger state has ONE writer stack —
# TenantBudgetLedger construction is confined to pipelinedp_tpu/serve/
# (+ budget_accounting.py, the module whose two-phase state it lifts),
# and the batch engine modules never import pipelinedp_tpu.serve (the
# service depends on the engine, never the reverse — batch mode stays
# byte-for-byte oblivious to serving). Docstring/comment mentions
# (backquoted or #-prefixed) are ignored. (tests/test_serve.py
# enforces the same two rules in-tree, AST-precise.)
noserve:
	@bad=$$(grep -rn "TenantBudgetLedger *(" --include='*.py' pipelinedp_tpu bench.py \
	  | grep -v "pipelinedp_tpu/serve/" \
	  | grep -v "pipelinedp_tpu/budget_accounting\.py" \
	  | grep -v '``' | grep -vE ':[0-9]+: *#' || true); \
	if [ -n "$$bad" ]; then \
	  echo "$$bad"; \
	  echo "ERROR: budget-ledger construction outside pipelinedp_tpu/serve/"; \
	  echo "+ budget_accounting.py — budget debits must flow through the"; \
	  echo "serve layer's durable ledger"; \
	  exit 1; \
	fi; \
	bad=$$(grep -rnE "(from|import)[^#\"']*pipelinedp_tpu\.serve" \
	  --include='*.py' pipelinedp_tpu \
	  | grep -v "pipelinedp_tpu/serve/" \
	  | grep -v '``' | grep -vE ':[0-9]+: *#' || true); \
	if [ -n "$$bad" ]; then \
	  echo "$$bad"; \
	  echo "ERROR: serve import in a batch engine module — the service"; \
	  echo "depends on the engine, never the reverse"; \
	  exit 1; \
	fi; \
	echo "noserve: OK"

# Lint-style check: pallas imports and pallas_call sites are confined
# to pipelinedp_tpu/ops/kernels/ — every other module must dispatch
# through the kernels package (kernel_backend knob -> select_backend),
# so the fallback events, the envelope checks and the interpret-mode
# story stay in ONE place. Docstring/comment mentions (backquoted or
# #-prefixed) are ignored. (tests/test_kernels.py enforces the same
# rule in-tree, AST-precise.)
nopallas:
	@bad=$$(grep -rnE "(from|import)[^#\"']*pallas|pallas_call *\(|[^a-zA-Z_.]pl\.|^pl\." \
	  --include='*.py' pipelinedp_tpu bench.py \
	  | grep -v "pipelinedp_tpu/ops/kernels/" \
	  | grep -v '``' | grep -vE ':[0-9]+: *#' || true); \
	if [ -n "$$bad" ]; then \
	  echo "$$bad"; \
	  echo "ERROR: pallas usage outside pipelinedp_tpu/ops/kernels/ —"; \
	  echo "dispatch through pipelinedp_tpu.ops.kernels (the"; \
	  echo "kernel_backend knob + select_backend fallback seam)"; \
	  exit 1; \
	fi; \
	echo "nopallas: OK"

# Observability acceptance suite: tracer thread-safety under a live
# overlapped-ingest run, no-op-mode zero emission, bench-field parity
# (names/semantics unchanged, DP outputs bit-identical trace on/off),
# Chrome-trace round-trip, run-report schema, resilience/fault event
# coverage — plus the no-raw-perf-counter and no-ad-hoc-artifact lints.
obscheck: noperf noartifacts
	$(PYTHON) -m pytest tests/test_obs.py -q

# Audit-record + run-ledger acceptance suite: schema-v2 privacy section
# (per-mechanism eps/delta + noise stddevs, selection pre/post counts,
# expected errors), audit on/off DP bit-parity, durable store semantics
# (fsync'd appends, v1->v2 reader tolerance, truncated-trailing-line
# recovery, concurrent appends, degraded-baseline exclusion) and the
# bench --compare regression gate (two in-process runs).
ledgercheck: noartifacts
	$(PYTHON) -m pytest tests/test_ledger.py tests/test_obs.py -q

# Live-telemetry acceptance suite: heartbeat atomic-replace under a
# concurrent reader, progress/pace fields, stall watchdog firing at
# the exact FakeClock deadline on a wedged staged fetch (zero orphan
# threads on drain), flight-record ring + thread-stack round-trip,
# heartbeat on/off DP bit-parity, the --summarize ledger analytics
# CLI, and the wedged-probe watchdog-cancel path.
watchcheck: noperf nosleep
	$(PYTHON) -m pytest tests/test_monitor.py tests/test_obs.py -q

# Device-cost observatory acceptance suite: roofline verdict math,
# instrumented_jit capture-once semantics (the compile-count
# assertion), analysis tolerance across jax versions, HBM watermark
# sampling, store schema tolerance v1->v2->v3 (last_known_good /
# --summarize / bench --compare on a mixed-schema ledger), --csv
# output, Chrome-trace counter tracks, the e2e device_costs report
# shape, and the costs on/off DP bit-parity (PARITY row 31, in
# tests/test_obs.py) — plus the no-direct-analysis-call lint.
costcheck: nocost
	$(PYTHON) -m pytest tests/test_costs.py tests/test_obs.py -q

# Execution-planner acceptance suite: cold-start byte-identity to the
# hardcoded defaults, env > seam > plan > default precedence, dp-unsafe
# knobs never applied from a plan, stale-fingerprint plan rejection
# (plan.stale), cost-model fit/predict/serialize + the static roofline
# fallback, the pass-B q_chunk pin, planner on/off DP bit-parity
# (PARITY row 32), the store's --since-run-id window, bench plan
# provenance + the --compare plan-mismatch refusal, and the in-process
# autotune→plan-file→plain-run acceptance flow — plus the
# no-direct-knob-read lint.
plancheck: noknobs
	$(PYTHON) -m pytest tests/test_plan.py -q

# Lint-style check: no direct reads of the registered knob constants
# (_SUBHIST_BYTE_CAP / _SELECT_UNITS_CAP / _TREE_ROWS_CAP / _Q_CHUNK)
# outside pipelinedp_tpu/plan/ — every consumer must resolve through
# the knob registry (plan.knobs: env > seam > plan file > default) so
# an autotuned plan can actually steer the value and every resolution
# lands in the run report's plan section. The defining modules keep
# the names as module-level assignments (the blessed test seams);
# docstring/comment mentions (backquoted or #-prefixed) are ignored.
# (tests/test_plan.py enforces the same rule in-tree, AST-precise.)
noknobs:
	@bad=$$(grep -rnE "_SUBHIST_BYTE_CAP|_SELECT_UNITS_CAP|_TREE_ROWS_CAP|_Q_CHUNK" \
	  --include='*.py' pipelinedp_tpu bench.py \
	  | grep -v "pipelinedp_tpu/plan/" \
	  | grep -v '``' | grep -vE ':[0-9]+: *#' \
	  | grep -vE '^pipelinedp_tpu/(jax_engine|streaming)\.py:[0-9]+:(_SUBHIST_BYTE_CAP|_SELECT_UNITS_CAP|_TREE_ROWS_CAP|_Q_CHUNK) *=' \
	  || true); \
	if [ -n "$$bad" ]; then \
	  echo "$$bad"; \
	  echo "ERROR: direct knob-constant access — resolve through"; \
	  echo "pipelinedp_tpu.plan (knobs.value / resolve / seam_override)"; \
	  exit 1; \
	fi; \
	echo "noknobs: OK"

# Lint-style check: no direct compiled-program analysis or live-array
# sampling outside pipelinedp_tpu/obs/ — cost_analysis( /
# memory_analysis( / live_arrays( calls must flow through the
# device-cost observatory (obs/costs.py) so every measurement lands in
# the schema-versioned run report keyed by the env fingerprint.
# (tests/test_costs.py enforces the same rule in-tree, AST-precise.)
nocost:
	@bad=$$(grep -rnE "cost_analysis *\(|memory_analysis *\(|live_arrays *\(" \
	  --include='*.py' pipelinedp_tpu bench.py \
	  | grep -v "pipelinedp_tpu/obs/" || true); \
	if [ -n "$$bad" ]; then \
	  echo "$$bad"; \
	  echo "ERROR: direct device-analysis call — route through"; \
	  echo "pipelinedp_tpu.obs.costs (instrumented_jit / sample_live_bytes)"; \
	  exit 1; \
	fi; \
	echo "nocost: OK"

# Lint-style check: no ad-hoc run-report/JSON-artifact writes — every
# json.dump( file write in library/bench code must live in
# pipelinedp_tpu/obs/ (the exporters + the durable ledger store),
# pipelinedp_tpu/plan/ (the atomically-replaced plan file) or
# bench.py (the one artifact emitter), so run knowledge lands in the
# schema-versioned report/store/plan instead of scattered one-off
# files. (tests/test_ledger.py enforces the same rule, AST-precise.)
noartifacts:
	@bad=$$(grep -rn "json\.dump *(" --include='*.py' pipelinedp_tpu \
	  | grep -v "pipelinedp_tpu/obs/" \
	  | grep -v "pipelinedp_tpu/plan/" || true); \
	if [ -n "$$bad" ]; then \
	  echo "$$bad"; \
	  echo "ERROR: ad-hoc JSON artifact write — route run reports/"; \
	  echo "artifacts through pipelinedp_tpu/obs (report/store) or bench.py"; \
	  exit 1; \
	fi; \
	echo "noartifacts: OK"

# Lint-style check: no bare time.perf_counter() phase timing outside
# pipelinedp_tpu/obs/ — every measured phase must flow through obs
# spans so it lands in the run ledger and the bench timing fields stay
# derived views over spans (bench.py's helpers route through
# obs.run_tracer; tests/test_obs.py enforces the same rule in-tree).
# obs/ is the ONE package allowed the raw timer — EXCEPT obs/monitor.py:
# the watchdog's entire deadline story rides the injectable resilience
# clock, so raw perf_counter there would reintroduce wall-time waits
# no FakeClock test could pin. (time.sleep in monitor.py is already
# banned by `nosleep`, which never excluded obs/.)
noperf:
	@bad=$$(grep -rn "perf_counter *(" --include='*.py' pipelinedp_tpu bench.py \
	  | grep -v "pipelinedp_tpu/obs/" || true); \
	badmon=$$(grep -n "perf_counter *(" pipelinedp_tpu/obs/monitor.py || true); \
	if [ -n "$$bad" ] || [ -n "$$badmon" ]; then \
	  echo "$$bad"; echo "$$badmon"; \
	  echo "ERROR: raw perf_counter timing — use pipelinedp_tpu.obs spans"; \
	  echo "(obs/monitor.py must use the injectable resilience clock)"; \
	  exit 1; \
	fi; \
	echo "noperf: OK"

# Lint-style check: no per-element vmap(fold_in) key constructions —
# they rebuild a full threefry key schedule per element, the cost the
# counter-based node-noise generator (ops/counter_rng.py, the one
# blessed keyed-generator module) removed from the quantile walk.
# (tests/test_walk.py enforces the same rule in-tree.)
nofoldin:
	@bad=$$(grep -rnE "vmap.*fold_in|fold_in.*vmap" --include='*.py' \
	  pipelinedp_tpu bench.py \
	  | grep -v "pipelinedp_tpu/ops/counter_rng\.py" || true); \
	if [ -n "$$bad" ]; then \
	  echo "$$bad"; \
	  echo "ERROR: per-element vmap(fold_in) key construction — use"; \
	  echo "the counter-based generator (pipelinedp_tpu/ops/counter_rng)"; \
	  exit 1; \
	fi; \
	echo "nofoldin: OK"

# Lint-style check: pass-B restreaming must flow through the sweep
# planner's ONE stream source (streaming.py run_sweep) — a new direct
# BackgroundStager construction outside pipelinedp_tpu/ingest/ and the
# two blessed streaming.py sites (pass A's overlapped loop + the
# pass-B sweep source) silently re-introduces per-tile restreaming.
# (tests/test_pass_b.py enforces the same rule in-tree, AST-precise.)
nostager:
	@bad=$$(grep -rn "BackgroundStager *(" --include='*.py' pipelinedp_tpu bench.py \
	  | grep -v "pipelinedp_tpu/ingest/" \
	  | grep -v "pipelinedp_tpu/streaming\.py" || true); \
	if [ -n "$$bad" ]; then \
	  echo "$$bad"; \
	  echo "ERROR: direct BackgroundStager construction — only"; \
	  echo "pipelinedp_tpu/ingest/ and the two blessed streaming.py"; \
	  echo "sites (pass A + the pass-B sweep source) may build stagers"; \
	  exit 1; \
	fi; \
	n=$$(grep -c "BackgroundStager *(" pipelinedp_tpu/streaming.py); \
	if [ "$$n" -gt 2 ]; then \
	  echo "ERROR: $$n BackgroundStager sites in pipelinedp_tpu/streaming.py"; \
	  echo "(max 2: pass A + the sweep planner's run_sweep) — pass-B"; \
	  echo "restreaming must go through the sweep planner"; \
	  exit 1; \
	fi; \
	echo "nostager: OK"

# Lint-style check: no library/bench code path may call time.sleep
# directly — waits must route through the injectable
# pipelinedp_tpu.resilience.clock so fault tests stay fast and
# deterministic — and no bare threading.Thread outside
# pipelinedp_tpu/ingest/ and pipelinedp_tpu/resilience/: every worker
# thread must go through the ingest executor's cancellable lifecycle
# so fault-injected kills can always drain to zero orphan threads.
# (tests/test_resilience.py enforces both in-tree.)
nosleep:
	@bad=$$(grep -rn "time\.sleep *(" --include='*.py' pipelinedp_tpu bench.py \
	  | grep -v "resilience/clock\.py" || true); \
	if [ -n "$$bad" ]; then \
	  echo "$$bad"; \
	  echo "ERROR: direct time.sleep — use pipelinedp_tpu.resilience.clock"; \
	  exit 1; \
	fi; \
	bad=$$(grep -rn "threading\.Thread *(" --include='*.py' pipelinedp_tpu bench.py \
	  | grep -v "pipelinedp_tpu/ingest/" \
	  | grep -v "pipelinedp_tpu/resilience/" || true); \
	if [ -n "$$bad" ]; then \
	  echo "$$bad"; \
	  echo "ERROR: bare threading.Thread — use the pipelinedp_tpu.ingest executor"; \
	  exit 1; \
	fi; \
	echo "nosleep: OK"

lint:
	@if $(PYTHON) -c "import pyflakes" 2>/dev/null; then \
	  $(PYTHON) -m pyflakes pipelinedp_tpu tests; \
	else \
	  $(PYTHON) -m py_compile $$(git ls-files '*.py'); \
	fi

dev:
	$(PYTHON) -m pip install -e . --no-deps --no-build-isolation

clean:
	rm -rf build *.egg-info .pytest_cache
	find . -name __pycache__ -type d -exec rm -rf {} +
	rm -f pipelinedp_tpu/native/_secure_noise.so pipelinedp_tpu/native/_encode.so
