# Developer entry points (the reference ships the same targets).

PYTHON ?= python

.PHONY: test test-fast bench smoke multichip lint dev clean

test:
	$(PYTHON) -m pytest tests/ -q

test-fast:
	$(PYTHON) -m pytest tests/ -q -x

bench:
	$(PYTHON) bench.py

smoke:
	$(PYTHON) bench.py --smoke

multichip:
	# dryrun_multichip self-bootstraps a virtual 8-device CPU mesh when
	# fewer real devices exist; it owns the platform selection (the env
	# var alone loses to auto-registered TPU plugins).
	$(PYTHON) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

lint:
	@if $(PYTHON) -c "import pyflakes" 2>/dev/null; then \
	  $(PYTHON) -m pyflakes pipelinedp_tpu tests; \
	else \
	  $(PYTHON) -m py_compile $$(git ls-files '*.py'); \
	fi

dev:
	$(PYTHON) -m pip install -e . --no-deps --no-build-isolation

clean:
	rm -rf build *.egg-info .pytest_cache
	find . -name __pycache__ -type d -exec rm -rf {} +
	rm -f pipelinedp_tpu/native/_secure_noise.so pipelinedp_tpu/native/_encode.so
