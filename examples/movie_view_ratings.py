#!/usr/bin/env python
"""DP aggregation over movie view ratings (the reference's flagship
example, ``examples/movie_view_ratings/`` — synthetic data generated
in-process so no download is needed).

Computes COUNT + SUM + MEAN (+ optional percentiles) of ratings per
movie, with private partition selection.

Usage:
  python examples/movie_view_ratings.py                 # fused TPU plane
  python examples/movie_view_ratings.py --backend local # generator plane
  python examples/movie_view_ratings.py --public        # public partitions
  python examples/movie_view_ratings.py --vector        # per-movie rating
                                                        # histogram (one-hot
                                                        # VECTOR_SUM)
  python examples/movie_view_ratings.py --bounds-enforced  # caller-bounded
                                                        # data, no privacy ids
"""

import argparse
import operator
import time

import numpy as np


def generate_data(n_rows=500_000, n_users=50_000, n_movies=2_000, seed=0):
    rng = np.random.default_rng(seed)
    import pipelinedp_tpu as pdp
    movies = rng.zipf(1.3, n_rows) % n_movies
    return pdp.ArrayDataset(
        privacy_ids=rng.integers(0, n_users, n_rows),
        partition_keys=movies.astype(np.int64),
        values=rng.integers(1, 6, n_rows).astype(np.float64))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--backend", choices=["jax", "local", "multiproc"],
                        default="jax")
    parser.add_argument("--public", action="store_true",
                        help="use public partitions (all movie ids)")
    parser.add_argument("--rows", type=int, default=500_000)
    parser.add_argument("--percentiles", action="store_true")
    parser.add_argument("--vector", action="store_true",
                        help="VECTOR_SUM demo: one-hot rating histogram "
                        "per movie (reference run_all_frameworks' vector "
                        "metrics demo)")
    parser.add_argument("--bounds-enforced", action="store_true",
                        help="contribution_bounds_already_enforced: no "
                        "privacy ids, the caller vouches for bounding")
    args = parser.parse_args()

    import pipelinedp_tpu as pdp

    if args.backend == "jax":
        from pipelinedp_tpu.backends import JaxBackend
        backend = JaxBackend()
    elif args.backend == "multiproc":
        backend = pdp.MultiProcLocalBackend()
    else:
        backend = pdp.LocalBackend()

    if args.vector and args.percentiles:
        parser.error("--vector and --percentiles are mutually exclusive")
    data = generate_data(n_rows=args.rows)
    if args.vector:
        # One-hot the 1..5 star ratings: VECTOR_SUM then releases a DP
        # per-movie rating histogram (reference
        # run_all_frameworks.py:91-97,189-192).
        one_hot = np.eye(5)[data.values.astype(int) - 1]
        data = pdp.ArrayDataset(privacy_ids=data.privacy_ids,
                                partition_keys=data.partition_keys,
                                values=one_hot)
        metrics = [pdp.Metrics.VECTOR_SUM]
        # The norm clip applies to the whole partition's accumulated
        # vector (reference add_noise_vector semantics), so it is set
        # far above any movie's view count — the per-coordinate noise,
        # calibrated on the l0/linf contribution bounds, provides the DP.
        extra = dict(vector_size=5, vector_max_norm=1e6,
                     vector_norm_kind=pdp.NormKind.L1)
    else:
        metrics = [pdp.Metrics.COUNT, pdp.Metrics.SUM, pdp.Metrics.MEAN]
        if args.percentiles:
            metrics += [pdp.Metrics.PERCENTILE(50),
                        pdp.Metrics.PERCENTILE(90)]
        extra = dict(min_value=1.0, max_value=5.0)
    if args.bounds_enforced:
        # The caller vouches the data is already contribution-bounded —
        # so actually BOUND it first (cap each user at 4 movies x 2
        # ratings, the declared l0/linf), then drop the privacy ids;
        # selection works from conservative row-count estimates.
        order = np.lexsort((data.partition_keys, data.privacy_ids))
        pid_s = data.privacy_ids[order]
        pk_s = data.partition_keys[order]
        val_s = data.values[order]
        idx = np.arange(len(pid_s))
        new_pair = np.r_[True, (pid_s[1:] != pid_s[:-1]) |
                         (pk_s[1:] != pk_s[:-1])]
        pair_id = np.cumsum(new_pair) - 1
        rank_in_pair = idx - np.maximum.accumulate(
            np.where(new_pair, idx, 0))
        new_user = np.r_[True, pid_s[1:] != pid_s[:-1]]
        first_pair_of_user = pair_id[np.maximum.accumulate(
            np.where(new_user, idx, 0))]
        pair_rank_in_user = pair_id - first_pair_of_user
        keep = (rank_in_pair < 2) & (pair_rank_in_user < 4)
        data = pdp.ArrayDataset(privacy_ids=None,
                                partition_keys=pk_s[keep],
                                values=val_s[keep])
        extra["contribution_bounds_already_enforced"] = True

    accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                           total_delta=1e-6)
    engine = pdp.DPEngine(accountant, backend)
    params = pdp.AggregateParams(
        metrics=metrics, noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=4, max_contributions_per_partition=2,
        **extra)
    report = pdp.ExplainComputationReport()
    public = list(range(2_000)) if args.public else None
    result = engine.aggregate(data, params, pdp.DataExtractors(),
                              public_partitions=public,
                              out_explain_computation_report=report)
    accountant.compute_budgets()

    t0 = time.perf_counter()
    rows = list(result)
    dt = time.perf_counter() - t0
    print(f"{len(rows)} movies released in {dt:.2f}s "
          f"({args.rows / dt:,.0f} rows/s) on backend={args.backend}")
    for movie, m in sorted(rows)[:5]:
        if args.vector:
            hist = ", ".join(f"{v:.0f}" for v in m.vector_sum)
            print(f"  movie {movie}: stars 1..5 = [{hist}]")
        else:
            print(f"  movie {movie}: count={m.count:.0f} sum={m.sum:.0f} "
                  f"mean={m.mean:.2f}")
    print()
    print(report.text())


if __name__ == "__main__":
    main()
