#!/usr/bin/env python
"""DP aggregation over movie view ratings (the reference's flagship
example, ``examples/movie_view_ratings/`` — synthetic data generated
in-process so no download is needed).

Computes COUNT + SUM + MEAN (+ optional percentiles) of ratings per
movie, with private partition selection.

Usage:
  python examples/movie_view_ratings.py                 # fused TPU plane
  python examples/movie_view_ratings.py --backend local # generator plane
  python examples/movie_view_ratings.py --public        # public partitions
"""

import argparse
import operator
import time

import numpy as np


def generate_data(n_rows=500_000, n_users=50_000, n_movies=2_000, seed=0):
    rng = np.random.default_rng(seed)
    import pipelinedp_tpu as pdp
    movies = rng.zipf(1.3, n_rows) % n_movies
    return pdp.ArrayDataset(
        privacy_ids=rng.integers(0, n_users, n_rows),
        partition_keys=movies.astype(np.int64),
        values=rng.integers(1, 6, n_rows).astype(np.float64))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--backend", choices=["jax", "local", "multiproc"],
                        default="jax")
    parser.add_argument("--public", action="store_true",
                        help="use public partitions (all movie ids)")
    parser.add_argument("--rows", type=int, default=500_000)
    parser.add_argument("--percentiles", action="store_true")
    args = parser.parse_args()

    import pipelinedp_tpu as pdp

    if args.backend == "jax":
        from pipelinedp_tpu.backends import JaxBackend
        backend = JaxBackend()
    elif args.backend == "multiproc":
        backend = pdp.MultiProcLocalBackend()
    else:
        backend = pdp.LocalBackend()

    data = generate_data(n_rows=args.rows)
    metrics = [pdp.Metrics.COUNT, pdp.Metrics.SUM, pdp.Metrics.MEAN]
    if args.percentiles:
        metrics += [pdp.Metrics.PERCENTILE(50), pdp.Metrics.PERCENTILE(90)]

    accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                           total_delta=1e-6)
    engine = pdp.DPEngine(accountant, backend)
    params = pdp.AggregateParams(
        metrics=metrics, noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=4, max_contributions_per_partition=2,
        min_value=1.0, max_value=5.0)
    report = pdp.ExplainComputationReport()
    public = list(range(2_000)) if args.public else None
    result = engine.aggregate(data, params, pdp.DataExtractors(),
                              public_partitions=public,
                              out_explain_computation_report=report)
    accountant.compute_budgets()

    t0 = time.perf_counter()
    rows = list(result)
    dt = time.perf_counter() - t0
    print(f"{len(rows)} movies released in {dt:.2f}s "
          f"({args.rows / dt:,.0f} rows/s) on backend={args.backend}")
    for movie, m in sorted(rows)[:5]:
        print(f"  movie {movie}: count={m.count:.0f} sum={m.sum:.0f} "
              f"mean={m.mean:.2f}")
    print()
    print(report.text())


if __name__ == "__main__":
    main()
