#!/usr/bin/env python
"""Hardened host releases: the native snapping-Laplace / discrete-Laplace
path (``pipelinedp_tpu/native``; opt-in via
``ops.noise.set_secure_host_noise``).

A textbook float Laplace release leaks information through the noise
sample's low-order mantissa bits (Mironov, CCS 2012). With secure host
noise enabled, integer queries (counts) release exact two-sided-geometric
noise — no float bits at all — and float queries release through the
snapping mechanism, rounded to the power-of-two resolution Lambda. The
Gaussian mechanism is hardened the same way: exact discrete Gaussian
(Canonne–Kamath–Steinke) for counts, granularity-snapped discrete
Gaussian for float queries.

Usage: python examples/secure_noise.py
"""

import operator

import numpy as np

import pipelinedp_tpu as pdp
from pipelinedp_tpu import native
from pipelinedp_tpu.ops import noise as noise_ops


def main():
    if not native.available():
        print("native toolchain unavailable on this host; the NumPy "
              "noise path remains in effect")
        return

    rng = np.random.default_rng(0)
    rows = [(int(u), int(p), float(v))
            for u, p, v in zip(rng.integers(0, 500, 5000),
                               rng.integers(0, 10, 5000),
                               rng.uniform(0, 10, 5000))]
    extractors = pdp.DataExtractors(
        privacy_id_extractor=operator.itemgetter(0),
        partition_extractor=operator.itemgetter(1),
        value_extractor=operator.itemgetter(2))
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
        max_partitions_contributed=2, max_contributions_per_partition=2,
        min_value=0.0, max_value=10.0)

    noise_ops.set_secure_host_noise(True)
    try:
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                               total_delta=1e-6)
        engine = pdp.DPEngine(accountant, pdp.LocalBackend())
        result = engine.aggregate(rows, params, extractors)
        accountant.compute_budgets()
        print("partition  count (integer release)  sum (snapped release)")
        for pk, m in sorted(result):
            print(f"{pk:9d}  {m.count:23.1f}  {m.sum:21.3f}")
        print("\ncounts are exact integers (discrete Laplace); sums are "
              "multiples of the snapping resolution.")

        # Same pipeline under the hardened Gaussian mechanism.
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                               total_delta=1e-6)
        engine = pdp.DPEngine(accountant, pdp.LocalBackend())
        gauss_params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=2,
            max_contributions_per_partition=2,
            min_value=0.0, max_value=10.0,
            noise_kind=pdp.NoiseKind.GAUSSIAN)
        result = engine.aggregate(rows, gauss_params, extractors)
        accountant.compute_budgets()
        print("\nGaussian: counts get exact discrete-Gaussian noise; "
              "sums are granularity-snapped:")
        for pk, m in sorted(result):
            print(f"{pk:9d}  {m.count:23.1f}  {m.sum:21.3f}")
    finally:
        noise_ops.set_secure_host_noise(False)


if __name__ == "__main__":
    main()
