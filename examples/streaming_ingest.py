#!/usr/bin/env python
"""Streaming ingest: DP aggregation over datasets larger than one
device batch (``pipelinedp_tpu/streaming.py``).

The fused kernel's per-partition accumulators are additive, so the
engine transparently streams any pipeline whose row count exceeds one
chunk (default 2^26 rows, ``PIPELINEDP_TPU_STREAM_CHUNK``): rows are
grouped into privacy-id-disjoint batches, each batch runs the same
bounding + reduction kernel, partials fold into exact host
int64/float64 accumulators, and selection + release run once at the
end. Percentiles stream too, in two passes (see the module docstring).

Nothing in the user code changes — this demo just forces a small chunk
so a 2M-row dataset visibly streams. With the default chunk a dataset
only streams past 67M rows per device (the bench's ``--stream-rows``
record runs 150M). Streaming composes with a device mesh
(``JaxBackend(mesh=make_mesh())``): each chunk shards by privacy id
over the mesh and the per-chunk budget scales with the device count.
The overlapped ingest executor (``pipelinedp_tpu/ingest``, on by
default) stages batch b+1 on a background thread while the device
computes batch b and folds finished batches on an ordered worker —
bit-identical to the serial path (``PIPELINEDP_TPU_INGEST_EXECUTOR=0``
to compare) — and percentile pass B re-reads shipped batches from a
device cache (``PIPELINEDP_TPU_STREAM_CACHE``) instead of re-shipping
them.

Usage: python examples/streaming_ingest.py
"""

import os
import time

import numpy as np

os.environ.setdefault("PIPELINEDP_TPU_STREAM_CHUNK", "500000")

import pipelinedp_tpu as pdp
from pipelinedp_tpu.backends import JaxBackend


def main():
    rng = np.random.default_rng(0)
    n = 2_000_000
    print(f"generating {n:,} rows ...")
    ds = pdp.ArrayDataset(
        privacy_ids=rng.integers(0, 300_000, n).astype(np.int32),
        partition_keys=(rng.zipf(1.3, n) % 1_000).astype(np.int32),
        values=rng.uniform(0.0, 10.0, n).astype(np.float32))

    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM, pdp.Metrics.MEAN,
                 pdp.Metrics.PERCENTILE(50)],
        max_partitions_contributed=4,
        max_contributions_per_partition=2,
        min_value=0.0, max_value=10.0)

    accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                           total_delta=1e-6)
    engine = pdp.DPEngine(accountant, JaxBackend())
    result = engine.aggregate(ds, params, pdp.DataExtractors())
    accountant.compute_budgets()

    t0 = time.perf_counter()
    rows = sorted(result)
    dt = time.perf_counter() - t0
    batches = result.timings.get("stream_batches", 1)
    print(f"aggregated in {dt:.1f}s across {batches} streamed batches "
          f"({len(rows)} partitions kept)")
    t = result.timings
    if "stream_t_total" in t:
        print(f"pass-A phases: stage {t['stream_t_stage']:.2f}s + fold "
              f"{t['stream_t_fold']:.2f}s + device "
              f"{t['stream_t_device']:.2f}s vs wall "
              f"{t['stream_t_total']:.2f}s "
              f"({t['stream_executor']}, overlap "
              f"{t['stream_overlap_frac']:.0%})")
    print("partition  count      sum     mean   p50")
    for pk, m in rows[:8]:
        print(f"{pk:9d} {m.count:7.0f} {m.sum:9.0f} {m.mean:7.2f} "
              f"{m.percentile_50:5.2f}")


if __name__ == "__main__":
    main()
