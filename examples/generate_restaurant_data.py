#!/usr/bin/env python
"""Generates ``restaurants_week_data.csv`` — a synthetic week of
restaurant visits with the same schema as the reference dataset
(``examples/restaurant_visits/restaurants_week_data.csv`` in PipelineDP:
VisitorId, Time entered, Time spent (minutes), Money spent (euros), Day).

Deterministic (fixed seed), so the checked-in CSV regenerates
bit-identically: ``python examples/generate_restaurant_data.py``.
"""

import csv
import os

import numpy as np

OUT = os.path.join(os.path.dirname(__file__), "restaurants_week_data.csv")


def generate(path=OUT, n_visitors=1200, seed=2026):
    rng = np.random.default_rng(seed)
    rows = []
    for visitor in range(1, n_visitors + 1):
        # Most guests visit once or twice a week; regulars come daily.
        n_visits = int(rng.choice([1, 1, 2, 2, 3, 5, 7]))
        days = rng.choice(7, size=min(n_visits, 7), replace=False) + 1
        for day in sorted(int(d) for d in days):
            hour = int(rng.integers(9, 21))
            minute = int(rng.integers(0, 60))
            ampm = "AM" if hour < 12 else "PM"
            h12 = hour if hour <= 12 else hour - 12
            spent_minutes = int(rng.integers(5, 90))
            money = int(np.clip(rng.normal(18, 8), 3, 60))
            rows.append((visitor, f"{h12}:{minute:02d}{ampm}",
                         spent_minutes, money, day))
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["VisitorId", "Time entered", "Time spent (minutes)",
                    "Money spent (euros)", "Day"])
        w.writerows(rows)
    return len(rows)


if __name__ == "__main__":
    n = generate()
    print(f"wrote {n} visits to {OUT}")
