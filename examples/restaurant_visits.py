#!/usr/bin/env python
"""DP analysis of weekly restaurant visits with utility analysis and
parameter tuning (the reference's ``examples/restaurant_visits/``,
synthetic data generated in-process).

Usage:
  python examples/restaurant_visits.py             # DP privacy-id count
  python examples/restaurant_visits.py --analyze   # utility analysis
  python examples/restaurant_visits.py --tune      # parameter tuning
"""

import argparse
import operator

import numpy as np


def generate_visits(n_visitors=2_000, n_restaurants=40, seed=0):
    """(visitor_id, restaurant, spend) rows: frequent diners visit several
    restaurants several times a week."""
    rng = np.random.default_rng(seed)
    rows = []
    for v in range(n_visitors):
        n_visits = int(rng.integers(1, 8))
        for _ in range(n_visits):
            rows.append((v, int(rng.integers(0, n_restaurants)),
                         float(rng.uniform(5, 50))))
    return rows


def extractors():
    import pipelinedp_tpu as pdp
    return pdp.DataExtractors(privacy_id_extractor=operator.itemgetter(0),
                              partition_extractor=operator.itemgetter(1),
                              value_extractor=operator.itemgetter(2))


def run_dp_count(data):
    import pipelinedp_tpu as pdp
    backend = pdp.LocalBackend()
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                           total_delta=1e-6)
    pcol = pdp.make_private(data, backend, accountant,
                            operator.itemgetter(0))
    result = pcol.privacy_id_count(
        pdp.PrivacyIdCountParams(
            max_partitions_contributed=3,
            partition_extractor=operator.itemgetter(1)))
    accountant.compute_budgets()
    out = sorted(dict(result).items())
    print(f"{len(out)} restaurants selected; first 5:")
    for r, c in out[:5]:
        print(f"  restaurant {r}: ~{c:.0f} distinct visitors")


def run_analysis(data):
    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import analysis
    backend = pdp.LocalBackend()
    options = analysis.UtilityAnalysisOptions(
        epsilon=1.0, delta=1e-6,
        aggregate_params=pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT], max_partitions_contributed=3,
            max_contributions_per_partition=2),
        multi_param_configuration=analysis.MultiParameterConfiguration(
            max_contributions_per_partition=[1, 2, 4, 8]))
    results = list(
        analysis.perform_utility_analysis(data, backend, options,
                                          extractors()))[0]
    print("linf sweep (COUNT):")
    for am in results:
        p = am.input_aggregate_params
        cm = am.count_metrics
        print(f"  linf={p.max_contributions_per_partition}: "
              f"rmse={cm.absolute_rmse():.2f} "
              f"dropped_linf={cm.ratio_data_dropped_linf:.1%}")


def run_tuning(data):
    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import analysis
    backend = pdp.LocalBackend()
    hist = list(
        analysis.compute_dataset_histograms(data, extractors(),
                                            backend))[0]
    options = analysis.TuneOptions(
        epsilon=1.0, delta=1e-6,
        aggregate_params=pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT], max_partitions_contributed=1,
            max_contributions_per_partition=1),
        function_to_minimize=analysis.MinimizingFunction.ABSOLUTE_ERROR,
        parameters_to_tune=analysis.ParametersToTune(
            max_partitions_contributed=True,
            max_contributions_per_partition=True))
    result = list(
        analysis.tune(data, backend, hist, options, extractors()))[0]
    best = result.utility_analysis_parameters.get_aggregate_params(
        options.aggregate_params, result.index_best)
    print(f"tuned over {result.utility_analysis_parameters.size} configs")
    print(f"best: l0={best.max_partitions_contributed} "
          f"linf={best.max_contributions_per_partition} "
          f"(rmse={result.utility_analysis_results[result.index_best].count_metrics.absolute_rmse():.2f})")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--analyze", action="store_true")
    parser.add_argument("--tune", action="store_true")
    args = parser.parse_args()
    data = generate_visits()
    if args.analyze:
        run_analysis(data)
    elif args.tune:
        run_tuning(data)
    else:
        run_dp_count(data)


if __name__ == "__main__":
    main()
