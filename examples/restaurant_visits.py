#!/usr/bin/env python
"""DP analysis of a week of restaurant visits, read from a CSV file
through real extractors (the reference's ``examples/restaurant_visits/``
workflow: ``run_without_frameworks*.py`` over
``restaurants_week_data.csv``).

The dataset partitions by week day; metrics are per-day visit counts and
money totals, plus utility analysis / parameter tuning over the same
file. Regenerate the CSV with ``python examples/generate_restaurant_data.py``.

Usage:
  python examples/restaurant_visits.py               # DP count + sum per day
  python examples/restaurant_visits.py --analyze     # utility analysis
  python examples/restaurant_visits.py --tune        # parameter tuning
  python examples/restaurant_visits.py --columnar    # ArrayDataset fast path
"""

import argparse
import csv
import operator
import os

DATA = os.path.join(os.path.dirname(__file__), "restaurants_week_data.csv")


def load_rows(path=DATA):
    """(visitor_id, day, money) tuples straight from the CSV. Plain
    ``operator.itemgetter`` extractors over these rows take the
    vectorized ingest bridge — no per-row Python extractor calls."""
    with open(path, newline="") as f:
        reader = csv.DictReader(f)
        return [(int(r["VisitorId"]), int(r["Day"]),
                 float(r["Money spent (euros)"])) for r in reader]


def load_columns(path=DATA):
    """The same file as a columnar ArrayDataset (the zero-copy fast path
    into the fused TPU plane)."""
    import numpy as np

    import pipelinedp_tpu as pdp
    visitors, days, money = [], [], []
    with open(path, newline="") as f:
        for r in csv.DictReader(f):
            visitors.append(int(r["VisitorId"]))
            days.append(int(r["Day"]))
            money.append(float(r["Money spent (euros)"]))
    return pdp.ArrayDataset(privacy_ids=np.asarray(visitors),
                            partition_keys=np.asarray(days),
                            values=np.asarray(money))


def extractors():
    import pipelinedp_tpu as pdp
    return pdp.DataExtractors(privacy_id_extractor=operator.itemgetter(0),
                              partition_extractor=operator.itemgetter(1),
                              value_extractor=operator.itemgetter(2))


def run_dp_week(data, ext=None, backend=None):
    """Per-day DP visit count + DP money total, public partitions =
    the seven week days."""
    import pipelinedp_tpu as pdp
    backend = backend or pdp.LocalBackend()
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                           total_delta=1e-7)
    engine = pdp.DPEngine(accountant, backend)
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
        noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=3,
        max_contributions_per_partition=2,
        min_value=0.0, max_value=60.0)
    result = engine.aggregate(data, params, ext or extractors(),
                              public_partitions=list(range(1, 8)))
    accountant.compute_budgets()
    print("day  ~visits  ~euros")
    for day, m in sorted(dict(result).items()):
        print(f"  {day}   {m.count:6.0f}  {m.sum:7.0f}")


def run_analysis(data):
    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import analysis
    backend = pdp.LocalBackend()
    options = analysis.UtilityAnalysisOptions(
        epsilon=1.0, delta=1e-7,
        aggregate_params=pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT], max_partitions_contributed=3,
            max_contributions_per_partition=2),
        multi_param_configuration=analysis.MultiParameterConfiguration(
            max_contributions_per_partition=[1, 2, 4, 8]))
    results = list(
        analysis.perform_utility_analysis(data, backend, options,
                                          extractors()))[0]
    print("linf sweep (COUNT):")
    for am in results:
        p = am.input_aggregate_params
        cm = am.count_metrics
        print(f"  linf={p.max_contributions_per_partition}: "
              f"rmse={cm.absolute_rmse():.2f} "
              f"dropped_linf={cm.ratio_data_dropped_linf:.1%}")


def run_tuning(data):
    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import analysis
    backend = pdp.LocalBackend()
    hist = list(
        analysis.compute_dataset_histograms(data, extractors(),
                                            backend))[0]
    options = analysis.TuneOptions(
        epsilon=1.0, delta=1e-7,
        aggregate_params=pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT], max_partitions_contributed=1,
            max_contributions_per_partition=1),
        function_to_minimize=analysis.MinimizingFunction.ABSOLUTE_ERROR,
        parameters_to_tune=analysis.ParametersToTune(
            max_partitions_contributed=True,
            max_contributions_per_partition=True))
    result = list(
        analysis.tune(data, backend, hist, options, extractors()))[0]
    best = result.utility_analysis_parameters.get_aggregate_params(
        options.aggregate_params, result.index_best)
    print(f"tuned over {result.utility_analysis_parameters.size} configs")
    print(f"best: l0={best.max_partitions_contributed} "
          f"linf={best.max_contributions_per_partition} "
          f"(rmse={result.utility_analysis_results[result.index_best].count_metrics.absolute_rmse():.2f})")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--analyze", action="store_true")
    parser.add_argument("--tune", action="store_true")
    parser.add_argument("--columnar", action="store_true",
                        help="ingest via ArrayDataset columns")
    args = parser.parse_args()
    if not os.path.exists(DATA):
        import generate_restaurant_data
        generate_restaurant_data.generate()
    if args.analyze:
        run_analysis(load_rows())
    elif args.tune:
        run_tuning(load_rows())
    elif args.columnar:
        import pipelinedp_tpu as pdp
        from pipelinedp_tpu.backends import JaxBackend
        run_dp_week(load_columns(), ext=pdp.DataExtractors(),
                    backend=JaxBackend())
    else:
        run_dp_week(load_rows())


if __name__ == "__main__":
    main()
