#!/usr/bin/env python
"""Custom-combiner extension point demo (the reference's
``examples/experimental/custom_combiners.py``): a user-defined DP sum
combiner with a hand-rolled Laplace mechanism."""

import operator

import numpy as np


def main():
    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import combiners
    from pipelinedp_tpu.ops import noise as noise_ops

    class SumCombiner(combiners.CustomCombiner):
        """DP sum with explicit budget request and manual noise."""

        def __init__(self, min_value, max_value,
                     max_partitions_contributed):
            self._min = min_value
            self._max = max_value
            self._l0 = max_partitions_contributed

        def request_budget(self, budget_accountant):
            self._budget = budget_accountant.request_budget(
                pdp.MechanismType.LAPLACE)

        def create_accumulator(self, values):
            return float(np.clip(values, self._min, self._max).sum())

        def merge_accumulators(self, a, b):
            return a + b

        def compute_metrics(self, total):
            linf = max(abs(self._min), abs(self._max))
            scale = noise_ops.laplace_scale(self._budget.eps,
                                            self._l0 * linf)
            return total + noise_ops.np_laplace(scale)

        def explain_computation(self):
            return lambda: f"Custom DP sum (eps={self._budget.eps})"

        def metrics_names(self):
            return ["custom_sum"]

    data = [(u, pk, 3.0) for u in range(200) for pk in ("a", "b")]
    backend = pdp.LocalBackend()
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                           total_delta=1e-6)
    engine = pdp.DPEngine(accountant, backend)
    params = pdp.AggregateParams(
        custom_combiners=[SumCombiner(0.0, 5.0, 2)],
        max_partitions_contributed=2, max_contributions_per_partition=1)
    ext = pdp.DataExtractors(privacy_id_extractor=operator.itemgetter(0),
                             partition_extractor=operator.itemgetter(1),
                             value_extractor=operator.itemgetter(2))
    result = engine.aggregate(data, params, ext)
    accountant.compute_budgets()
    for pk, metrics in sorted(dict(result).items()):
        print(f"partition {pk}: custom DP sum = {metrics[0]:.1f}")


if __name__ == "__main__":
    main()
