#!/usr/bin/env python
"""The restaurant-visits pipeline on Spark RDDs through the fluent
``private_spark`` API (the reference's
``examples/restaurant_visits/run_on_spark.py`` workflow).

Requires ``pip install pyspark`` (not bundled)."""

import operator

from restaurant_visits import DATA, load_rows


def main():
    try:
        import pyspark
    except ImportError:
        raise SystemExit("pyspark is not installed; "
                         "`pip install pyspark` to run this example.")

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import private_spark

    master = pyspark.SparkConf().setMaster("local[1]")
    sc = pyspark.SparkContext(conf=master)
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                           total_delta=1e-7)
    rdd = sc.parallelize(load_rows(DATA))
    private = private_spark.make_private(rdd, accountant,
                                         operator.itemgetter(0))
    result = private.sum(
        pdp.SumParams(partition_extractor=operator.itemgetter(1),
                      value_extractor=operator.itemgetter(2),
                      max_partitions_contributed=3,
                      max_contributions_per_partition=2,
                      min_value=0.0, max_value=60.0),
        public_partitions=list(range(1, 8)))
    accountant.compute_budgets()
    for day, total in sorted(result.collect()):
        print(f"day {day}: ~{total:.0f} EUR")
    sc.stop()


if __name__ == "__main__":
    main()
