#!/usr/bin/env python
"""The restaurant-visits pipeline on Apache Beam through the fluent
``private_beam`` API (the reference's
``examples/restaurant_visits/run_on_beam.py`` workflow).

Requires ``pip install apache-beam`` (not bundled); the DP engine and the
two-phase budget protocol are exactly the ones the local/TPU planes use —
Beam only supplies the distributed shuffle.
"""

import operator

from restaurant_visits import DATA, load_rows


def main():
    try:
        import apache_beam as beam
    except ImportError:
        raise SystemExit("apache-beam is not installed; "
                         "`pip install apache-beam` to run this example.")

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import private_beam

    accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                           total_delta=1e-7)
    with beam.Pipeline() as pipeline:
        visits = pipeline | beam.Create(load_rows(DATA))
        private = visits | private_beam.MakePrivate(
            budget_accountant=accountant,
            privacy_id_extractor=operator.itemgetter(0))
        sums = private | private_beam.Sum(
            pdp.SumParams(
                partition_extractor=operator.itemgetter(1),
                value_extractor=operator.itemgetter(2),
                max_partitions_contributed=3,
                max_contributions_per_partition=2,
                min_value=0.0, max_value=60.0),
            public_partitions=list(range(1, 8)))
        accountant.compute_budgets()
        sums | beam.Map(lambda kv: print(f"day {kv[0]}: ~{kv[1]:.0f} EUR"))


if __name__ == "__main__":
    main()
