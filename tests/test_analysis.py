"""Tests for the utility-analysis package — error models vs closed-form
expectations, Poisson-binomial exactness, histograms, the full sweep and
tuning E2E (mirrors the reference's ``analysis/tests/`` strategy)."""

import math
import operator

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import analysis
from pipelinedp_tpu.analysis import combiners as ua_combiners
from pipelinedp_tpu.analysis import data_structures, histograms, metrics
from pipelinedp_tpu.analysis import poisson_binomial
from pipelinedp_tpu.budget_accounting import MechanismSpec
from pipelinedp_tpu.combiners import CombinerParams
from pipelinedp_tpu.aggregate_params import MechanismType


def extractors():
    return pdp.DataExtractors(privacy_id_extractor=operator.itemgetter(0),
                              partition_extractor=operator.itemgetter(1),
                              value_extractor=operator.itemgetter(2))


def count_params(l0=1, linf=1, **kw):
    base = dict(metrics=[pdp.Metrics.COUNT], max_partitions_contributed=l0,
                max_contributions_per_partition=linf)
    base.update(kw)
    return pdp.AggregateParams(**base)


class TestPoissonBinomial:

    def test_exact_pmf_matches_binomial(self):
        # All p equal -> binomial distribution.
        from scipy.stats import binom
        p = 0.3
        pmf = poisson_binomial.compute_pmf([p] * 10)
        expected = binom.pmf(np.arange(11), 10, p)
        np.testing.assert_allclose(pmf.probabilities, expected, atol=1e-12)

    def test_approximation_close_to_exact(self):
        rng = np.random.default_rng(0)
        probs = rng.uniform(0.2, 0.8, 200).tolist()
        exact = poisson_binomial.compute_pmf(probs)
        exp, std, skew = poisson_binomial.compute_exp_std_skewness(probs)
        approx = poisson_binomial.compute_pmf_approximation(
            exp, std, skew, len(probs))
        # Compare a central slice of the distributions.
        for v in range(int(exp - std), int(exp + std)):
            pe = exact.probabilities[v - exact.start]
            pa = approx.probabilities[v - approx.start]
            assert pe == pytest.approx(pa, abs=2e-3)

    def test_zero_sigma(self):
        pmf = poisson_binomial.compute_pmf_approximation(5.0, 0.0, 0.0, 10)
        assert pmf.start == 5
        assert pmf.probabilities.tolist() == [1.0]


class TestProbabilityComputations:
    """MC quantiles of the Laplace+Gaussian convolution vs analytically
    computed expectations (reference
    ``analysis/tests/probability_computations_test.py``: the expected
    values there are derived analytically to 1e-10; the distribution is
    symmetric, so q and 1-q must be mirror images)."""

    @pytest.mark.parametrize("b,sigma,qs,expected", [
        (1.0, 2.0, [0.1, 0.5, 0.9], [-3.0874, 0.0, 3.0874]),
        (1.01, 0.55, [0.5, 0.7, 0.9, 0.99],
         [0.0, 0.63892, 1.77515, 4.10093]),
    ])
    def test_quantiles_match_analytic(self, b, sigma, qs, expected):
        from pipelinedp_tpu.analysis import probability_computations as pc
        got = pc.compute_sum_laplace_gaussian_quantiles(
            b, sigma, qs, 4 * 10**6, rng=np.random.default_rng(0))
        np.testing.assert_allclose(got, expected, atol=0.02)

    def test_symmetry(self):
        from pipelinedp_tpu.analysis import probability_computations as pc
        got = pc.compute_sum_laplace_gaussian_quantiles(
            2.0, 1.0, [0.05, 0.25, 0.75, 0.95], 10**6,
            rng=np.random.default_rng(1))
        assert got[0] == pytest.approx(-got[3], abs=0.05)
        assert got[1] == pytest.approx(-got[2], abs=0.05)

    def test_batch_matches_scalar(self):
        from pipelinedp_tpu.analysis import probability_computations as pc
        qs = [0.1, 0.5, 0.9]
        batch = pc.compute_sum_laplace_gaussian_quantiles_batch(
            np.array([1.0, 3.0]), np.array([2.0, 0.5]), qs, 10**6,
            rng=np.random.default_rng(2))
        for i, (b, s) in enumerate([(1.0, 2.0), (3.0, 0.5)]):
            scalar = pc.compute_sum_laplace_gaussian_quantiles(
                b, s, qs, 10**6, rng=np.random.default_rng(3))
            np.testing.assert_allclose(batch[i], scalar, atol=0.05)


class TestAnalysisContributionBounders:
    """The analysis bounders record, not enforce (reference
    ``analysis/tests/contribution_bounders_test.py``)."""

    def _bound(self, rows, prob=1.0):
        from pipelinedp_tpu.analysis.contribution_bounders import (
            SamplingL0LinfContributionBounder)
        backend = pdp.LocalBackend()
        out = SamplingL0LinfContributionBounder(prob).bound_contributions(
            rows, count_params(), backend, None, lambda x: x)
        return dict(out)

    def test_emits_count_sum_npartitions_per_pid_pk(self):
        rows = [("u1", "a", 1.0), ("u1", "a", 2.0), ("u1", "b", 5.0),
                ("u2", "a", 7.0)]
        got = self._bound(rows)
        # No bounding happens regardless of tiny caps in params.
        assert got[("u1", "a")] == (2, 3.0, 2)
        assert got[("u1", "b")] == (1, 5.0, 2)
        assert got[("u2", "a")] == (1, 7.0, 1)

    def test_n_partitions_counts_pre_sampling_partitions(self):
        # Partition sampling drops partitions deterministically but
        # n_partitions still reflects the privacy id's full spread.
        rows = [("u1", pk, 1.0) for pk in range(200)]
        got = self._bound(rows, prob=0.5)
        assert 0 < len(got) < 200  # some partitions sampled away
        assert all(v == (1, 1.0, 200) for v in got.values())
        # Deterministic: same keys kept on a second run.
        assert got == self._bound(rows, prob=0.5)

    def test_noop_bounder_preaggregated(self):
        from pipelinedp_tpu.analysis.contribution_bounders import (
            NoOpContributionBounder)
        rows = [("a", (2, 3.0, 4)), ("b", (1, 1.0, 4))]
        out = dict(NoOpContributionBounder().bound_contributions(
            rows, count_params(), pdp.LocalBackend(), None, lambda x: x))
        assert out == {(None, "a"): (2, 3.0, 4), (None, "b"): (1, 1.0, 4)}


class TestMultiParameterConfiguration:

    def test_validation(self):
        with pytest.raises(ValueError, match="at least 1"):
            data_structures.MultiParameterConfiguration()
        with pytest.raises(ValueError, match="same length"):
            data_structures.MultiParameterConfiguration(
                max_partitions_contributed=[1, 2],
                max_contributions_per_partition=[1])

    def test_get_aggregate_params(self):
        mpc = data_structures.MultiParameterConfiguration(
            max_partitions_contributed=[1, 2],
            max_contributions_per_partition=[10, 11])
        base = count_params(l0=5, linf=5)
        p0 = mpc.get_aggregate_params(base, 0)
        p1 = mpc.get_aggregate_params(base, 1)
        assert (p0.max_partitions_contributed,
                p0.max_contributions_per_partition) == (1, 10)
        assert (p1.max_partitions_contributed,
                p1.max_contributions_per_partition) == (2, 11)


class TestAnalysisCombiners:

    def _params(self, agg_params, eps=1.0, delta=1e-6):
        spec = MechanismSpec(MechanismType.LAPLACE, _eps=eps, _delta=delta)
        return CombinerParams(spec, agg_params)

    def test_count_combiner_error_model(self):
        # One user contributes 5 rows, linf=3 -> linf error = -2;
        # n_partitions=2, l0=1 -> keep prob 0.5 ->
        # expected l0 error = -3*0.5, var = 9*0.25.
        params = self._params(count_params(l0=1, linf=3))
        c = ua_combiners.CountCombiner(params)
        acc = c.create_accumulator(
            (np.array([5]), np.array([0.0]), np.array([2])))
        m = c.compute_metrics(acc)
        assert m.sum == 5
        assert m.per_partition_error_max == -2
        assert m.expected_cross_partition_error == pytest.approx(-1.5)
        assert m.std_cross_partition_error == pytest.approx(1.5)
        assert m.std_noise > 0

    def test_privacy_id_count_combiner(self):
        params = self._params(count_params(l0=2, linf=1))
        c = ua_combiners.PrivacyIdCountCombiner(params)
        acc = c.create_accumulator(
            (np.array([7, 0]), np.array([0.0, 0.0]), np.array([4, 4])))
        m = c.compute_metrics(acc)
        assert m.sum == 1.0  # only one user has counts > 0
        assert m.expected_cross_partition_error == pytest.approx(-0.5)

    def test_sum_combiner_clipping_errors(self):
        agg = pdp.AggregateParams(
            metrics=[pdp.Metrics.SUM], max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_sum_per_partition=0.0, max_sum_per_partition=10.0)
        c = ua_combiners.SumCombiner(self._params(agg))
        acc = c.create_accumulator(
            (None, np.array([15.0, -5.0]), np.array([1, 1])))
        m = c.compute_metrics(acc)
        assert m.sum == 10.0
        assert m.per_partition_error_max == -5.0  # 15 clipped to 10
        assert m.per_partition_error_min == 5.0  # -5 clipped to 0

    def test_partition_selection_combiner_probability(self):
        params = self._params(count_params(l0=1, linf=1), eps=1.0,
                              delta=1e-5)
        c = ua_combiners.PartitionSelectionCombiner(params)
        # 200 users each contributing to this partition only -> all keep
        # probability 1 -> partition almost surely kept.
        acc = c.create_accumulator(
            (np.ones(200), np.zeros(200), np.ones(200)))
        prob = c.compute_metrics(acc)
        assert prob == pytest.approx(1.0, abs=1e-3)

    def test_sparse_to_dense_switch(self):
        params = self._params(count_params())
        compound = ua_combiners.CompoundCombiner(
            [ua_combiners.CountCombiner(params)], return_named_tuple=False)
        acc = compound.create_accumulator((1, 1.0, 1))
        # Merge many: should flip to dense (2 * 1 combiner = 2 max sparse).
        for _ in range(5):
            acc = compound.merge_accumulators(
                acc, compound.create_accumulator((1, 1.0, 1)))
        sparse, dense = acc
        assert sparse is None
        assert dense is not None

    def test_moments_merge_beyond_cap(self):
        probs = [0.5] * (ua_combiners.MAX_PROBABILITIES_IN_ACCUMULATOR + 1)
        acc1 = (probs[:60], None)
        acc2 = (probs[:60], None)
        merged = ua_combiners._merge_partition_selection_accumulators(
            acc1, acc2)
        assert merged[0] is None
        assert merged[1].count == 120
        assert merged[1].expectation == pytest.approx(60.0)


class TestHistograms:

    def test_bin_lower(self):
        assert histograms._to_bin_lower(123) == 123
        assert histograms._to_bin_lower(1234) == 1230
        assert histograms._to_bin_lower(12345) == 12300

    def test_dataset_histograms(self):
        # 3 users: u0 -> 2 partitions (1 row each); u1 -> 1 partition with
        # 3 rows; u2 -> 1 partition 1 row.
        data = ([(0, "a", 1.0), (0, "b", 1.0)] + [(1, "a", 1.0)] * 3 +
                [(2, "b", 1.0)])
        backend = pdp.LocalBackend()
        result = analysis.compute_dataset_histograms(
            data, extractors(), backend)
        hist = list(result)[0]
        assert hist.l0_contributions_histogram.total_count() == 3
        assert hist.l0_contributions_histogram.max_value == 2
        assert hist.linf_contributions_histogram.max_value == 3
        assert hist.count_per_partition_histogram.total_count() == 2
        assert hist.count_privacy_id_per_partition.max_value == 2

    def test_quantiles(self):
        bins = [
            histograms.FrequencyBin(lower=i, count=10, sum=10 * i, max=i)
            for i in range(1, 11)
        ]
        h = histograms.Histogram(histograms.HistogramType.L0_CONTRIBUTIONS,
                                 bins)
        q = h.quantiles([0.05, 0.5, 0.95])
        assert q[0] == 1
        assert q[1] in (5, 6)
        assert q[2] == 10


class TestPerformUtilityAnalysis:

    def test_count_analysis_private_partitions(self):
        n_users = 60
        data = [(u, pk, 1.0) for u in range(n_users)
                for pk in ("a", "b")]
        backend = pdp.LocalBackend()
        options = analysis.UtilityAnalysisOptions(
            epsilon=2.0, delta=1e-5,
            aggregate_params=count_params(l0=2, linf=1))
        result = list(
            analysis.perform_utility_analysis(data, backend, options,
                                              extractors()))[0]
        assert len(result) == 1
        am = result[0]
        assert am.count_metrics is not None
        assert am.partition_selection_metrics is not None
        assert am.partition_selection_metrics.num_partitions == 2
        # No contribution bounding error (bounds are not binding).
        assert am.count_metrics.error_expected == pytest.approx(0.0,
                                                                abs=1e-6)
        assert am.count_metrics.noise_std > 0

    def test_multi_configuration_sweep(self):
        data = [(u, "a", 1.0) for u in range(30) for _ in range(4)]
        backend = pdp.LocalBackend()
        mpc = analysis.MultiParameterConfiguration(
            max_contributions_per_partition=[1, 2, 4])
        options = analysis.UtilityAnalysisOptions(
            epsilon=2.0, delta=1e-5,
            aggregate_params=count_params(l0=1, linf=1),
            multi_param_configuration=mpc)
        result = list(
            analysis.perform_utility_analysis(data, backend, options,
                                              extractors()))[0]
        assert len(result) == 3
        # linf=1 truncates 3/4 of rows; linf=4 keeps all.
        err1 = result[0].count_metrics.error_linf_expected
        err4 = result[2].count_metrics.error_linf_expected
        assert err1 == pytest.approx(-90.0)  # 30 users * (1 - 4)
        assert err4 == pytest.approx(0.0)

    def test_public_partitions(self):
        data = [(u, "a", 1.0) for u in range(20)]
        backend = pdp.LocalBackend()
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-5, aggregate_params=count_params())
        result = list(
            analysis.perform_utility_analysis(
                data, backend, options, extractors(),
                public_partitions=["a", "b"]))[0]
        am = result[0]
        assert am.partition_selection_metrics is None
        assert am.count_metrics is not None


class TestPreAggregation:

    def test_preaggregate_output(self):
        data = [(0, "a", 2.0), (0, "a", 3.0), (0, "b", 1.0), (1, "a", 4.0)]
        backend = pdp.LocalBackend()
        result = sorted(
            analysis.preaggregate(data, backend, extractors()),
            key=repr)
        # (pk, (count, sum, n_partitions))
        assert ("a", (2, 5.0, 2)) in result
        assert ("b", (1, 1.0, 2)) in result
        assert ("a", (1, 4.0, 1)) in result

    def test_analysis_on_preaggregated(self):
        data = [(0, "a", 1.0), (1, "a", 1.0), (2, "a", 1.0)]
        backend = pdp.LocalBackend()
        pre = list(analysis.preaggregate(data, backend, extractors()))
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-5, aggregate_params=count_params(),
            pre_aggregated_data=True)
        pre_extractors = analysis.PreAggregateExtractors(
            partition_extractor=operator.itemgetter(0),
            preaggregate_extractor=operator.itemgetter(1))
        result = list(
            analysis.perform_utility_analysis(pre, backend, options,
                                              pre_extractors))[0]
        assert result[0].count_metrics is not None


class TestTune:

    def test_tune_count(self):
        rng = np.random.default_rng(0)
        # Users with varying contribution counts across partitions.
        data = []
        for u in range(100):
            n_parts = rng.integers(1, 6)
            for pk in rng.choice(20, n_parts, replace=False):
                for _ in range(rng.integers(1, 4)):
                    data.append((u, int(pk), 1.0))
        backend = pdp.LocalBackend()
        hist = list(
            analysis.compute_dataset_histograms(data, extractors(),
                                                backend))[0]
        options = analysis.TuneOptions(
            epsilon=2.0, delta=1e-5,
            aggregate_params=count_params(),
            function_to_minimize=analysis.MinimizingFunction.ABSOLUTE_ERROR,
            parameters_to_tune=analysis.ParametersToTune(
                max_partitions_contributed=True,
                max_contributions_per_partition=True))
        result = list(
            analysis.tune(data, backend, hist, options, extractors()))[0]
        assert isinstance(result, analysis.TuneResult)
        n_configs = result.utility_analysis_parameters.size
        assert len(result.utility_analysis_results) == n_configs
        assert 0 <= result.index_best < n_configs

    def test_tune_sum(self):
        # Exceeds the reference (its tuner rejects SUM outright,
        # reference parameter_tuning.py:255-270): the L0 bound tunes for
        # SUM under supplied per-partition clip bounds, on both planes.
        from pipelinedp_tpu.backends import JaxBackend
        rng = np.random.default_rng(1)
        data = []
        for u in range(150):
            # A wide L0 spread (heavy tail) so the histogram quantiles
            # yield several distinct candidates.
            n_parts = 1 + min(int(rng.pareto(1.0) * 3), 40)
            for pk in rng.choice(50, n_parts, replace=False):
                data.append((u, int(pk), float(rng.uniform(0, 5))))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.SUM], max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_sum_per_partition=0.0, max_sum_per_partition=10.0)
        options = analysis.TuneOptions(
            epsilon=1.0, delta=1e-5, aggregate_params=params,
            function_to_minimize=analysis.MinimizingFunction.ABSOLUTE_ERROR,
            parameters_to_tune=analysis.ParametersToTune(
                max_partitions_contributed=True))
        hist = list(analysis.compute_dataset_histograms(
            data, extractors(), pdp.LocalBackend()))[0]
        for backend in (pdp.LocalBackend(), JaxBackend()):
            result = list(analysis.tune(data, backend, hist, options,
                                        extractors()))[0]
            n_configs = result.utility_analysis_parameters.size
            assert n_configs > 1
            assert 0 <= result.index_best < n_configs
            best = result.utility_analysis_results[result.index_best]
            assert best.sum_metrics is not None

    def test_tune_sum_requires_clip_bounds(self):
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.SUM], max_partitions_contributed=1,
            max_contributions_per_partition=1, min_value=0.0,
            max_value=1.0)
        with pytest.raises(ValueError, match="min/max_sum_per_partition"):
            analysis.tune([1], pdp.LocalBackend(), None,
                          analysis.TuneOptions(
                              epsilon=1.0, delta=1e-5,
                              aggregate_params=params,
                              function_to_minimize=(
                                  analysis.MinimizingFunction.ABSOLUTE_ERROR),
                              parameters_to_tune=analysis.ParametersToTune(
                                  max_partitions_contributed=True)),
                          extractors())

    def test_tune_rejects_unsupported(self):
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.VECTOR_SUM], max_partitions_contributed=1,
            max_contributions_per_partition=1, vector_size=2,
            vector_max_norm=1.0, vector_norm_kind=pdp.NormKind.L2)
        with pytest.raises(NotImplementedError):
            analysis.tune([1], pdp.LocalBackend(), None,
                          analysis.TuneOptions(
                              epsilon=1.0, delta=1e-5,
                              aggregate_params=params,
                              function_to_minimize=(
                                  analysis.MinimizingFunction.ABSOLUTE_ERROR),
                              parameters_to_tune=analysis.ParametersToTune(
                                  max_partitions_contributed=True)),
                          extractors())


class TestUtilityAnalysisEngineValidation:

    def test_aggregate_raises(self):
        acc = pdp.NaiveBudgetAccountant(1.0, 1e-6)
        engine = analysis.UtilityAnalysisEngine(acc, pdp.LocalBackend())
        with pytest.raises(ValueError, match="can't be called"):
            engine.aggregate([1], count_params(), extractors())

    def test_unsupported_metrics_rejected(self):
        acc = pdp.NaiveBudgetAccountant(1.0, 1e-6)
        engine = analysis.UtilityAnalysisEngine(acc, pdp.LocalBackend())
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6,
            aggregate_params=pdp.AggregateParams(
                metrics=[pdp.Metrics.MEAN], max_partitions_contributed=1,
                max_contributions_per_partition=1, min_value=0.0,
                max_value=1.0))
        with pytest.raises(NotImplementedError):
            engine.analyze([(0, "a", 1.0)], options, extractors())


class TestFusedSweep:
    """Differential tests: the on-device configuration-axis sweep
    (``analysis/jax_sweep.py``) against the host oracle graph.

    Tolerances reflect the documented approximation contract: the device
    path always uses the moment approximation for P(keep) where the host
    uses exact PMF convolution below 100 users, and Laplace error
    quantiles come from a quantile table instead of per-partition
    Monte-Carlo."""

    @staticmethod
    def _dataset(n=4000, users=300, parts=25, seed=0):
        rng = np.random.default_rng(seed)
        return pdp.ArrayDataset(
            privacy_ids=rng.integers(0, users, n),
            partition_keys=rng.integers(0, parts, n),
            values=rng.uniform(0, 5, n).astype(np.float64))

    @staticmethod
    def _run_both(ds, options, public=None):
        from pipelinedp_tpu.backends import JaxBackend
        ex = pdp.DataExtractors()
        host = list(analysis.perform_utility_analysis(
            ds, pdp.LocalBackend(), options, ex, public_partitions=public))
        fused_result = analysis.perform_utility_analysis(
            ds, JaxBackend(), options, ex, public_partitions=public)
        from pipelinedp_tpu.analysis import jax_sweep
        assert isinstance(fused_result, jax_sweep.LazySweepResult), (
            "fused backend must dispatch to the device sweep")
        return host[0], list(fused_result)[0]

    @staticmethod
    def _assert_metrics_close(h, f, rtol=0.05, atol=0.5):
        for field in ("error_l0_expected", "error_linf_expected",
                      "error_expected", "error_variance",
                      "ratio_data_dropped_l0", "ratio_data_dropped_linf",
                      "error_expected_w_dropped_partitions", "noise_std"):
            hv, fv = getattr(h, field), getattr(f, field)
            assert fv == pytest.approx(hv, rel=rtol, abs=atol), (
                field, hv, fv)
        # Quantiles: the host path Monte-Carlos Laplace quantiles with only
        # 1k samples, so compare at the scale of the whole error
        # distribution, not of each (possibly near-zero) quantile.
        spread = max(abs(q) for q in h.error_quantiles) or 1.0
        for hq, fq in zip(h.error_quantiles, f.error_quantiles):
            scale = max(1.0, abs(hq), 0.1 * spread)
            assert abs(hq - fq) / scale < 0.15, (h.error_quantiles,
                                                 f.error_quantiles)

    def test_count_multi_config_truncated_geometric(self):
        ds = self._dataset()
        multi = data_structures.MultiParameterConfiguration(
            max_partitions_contributed=[1, 3, 9, 27],
            max_contributions_per_partition=[1, 2, 4, 8])
        options = analysis.UtilityAnalysisOptions(
            epsilon=2.0, delta=1e-6,
            aggregate_params=count_params(l0=4, linf=2),
            multi_param_configuration=multi)
        host, fused = self._run_both(ds, options)
        assert len(host) == len(fused) == 4
        for h, f in zip(host, fused):
            self._assert_metrics_close(h.count_metrics, f.count_metrics)
            hp = h.partition_selection_metrics
            fp = f.partition_selection_metrics
            assert fp.num_partitions == hp.num_partitions
            assert fp.dropped_partitions_expected == pytest.approx(
                hp.dropped_partitions_expected, rel=0.05, abs=0.3)

    def test_all_metrics_gaussian(self):
        ds = self._dataset(seed=1)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM,
                     pdp.Metrics.PRIVACY_ID_COUNT],
            noise_kind=pdp.NoiseKind.GAUSSIAN,
            max_partitions_contributed=3,
            max_contributions_per_partition=2,
            min_value=0.0, max_value=5.0,
            min_sum_per_partition=None, max_sum_per_partition=None)
        # SUM analysis uses per-partition sum bounds.
        params.min_sum_per_partition = 0.0
        params.max_sum_per_partition = 20.0
        params.min_value = params.max_value = None
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6, aggregate_params=params)
        host, fused = self._run_both(ds, options)
        h, f = host[0], fused[0]
        self._assert_metrics_close(h.count_metrics, f.count_metrics)
        self._assert_metrics_close(h.sum_metrics, f.sum_metrics)
        self._assert_metrics_close(h.privacy_id_count_metrics,
                                   f.privacy_id_count_metrics)

    def test_public_partitions_with_empty(self):
        ds = self._dataset(parts=10, seed=2)
        public = list(range(14))  # 4 empty public partitions
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6,
            aggregate_params=count_params(l0=2, linf=2))
        host, fused = self._run_both(ds, options, public=public)
        assert fused[0].partition_selection_metrics is None
        self._assert_metrics_close(host[0].count_metrics,
                                   fused[0].count_metrics)

    @pytest.mark.parametrize("strategy", [
        pdp.PartitionSelectionStrategy.LAPLACE_THRESHOLDING,
        pdp.PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING,
    ])
    def test_thresholding_strategies(self, strategy):
        ds = self._dataset(seed=3)
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6,
            aggregate_params=count_params(
                l0=3, linf=2, partition_selection_strategy=strategy))
        host, fused = self._run_both(ds, options)
        hp = host[0].partition_selection_metrics
        fp = fused[0].partition_selection_metrics
        assert fp.dropped_partitions_expected == pytest.approx(
            hp.dropped_partitions_expected, rel=0.05, abs=0.3)
        self._assert_metrics_close(host[0].count_metrics,
                                   fused[0].count_metrics)

    def test_chunked_configs_match_single_chunk(self, monkeypatch):
        from pipelinedp_tpu.analysis import jax_sweep
        from pipelinedp_tpu.backends import JaxBackend
        ds = self._dataset(n=1000, users=100, parts=8)
        multi = data_structures.MultiParameterConfiguration(
            max_partitions_contributed=[1, 2, 3, 4, 5],
            max_contributions_per_partition=[1, 1, 2, 2, 3])
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6,
            aggregate_params=count_params(l0=2, linf=1),
            multi_param_configuration=multi)
        ex = pdp.DataExtractors()
        one = list(analysis.perform_utility_analysis(
            ds, JaxBackend(), options, ex))[0]
        monkeypatch.setattr(jax_sweep, "_CHUNK_CAP", 2)
        chunked = list(analysis.perform_utility_analysis(
            ds, JaxBackend(), options, ex))[0]
        for a, b in zip(one, chunked):
            assert b.count_metrics.error_expected == pytest.approx(
                a.count_metrics.error_expected, rel=1e-5)
            assert b.count_metrics.error_variance == pytest.approx(
                a.count_metrics.error_variance, rel=1e-5)

    def test_host_fallback_paths(self):
        # Pre-aggregated data and per-partition results use the host
        # graph; partition sampling is fused (TestFusedSweepSampling).
        from pipelinedp_tpu.analysis import jax_sweep
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6,
            aggregate_params=count_params(l0=2, linf=1),
            partitions_sampling_prob=0.5)
        assert jax_sweep.sweep_is_supported(options, None, False)
        options2 = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6,
            aggregate_params=count_params(l0=2, linf=1))
        # return_per_partition runs fused too since r4 (byte-capped).
        assert jax_sweep.sweep_is_supported(options2, None, True)
        assert jax_sweep.sweep_is_supported(options2, None, False)
        pre = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6,
            aggregate_params=count_params(l0=2, linf=1),
            pre_aggregated_data=True)
        # Pre-aggregated input runs fused since r3 (stage A skipped).
        assert jax_sweep.sweep_is_supported(pre, None, False)


class TestAnalysisErrorModelClosedForm:
    """Closed-form checks of the per-partition error model and the
    cross-partition aggregation — a representative subset of the
    reference's ``analysis/tests/combiners_test.py`` matrix."""

    def _params(self, agg_params, eps=1.0, delta=1e-6):
        spec = MechanismSpec(MechanismType.LAPLACE, _eps=eps, _delta=delta)
        return CombinerParams(spec, agg_params)

    @pytest.mark.parametrize(
        "counts,n_parts,l0,linf,exp_sum,exp_min,exp_max,exp_l0,exp_var",
        [
            # Single user under all caps: no errors at all.
            ([2], [1], 4, 4, 2.0, 0.0, 0.0, 0.0, 0.0),
            # linf clip only: 7 -> 3, keep prob 1 (n_parts <= l0).
            ([7], [1], 4, 3, 7.0, 0.0, -4.0, 0.0, 0.0),
            # l0 drop only: contribution 2 kept w.p. 1/2.
            ([2], [2], 1, 4, 2.0, 0.0, 0.0, -1.0, 1.0),
            # Both: clip 9->2, keep prob 1/4 -> E=-2*(3/4), Var=4*3/16.
            ([9], [4], 1, 2, 9.0, 0.0, -7.0, -1.5, 0.75),
            # Two users sum their independent errors.
            ([9, 1], [4, 1], 1, 2, 10.0, 0.0, -7.0, -1.5, 0.75),
        ])
    def test_count_error_decomposition(self, counts, n_parts, l0, linf,
                                       exp_sum, exp_min, exp_max, exp_l0,
                                       exp_var):
        c = ua_combiners.CountCombiner(
            self._params(count_params(l0=l0, linf=linf)))
        m = c.compute_metrics(
            c.create_accumulator((np.array(counts), np.zeros(len(counts)),
                                  np.array(n_parts))))
        assert m.sum == exp_sum
        assert m.per_partition_error_min == pytest.approx(exp_min)
        assert m.per_partition_error_max == pytest.approx(exp_max)
        assert m.expected_cross_partition_error == pytest.approx(exp_l0)
        assert m.std_cross_partition_error**2 == pytest.approx(exp_var)
        # Documented invariant (metrics.py): E[bounded sum] decomposition.
        e_bounded = (m.sum + m.per_partition_error_min +
                     m.per_partition_error_max +
                     m.expected_cross_partition_error)
        clipped = np.clip(counts, 0, linf)
        probs = np.minimum(1, l0 / np.array(n_parts))
        assert e_bounded == pytest.approx(float((clipped * probs).sum()))

    @pytest.mark.parametrize("sums,bounds,exp_min,exp_max", [
        ([15.0], (0.0, 10.0), 0.0, -5.0),
        ([-5.0], (0.0, 10.0), 5.0, 0.0),
        ([-5.0, 15.0, 3.0], (0.0, 10.0), 5.0, -5.0),
        ([2.0], (-1.0, 1.0), 0.0, -1.0),
    ])
    def test_sum_clip_errors(self, sums, bounds, exp_min, exp_max):
        agg = pdp.AggregateParams(
            metrics=[pdp.Metrics.SUM], max_partitions_contributed=4,
            max_contributions_per_partition=4,
            min_sum_per_partition=bounds[0], max_sum_per_partition=bounds[1])
        c = ua_combiners.SumCombiner(self._params(agg))
        m = c.compute_metrics(c.create_accumulator(
            (None, np.array(sums), np.ones(len(sums), int))))
        assert m.per_partition_error_min == pytest.approx(exp_min)
        assert m.per_partition_error_max == pytest.approx(exp_max)

    def test_merge_is_elementwise_addition(self):
        c = ua_combiners.CountCombiner(
            self._params(count_params(l0=1, linf=2)))
        a1 = c.create_accumulator((np.array([5]), np.zeros(1), np.array([2])))
        a2 = c.create_accumulator((np.array([1]), np.zeros(1), np.array([1])))
        merged = c.merge_accumulators(a1, a2)
        assert merged == tuple(x + y for x, y in zip(a1, a2))

    def test_partition_selection_exact_pmf_vs_moments(self):
        """Below MAX_PROBABILITIES the calculator uses the exact PMF; the
        moment approximation must agree closely for homogeneous probs."""
        from pipelinedp_tpu.aggregate_params import (
            PartitionSelectionStrategy)
        probs = [0.7] * 80
        exact = ua_combiners.PartitionSelectionCalculator(
            probabilities=list(probs))
        approx = ua_combiners.PartitionSelectionCalculator(
            moments=ua_combiners._probabilities_to_moments(probs))
        for strat in (PartitionSelectionStrategy.TRUNCATED_GEOMETRIC,
                      PartitionSelectionStrategy.LAPLACE_THRESHOLDING):
            pe = exact.compute_probability_to_keep(strat, 1.0, 1e-6, 2)
            pa = approx.compute_probability_to_keep(strat, 1.0, 1e-6, 2)
            assert pa == pytest.approx(pe, abs=2e-3)

    def test_aggregate_error_combiner_weights_by_keep_probability(self):
        comb = ua_combiners.SumAggregateErrorMetricsCombiner(
            metrics.AggregateMetricType.COUNT, [0.5])
        sm = metrics.SumMetrics(
            sum=10.0, per_partition_error_min=0.0,
            per_partition_error_max=-2.0,
            expected_cross_partition_error=-3.0,
            std_cross_partition_error=2.0, std_noise=1.0,
            noise_kind=pdp.NoiseKind.GAUSSIAN)
        acc = comb.create_accumulator(sm, prob_to_keep=0.5)
        assert acc.kept_partitions_expected == 0.5
        assert acc.error_l0_expected == pytest.approx(0.5 * -3.0)
        assert acc.error_linf_max_expected == pytest.approx(0.5 * -2.0)
        assert acc.error_l0_variance == pytest.approx(0.5 * 4.0)
        assert acc.error_variance == pytest.approx(0.5 * (4.0 + 1.0))
        # Data dropped by selection: (1-p) * surviving contribution.
        assert acc.data_dropped_partition_selection == pytest.approx(
            0.5 * (10.0 - 3.0 - 2.0))
        assert acc.error_expected_w_dropped_partitions == pytest.approx(
            0.5 * (-3.0 - 2.0) + 0.5 * -10.0)
        # Gaussian quantile: closed-form normal ppf at inverted levels.
        import scipy.stats
        want = scipy.stats.norm.ppf(0.5, loc=-3.0,
                                    scale=math.sqrt(4.0 + 1.0))
        assert acc.error_quantiles[0] == pytest.approx(
            0.5 * (want + (-2.0)))

    def test_aggregate_error_metrics_normalization(self):
        comb = ua_combiners.SumAggregateErrorMetricsCombiner(
            metrics.AggregateMetricType.COUNT, [0.5])
        sm = metrics.SumMetrics(
            sum=10.0, per_partition_error_min=0.0,
            per_partition_error_max=0.0,
            expected_cross_partition_error=-4.0,
            std_cross_partition_error=0.0, std_noise=1.0,
            noise_kind=pdp.NoiseKind.GAUSSIAN)
        acc = comb.merge_accumulators(
            comb.create_accumulator(sm, prob_to_keep=1.0),
            comb.create_accumulator(sm, prob_to_keep=0.5))
        m = comb.compute_metrics(acc)
        # Averages over EXPECTED kept partitions (1.5), except the
        # dropped-partition-aware error which averages over all (2).
        assert m.error_l0_expected == pytest.approx(
            (1.0 * -4.0 + 0.5 * -4.0) / 1.5)
        assert m.error_expected_w_dropped_partitions == pytest.approx(
            ((1.0 * -4.0 + 0.0) + (0.5 * -4.0 + 0.5 * -10.0)) / 2.0)
        # Global drop ratios divide by the total true aggregate.
        assert m.ratio_data_dropped_l0 == pytest.approx((4.0 + 4.0) / 20.0)

    def test_compound_uses_each_configs_own_keep_probability(self):
        """Regression: the reference scored every configuration with the
        FIRST configuration's keep probability (reference
        ``analysis/combiners.py:470-483``); each configuration must use
        its own."""
        sel = ua_combiners.PrivatePartitionSelectionAggregateErrorMetricsCombiner(
            [0.5])
        mk = ua_combiners.SumAggregateErrorMetricsCombiner(
            metrics.AggregateMetricType.COUNT, [0.5])
        compound = ua_combiners.AggregateErrorMetricsCompoundCombiner(
            [sel, mk, sel, mk], return_named_tuple=False)
        sm = metrics.SumMetrics(
            sum=10.0, per_partition_error_min=0.0,
            per_partition_error_max=0.0,
            expected_cross_partition_error=-4.0,
            std_cross_partition_error=0.0, std_noise=1.0,
            noise_kind=pdp.NoiseKind.GAUSSIAN)
        _, accs = compound.create_accumulator((1.0, sm, 0.25, sm))
        assert accs[1].kept_partitions_expected == 1.0
        assert accs[3].kept_partitions_expected == 0.25
        assert accs[3].error_l0_expected == pytest.approx(0.25 * -4.0)


class TestFusedSweepMultiSumBounds:
    """Per-configuration sum-bound VECTORS (MultiParameterConfiguration
    .min/max_sum_per_partition) through the device sweep."""

    def test_sum_bound_vectors_match_host(self):
        from pipelinedp_tpu.ops import noise as noise_ops
        noise_ops.seed_host_rng(0)  # host MC quantiles: reproducible draws
        ds = TestFusedSweep._dataset(n=3000, users=150, parts=20, seed=11)
        multi = data_structures.MultiParameterConfiguration(
            min_sum_per_partition=[0.0, 0.0, 0.0],
            max_sum_per_partition=[2.0, 10.0, 60.0])
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.SUM], max_partitions_contributed=3,
            max_contributions_per_partition=2,
            min_sum_per_partition=0.0, max_sum_per_partition=5.0)
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6, aggregate_params=params,
            multi_param_configuration=multi)
        host, fused = TestFusedSweep._run_both(ds, options)
        assert len(host) == len(fused) == 3
        for h, f in zip(host, fused):
            TestFusedSweep._assert_metrics_close(h.sum_metrics,
                                                 f.sum_metrics)
        # Tighter clip bounds must produce larger (more negative)
        # expected clipping error.
        errs = [f.sum_metrics.error_linf_max_expected for f in fused]
        assert errs[0] <= errs[1] <= errs[2] <= 0.0


class TestFusedSweepSampling:
    """partitions_sampling_prob on the device sweep: both planes use the
    same deterministic SHA1 sampler, so they analyze the same subset."""

    @pytest.mark.parametrize("public", [False, True])
    def test_sampling_matches_host(self, public):
        from pipelinedp_tpu.ops import noise as noise_ops
        noise_ops.seed_host_rng(0)
        ds = TestFusedSweep._dataset(n=3000, users=200, parts=30, seed=9)
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6,
            aggregate_params=count_params(l0=3, linf=2),
            partitions_sampling_prob=0.5)
        pub = (sorted(np.unique(ds.partition_keys).tolist())
               if public else None)
        host, fused = TestFusedSweep._run_both(ds, options, public=pub)
        h, f = host[0], fused[0]
        TestFusedSweep._assert_metrics_close(h.count_metrics,
                                             f.count_metrics)
        if not public:
            assert (f.partition_selection_metrics.num_partitions ==
                    h.partition_selection_metrics.num_partitions)
            # Sampling at 0.5 must actually have dropped partitions.
            assert h.partition_selection_metrics.num_partitions < 30

    def test_sampling_prob_one_unchanged(self):
        ds = TestFusedSweep._dataset(n=1000, users=100, parts=10, seed=10)
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6,
            aggregate_params=count_params(l0=2, linf=2),
            partitions_sampling_prob=1)
        host, fused = TestFusedSweep._run_both(ds, options)
        assert (fused[0].partition_selection_metrics.num_partitions ==
                host[0].partition_selection_metrics.num_partitions == 10)


class TestFusedSweepFuzz:
    """Randomized sweep configurations, device vs host — the sweep
    counterpart of ``tests/test_differential_fuzz.py``. Reuses the
    dataset/compare helpers; fixed seeds keep failures reproducible."""

    _dataset = staticmethod(TestFusedSweep._dataset)
    _run_both = staticmethod(TestFusedSweep._run_both)
    _assert_metrics_close = staticmethod(TestFusedSweep._assert_metrics_close)

    @pytest.mark.parametrize("seed", range(9))
    def test_random_config(self, seed):
        from pipelinedp_tpu.ops import noise as noise_ops
        # The host oracle Monte-Carlos its Laplace error quantiles from
        # the module-level host RNG; seed it so failures reproduce.
        noise_ops.seed_host_rng(seed)
        rng = np.random.default_rng(1000 + seed)
        ds = self._dataset(n=int(rng.integers(500, 4000)),
                           users=int(rng.integers(30, 400)),
                           parts=int(rng.integers(5, 40)),
                           seed=seed)
        metric = [pdp.Metrics.COUNT, pdp.Metrics.PRIVACY_ID_COUNT,
                  pdp.Metrics.SUM][int(rng.integers(0, 3))]
        kw = dict(metrics=[metric],
                  noise_kind=(pdp.NoiseKind.LAPLACE if rng.random() < 0.5
                              else pdp.NoiseKind.GAUSSIAN),
                  max_partitions_contributed=int(rng.integers(1, 6)),
                  max_contributions_per_partition=int(rng.integers(1, 4)),
                  partition_selection_strategy=list(
                      pdp.PartitionSelectionStrategy)[
                          int(rng.integers(0, 3))])
        if metric == pdp.Metrics.SUM:
            kw.update(min_sum_per_partition=0.0,
                      max_sum_per_partition=float(rng.uniform(2, 30)))
        params = pdp.AggregateParams(**kw)
        n_cfg = int(rng.integers(1, 5))
        multi = None
        if n_cfg > 1:
            # Per-config mechanism vectors (fused since r3) are drawn
            # too: mixed noise kinds and mixed selection strategies.
            kinds = None
            if rng.random() < 0.4:
                kinds = [list(pdp.NoiseKind)[int(i)]
                         for i in rng.integers(0, 2, n_cfg)]
            strategies = None
            if rng.random() < 0.4:
                strategies = [list(pdp.PartitionSelectionStrategy)[int(i)]
                              for i in rng.integers(0, 3, n_cfg)]
            multi = data_structures.MultiParameterConfiguration(
                max_partitions_contributed=sorted(
                    int(x) for x in rng.integers(1, 12, n_cfg)),
                max_contributions_per_partition=[
                    int(x) for x in rng.integers(1, 5, n_cfg)],
                noise_kind=kinds,
                partition_selection_strategy=strategies)
        options = analysis.UtilityAnalysisOptions(
            epsilon=float(rng.uniform(0.3, 5.0)),
            delta=float(10.0**-rng.integers(4, 9)),
            aggregate_params=params,
            multi_param_configuration=multi,
            partitions_sampling_prob=(
                1 if rng.random() < 0.5 else float(rng.uniform(0.3, 0.9))))
        public = (sorted(np.unique(ds.partition_keys).tolist())
                  if rng.random() < 0.4 else None)
        host, fused = self._run_both(ds, options, public=public)
        assert len(host) == len(fused) == (multi.size if multi else 1)
        field = {pdp.Metrics.COUNT: "count_metrics",
                 pdp.Metrics.PRIVACY_ID_COUNT: "privacy_id_count_metrics",
                 pdp.Metrics.SUM: "sum_metrics"}[metric]
        for h, f in zip(host, fused):
            self._assert_metrics_close(getattr(h, field),
                                       getattr(f, field))
            if public is None:
                hp = h.partition_selection_metrics
                fp = f.partition_selection_metrics
                assert fp.num_partitions == hp.num_partitions
                assert fp.dropped_partitions_expected == pytest.approx(
                    hp.dropped_partitions_expected, rel=0.07, abs=0.5)


class TestFusedSweepPerPartition:
    """``return_per_partition=True`` runs fused too (VERDICT r3 #6): the
    per-(partition, config) SumMetrics rows fetched from stage B must
    match the host oracle's per-partition rows; past the fetch byte cap
    the sweep reroutes itself to the host graph and still returns the
    same rows."""

    _dataset = staticmethod(TestFusedSweep._dataset)

    @staticmethod
    def _run_both_pp(ds, options, public=None, backend=None):
        from pipelinedp_tpu.backends import JaxBackend
        ex = pdp.DataExtractors()
        _, host_pp = analysis.perform_utility_analysis(
            ds, pdp.LocalBackend(), options, ex, public_partitions=public,
            return_per_partition=True)
        fused_res, fused_pp = analysis.perform_utility_analysis(
            ds, backend or JaxBackend(), options, ex,
            public_partitions=public, return_per_partition=True)
        return dict(host_pp), dict(fused_pp), fused_res

    @staticmethod
    def _assert_rows_match(host, fused, private):
        assert set(host) == set(fused)
        for k in host:
            h, f = host[k], fused[k]
            assert len(h) == len(f), (k, len(h), len(f))
            for hv, fv in zip(h, f):
                if isinstance(hv, float):  # p_keep
                    # Device: moment approximation; host: exact PMF below
                    # 100 users (documented contract).
                    assert abs(hv - fv) < 0.06, (k, hv, fv)
                else:
                    assert hv.noise_kind == fv.noise_kind
                    for fld in ("sum", "per_partition_error_min",
                                "per_partition_error_max",
                                "expected_cross_partition_error",
                                "std_cross_partition_error", "std_noise"):
                        a, b = getattr(hv, fld), getattr(fv, fld)
                        assert abs(a - b) <= 1e-3 * max(1.0, abs(a)), (
                            k, fld, a, b)

    def test_matches_host_rows_private(self):
        ds = self._dataset(n=2000, users=150, parts=8, seed=3)
        multi = data_structures.MultiParameterConfiguration(
            max_partitions_contributed=[1, 3],
            max_contributions_per_partition=[2, 4])
        options = analysis.UtilityAnalysisOptions(
            epsilon=2.0, delta=1e-6,
            aggregate_params=count_params(l0=4, linf=2),
            multi_param_configuration=multi)
        host, fused, fused_res = self._run_both_pp(ds, options)
        from pipelinedp_tpu.analysis import jax_sweep
        assert isinstance(fused_res, jax_sweep.LazySweepResult)
        self._assert_rows_match(host, fused, private=True)

    def test_matches_host_rows_public_with_empty_partition(self):
        ds = self._dataset(n=1500, users=100, parts=6, seed=4)
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.5, delta=1e-6,
            aggregate_params=pdp.AggregateParams(
                metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
                max_partitions_contributed=3,
                max_contributions_per_partition=2,
                min_sum_per_partition=0.0, max_sum_per_partition=8.0))
        public = list(range(8))  # 6 and 7 are empty -> pseudo rows
        host, fused, _ = self._run_both_pp(ds, options, public=public)
        assert set(fused) == set(range(8))
        self._assert_rows_match(host, fused, private=False)

    def test_matches_host_rows_on_mesh(self, monkeypatch):
        """return_per_partition stays FUSED on a multi-device mesh
        (VERDICT r4 #7): the config-axis-sharded [P, C] blocks gather
        to the same rows the host oracle produces."""
        import jax
        from pipelinedp_tpu.backends import JaxBackend
        from pipelinedp_tpu.parallel import make_mesh
        from pipelinedp_tpu.analysis import jax_sweep
        # A 1-device mesh would take the single-device branch and make
        # everything below vacuous.
        assert len(jax.devices()) >= 8
        # Fail LOUDLY if the mesh run reroutes to the host graph — the
        # rows would trivially match the oracle and mask the regression.
        monkeypatch.setattr(
            jax_sweep.LazySweepResult, "_host_fallback",
            lambda self: (_ for _ in ()).throw(AssertionError(
                "mesh + return_per_partition took the host fallback")))
        ds = self._dataset(n=2000, users=150, parts=8, seed=6)
        multi = data_structures.MultiParameterConfiguration(
            max_partitions_contributed=list(range(1, 9)),
            max_contributions_per_partition=[2] * 8)
        options = analysis.UtilityAnalysisOptions(
            epsilon=2.0, delta=1e-6,
            aggregate_params=count_params(l0=4, linf=2),
            multi_param_configuration=multi)
        host, fused, fused_res = self._run_both_pp(
            ds, options, backend=JaxBackend(mesh=make_mesh(8)))
        assert isinstance(fused_res, jax_sweep.LazySweepResult), (
            "mesh + return_per_partition fell back to the host graph")
        self._assert_rows_match(host, fused, private=True)

    def test_byte_cap_falls_back_to_host(self, monkeypatch):
        from pipelinedp_tpu.analysis import jax_sweep
        monkeypatch.setattr(jax_sweep, "_PP_BYTE_CAP", 64)
        ds = self._dataset(n=800, users=80, parts=5, seed=5)
        options = analysis.UtilityAnalysisOptions(
            epsilon=2.0, delta=1e-6,
            aggregate_params=count_params(l0=2, linf=2))
        host, fused, _ = self._run_both_pp(ds, options)
        # Fallback produces the HOST rows: exact equality.
        for k in host:
            assert host[k] == fused[k], k


class TestFusedSweepMixedMechanisms:
    """VERDICT r2 #6: per-config ``noise_kind`` /
    ``partition_selection_strategy`` vectors run FUSED (previously host
    fallback), matching the host oracle per configuration."""

    _run_both = staticmethod(TestFusedSweep._run_both)
    _assert_metrics_close = staticmethod(TestFusedSweep._assert_metrics_close)
    _dataset = staticmethod(TestFusedSweep._dataset)

    def test_per_config_mechanism_vectors(self):
        from pipelinedp_tpu.ops import noise as noise_ops
        noise_ops.seed_host_rng(7)
        ds = self._dataset(n=3000, users=150, parts=20, seed=7)
        S = pdp.PartitionSelectionStrategy
        multi = data_structures.MultiParameterConfiguration(
            max_partitions_contributed=[1, 3, 5, 8],
            max_contributions_per_partition=[2, 2, 1, 3],
            noise_kind=[pdp.NoiseKind.LAPLACE, pdp.NoiseKind.GAUSSIAN,
                        pdp.NoiseKind.GAUSSIAN, pdp.NoiseKind.LAPLACE],
            partition_selection_strategy=[
                S.TRUNCATED_GEOMETRIC, S.LAPLACE_THRESHOLDING,
                S.GAUSSIAN_THRESHOLDING, S.TRUNCATED_GEOMETRIC])
        options = analysis.UtilityAnalysisOptions(
            epsilon=2.0, delta=1e-6,
            aggregate_params=count_params(l0=2, linf=2),
            multi_param_configuration=multi)
        host, fused = self._run_both(ds, options)
        assert len(host) == len(fused) == 4
        for h, f in zip(host, fused):
            self._assert_metrics_close(h.count_metrics, f.count_metrics)
            assert (f.partition_selection_metrics.dropped_partitions_expected
                    == pytest.approx(
                        h.partition_selection_metrics
                        .dropped_partitions_expected, rel=0.07, abs=0.5))


class TestFusedSweepPreAggregated:
    """VERDICT r2 #6: pre-aggregated input runs fused (stage A skipped);
    results must match the host graph on the same pre-aggregated rows."""

    _assert_metrics_close = staticmethod(TestFusedSweep._assert_metrics_close)

    @pytest.mark.parametrize("metric", ["COUNT", "SUM"])
    def test_matches_host(self, metric):
        import operator

        from pipelinedp_tpu.backends import JaxBackend
        from pipelinedp_tpu.analysis import jax_sweep
        from pipelinedp_tpu.ops import noise as noise_ops

        noise_ops.seed_host_rng(11)
        rng = np.random.default_rng(11)
        rows = [(int(u), f"p{rng.integers(0, 12)}", float(rng.uniform(0, 5)))
                for u in range(120) for _ in range(rng.integers(1, 6))]
        raw_ex = pdp.DataExtractors(
            privacy_id_extractor=operator.itemgetter(0),
            partition_extractor=operator.itemgetter(1),
            value_extractor=operator.itemgetter(2))
        pre_rows = list(analysis.preaggregate(
            rows, pdp.LocalBackend(), raw_ex))
        ex = analysis.PreAggregateExtractors(
            partition_extractor=operator.itemgetter(0),
            preaggregate_extractor=operator.itemgetter(1))
        kw = dict(metrics=[getattr(pdp.Metrics, metric)],
                  max_partitions_contributed=3,
                  max_contributions_per_partition=2)
        if metric == "SUM":
            kw.update(min_sum_per_partition=0.0,
                      max_sum_per_partition=6.0)
        multi = data_structures.MultiParameterConfiguration(
            max_partitions_contributed=[1, 2, 6])
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.5, delta=1e-6,
            aggregate_params=pdp.AggregateParams(**kw),
            multi_param_configuration=multi,
            pre_aggregated_data=True)
        host = list(analysis.perform_utility_analysis(
            pre_rows, pdp.LocalBackend(), options, ex))[0]
        fused_result = analysis.perform_utility_analysis(
            pre_rows, JaxBackend(), options, ex)
        assert isinstance(fused_result, jax_sweep.LazySweepResult)
        fused = list(fused_result)[0]
        assert len(host) == len(fused) == 3
        field = "count_metrics" if metric == "COUNT" else "sum_metrics"
        for h, f in zip(host, fused):
            self._assert_metrics_close(getattr(h, field),
                                       getattr(f, field))


class TestFusedSweepSharded:
    """The configuration-axis sweep over the 8-device virtual mesh:
    each device analyzes its slice of the parameter grid; results must
    match the single-device sweep."""

    def test_sharded_matches_single_device(self):
        import jax
        from pipelinedp_tpu.backends import JaxBackend
        from pipelinedp_tpu.parallel import make_mesh
        assert len(jax.devices()) >= 8
        rng = np.random.default_rng(5)
        ds = pdp.ArrayDataset(
            privacy_ids=rng.integers(0, 200, 3000),
            partition_keys=rng.integers(0, 20, 3000),
            values=rng.uniform(0, 5, 3000))
        multi = data_structures.MultiParameterConfiguration(
            max_partitions_contributed=list(range(1, 17)),
            max_contributions_per_partition=[2] * 16)
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6,
            aggregate_params=count_params(l0=4, linf=2),
            multi_param_configuration=multi)
        ex = pdp.DataExtractors()
        single = list(analysis.perform_utility_analysis(
            ds, JaxBackend(), options, ex))[0]
        sharded = list(analysis.perform_utility_analysis(
            ds, JaxBackend(mesh=make_mesh(8)), options, ex))[0]
        assert len(single) == len(sharded) == 16
        for s, m in zip(single, sharded):
            a, b = s.count_metrics, m.count_metrics
            assert b.error_expected == pytest.approx(a.error_expected,
                                                     rel=1e-4, abs=1e-4)
            assert b.error_variance == pytest.approx(a.error_variance,
                                                     rel=1e-4)
            sp = s.partition_selection_metrics
            mp = m.partition_selection_metrics
            assert mp.dropped_partitions_expected == pytest.approx(
                sp.dropped_partitions_expected, rel=1e-4, abs=1e-5)


class TestMegasweepWidthParity:
    """PARITY row 41: the config-batched megasweep is bit-identical per
    config at EVERY batch width — walked (chunk=1) through batched
    (chunk=K), including widths that do not divide the grid (the padded
    tail repeats the last config and must not leak into real configs).
    The width knob is dp-safe precisely because of this invariance."""

    GRID = 16

    @staticmethod
    def _ds():
        rng = np.random.default_rng(23)
        n = 12_000
        return pdp.ArrayDataset(
            privacy_ids=rng.integers(0, 800, n),
            partition_keys=(rng.zipf(1.3, n) % 120).astype(np.int64),
            values=rng.uniform(0, 10, n))

    @classmethod
    def _options(cls):
        side = int(math.isqrt(cls.GRID))
        pairs = [(a, b) for a in range(1, side + 1)
                 for b in range(1, side + 1)]
        multi = data_structures.MultiParameterConfiguration(
            max_partitions_contributed=[p[0] for p in pairs],
            max_contributions_per_partition=[p[1] for p in pairs])
        return analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6,
            aggregate_params=count_params(
                l0=4, linf=2, noise_kind=pdp.NoiseKind.LAPLACE),
            multi_param_configuration=multi)

    @classmethod
    def _run(cls, width, mesh=None):
        import dataclasses

        from pipelinedp_tpu import plan as plan_mod
        from pipelinedp_tpu.backends import JaxBackend
        with plan_mod.seam_override("sweep_config_batch", width):
            out = list(analysis.perform_utility_analysis(
                cls._ds(), JaxBackend(rng_seed=0, mesh=mesh),
                cls._options(), pdp.DataExtractors()))[0]
        assert len(out) == cls.GRID
        return [dataclasses.asdict(m.count_metrics) for m in out]

    @staticmethod
    def _assert_bit_identical(got, ref, label):
        for ci, (a, b) in enumerate(zip(got, ref)):
            assert set(a) == set(b)
            for field in a:
                np.testing.assert_array_equal(
                    np.asarray(a[field]), np.asarray(b[field]),
                    err_msg=f"{label} cfg{ci}.{field}")

    def test_walked_vs_batched_bit_identical_single_device(self):
        """chunk=1 (the walked A/B leg) and every intermediate width
        against the full-grid batch, every AggregateErrorMetrics field
        EXACT — width 3, 5 and 7 leave a padded tail, so padding
        invariance rides the same assertion."""
        ref = self._run(self.GRID)
        for width in (1, 3, 5, 7, 8):
            self._assert_bit_identical(self._run(width), ref,
                                       f"width {width}")

    def test_walked_vs_batched_bit_identical_on_mesh(self):
        """The same invariance over the 8-device CPU mesh (the sharded
        kernel rounds widths to a device multiple, so 8 IS the mesh's
        walked mode: one config per device per dispatch)."""
        import jax

        from pipelinedp_tpu.parallel import make_mesh
        assert len(jax.devices()) >= 8
        ref = self._run(self.GRID, mesh=make_mesh(8))
        got = self._run(8, mesh=make_mesh(8))
        self._assert_bit_identical(got, ref, "mesh width 8")


class TestFusedHistograms:
    """Device dataset histograms vs the host graph, bin by bin."""

    @staticmethod
    def _hists(backend, data):
        ex = extractors()
        return list(histograms.compute_dataset_histograms(
            data, ex, backend))[0]

    @staticmethod
    def _assert_equal(a, b):
        assert len(a.bins) == len(b.bins), (a.bins, b.bins)
        for x, y in zip(a.bins, b.bins):
            assert (x.lower, x.count, x.sum, x.max) == (
                y.lower, y.count, y.sum, y.max)

    def test_matches_host_graph(self):
        from pipelinedp_tpu.backends import JaxBackend
        rng = np.random.default_rng(11)
        data = [(int(u), int(p), 1.0)
                for u, p in zip(rng.integers(0, 60, 4000),
                                rng.integers(0, 25, 4000))]
        # Heavy-hitter user and a hot partition to spread bin decades.
        data += [(999, 7, 1.0)] * 2500
        host = self._hists(pdp.LocalBackend(), data)
        fused = self._hists(JaxBackend(), data)
        self._assert_equal(host.l0_contributions_histogram,
                           fused.l0_contributions_histogram)
        self._assert_equal(host.linf_contributions_histogram,
                           fused.linf_contributions_histogram)
        self._assert_equal(host.count_per_partition_histogram,
                           fused.count_per_partition_histogram)
        self._assert_equal(host.count_privacy_id_per_partition,
                           fused.count_privacy_id_per_partition)

    def test_bin_lower_roundtrip(self):
        from pipelinedp_tpu.analysis import jax_sweep
        import jax.numpy as jnp
        vals = np.array([1, 2, 999, 1000, 1001, 1010, 9999, 10000, 10001,
                         123456, 9876543, 2**30], np.int32)
        ids = np.asarray(jax_sweep._bin_ids(jnp.asarray(vals)))
        lowers = jax_sweep._bin_lower_of_id(ids)
        expected = [histograms._to_bin_lower(int(v)) for v in vals]
        assert lowers.tolist() == expected

    def test_quantiles_drive_tuning(self):
        # tune() consumes the histograms; check quantiles agree too.
        from pipelinedp_tpu.backends import JaxBackend
        rng = np.random.default_rng(12)
        data = [(int(u), int(p), 1.0)
                for u, p in zip(rng.integers(0, 100, 3000),
                                rng.zipf(1.5, 3000) % 40)]
        host = self._hists(pdp.LocalBackend(), data)
        fused = self._hists(JaxBackend(), data)
        qs = [0.9, 0.95, 0.99]
        assert (host.l0_contributions_histogram.quantiles(qs) ==
                fused.l0_contributions_histogram.quantiles(qs))
        assert (host.linf_contributions_histogram.quantiles(qs) ==
                fused.linf_contributions_histogram.quantiles(qs))

    def test_value_1000_shares_bin_with_1001(self):
        # Regression: 1000 and 1003 must merge into one lower-1000 bin on
        # both planes (host _to_bin_lower(1000) == _to_bin_lower(1003)).
        from pipelinedp_tpu.backends import JaxBackend
        data = ([(u, 0, 1.0) for u in range(1000)] +
                [(u, 1, 1.0) for u in range(1003)])
        host = self._hists(pdp.LocalBackend(), data)
        fused = self._hists(JaxBackend(), data)
        hb = host.count_per_partition_histogram.bins
        fb = fused.count_per_partition_histogram.bins
        assert [(b.lower, b.count, b.sum, b.max) for b in hb] == \
               [(b.lower, b.count, b.sum, b.max) for b in fb]
        assert len(fb) == 1 and fb[0].lower == 1000 and fb[0].count == 2


class TestUtilityReport:
    """The richer report schema, wired via to_utility_report (the
    reference carries the schema but never wires it)."""

    def _analysis(self):
        rng = np.random.default_rng(20)
        ds = pdp.ArrayDataset(
            privacy_ids=rng.integers(0, 200, 3000),
            partition_keys=rng.integers(0, 20, 3000),
            values=rng.uniform(0, 5, 3000))
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6,
            aggregate_params=count_params(l0=3, linf=2))
        return list(analysis.perform_utility_analysis(
            ds, pdp.LocalBackend(), options, pdp.DataExtractors(
                privacy_id_extractor=operator.itemgetter(0),
                partition_extractor=operator.itemgetter(1),
                value_extractor=operator.itemgetter(2))))[0][0]

    def test_conversion_structure(self):
        agg = self._analysis()
        report = analysis.to_utility_report(agg)
        assert report.input_aggregate_params is agg.input_aggregate_params
        assert len(report.metric_errors) == 1
        mu = report.metric_errors[0]
        assert mu.metric == pdp.Metrics.COUNT
        m = agg.count_metrics
        assert mu.noise_std == m.noise_std
        assert mu.ratio_data_dropped.l0 == m.ratio_data_dropped_l0
        ae = mu.absolute_error
        assert ae.bias == m.error_expected
        assert ae.variance == m.error_variance
        assert ae.rmse == pytest.approx(m.absolute_rmse())
        assert ae.bounding_errors.l0.mean == m.error_l0_expected
        assert ae.bounding_errors.linf == m.error_linf_expected
        assert ae.l1 >= abs(ae.bias) - 1e-9  # E|X| >= |E X|
        re = mu.relative_error
        assert re.rmse == pytest.approx(m.relative_rmse())
        sel = report.partition_selection_metrics
        assert sel is not None
        assert sel.num_partitions == (
            agg.partition_selection_metrics.num_partitions)
        assert sel.dropped_partitions.mean == (
            agg.partition_selection_metrics.dropped_partitions_expected)

    def test_l1_gaussian_identity(self):
        # Zero bias: E|N(0, s^2)| = s*sqrt(2/pi).
        from pipelinedp_tpu.analysis.metrics import _value_errors
        agg = self._analysis()
        m = agg.count_metrics
        import dataclasses as dc
        m0 = dc.replace(m, error_expected=0.0, rel_error_expected=0.0)
        v = _value_errors(m0, relative=False)
        assert v.l1 == pytest.approx(
            math.sqrt(m0.error_variance) * math.sqrt(2 / math.pi))
