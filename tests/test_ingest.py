"""Overlapped-ingest executor tests: serial/overlapped bit-parity
(released values, kept-partition sets, checkpoint bytes at every
``ckpt_every`` boundary), fault-kill drain with zero orphan threads,
the O(n) batch assignment, the id-narrowing tiers end-to-end, and the
persistent compile cache. ``make perfcheck`` runs this file plus
``tests/test_faults.py``.
"""

import os
import threading

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import ingest
from pipelinedp_tpu import jax_engine as je
from pipelinedp_tpu.backends import JaxBackend
from pipelinedp_tpu.ingest import executor as ingest_executor
from pipelinedp_tpu.resilience import CheckpointStore, FaultPlan, injected_faults
from pipelinedp_tpu.resilience.faults import ChunkFailure


@pytest.fixture(autouse=True)
def tiny_chunks(monkeypatch):
    monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "997")


def ingest_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith(ingest.THREAD_PREFIX) and t.is_alive()]


@pytest.fixture(autouse=True)
def no_orphan_threads():
    """EVERY test in this file — including the fault-kill ones — must
    leave zero executor threads behind."""
    yield
    assert not ingest_threads(), (
        f"orphan ingest threads: {[t.name for t in ingest_threads()]}")


def run_streamed(ds, params, *, executor, seed=0, eps=5.0, delta=1e-6,
                 public=None, checkpoint=None, mesh=None,
                 min_batches=2):
    ds.invalidate_cache()
    prev = os.environ.get(ingest_executor.ENV_VAR)
    os.environ[ingest_executor.ENV_VAR] = "1" if executor else "0"
    try:
        acc = pdp.NaiveBudgetAccountant(total_epsilon=eps,
                                        total_delta=delta)
        engine = pdp.DPEngine(acc, JaxBackend(rng_seed=seed, mesh=mesh,
                                              checkpoint=checkpoint))
        res = engine.aggregate(ds, params, pdp.DataExtractors(),
                               public_partitions=public)
        acc.compute_budgets()
        got = dict(res)
    finally:
        if prev is None:
            os.environ.pop(ingest_executor.ENV_VAR, None)
        else:
            os.environ[ingest_executor.ENV_VAR] = prev
    assert res.timings.get("stream_batches", 0) >= min_batches, (
        "dataset did not stream — executor parity not exercised")
    want = "overlapped" if executor else "serial"
    assert res.timings["stream_executor"] == want
    return got, res.timings


def make_ds(seed=1, n=9_000, users=2_000, parts=12):
    rng = np.random.default_rng(seed)
    return pdp.ArrayDataset(privacy_ids=rng.integers(0, users, n),
                            partition_keys=rng.integers(0, parts, n),
                            values=rng.uniform(0.0, 10.0, n)), parts


def assert_bit_identical(got_a, got_b):
    """EXACT equality of kept sets and every released metric value."""
    assert set(got_a) == set(got_b), (
        f"kept sets differ: {sorted(set(got_a) ^ set(got_b))}")
    for k in got_a:
        ta, tb = got_a[k], got_b[k]
        assert ta._fields == tb._fields
        for f in ta._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(ta, f)), np.asarray(getattr(tb, f)),
                err_msg=f"partition {k}.{f}")


class RecordingStore(CheckpointStore):
    """Checkpoint store that snapshots every save — the evidence that
    serial and overlapped runs write IDENTICAL checkpoint files at
    every ``ckpt_every`` boundary."""

    def __init__(self, path):
        super().__init__(path)
        self.snapshots = []

    def save(self, ckpt):
        self.snapshots.append(
            (ckpt.next_batch,
             {k: np.array(v, copy=True) for k, v in ckpt.arrays.items()}))
        super().save(ckpt)


class TestExecutorBitParity:
    """The acceptance oracle: executor on and off produce bit-identical
    releases, kept sets and checkpoint bytes under the same seed."""

    def _params(self, parts):
        return pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM, pdp.Metrics.MEAN,
                     pdp.Metrics.PRIVACY_ID_COUNT],
            max_partitions_contributed=parts,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=10.0)

    def test_single_device_parity_with_checkpoints(self, tmp_path):
        ds, parts = make_ds(seed=1)
        params = self._params(parts)
        stores = {}
        results = {}
        for mode in (False, True):
            stores[mode] = RecordingStore(
                str(tmp_path / f"par_{mode}.ckpt"))
            results[mode], _ = run_streamed(ds, params, executor=mode,
                                            seed=42,
                                            checkpoint=stores[mode])
        assert_bit_identical(results[False], results[True])
        ser, ovl = stores[False].snapshots, stores[True].snapshots
        assert len(ser) == len(ovl) > 1
        for (nb_s, arr_s), (nb_o, arr_o) in zip(ser, ovl):
            assert nb_s == nb_o
            assert sorted(arr_s) == sorted(arr_o)
            for k in arr_s:
                np.testing.assert_array_equal(arr_s[k], arr_o[k],
                                              err_msg=f"ckpt {nb_s}:{k}")
        # Success cleared both stores.
        assert not stores[False].exists() and not stores[True].exists()

    def test_mesh_parity(self, monkeypatch):
        """Same contract on the 8-device CPU mesh (sharded kernels +
        owner-block fetch under the fold worker)."""
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "500")
        from pipelinedp_tpu.parallel import make_mesh
        mesh = make_mesh()
        ds, parts = make_ds(seed=8, n=14_000)
        params = self._params(parts)
        serial, _ = run_streamed(ds, params, executor=False, seed=21,
                                 mesh=mesh)
        overlapped, _ = run_streamed(ds, params, executor=True, seed=21,
                                     mesh=mesh)
        assert_bit_identical(serial, overlapped)

    def test_percentile_two_pass_parity(self):
        """Percentile configs run pass B through the stager too (device
        cache or re-ship) and must stay bit-identical."""
        rng = np.random.default_rng(11)
        n = 8_000
        ds = pdp.ArrayDataset(privacy_ids=rng.integers(0, 2_000, n),
                              partition_keys=rng.integers(0, 4, n),
                              values=rng.uniform(0.0, 10.0, n))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50), pdp.Metrics.PERCENTILE(90),
                     pdp.Metrics.COUNT],
            max_partitions_contributed=4,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=10.0)
        public = list(range(4))
        serial, _ = run_streamed(ds, params, executor=False, seed=13,
                                 public=public)
        overlapped, _ = run_streamed(ds, params, executor=True, seed=13,
                                     public=public)
        assert_bit_identical(serial, overlapped)

    def test_percentile_reship_parity(self, monkeypatch):
        """Pass B with the device cache disabled re-streams through a
        fresh BackgroundStager per sweep, staging into the rotating
        StagingRing buffers (fresh-copy retention is only needed while
        feeding the cache — see tests/test_pass_b.py for the staging-
        mode parity matrix)."""
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CACHE", "0")
        self.test_percentile_two_pass_parity()

    def test_overlap_breakdown_in_timings(self):
        """The executor reports the per-phase breakdown the bench JSON
        emits; phase accounting must cover actual work."""
        ds, parts = make_ds(seed=3)
        params = self._params(parts)
        _, timings = run_streamed(ds, params, executor=True, seed=7)
        for k in ("stream_t_stage", "stream_t_fold", "stream_t_device",
                  "stream_t_total", "stream_overlap_frac"):
            assert k in timings, k
        assert timings["stream_t_stage"] > 0
        assert timings["stream_t_total"] > 0
        assert 0.0 <= timings["stream_overlap_frac"] < 1.0


class TestExecutorFaultDrain:
    """A fault-injected chunk kill must sever the overlapped pipeline at
    the chunk boundary, leave no orphan threads (the autouse fixture
    asserts it after EVERY test here), and resume bit-identically."""

    def test_kill_drains_and_resumes_bit_identically(self, tmp_path):
        ds, parts = make_ds(seed=5)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=parts,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=10.0)
        baseline, _ = run_streamed(ds, params, executor=True, seed=42)
        store = CheckpointStore(str(tmp_path / "kill.ckpt"))
        with injected_faults(FaultPlan(fail_chunks=(3,))):
            with pytest.raises(ChunkFailure):
                run_streamed(ds, params, executor=True, seed=42,
                             checkpoint=store)
        assert not ingest_threads(), "kill left orphan executor threads"
        assert store.exists(), "no checkpoint survived the kill"
        resumed, timings = run_streamed(ds, params, executor=True,
                                        seed=42, checkpoint=store)
        assert timings["stream_resumed_from"] >= 1
        assert_bit_identical(baseline, resumed)
        assert not store.exists()

    def test_serial_kill_resumes_into_overlapped(self, tmp_path):
        """Cross-mode resume: a checkpoint written by the serial path
        restores into the overlapped path bit-identically (the fold
        prefix is mode-independent monoid state)."""
        ds, parts = make_ds(seed=6)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            max_partitions_contributed=parts,
            max_contributions_per_partition=50)
        baseline, _ = run_streamed(ds, params, executor=False, seed=9)
        store = CheckpointStore(str(tmp_path / "cross.ckpt"))
        with injected_faults(FaultPlan(fail_chunks=(4,))):
            with pytest.raises(ChunkFailure):
                run_streamed(ds, params, executor=False, seed=9,
                             checkpoint=store)
        resumed, _ = run_streamed(ds, params, executor=True, seed=9,
                                  checkpoint=store)
        assert_bit_identical(baseline, resumed)


class TestExecutorPrimitives:
    """Unit tests for the cancellable worker machinery."""

    def test_stager_orders_and_exhausts(self):
        with ingest.BackgroundStager(lambda c: iter(range(50)),
                                     depth=1) as st:
            assert list(st.items()) == list(range(50))

    def test_stager_propagates_generator_exception(self):
        def gen(cancelled):
            yield 1
            raise RuntimeError("stage boom")

        st = ingest.BackgroundStager(gen, depth=1)
        with pytest.raises(RuntimeError, match="stage boom"):
            list(st.items())
        # close() after the exception was delivered must not re-raise.
        st.close()

    def test_stager_close_unblocks_full_queue(self):
        def gen(cancelled):
            for i in range(10_000):
                yield i

        st = ingest.BackgroundStager(gen, depth=1)
        it = st.items()
        assert next(it) == 0
        st.close()  # generator still had ~10k items queued/pending
        assert not ingest_threads()

    def test_fold_worker_is_ordered_and_drains(self):
        seen = []
        w = ingest.OrderedFoldWorker(seen.append, depth=2)
        for i in range(100):
            w.submit(i)
        w.finish()
        assert seen == list(range(100))

    def test_fold_worker_propagates_exception(self):
        def fold(item):
            raise ValueError("fold boom")

        w = ingest.OrderedFoldWorker(fold, depth=2)
        with pytest.raises(ValueError, match="fold boom"):
            for i in range(100):
                w.submit(i)
            w.finish()
        w.cancel()

    def test_fold_worker_cancel_drops_queue(self):
        release = threading.Event()
        seen = []

        def fold(item):
            release.wait(10.0)
            seen.append(item)

        w = ingest.OrderedFoldWorker(fold, depth=3)
        w.submit(0)
        w.submit(1)
        w.submit(2)
        # Cancel while fold(0) is in progress; only release the fold
        # once the stop flag is visibly set, so the worker observes the
        # cancel deterministically before it could take item 1.
        canceller = threading.Thread(target=w.cancel)
        canceller.start()
        assert w._cancelled.wait(10.0)
        release.set()
        canceller.join(10.0)
        assert not canceller.is_alive()
        # The in-progress fold finishes; queued items are dropped.
        assert seen in ([], [0]), seen
        assert not ingest_threads()

    def test_staging_ring_gates_reuse(self):
        ring = ingest.StagingRing(2)
        ring.acquire()
        ring.acquire()
        cancelled = threading.Event()
        cancelled.set()
        with pytest.raises(ingest.IngestCancelled):
            ring.acquire(cancelled)  # full + cancelled -> aborts
        ring.retire()
        ring.acquire()  # a retire frees a slot


class TestBatchAssignment:
    """The O(n) counting-sort scatter must reproduce the stable argsort
    order exactly (bit-identical batch contents)."""

    @pytest.mark.parametrize("n,cells", [(10_000, 3), (10_000, 96),
                                         (4_096, 1), (20_000, 70_000)])
    def test_matches_stable_argsort(self, n, cells):
        rng = np.random.default_rng(n + cells)
        cell = rng.integers(0, cells, n).astype(np.int64)
        order, counts = ingest.group_rows_by_cell(cell, cells)
        np.testing.assert_array_equal(order,
                                      np.argsort(cell, kind="stable"))
        np.testing.assert_array_equal(counts,
                                      np.bincount(cell, minlength=cells))

    def test_assignment_unchanged_by_rewrite(self):
        """_batch_assignment end-to-end: same (order, counts) contract
        as the seed's argsort implementation, units stay whole."""
        from pipelinedp_tpu import streaming
        rng = np.random.default_rng(77)
        n = 6_000
        pid = rng.integers(0, 500, n)
        enc = je.EncodedData(pid=pid.astype(np.int32),
                             pk=np.zeros(n, np.int32),
                             values=np.zeros(n, np.float32),
                             pk_vocab=[0], n_rows=n)
        config = je.FusedConfig.from_params(
            pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                max_partitions_contributed=1,
                                max_contributions_per_partition=1),
            public=True)
        for n_dev in (1, 8):
            order, counts = streaming._batch_assignment(config, enc, 5,
                                                        321, n_dev)
            assert counts.sum() == n
            # Reference: the seed's explicit stable argsort over cells.
            from pipelinedp_tpu.ops.segment import fmix32
            h = fmix32(pid.astype(np.uint32) ^ np.uint32(321))
            batch = ((h.astype(np.uint64) * np.uint64(5)) >>
                     np.uint64(32)).astype(np.int64)
            cell = batch
            if n_dev > 1:
                cell = batch * n_dev + (fmix32(pid.astype(np.uint32)) %
                                        np.uint32(n_dev)).astype(np.int64)
            np.testing.assert_array_equal(
                order, np.argsort(cell, kind="stable"))


class TestIdNarrowingTiers:
    """Satellite: the three byte-plane tiers, at their boundaries and
    end-to-end through streaming."""

    @pytest.mark.parametrize("max_id,spec", [
        ((1 << 16) - 1, "u16"), (1 << 16, "u8x3"),
        ((1 << 24) - 1, "u8x3"), (1 << 24, "i32"),
    ])
    def test_round_trip_at_tier_boundaries(self, max_id, spec):
        assert je._plane_spec(max_id) == spec
        ids = np.array([0, 1, 255, 256, 65_535, max_id // 2,
                        max_id - 1, max_id], np.int64)
        ids = np.unique(np.clip(ids, 0, max_id)).astype(np.int32)
        planes = je._narrow_ids(ids, spec)
        widened = np.asarray(je._widen_ids(planes))
        np.testing.assert_array_equal(widened, ids)

    @pytest.mark.parametrize("pid_hi,spec", [
        ((1 << 16) - 1, "u16"),
        ((1 << 24) - 1, "u8x3"),
        ((1 << 24) + (1 << 20), "i32"),
    ])
    def test_streaming_end_to_end_per_tier(self, pid_hi, spec):
        """Each tier's ship path must stream exact aggregates. The pid
        column pins the tier: ids pass through encode un-densified, and
        the max is planted so the tier is exactly the one under test."""
        rng = np.random.default_rng(pid_hi % 1000)
        n = 5_000
        pid = rng.integers(max(0, pid_hi - 50_000), pid_hi, n)
        pid[0] = pid_hi  # plant the max: the tier decision is global
        ds = pdp.ArrayDataset(privacy_ids=pid,
                              partition_keys=rng.integers(0, 8, n),
                              values=rng.uniform(0.0, 10.0, n))
        enc = je.encode(ds, pdp.DataExtractors(), None, None)
        assert je._plane_spec(int(enc.pid.max())) == spec
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=8,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=10.0)
        for executor in (False, True):
            got, _ = run_streamed(ds, params, executor=executor,
                                  seed=3, eps=1e12, delta=1e-2,
                                  public=list(range(8)))
            for p in range(8):
                m = ds.partition_keys == p
                assert got[p].count == pytest.approx(m.sum(), abs=0.5)
                assert got[p].sum == pytest.approx(
                    ds.values[m].sum(), rel=1e-5)


class TestSweepCheckpointResume:
    """Satellite: budget-safe chunk-prefix resume of the analysis sweep
    (the ROADMAP open item)."""

    def _setup(self, monkeypatch):
        from pipelinedp_tpu import analysis
        from pipelinedp_tpu.analysis import jax_sweep
        monkeypatch.setattr(jax_sweep, "_CHUNK_CAP", 4)  # force chunks
        rng = np.random.default_rng(1)
        n = 4_000
        ds = pdp.ArrayDataset(privacy_ids=rng.integers(0, 800, n),
                              partition_keys=rng.integers(0, 10, n),
                              values=rng.uniform(0, 5, n))
        caps = list(range(1, 13))
        multi = analysis.MultiParameterConfiguration(
            max_partitions_contributed=caps,
            max_contributions_per_partition=[2] * len(caps))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT], noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=4,
            max_contributions_per_partition=2)
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6, aggregate_params=params,
            multi_param_configuration=multi)

        def run(backend):
            return list(analysis.perform_utility_analysis(
                ds, backend, options, pdp.DataExtractors()))[0]

        return ds, run

    def test_killed_sweep_resumes_bit_identically(self, tmp_path,
                                                  monkeypatch):
        _, run = self._setup(monkeypatch)
        baseline = run(JaxBackend(rng_seed=0))
        store = CheckpointStore(str(tmp_path / "sweep.ckpt"))
        # The sweep writes a SIBLING file (the backend's own checkpoint
        # path belongs to streamed aggregations).
        sweep_file = CheckpointStore(store.path + ".sweep")
        with injected_faults(FaultPlan(fail_chunks=(2,))):
            with pytest.raises(ChunkFailure):
                run(JaxBackend(rng_seed=0, checkpoint=store))
        assert sweep_file.exists(), "no sweep checkpoint survived"
        assert not store.exists(), (
            "the sweep must not touch the stream's checkpoint path")
        resumed = run(JaxBackend(rng_seed=0, checkpoint=store))
        # Success must clear the checkpoint (finished sweeps never
        # resume into a fresh run).
        assert not sweep_file.exists()
        assert len(resumed) == len(baseline) == 12
        for a, b in zip(baseline, resumed):
            assert (a.count_metrics.error_expected ==
                    b.count_metrics.error_expected)
            assert (a.count_metrics.error_quantiles ==
                    b.count_metrics.error_quantiles)
            assert (a.partition_selection_metrics.dropped_partitions_expected
                    == b.partition_selection_metrics
                    .dropped_partitions_expected)

    def test_sweep_and_stream_checkpoints_coexist(self, tmp_path,
                                                  monkeypatch):
        """One backend protecting BOTH features: a killed stream's
        checkpoint must not break (or be destroyed by) a later sweep on
        the same backend — the sweep uses its sibling file."""
        store = CheckpointStore(str(tmp_path / "both.ckpt"))
        ds, parts = make_ds(seed=12, n=6_000)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            max_partitions_contributed=parts,
            max_contributions_per_partition=50)
        with injected_faults(FaultPlan(fail_chunks=(3,))):
            with pytest.raises(ChunkFailure):
                run_streamed(ds, params, executor=True, seed=4,
                             checkpoint=store)
        assert store.exists()
        stream_bytes = open(store.path, "rb").read()
        # The sweep on the same backend runs clean and leaves the
        # stream's resume state untouched.
        _, run = self._setup(monkeypatch)
        assert len(run(JaxBackend(rng_seed=0, checkpoint=store))) == 12
        assert open(store.path, "rb").read() == stream_bytes
        # And the stream still resumes bit-identically afterwards.
        resumed, timings = run_streamed(ds, params, executor=True,
                                        seed=4, checkpoint=store)
        assert timings["stream_resumed_from"] >= 1
        baseline, _ = run_streamed(ds, params, executor=True, seed=4)
        assert_bit_identical(baseline, resumed)

    def test_mismatched_sweep_checkpoint_refuses(self, tmp_path,
                                                 monkeypatch):
        from pipelinedp_tpu.resilience import CheckpointMismatch
        _, run = self._setup(monkeypatch)
        store = CheckpointStore(str(tmp_path / "sweep2.ckpt"))
        with injected_faults(FaultPlan(fail_chunks=(2,))):
            with pytest.raises(ChunkFailure):
                run(JaxBackend(rng_seed=0, checkpoint=store))
        # Different DATA, same shape: the content digest must refuse.
        rng = np.random.default_rng(99)
        n = 4_000
        ds_b = pdp.ArrayDataset(privacy_ids=rng.integers(0, 800, n),
                                partition_keys=rng.integers(0, 10, n),
                                values=rng.uniform(0, 5, n))
        from pipelinedp_tpu import analysis
        caps = list(range(1, 13))
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6,
            aggregate_params=pdp.AggregateParams(
                metrics=[pdp.Metrics.COUNT],
                noise_kind=pdp.NoiseKind.LAPLACE,
                max_partitions_contributed=4,
                max_contributions_per_partition=2),
            multi_param_configuration=analysis.MultiParameterConfiguration(
                max_partitions_contributed=caps,
                max_contributions_per_partition=[2] * len(caps)))
        with pytest.raises(CheckpointMismatch):
            list(analysis.perform_utility_analysis(
                ds_b, JaxBackend(rng_seed=0, checkpoint=store), options,
                pdp.DataExtractors()))[0]


class TestCompileCache:
    """Satellite: the opt-in persistent XLA compile cache."""

    def test_env_knob_populates_cache_dir(self, tmp_path, monkeypatch):
        from pipelinedp_tpu.ingest import compile_cache
        import jax
        cache_dir = tmp_path / "xla_cache"
        monkeypatch.setenv(compile_cache.ENV_VAR, str(cache_dir))
        monkeypatch.setattr(compile_cache, "_configured", None)
        try:
            assert (compile_cache.maybe_enable_compile_cache() ==
                    str(cache_dir))
            # Idempotent re-entry (every backend construction calls it).
            assert (compile_cache.maybe_enable_compile_cache() ==
                    str(cache_dir))
            backend = JaxBackend(rng_seed=0)  # engine init wires it
            # Drop the in-process executable caches: earlier tests have
            # already compiled the engine's program shapes, and a jit
            # cache hit never reaches the persistent cache.
            import jax
            jax.clear_caches()
            rng = np.random.default_rng(0)
            ds = pdp.ArrayDataset(
                privacy_ids=rng.integers(0, 50, 3_000),
                partition_keys=rng.integers(0, 5, 3_000),
                values=rng.uniform(0, 1, 3_000))
            acc = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                            total_delta=1e-6)
            engine = pdp.DPEngine(acc, backend)
            res = engine.aggregate(
                ds, pdp.AggregateParams(
                    metrics=[pdp.Metrics.COUNT],
                    max_partitions_contributed=5,
                    max_contributions_per_partition=2),
                pdp.DataExtractors(), public_partitions=list(range(5)))
            acc.compute_budgets()
            assert len(dict(res)) == 5
            assert any(cache_dir.iterdir()), (
                "no compiled executables persisted to the cache dir")
        finally:
            # Un-point jax from the tmp dir (deleted after the test).
            jax.config.update("jax_compilation_cache_dir", None)
            try:
                from jax._src import compilation_cache as _cc
                _cc.reset_cache()
            except Exception:
                pass
            monkeypatch.setattr(compile_cache, "_configured", None)

    def test_unset_env_is_noop(self, monkeypatch):
        from pipelinedp_tpu.ingest import compile_cache
        monkeypatch.delenv(compile_cache.ENV_VAR, raising=False)
        monkeypatch.setattr(compile_cache, "_configured", None)
        assert compile_cache.maybe_enable_compile_cache() is None
