"""Pallas hot-path kernel tests (PR 10).

The ``kernel_backend`` knob dispatches the pass-B multi-tile histogram
binner and the fused lane-packed segment sum to hand-tiled Pallas
kernels (``pipelinedp_tpu/ops/kernels/``) — interpret mode off-TPU, so
every assertion here runs on the CPU proxy. Covered:

* kernel-level bit-parity against the XLA scatter paths, including
  max-value lanes at every lane-plan width (12/11/4 bits) with
  per-partition totals past 2^24 (the f32-block-exactness cliff);
* the wide-D vector twin (``segment_sum_wide``, ISSUE 17): D-tiled
  [P, Dt] slabs bit-identical at every tile width, the
  ``segsum_wide_d_block`` pin, and the ``vector_f32_accumulator``
  refusal (the f32 accumulator never rides the MXU kernel);
* the end-to-end lane-cap boundary shape from ``test_jax_engine.py``
  (525,000 rows — the 12->11-bit plan switch) bit-identical across
  backends;
* the out-of-envelope and pallas-unavailable fallbacks: XLA results
  plus a ``kernel.fallback`` obs event — never a silent path change;
* ``kernel_backend`` knob precedence (env > seam > plan > default)
  and unknown-value hardening;
* the interpret-mode CPU row in the cost observatory's peak table
  (Pallas-path programs on an interpreter backend get a roofline
  verdict instead of ``unknown``);
* the in-tree ``nopallas`` lint twin: pallas imports confined to
  ``pipelinedp_tpu/ops/kernels/``.
"""

import ast
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import pipelinedp_tpu as pdp
from pipelinedp_tpu import jax_engine as je
from pipelinedp_tpu import obs
from pipelinedp_tpu import plan as plan_mod
from pipelinedp_tpu.backends import JaxBackend
from pipelinedp_tpu.ops import kernels
from pipelinedp_tpu.ops.kernels import dispatch
from pipelinedp_tpu.plan import knobs as knobs_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SPEC = knobs_mod.BY_NAME["kernel_backend"]


def _fallback_events(reason=None):
    events = [e for e in obs.ledger().snapshot()["events"]
              if e["name"] == "kernel.fallback"]
    if reason is not None:
        events = [e for e in events if e.get("reason") == reason]
    return events


class TestSegsumKernelParity:
    """``segment_sum_lanes`` must equal ``jax.ops.segment_sum`` bit
    for bit — the whole dispatch rests on it."""

    @pytest.mark.parametrize("P,C,n", [
        (8, 2, 1000), (64, 11, 5000), (1024, 14, 20_000),
        (8192, 4, 3000),
    ])
    def test_random_parity(self, P, C, n):
        rng = np.random.default_rng(P * C)
        pk = jnp.asarray(rng.integers(0, P, n).astype(np.int32))
        cols = jnp.asarray(
            rng.integers(0, 4096, (n, C)).astype(np.int32))
        rb = kernels.segsum_envelope(P, C)
        assert rb is not None
        got = kernels.segment_sum_lanes(cols, pk, P, rb,
                                        kernels.use_interpret())
        ref = jax.ops.segment_sum(cols, pk, num_segments=P)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    @pytest.mark.parametrize("bits", [12, 11, 4])
    def test_max_lane_values_past_f32_exactness(self, bits):
        """Every row carries the lane plan's maximum value into ONE
        partition: the total (8192 * (2^bits - 1), up to 33.5M at 12
        bits) exceeds 2^24, so any f32 TOTAL accumulation would go
        inexact — the per-block-partials-then-int32 design must not."""
        n, P = 8192, 16
        lane_max = (1 << bits) - 1
        pk = jnp.zeros(n, jnp.int32)
        cols = jnp.full((n, 3), lane_max, jnp.int32)
        rb = kernels.segsum_envelope(P, 3)
        got = np.asarray(kernels.segment_sum_lanes(
            cols, pk, P, rb, kernels.use_interpret()))
        assert int(got[0, 0]) == n * lane_max
        ref = np.asarray(jax.ops.segment_sum(cols, pk, num_segments=P))
        np.testing.assert_array_equal(got, ref)


class TestWideSegsumKernelParity:
    """``segment_sum_wide`` must equal ``jax.ops.segment_sum`` bit for
    bit over [N, D] fixed-point vector coordinate lanes — the kernel
    leg of PARITY row 39."""

    @pytest.mark.parametrize("P,D,n", [
        (8, 64, 1000), (37, 200, 511), (512, 1024, 1300),
        (8192, 64, 700),
    ])
    def test_random_parity(self, P, D, n):
        rng = np.random.default_rng(P + D + n)
        pk = jnp.asarray(rng.integers(0, P, n).astype(np.int32))
        cols = jnp.asarray(
            rng.integers(0, 4096, (n, D)).astype(np.int32))
        env = kernels.segsum_wide_envelope(P, D)
        assert env is not None
        rb, db = env
        got = kernels.segment_sum_wide(cols, pk, P, rb, db,
                                       kernels.use_interpret())
        ref = jax.ops.segment_sum(cols, pk, num_segments=P)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_max_lane_values_past_f32_exactness(self):
        """Every row carries the 12-bit lane max into ONE partition at
        a D that is NOT a d_block multiple: per-coordinate totals
        (8192 * 4095 > 2^24) exceed f32 exactness, and the ragged last
        D tile must mask its padding columns out of the result."""
        n, P, D = 8192, 16, 130
        lane_max = (1 << 12) - 1
        pk = jnp.zeros(n, jnp.int32)
        cols = jnp.full((n, D), lane_max, jnp.int32)
        rb, db = kernels.segsum_wide_envelope(P, D)
        got = np.asarray(kernels.segment_sum_wide(
            cols, pk, P, rb, db, kernels.use_interpret()))
        assert int(got[0, 0]) == n * lane_max
        ref = np.asarray(jax.ops.segment_sum(cols, pk, num_segments=P))
        np.testing.assert_array_equal(got, ref)

    def test_every_d_block_is_bit_identical(self):
        """The D tile width (the ``segsum_wide_d_block`` autotune axis)
        is a performance hint only: every candidate reduces to the
        same bits, so the sweep can never change released values."""
        rng = np.random.default_rng(40)
        n, P, D = 3000, 64, 640
        pk = jnp.asarray(rng.integers(0, P, n).astype(np.int32))
        cols = jnp.asarray(
            rng.integers(0, 4096, (n, D)).astype(np.int32))
        ref = np.asarray(jax.ops.segment_sum(cols, pk, num_segments=P))
        rb, _ = kernels.segsum_wide_envelope(P, D)
        for db in dispatch._D_BLOCKS:
            got = kernels.segment_sum_wide(cols, pk, P, rb, db,
                                           kernels.use_interpret())
            np.testing.assert_array_equal(np.asarray(got), ref,
                                          err_msg=f"d_block={db}")


class TestWideSegsumDispatch:
    """The wide-D dispatch seam: envelope geometry, the d_block pin,
    and the visible fallbacks (``kernel.fallback`` events, never a
    silent path change)."""

    def test_envelope_geometry(self):
        # Max-P narrows BOTH axes: the [P, R] one-hot and the [P, Dt]
        # slab each hit their 4 MB budget exactly at 128.
        assert kernels.segsum_wide_envelope(8192, 1024) == (128, 128)
        # Small P affords the widest tile.
        assert kernels.segsum_wide_envelope(64, 1024) == (512, 512)
        # No column cap — D is tiled, unlike the scalar lane kernel.
        assert kernels.segsum_wide_envelope(
            64, dispatch._SEGSUM_MAX_COLS * 128) is not None
        # P past the one-block one-hot/accumulator cap is out.
        assert kernels.segsum_wide_envelope(
            dispatch._SEGSUM_MAX_P * 2, 64) is None

    def test_out_of_envelope_event(self):
        rng = np.random.default_rng(1)
        n, P = 200, dispatch._SEGSUM_MAX_P * 2
        pk = jnp.asarray(rng.integers(0, P, n).astype(np.int32))
        cols = jnp.asarray(
            rng.integers(0, 4096, (n, 8)).astype(np.int32))
        obs.reset()
        assert dispatch.try_segment_sum_wide(cols, pk, P,
                                             "pallas") is None
        events = _fallback_events("out_of_envelope")
        assert events and events[0]["site"] == "segment_sum_wide"

    def test_xla_request_short_circuits(self):
        pk = jnp.zeros(4, jnp.int32)
        cols = jnp.ones((4, 8), jnp.int32)
        obs.reset()
        assert dispatch.try_segment_sum_wide(cols, pk, 8, "xla") is None
        assert not _fallback_events()

    def test_pin_honored_and_bad_pin_ignored(self):
        """An in-envelope ``segsum_wide_d_block`` pin is used; a pin
        whose [P, Dt] slab would blow VMEM falls back to the
        envelope's own tile — never to XLA (the knob is a dp-safe
        performance hint, not a correctness gate)."""
        rng = np.random.default_rng(2)
        n, D = 1000, 300
        pk_small = jnp.asarray(rng.integers(0, 64, n).astype(np.int32))
        cols = jnp.asarray(
            rng.integers(0, 4096, (n, D)).astype(np.int32))
        ref64 = np.asarray(
            jax.ops.segment_sum(cols, pk_small, num_segments=64))
        obs.reset()
        got = dispatch.try_segment_sum_wide(cols, pk_small, 64,
                                            "pallas", d_block=128)
        assert got is not None
        np.testing.assert_array_equal(np.asarray(got), ref64)
        # At P=8192 a 512-wide slab is 16 MB — pin ignored, still
        # a pallas dispatch, still exact.
        pk_big = jnp.asarray(
            rng.integers(0, 8192, n).astype(np.int32))
        ref8k = np.asarray(
            jax.ops.segment_sum(cols, pk_big, num_segments=8192))
        got = dispatch.try_segment_sum_wide(cols, pk_big, 8192,
                                            "pallas", d_block=512)
        assert got is not None
        np.testing.assert_array_equal(np.asarray(got), ref8k)
        assert not _fallback_events()

    def test_f32_accumulator_refuses_pallas_visibly(self):
        """A pallas request over the default f32 vector accumulator
        cannot be bit-identical (MXU partial-sum order differs from
        the XLA scatter), so ``_reduce_per_pk`` refuses the kernel
        VISIBLY: XLA results, a ``vector_f32_accumulator`` fallback
        event — the ISSUE-17 'visibly falling back' clause."""
        rng = np.random.default_rng(17)
        data = [(u, f"p{u % 4}", rng.uniform(-1, 1, 64))
                for u in range(300)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.VECTOR_SUM],
            max_partitions_contributed=2,
            max_contributions_per_partition=1,
            vector_size=64, vector_max_norm=4.0,
            vector_norm_kind=pdp.NormKind.L2)

        def run(seed):
            from pipelinedp_tpu.ops import noise as noise_ops
            noise_ops.seed_host_rng(0)
            acc = pdp.NaiveBudgetAccountant(total_epsilon=1e5,
                                            total_delta=1e-6)
            engine = pdp.DPEngine(acc, JaxBackend(rng_seed=seed))
            import operator
            ext = pdp.DataExtractors(
                privacy_id_extractor=operator.itemgetter(0),
                partition_extractor=operator.itemgetter(1),
                value_extractor=operator.itemgetter(2))
            res = engine.aggregate(data, params, ext,
                                   public_partitions=[f"p{i}"
                                                      for i in range(4)])
            acc.compute_budgets()
            return {k: np.asarray(v.vector_sum)
                    for k, v in dict(res).items()}

        base = run(9)
        obs.reset()
        with plan_mod.seam_override("kernel_backend", "pallas"):
            pal = run(9)
        events = _fallback_events("vector_f32_accumulator")
        assert events and events[0]["site"] == "segment_sum_wide"
        assert set(base) == set(pal)
        for k in base:
            np.testing.assert_array_equal(base[k], pal[k])


class TestHistKernelParity:
    """``hist_bin_multi`` vs ``_subtree_counts_multi``'s XLA scatter
    loop, on dense multi-tile shapes (every row in range)."""

    @pytest.mark.parametrize("T,Pb,Qc,seed", [
        (1, 8, 1, 0), (3, 8, 2, 1), (5, 16, 4, 2),
    ])
    def test_random_parity(self, T, Pb, Qc, seed):
        span = 16
        rng = np.random.default_rng(seed)
        n = 9000
        qpk = jnp.asarray(rng.integers(0, T * Pb, n).astype(np.int32))
        leaf = jnp.asarray(rng.integers(0, 64, n).astype(np.int32))
        kept = jnp.asarray(rng.random(n) < 0.8)
        sub_starts = jnp.asarray(
            rng.integers(0, 48, (T, Pb, Qc)).astype(np.int32))
        p_offsets = jnp.asarray(
            (np.arange(T) * Pb).astype(np.int32))
        rb = kernels.hist_envelope(T, Pb, Qc, span)
        assert rb is not None
        got = kernels.hist_bin_multi(qpk, leaf, kept, sub_starts,
                                     p_offsets, Pb, span, rb,
                                     kernels.use_interpret())
        ref = je._subtree_counts_multi(qpk, leaf, kept, sub_starts,
                                       p_offsets, Pb, span)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
        # Dense shape: the parity must not be vacuous.
        assert int(np.asarray(ref).sum()) > 100


class TestLaneCapBoundaryEndToEnd:
    """The e2e lane-cap boundary shape from ``test_jax_engine.py``
    (525,000 rows — the first 11-bit/3-lane plan), released
    bit-identically under both backends in interpret mode."""

    def test_sum_at_plan_boundary_bit_identical(self):
        n = 525_000
        assert je._fx_plan(n) == (11, 3)
        rng = np.random.default_rng(n)
        ds = pdp.ArrayDataset(
            privacy_ids=np.arange(n) % (1 << 18),
            partition_keys=np.zeros(n, np.int64),
            values=rng.uniform(0.0, 10.0, n))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.SUM], max_partitions_contributed=4,
            max_contributions_per_partition=4, min_value=0.0,
            max_value=10.0)

        def run():
            ds.invalidate_cache()
            acc = pdp.NaiveBudgetAccountant(total_epsilon=1e12,
                                            total_delta=1e-2)
            engine = pdp.DPEngine(acc, JaxBackend(rng_seed=0))
            res = engine.aggregate(ds, params, pdp.DataExtractors())
            acc.compute_budgets()
            return dict(res)

        base = run()
        obs.reset()
        with plan_mod.seam_override("kernel_backend", "pallas"):
            pal = run()
        counters = obs.ledger().snapshot()["counters"]
        assert counters.get("kernel.pallas_dispatches", 0) >= 1
        assert set(base) == set(pal)
        for k in base:
            for f in base[k]._fields:
                assert getattr(base[k], f) == getattr(pal[k], f), (k, f)


class TestEnvelopeFallback:
    """A requested-but-infeasible pallas dispatch degrades to XLA with
    a ``kernel.fallback`` event — visible in the run report, never a
    silent path change."""

    def test_segsum_out_of_envelope(self):
        assert kernels.segsum_envelope(dispatch._SEGSUM_MAX_P * 2,
                                       4) is None
        assert kernels.segsum_envelope(
            64, dispatch._SEGSUM_MAX_COLS + 1) is None
        obs.reset()
        assert dispatch.select_backend("pallas", "segment_sum_lanes",
                                       None, P=16384, C=4) == "xla"
        events = _fallback_events("out_of_envelope")
        assert events and events[0]["site"] == "segment_sum_lanes"

    def test_hist_out_of_envelope_falls_back_bit_identical(self):
        """An over-VMEM [T, Pb, Qc, span] request through the REAL
        dispatch seam: XLA result, fallback event."""
        span = 256
        Pb = (dispatch._OUT_BYTES_CAP // (span * 4)) * 2  # 2x the cap
        assert kernels.hist_envelope(1, Pb, 1, span) is None
        rng = np.random.default_rng(3)
        n = 1000
        qpk = jnp.asarray(rng.integers(0, Pb, n).astype(np.int32))
        leaf = jnp.asarray(rng.integers(0, 512, n).astype(np.int32))
        kept = jnp.ones(n, bool)
        sub_starts = jnp.zeros((1, Pb, 1), jnp.int32)
        p_offsets = jnp.zeros(1, jnp.int32)
        obs.reset()
        got = je._subtree_counts_multi(qpk, leaf, kept, sub_starts,
                                       p_offsets, Pb, span,
                                       kernel_backend="pallas")
        ref = je._subtree_counts_multi(qpk, leaf, kept, sub_starts,
                                       p_offsets, Pb, span)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
        events = _fallback_events("out_of_envelope")
        assert events and events[0]["site"] == "hist_bin_multi"
        counters = obs.ledger().snapshot()["counters"]
        assert counters.get("kernel.fallbacks", 0) >= 1

    def test_single_batch_walk_degrades_visibly(self):
        """The single-batch quantile walk has no Pallas twin (only
        streamed pass B's binner): a pallas request on a non-streamed
        percentile run must say so with a kernel.fallback event —
        while the same program's per-pk reduction still dispatches —
        and stay bit-identical to xla."""
        rng = np.random.default_rng(13)
        n = 6000
        ds = pdp.ArrayDataset(
            privacy_ids=rng.integers(0, 600, n),
            partition_keys=rng.integers(0, 12, n),
            values=rng.uniform(0.0, 10.0, n))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50), pdp.Metrics.COUNT],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=4,
            max_contributions_per_partition=3,
            min_value=0.0, max_value=10.0)

        def run():
            ds.invalidate_cache()
            acc = pdp.NaiveBudgetAccountant(total_epsilon=2.0,
                                            total_delta=1e-3)
            engine = pdp.DPEngine(acc, JaxBackend(rng_seed=5))
            res = engine.aggregate(ds, params, pdp.DataExtractors())
            acc.compute_budgets()
            return dict(res)

        base = run()
        obs.reset()
        with plan_mod.seam_override("kernel_backend", "pallas"):
            pal = run()
        events = _fallback_events("single_batch_walk")
        assert events and events[0]["site"] == "walk_subtree_counts"
        counters = obs.ledger().snapshot()["counters"]
        assert counters.get("kernel.pallas_dispatches", 0) >= 1
        assert set(base) == set(pal)
        for k in base:
            for f in base[k]._fields:
                assert getattr(base[k], f) == getattr(pal[k], f)

    def test_pallas_unavailable_falls_back(self, monkeypatch):
        """A host without Pallas (forced via the dispatch seam) runs
        the whole aggregation on XLA — same outputs, fallback event."""
        monkeypatch.setattr(dispatch, "_FORCE_UNAVAILABLE", True)
        assert not kernels.pallas_available()
        rng = np.random.default_rng(7)
        n = 5000
        ds = pdp.ArrayDataset(
            privacy_ids=rng.integers(0, 500, n),
            partition_keys=rng.integers(0, 20, n),
            values=rng.uniform(0.0, 10.0, n))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=4,
            max_contributions_per_partition=2,
            min_value=0.0, max_value=10.0)

        def run():
            ds.invalidate_cache()
            acc = pdp.NaiveBudgetAccountant(total_epsilon=2.0,
                                            total_delta=1e-3)
            engine = pdp.DPEngine(acc, JaxBackend(rng_seed=1))
            res = engine.aggregate(ds, params, pdp.DataExtractors())
            acc.compute_budgets()
            return dict(res)

        base = run()
        obs.reset()
        with plan_mod.seam_override("kernel_backend", "pallas"):
            degraded = run()
        events = _fallback_events("pallas_unavailable")
        assert events
        counters = obs.ledger().snapshot()["counters"]
        assert not counters.get("kernel.pallas_dispatches")
        assert set(base) == set(degraded)
        for k in base:
            for f in base[k]._fields:
                assert getattr(base[k], f) == getattr(degraded[k], f)


class TestKernelBackendKnob:
    """``kernel_backend`` resolves through the registry precedence
    (env > seam > plan > default) like every other knob."""

    def test_registered_dp_safe_str(self):
        assert SPEC.dp_safe
        assert SPEC.kind is str
        assert SPEC.default == "xla"
        assert SPEC.choices == ("xla", "pallas")
        assert SPEC.env_var == "PIPELINEDP_TPU_KERNEL_BACKEND"

    def test_default(self, monkeypatch):
        monkeypatch.delenv(SPEC.env_var, raising=False)
        assert knobs_mod.resolve_value(SPEC, None) == ("xla", "default")

    def test_plan_applies(self, monkeypatch):
        monkeypatch.delenv(SPEC.env_var, raising=False)
        got = knobs_mod.resolve_value(
            SPEC, {"kernel_backend": "pallas"})
        assert got == ("pallas", "plan")

    def test_seam_beats_plan(self, monkeypatch):
        monkeypatch.delenv(SPEC.env_var, raising=False)
        with plan_mod.seam_override("kernel_backend", "pallas"):
            got = knobs_mod.resolve_value(
                SPEC, {"kernel_backend": "xla"})
        assert got == ("pallas", "seam")

    def test_env_beats_seam(self, monkeypatch):
        monkeypatch.setenv(SPEC.env_var, "xla")
        with plan_mod.seam_override("kernel_backend", "pallas"):
            got = knobs_mod.resolve_value(SPEC, None)
        assert got == ("xla", "env")

    def test_unknown_value_hardens_to_default(self, monkeypatch):
        monkeypatch.setenv(SPEC.env_var, "cuda")
        value, source = knobs_mod.resolve_value(SPEC, None)
        assert (value, source) == ("xla", "env")

    def test_env_dispatches_pallas_end_to_end(self, monkeypatch):
        monkeypatch.setenv(SPEC.env_var, "pallas")
        rng = np.random.default_rng(11)
        n = 4000
        ds = pdp.ArrayDataset(
            privacy_ids=rng.integers(0, 400, n),
            partition_keys=rng.integers(0, 10, n),
            values=rng.uniform(0.0, 10.0, n))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.MEAN],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=4,
            max_contributions_per_partition=2,
            min_value=0.0, max_value=10.0)
        obs.reset()
        acc = pdp.NaiveBudgetAccountant(total_epsilon=2.0,
                                        total_delta=1e-3)
        engine = pdp.DPEngine(acc, JaxBackend(rng_seed=2))
        res = engine.aggregate(ds, params, pdp.DataExtractors())
        acc.compute_budgets()
        assert len(dict(res)) > 0
        counters = obs.ledger().snapshot()["counters"]
        assert counters.get("kernel.pallas_dispatches", 0) >= 1

    def test_autotune_candidates_sweep_the_backend(self):
        cands = plan_mod.autotune_candidates()
        assert all("kernel_backend" in vec for vec in cands)
        assert any(vec["kernel_backend"] == "pallas" for vec in cands)
        assert cands[0]["kernel_backend"] == "xla"  # default vector


class TestInterpretPeakRow:
    """The cost observatory's static peak table covers interpreter
    backends, so Pallas-path programs on the CPU proxy classify
    against a (proxy) roofline instead of ``unknown``."""

    def test_interpreter_row_matches(self):
        from pipelinedp_tpu.obs import costs
        row = costs.device_peaks("Interpreter")
        assert row is not None and row["kind"] == "cpu_interpret"
        assert row["proxy"] is True
        verdict = costs.roofline_verdict(1e9, 1e6, row)
        assert verdict["verdict"] != "unknown"

    def test_cpu_still_matches_the_proxy_row(self):
        from pipelinedp_tpu.obs import costs
        assert costs.device_peaks("cpu")["kind"] == "cpu_proxy"


class TestNoPallasLint:
    """In-tree twin of ``make nopallas``: pallas imports are confined
    to ``pipelinedp_tpu/ops/kernels/`` — every other module dispatches
    through the kernels package (you cannot call ``pallas_call`` or
    ``pl.*`` without importing pallas, so banning the import is the
    AST-precise version of the grep)."""

    def test_pallas_imports_confined_to_kernels_package(self):
        # Delegates to the shared AST engine; `make nopallas` is the
        # same rule.
        from pipelinedp_tpu import lint
        assert lint.check_tree("nopallas") == []

    def test_kernels_package_does_import_pallas(self):
        """The lint must be testing something: the kernels package
        itself carries the (lazy) pallas imports."""
        path = os.path.join(REPO, "pipelinedp_tpu", "ops", "kernels",
                            "hist.py")
        with open(path, encoding="utf-8") as fh:
            assert "pallas" in fh.read()
