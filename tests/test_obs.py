"""Observability-layer tests (``pipelinedp_tpu/obs``).

Coverage contract (``make obscheck``):

* tracer thread-safety under a LIVE overlapped-ingest run — the
  ``BackgroundStager`` and ``OrderedFoldWorker`` threads emit spans
  concurrently with the dispatch thread and none are dropped or
  interleaved-corrupt;
* no-op mode (``PIPELINEDP_TPU_TRACE`` unset) emits nothing: the
  global tracer is the shared no-op singleton, a full streamed run
  leaves zero spans in the ledger, and no attributes are added to hot
  objects;
* bench-field parity: with tracing on vs off the DP outputs are
  bit-identical and every timing field keeps its name — and the same
  bit-parity for audit capture and device-cost capture
  (``PIPELINEDP_TPU_COSTS``, PARITY row 31, incl. the no-second-compile
  counter assertion);
* Chrome-trace export round-trips through ``json.loads`` with valid
  ``ph``/``ts``/``dur`` fields;
* the run report carries its schema version and environment
  fingerprint;
* resilience branches (retry attempts with backoff delays, checkpoint
  resume/mismatch-refusal, health degradation, fault injection) emit
  structured events;
* lint twin: no raw ``time.perf_counter()`` phase timing outside
  ``pipelinedp_tpu/obs/`` (``make noperf`` runs the same check).
"""

import json
import os
import re
import threading

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import obs
from pipelinedp_tpu.backends import JaxBackend
from pipelinedp_tpu.obs import report as obs_report
from pipelinedp_tpu.obs.tracer import RunLedger, Span
from pipelinedp_tpu.resilience import (CheckpointStore, FaultPlan,
                                       RetriesExhausted, RetryPolicy,
                                       call_with_retry, injected_faults)
from pipelinedp_tpu.resilience.checkpoint import (CheckpointMismatch,
                                                  StreamCheckpoint)
from pipelinedp_tpu.resilience.clock import FakeClock
from pipelinedp_tpu.resilience.faults import ChunkFailure, check_chunk

BIG_EPS = 1e12


@pytest.fixture(autouse=True)
def fresh_ledger(monkeypatch):
    """Each test starts with an empty ledger, tiny stream chunks, and
    tracing OFF unless it opts in."""
    monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "997")
    monkeypatch.delenv(obs.ENV_VAR, raising=False)
    obs.reset()
    yield
    obs.reset()


def run_streamed(ds, params, seed=0, eps=BIG_EPS):
    ds.invalidate_cache()
    acc = pdp.NaiveBudgetAccountant(total_epsilon=eps, total_delta=1e-2)
    engine = pdp.DPEngine(acc, JaxBackend(rng_seed=seed))
    res = engine.aggregate(ds, params, pdp.DataExtractors())
    acc.compute_budgets()
    got = dict(res)
    assert res.timings.get("stream_batches", 0) > 1, (
        "dataset did not stream — test is not covering the chunked path")
    return got, res.timings


def make_ds(seed=1, n=9_000, users=2_000, parts=12):
    rng = np.random.default_rng(seed)
    return pdp.ArrayDataset(privacy_ids=rng.integers(0, users, n),
                            partition_keys=rng.integers(0, parts, n),
                            values=rng.uniform(0.0, 10.0, n)), parts


def count_params(parts):
    return pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM, pdp.Metrics.MEAN],
        max_partitions_contributed=parts,
        max_contributions_per_partition=50,
        min_value=0.0, max_value=10.0)


class TestTracerCore:
    """The span substrate: totals, durations, ledger recording — all
    driven by the injectable FakeClock (zero wall time)."""

    def test_totals_and_duration_from_fake_clock(self):
        clock = FakeClock()
        tr = obs.Tracer(clock=clock)
        with tr.span("outer", cat="t") as outer:
            clock.sleep(2.0)
            with tr.span("inner", cat="t"):
                clock.sleep(0.5)
        assert outer.duration == pytest.approx(2.5)
        assert tr.total("outer") == pytest.approx(2.5)
        assert tr.total("inner") == pytest.approx(0.5)
        assert tr.count("outer") == 1
        # Repeat spans accumulate (the bench-field accumulator rule).
        with tr.span("inner"):
            clock.sleep(1.0)
        assert tr.total("inner") == pytest.approx(1.5)
        assert tr.count("inner") == 2

    def test_ledger_records_spans_with_thread_identity(self):
        led = RunLedger()
        tr = obs.Tracer(clock=FakeClock(), ledger=led)
        with tr.span("a", cat="t", batch=3):
            pass
        snap = led.snapshot()
        assert len(snap["spans"]) == 1
        s = snap["spans"][0]
        assert s.name == "a" and s.cat == "t"
        assert s.args == {"batch": 3}
        assert s.tid == threading.current_thread().ident
        assert s.thread == threading.current_thread().name

    def test_span_cap_counts_drops(self):
        led = RunLedger()
        led.spans = [None] * obs.MAX_SPANS  # simulate a full ledger
        tr = obs.Tracer(clock=FakeClock(), ledger=led)
        with tr.span("over"):
            pass
        assert led.dropped_spans == 1
        assert len(led.spans) == obs.MAX_SPANS


class TestNoopMode:
    """PIPELINEDP_TPU_TRACE unset: the global tracer emits NOTHING and
    adds no attributes to hot objects."""

    def test_global_tracer_is_shared_noop(self):
        t = obs.tracer()
        assert t is obs.NOOP_TRACER
        # span() hands back ONE shared context manager — no per-call
        # allocation on the hot path.
        assert t.span("x", batch=1) is obs.NOOP_SPAN
        assert t.span("y") is obs.NOOP_SPAN
        with t.span("z") as sp:
            assert sp.duration == 0.0
        # No instance dict anywhere a hot loop could bloat.
        assert not hasattr(obs.NOOP_SPAN, "__dict__")
        assert not hasattr(obs.NOOP_TRACER, "__dict__")

    def test_streamed_run_emits_no_spans(self):
        ds, parts = make_ds(seed=3)
        run_streamed(ds, count_params(parts), seed=11)
        snap = obs.ledger().snapshot()
        assert snap["spans"] == [], (
            "no-op mode leaked spans into the ledger")

    def test_run_tracer_still_measures(self):
        """Bench fields need real totals with tracing off: run_tracer
        measures always, it just does not RECORD."""
        clock = FakeClock()
        tr = obs.run_tracer(clock=clock)
        assert not tr.recording
        with tr.span("phase"):
            clock.sleep(1.25)
        assert tr.total("phase") == pytest.approx(1.25)
        assert obs.ledger().snapshot()["spans"] == []


class TestLiveExecutorThreadSafety:
    """Tracing ON under a live BackgroundStager + OrderedFoldWorker run:
    spans arrive from three threads concurrently; none may be dropped
    or corrupt."""

    def test_spans_complete_and_well_formed(self, monkeypatch):
        monkeypatch.setenv(obs.ENV_VAR, "1")
        monkeypatch.setenv("PIPELINEDP_TPU_INGEST_EXECUTOR", "1")
        ds, parts = make_ds(seed=5, n=9_000)
        _, timings = run_streamed(ds, count_params(parts), seed=7)
        assert timings["stream_executor"] == "overlapped"
        n_batches = timings["stream_batches"]
        snap = obs.ledger().snapshot()
        by_name = {}
        for s in snap["spans"]:
            assert isinstance(s, Span)
            assert isinstance(s.name, str) and s.name
            assert isinstance(s.ts, float)
            assert isinstance(s.dur, float) and s.dur >= 0.0
            assert isinstance(s.tid, int)
            by_name.setdefault(s.name, []).append(s)
        assert snap["dropped_spans"] == 0
        # One stage/fetch/fold span per batch — none dropped, none
        # double-counted, batch args intact (interleaving corruption
        # would duplicate or lose batch ids).
        for name in ("ingest.stage", "ingest.fetch", "ingest.fold"):
            batches = sorted(s.args["batch"] for s in by_name[name])
            assert batches == list(range(n_batches)), (
                f"{name}: expected one span per batch, got {batches}")
        assert len(by_name["ingest.pass_a"]) == 1
        # The three pipeline roles really ran on distinct threads.
        tids = {s.tid for s in snap["spans"]}
        assert len(tids) >= 3, (
            "expected spans from stager + fold + dispatch threads")
        stage_tids = {s.tid for s in by_name["ingest.stage"]}
        fold_tids = {s.tid for s in by_name["ingest.fold"]}
        assert stage_tids.isdisjoint(fold_tids)

    def test_percentile_pass_b_spans(self, monkeypatch):
        monkeypatch.setenv(obs.ENV_VAR, "1")
        rng = np.random.default_rng(30)
        n = 6_000
        ds = pdp.ArrayDataset(privacy_ids=rng.integers(0, 1_500, n),
                              partition_keys=rng.integers(0, 4, n),
                              values=rng.uniform(0, 10, n))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50),
                     pdp.Metrics.PERCENTILE(90)],
            max_partitions_contributed=4,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=10.0)
        run_streamed(ds, params, seed=3)
        names = {s.name for s in obs.ledger().snapshot()["spans"]}
        assert {"walk.top", "walk.bottom", "ingest.pass_b_sweep",
                "ingest.stage", "ingest.fetch", "ingest.fold",
                "ingest.pass_a"} <= names


class TestParity:
    """Acceptance: tracing on/off changes ONLY observability — DP
    outputs bit-identical, every timing field present either way."""

    TIMING_KEYS = ("host_encode_s", "device_s", "host_decode_s",
                   "stream_batches", "stream_stage_s",
                   "stream_fold_wait_s", "stream_t_stage",
                   "stream_t_fold", "stream_t_device", "stream_t_total",
                   "stream_overlap_frac", "stream_executor")

    def test_outputs_bit_identical_and_fields_stable(self, monkeypatch):
        ds, parts = make_ds(seed=9)
        params = count_params(parts)
        results, timings = {}, {}
        for mode in ("off", "on"):
            obs.reset()
            if mode == "on":
                monkeypatch.setenv(obs.ENV_VAR, "1")
            else:
                monkeypatch.delenv(obs.ENV_VAR, raising=False)
            results[mode], timings[mode] = run_streamed(ds, params,
                                                        seed=17)
        assert set(results["off"]) == set(results["on"])
        for k in results["off"]:
            ta, tb = results["off"][k], results["on"][k]
            assert ta._fields == tb._fields
            for f in ta._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(ta, f)),
                    np.asarray(getattr(tb, f)),
                    err_msg=f"partition {k}.{f}")
        for mode in ("off", "on"):
            for key in self.TIMING_KEYS:
                assert key in timings[mode], (mode, key)
            assert timings[mode]["stream_t_total"] > 0.0
            # Phase totals really accumulated (spans measured even with
            # tracing off).
            busy = (timings[mode]["stream_t_stage"] +
                    timings[mode]["stream_t_fold"] +
                    timings[mode]["stream_t_device"])
            assert busy > 0.0

    def test_audit_on_off_outputs_bit_identical(self, monkeypatch):
        """The audit knob (PIPELINEDP_TPU_AUDIT) changes ONLY the
        record: DP outputs bit-identical with capture on vs off, and
        only the 'on' run populates the privacy section + selection
        counters (same acceptance shape as the trace on/off parity)."""
        ds, parts = make_ds(seed=23)
        params = count_params(parts)
        results, reports = {}, {}
        for mode in ("off", "on"):
            obs.reset()
            if mode == "off":
                monkeypatch.setenv(obs.audit.ENV_VAR, "0")
            else:
                monkeypatch.delenv(obs.audit.ENV_VAR, raising=False)
            results[mode], _ = run_streamed(ds, params, seed=29)
            reports[mode] = obs.build_run_report()
        assert set(results["off"]) == set(results["on"])
        for k in results["off"]:
            ta, tb = results["off"][k], results["on"][k]
            assert ta._fields == tb._fields
            for f in ta._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(ta, f)),
                    np.asarray(getattr(tb, f)),
                    err_msg=f"partition {k}.{f}")
        priv_on = reports["on"]["privacy"]
        assert priv_on["enabled"] is True
        assert priv_on["accountants"], "no accountant audit captured"
        mech = {m["metric"] for a in priv_on["accountants"]
                for m in a["mechanisms"]}
        assert "partition_selection" in mech
        assert priv_on["partition_selection"]["partitions_pre"] > 0
        assert priv_on["aggregations"][0]["method"] == "aggregate"
        assert priv_on["expected_errors"], "no expected errors captured"
        # Capture disabled: the section records only that it was off.
        priv_off = reports["off"]["privacy"]
        assert priv_off["enabled"] is False
        assert priv_off["accountants"] == []
        assert priv_off["partition_selection"]["partitions_pre"] == 0

    def test_costs_on_off_outputs_bit_identical(self, monkeypatch):
        """PARITY row 31: the device-cost knob (PIPELINEDP_TPU_COSTS)
        changes ONLY the record — DP outputs bit-identical with capture
        on vs off, only the 'on' run's report carries the
        ``device_costs`` section, and a repeat run at the same jitted
        signatures captures zero new programs (cost capture never pays
        a second XLA compile — the compile-count assertion)."""
        # A chunk size unique to this test: kernel abstract shapes must
        # be fresh so the 'on' run actually captures.
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "991")
        ds, parts = make_ds(seed=41)
        params = count_params(parts)
        results, reports = {}, {}
        for mode in ("off", "on"):
            obs.reset()
            if mode == "on":
                monkeypatch.setenv(obs.costs.ENV_VAR, "1")
            else:
                monkeypatch.delenv(obs.costs.ENV_VAR, raising=False)
            results[mode], _ = run_streamed(ds, params, seed=37)
            reports[mode] = obs.build_run_report()
        assert set(results["off"]) == set(results["on"])
        for k in results["off"]:
            ta, tb = results["off"][k], results["on"][k]
            assert ta._fields == tb._fields
            for f in ta._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(ta, f)),
                    np.asarray(getattr(tb, f)),
                    err_msg=f"partition {k}.{f}")
        assert "device_costs" not in reports["off"]
        dc = reports["on"]["device_costs"]
        assert len(dc["programs"]) >= 1
        for entry in dc["programs"].values():
            assert entry["compile_s"] > 0.0
            assert entry["compile_cache"] in ("hit", "miss",
                                              "disabled", "unknown")
        assert any(ph["verdict"] != "unknown" or ph["analyzed"] == 0
                   for ph in dc["phases"].values())
        n1 = obs.ledger().snapshot()["counters"][
            "cost.programs_captured"]
        assert n1 >= 1
        # Second identical run, flag still on: dispatch reuses the
        # captured executables — zero new compiles.
        again, _ = run_streamed(ds, params, seed=37)
        n2 = obs.ledger().snapshot()["counters"][
            "cost.programs_captured"]
        assert n2 == n1, "repeat run recompiled a captured program"
        for k in results["on"]:
            ta, tb = results["on"][k], again[k]
            for f in ta._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(ta, f)),
                    np.asarray(getattr(tb, f)))


class TestChromeTrace:
    """Export round-trip: valid JSON, valid ph/ts/dur, thread lanes."""

    def _ledger_with_spans(self):
        led = RunLedger(clock=FakeClock())
        clock = FakeClock(10.0)
        tr = obs.Tracer(clock=clock, ledger=led)

        def worker():
            with tr.span("w", cat="test", batch=1):
                clock.sleep(0.25)

        t = threading.Thread(target=worker, name="obs-test-worker")
        with tr.span("main", cat="test"):
            t.start()
            t.join()
            clock.sleep(0.5)
        led.event("marker", detail="hello")
        return led

    def test_round_trip(self, tmp_path):
        led = self._ledger_with_spans()
        path = str(tmp_path / "trace.json")
        obs_report.write_chrome_trace(path, led.snapshot())
        with open(path, encoding="utf-8") as f:
            payload = json.loads(f.read())
        events = payload["traceEvents"]
        xs = [e for e in events if e["ph"] == "X"]
        assert {e["name"] for e in xs} == {"w", "main"}
        for e in xs:
            assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
            assert isinstance(e["tid"], int)
            assert e["pid"] == os.getpid()
        w = next(e for e in xs if e["name"] == "w")
        assert w["dur"] == pytest.approx(0.25e6)
        assert w["args"]["batch"] == 1
        instants = [e for e in events if e["ph"] == "i"]
        assert any(e["name"] == "marker" for e in instants)
        metas = [e for e in events if e["ph"] == "M"]
        assert {m["args"]["name"] for m in metas} >= {"obs-test-worker"}

    def test_global_export_path(self, tmp_path, monkeypatch):
        monkeypatch.setenv(obs.ENV_VAR, str(tmp_path / "t.json"))
        with obs.tracer().span("one", cat="test"):
            pass
        out = obs.write_chrome_trace()
        assert out == str(tmp_path / "t.json")
        payload = json.load(open(out, encoding="utf-8"))
        assert any(e["name"] == "one" for e in payload["traceEvents"])


class TestRunReport:
    """Schema version, environment fingerprint, counters, summaries."""

    def test_schema_version_and_sections(self, monkeypatch):
        monkeypatch.setenv(obs.ENV_VAR, "1")
        with obs.tracer().span("phase", cat="test"):
            pass
        obs.inc("retry.attempts", 2)
        obs.event("health.degraded", target="cpu_platform")
        report = obs.build_run_report(extra={"note": "t"})
        assert report["schema_version"] == obs.SCHEMA_VERSION == 6
        assert report["counters"]["retry.attempts"] == 2
        assert report["spans"]["phase"]["count"] == 1
        assert any(e["name"] == "health.degraded"
                   for e in report["events"])
        assert report["note"] == "t"
        assert report["dropped"] == {"spans": 0, "events": 0,
                                     "samples": 0}
        # v3: the device_costs section appears only when programs were
        # captured — absent here (the v1/v2-compatible reading).
        assert "device_costs" not in report
        # v2: the structured privacy audit section is always present.
        priv = report["privacy"]
        assert priv["enabled"] is True
        assert set(priv) >= {"accountants", "aggregations",
                             "expected_errors", "partition_selection"}

    def test_environment_fingerprint(self, monkeypatch):
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "4242")
        fp = obs.environment_fingerprint()
        assert fp["jax_version"]
        assert fp["device_count"] >= 1
        assert fp["platform"]
        assert fp["flags"]["PIPELINEDP_TPU_STREAM_CHUNK"] == "4242"
        assert fp["degraded"] is False
        # The repo is a git work tree: the SHA must resolve — with
        # "-dirty" appended when the tree has uncommitted changes, so a
        # fingerprint can never alias uncommitted code.
        assert re.fullmatch(r"[0-9a-f]{40}(-dirty)?", fp["git_sha"] or "")


class TestResilienceEvents:
    """Formerly-silent resilience branches now land in the ledger."""

    def test_retry_attempts_with_backoff_delays(self):
        policy = RetryPolicy(max_attempts=3, base_delay_s=1.0,
                             multiplier=2.0, max_delay_s=30.0,
                             jitter=0.1, seed=4)
        clock = FakeClock()

        def always_fails():
            raise ValueError("boom")

        with pytest.raises(RetriesExhausted):
            call_with_retry(always_fails, policy, clock,
                            label="test.op")
        snap = obs.ledger().snapshot()
        attempts = [e for e in snap["events"]
                    if e["name"] == "retry.attempt"]
        assert [e["attempt"] for e in attempts] == [0, 1]
        assert all(e["label"] == "test.op" for e in attempts)
        # The recorded delays ARE the policy's deterministic schedule.
        assert [e["delay_s"] for e in attempts] == (
            pytest.approx(policy.delays()))
        assert snap["counters"]["retry.attempts"] == 2
        exhausted = [e for e in snap["events"]
                     if e["name"] == "retry.exhausted"]
        assert len(exhausted) == 1 and "boom" in exhausted[0]["error"]

    def test_checkpoint_resume_and_mismatch_events(self, tmp_path):
        store = CheckpointStore(str(tmp_path / "s.ckpt"))
        store.save(StreamCheckpoint("fp_a", 3,
                                    {"acc:count": np.arange(4)}))
        assert store.load_for("fp_a").next_batch == 3
        with pytest.raises(CheckpointMismatch):
            store.load_for("fp_b")
        snap = obs.ledger().snapshot()
        assert snap["counters"]["checkpoint.saves"] == 1
        assert snap["counters"]["checkpoint.resumes"] == 1
        assert snap["counters"]["checkpoint.mismatch_refusals"] == 1
        refusal = next(e for e in snap["events"]
                       if e["name"] == "checkpoint.mismatch_refusal")
        assert refusal["expected"] == "fp_b"[:16]

    def test_health_degradation_event(self):
        from pipelinedp_tpu.resilience import health
        env = {}
        with injected_faults(FaultPlan(wedged_init=5)):
            report = health.ensure_device_or_degrade(
                policy=RetryPolicy(max_attempts=2, base_delay_s=1.0,
                                   seed=0),
                clock=FakeClock(), env=env)
        assert report.degraded
        snap = obs.ledger().snapshot()
        assert snap["counters"]["health.degradations"] == 1
        ev = next(e for e in snap["events"]
                  if e["name"] == "health.degraded")
        assert ev["target"] == "cpu_platform"
        # The injected wedges themselves are on the record too.
        assert snap["counters"]["faults.injected"] == 2

    def test_fault_injection_event(self):
        with injected_faults(FaultPlan(fail_chunks=(2,))):
            check_chunk(0)
            with pytest.raises(ChunkFailure):
                check_chunk(2)
        ev = next(e for e in obs.ledger().snapshot()["events"]
                  if e["name"] == "fault.injected")
        assert ev["kind"] == "chunk_failure" and ev["index"] == 2


class TestNoRawPerfCounter:
    """Lint twin of ``make noperf``: raw ``time.perf_counter()`` phase
    timing is banned outside ``pipelinedp_tpu/obs/`` — timing must flow
    through obs spans so every measured phase lands in the run ledger
    (bench.py routes through ``obs.run_tracer``). ``obs/monitor.py`` is
    the one obs module NOT exempt: the stall watchdog's deadlines must
    ride the injectable resilience clock, never the raw timer."""

    def test_no_perf_counter_outside_obs(self):
        # Delegates to the shared AST engine (pipelinedp_tpu/lint/);
        # `make noperf` runs the same rule.
        from pipelinedp_tpu import lint
        assert lint.check_tree("noperf") == []
