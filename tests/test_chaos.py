"""The seeded chaos campaign (``make chaoscheck``), unit-tested: the
schedule is a pure function of the seed, every FaultPlan seam is in the
rotation, failures print the exact reproduction command, and single
episodes run green in-process. The full 20-episode campaign lives in
``make chaoscheck`` (wired into ``faultcheck``); this file pins the
harness semantics cheaply enough for tier 1."""

import os
import threading

import pytest

from pipelinedp_tpu import obs
from pipelinedp_tpu.resilience import chaos


@pytest.fixture(autouse=True)
def fresh_state():
    obs.reset()
    env_before = {k: v for k, v in os.environ.items()
                  if k.startswith("PIPELINEDP_TPU_")}
    yield
    obs.reset()
    orphans = [t.name for t in threading.enumerate()
               if t.name.startswith("pdp-") and t.is_alive()]
    assert not orphans, f"orphan threads: {orphans}"
    env_after = {k: v for k, v in os.environ.items()
                 if k.startswith("PIPELINEDP_TPU_")}
    # A leaked PIPELINEDP_TPU_* knob (stream chunk, fault plan, mesh
    # dir) would silently change every later test in the process —
    # the exact pollution that once re-chunked the fusion suite.
    assert env_after == env_before, (
        f"chaos leaked env: {set(env_after) ^ set(env_before) or env_after}")


class TestSchedule:

    def test_schedule_is_deterministic_in_the_seed(self):
        a = chaos.schedule_for(7, 40)
        b = chaos.schedule_for(7, 40)
        assert a == b
        c = chaos.schedule_for(8, 40)
        assert a != c
        # Distinct episode seeds: 40 episodes = 40 distinct schedules.
        assert len({e["episode_seed"] for e in a}) == 40

    def test_every_seam_is_covered(self):
        """A default campaign reaches every FaultPlan seam: each
        scenario name appears, and collectively they exercise all the
        plan fields plus the device-loss seam."""
        sched = chaos.schedule_for(0, chaos.DEFAULT_SCHEDULES)
        ran = {e["scenario"] for e in sched}
        assert ran == set(chaos.SCENARIO_NAMES)
        assert set(chaos.SCENARIO_NAMES) == set(chaos._SCENARIOS)

    def test_failure_prints_reproducing_seed(self, monkeypatch):
        """A failing episode's record (and the campaign output) carries
        the exact reproduction command, seed included."""

        def boom(rng, fx, tmp):
            raise chaos.ChaosViolation("synthetic failure")

        monkeypatch.setitem(chaos._SCENARIOS, "torn_ledger", boom)
        monkeypatch.setattr(chaos, "_EXPECT_INJECTED",
                            chaos._EXPECT_INJECTED - {"torn_ledger"})
        lines = []
        # Episode 7 of the rotation is torn_ledger.
        summary = chaos.run_campaign(123, 8, out=lines.append)
        assert summary["passed"] == 7
        (failure,) = summary["failures"]
        assert failure["scenario"] == "torn_ledger"
        assert "PIPELINEDP_TPU_CHAOS_SEED=123" in failure["repro"]
        assert "--only-episode 7" in failure["repro"]
        assert any("PIPELINEDP_TPU_CHAOS_SEED=123" in line
                   for line in lines)

    def test_cli_seed_defaults_from_env(self, monkeypatch, capsys):
        monkeypatch.setenv(chaos.CHAOS_SEED_ENV, "99")
        # --only-episode 7 is torn_ledger: cheap, no jax work.
        rc = chaos.main(["--only-episode", "7"])
        assert rc == 0
        assert "torn_ledger" in capsys.readouterr().out


class TestEpisodes:
    """Single-episode smoke: the cheap scenarios run green in-process
    (the jax-heavy ones are covered by test_faults/test_serve and the
    make chaoscheck campaign)."""

    def test_torn_ledger_episode(self):
        # Rotation slot 7 = torn_ledger.
        spec = chaos.run_episode(5, 7)
        assert spec["scenario"] == "torn_ledger"

    def test_wedged_probe_episode(self):
        # Rotation slot 4 = wedged_probe (FakeClock, zero wall time).
        spec = chaos.run_episode(5, 4)
        assert spec["scenario"] == "wedged_probe"
        snap = obs.ledger().snapshot()
        assert snap["counters"].get("faults.injected", 0) >= 1

    def test_violation_surfaces_with_context(self, monkeypatch):
        def boom(rng, fx, tmp):
            raise chaos.ChaosViolation("invariant X broke")

        monkeypatch.setitem(chaos._SCENARIOS, "wedged_probe", boom)
        with pytest.raises(chaos.ChaosViolation, match="invariant X"):
            chaos.run_episode(5, 4)
