"""Pass-B sweep-planner + hybrid prefix-cache tests (PR 5).

The streamed percentile pass B used to pay one full batch-stream
traversal per (quantile group x partition block) round; the sweep
planner (``streaming.plan_pass_b_sweeps``) packs as many tiles as fit
under ``je._SUBHIST_BYTE_CAP`` into one traversal, and the multi-tile
kernels scatter one batch's rows into every packed tile's histogram in
a single launch. Covered here:

* planner invariants (exact grid coverage, per-sweep byte bound, never
  more sweeps than the per-tile loop, refusal only below one block);
* the acceptance case: a shrunken cap forcing >= 4 tiles runs
  ``ceil(tiles / tiles_per_sweep)`` sweeps — strictly fewer than tiles
  — with released values and kept-partition sets BIT-IDENTICAL to the
  per-tile loop and to the unchunked walk, on one device and the
  8-device mesh;
* the hybrid prefix cache: overflow keeps the cached batch prefix and
  reships only the suffix, bit-identical to full reship with
  strictly fewer reshipped bytes;
* reship staging parity: the rotating-StagingRing reship (cache
  disabled) equals the fresh-copy cached path bit-for-bit;
* fault-kill mid-sweep drains the stager with zero orphan threads;
* the in-tree ``nostager`` lint twin: pass-B restreaming is confined
  to the planner-driven sweep loop.
"""

import ast
import os
import threading

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import ingest
from pipelinedp_tpu import jax_engine as je
from pipelinedp_tpu import streaming
from pipelinedp_tpu.backends import JaxBackend
from pipelinedp_tpu.resilience.faults import (ChunkFailure, FaultPlan,
                                              injected_faults)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BIG_EPS = 1e12

_, _, _, SPAN = streaming._tree_consts()
UNIT = SPAN * 4  # bytes of one [1, 1, span] int32 block


def ingest_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith(ingest.THREAD_PREFIX) and t.is_alive()]


@pytest.fixture(autouse=True)
def no_orphan_threads():
    yield
    assert not ingest_threads(), (
        f"orphan ingest threads: {[t.name for t in ingest_threads()]}")


class TestSweepPlanner:
    """``plan_pass_b_sweeps`` is pure host arithmetic — pin its
    invariants directly."""

    def _coverage(self, plan, P_pad, Q):
        cells = set()
        for q0, qc, p0 in plan.tiles:
            pb = min(plan.p_blk, P_pad - p0)
            for q in range(q0, q0 + qc):
                for p in range(p0, p0 + pb):
                    assert (q, p) not in cells, "tile overlap"
                    cells.add((q, p))
        assert cells == {(q, p) for q in range(Q) for p in range(P_pad)}

    def test_fast_path_is_one_sweep_one_tile(self):
        plan = streaming.plan_pass_b_sweeps(1 << 17, 3, SPAN, 600 << 20)
        assert plan.n_tiles == plan.n_sweeps == 1
        assert not plan.chunked
        assert (plan.q_chunk, plan.p_blk) == (3, 1 << 17)

    @pytest.mark.parametrize("P_pad,Q,budget", [
        (8, 4, 8), (8, 4, 5), (8, 2, 2), (16, 3, 7), (64, 5, 48),
        (8, 4, 31), (1 << 10, 3, 1000),
    ])
    def test_coverage_byte_bound_and_no_regression(self, P_pad, Q,
                                                   budget):
        plan = streaming.plan_pass_b_sweeps(P_pad, Q, SPAN,
                                            budget * UNIT)
        self._coverage(plan, P_pad, Q)
        for sweep in plan.sweeps:
            # Uniform tile shape within a sweep (one stacked kernel
            # launch) and the packed block within the byte cap.
            qn = {qc for _, qc, _ in sweep}
            pn = {min(plan.p_blk, P_pad - p0) for _, _, p0 in sweep}
            assert len(qn) == 1 and len(pn) == 1
            assert (len(sweep) * qn.pop() * pn.pop()) <= budget
        # Never more stream traversals than the per-tile loop paid.
        per_q = P_pad
        q_chunk = max(1, budget // per_q)
        if per_q <= budget:
            old_rounds = -(-Q // q_chunk)
        else:
            p_blk = 1 << (budget.bit_length() - 1)
            old_rounds = Q * -(-P_pad // p_blk)
        assert plan.n_sweeps <= old_rounds

    def test_packing_beats_per_tile_rounds(self):
        """The collapse the tentpole exists for: budget 5 on an
        [8 x 4] grid packs 32 unit tiles into ceil(32/5) = 7 sweeps
        where the per-tile loop paid 8 rounds."""
        plan = streaming.plan_pass_b_sweeps(8, 4, SPAN, 5 * UNIT)
        assert plan.n_tiles == 32
        assert plan.tiles_per_sweep == 5
        assert plan.n_sweeps == 7 == -(-plan.n_tiles //
                                       plan.tiles_per_sweep)
        assert plan.n_sweeps < plan.n_tiles

    def test_refusal_below_one_block(self):
        with pytest.raises(NotImplementedError, match="subtree block"):
            streaming.plan_pass_b_sweeps(8, 2, SPAN, UNIT - 4)


def _pct_params(percentiles=(25, 50, 75, 95), hi=20.0, parts=5):
    return pdp.AggregateParams(
        metrics=[pdp.Metrics.PERCENTILE(p) for p in percentiles] +
        [pdp.Metrics.COUNT],
        noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=parts,
        max_contributions_per_partition=50,
        min_value=0.0, max_value=hi)


def _dataset(seed=88, n=6_000, parts=5, hi=20.0, users=1_500):
    rng = np.random.default_rng(seed)
    return pdp.ArrayDataset(privacy_ids=rng.integers(0, users, n),
                            partition_keys=rng.integers(0, parts, n),
                            values=rng.uniform(0.0, hi, n))


def _pct_fields(got):
    return [f for f in got[next(iter(got))]._fields
            if f.startswith("percentile_") or f == "count"]


def _run(ds, params, *, seed=7, chunk=997, public=None, eps=BIG_EPS,
         backend=None, monkeypatch=None, **backend_kw):
    if monkeypatch is not None:
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", str(chunk))
    ds.invalidate_cache()
    acc = pdp.NaiveBudgetAccountant(total_epsilon=eps, total_delta=1e-2)
    engine = pdp.DPEngine(acc, backend or JaxBackend(rng_seed=seed,
                                                     **backend_kw))
    res = engine.aggregate(ds, params, pdp.DataExtractors(),
                           public_partitions=public)
    acc.compute_budgets()
    got = dict(res)
    assert res.timings["stream_batches"] > 1
    return got, res.timings


def _force_per_tile(monkeypatch):
    """Degrade the planner to the pre-sweep behavior: one tile per
    sweep (= one stream traversal per tile) — the bit-parity reference
    the multi-tile packing must reproduce exactly."""
    orig = streaming.plan_pass_b_sweeps

    def per_tile(P_pad, Q, span, cap, q_chunk=0):
        p = orig(P_pad, Q, span, cap, q_chunk)
        return streaming.PassBPlan(p.q_chunk, p.p_blk, 1, p.tiles,
                                   tuple((t,) for t in p.tiles))

    monkeypatch.setattr(streaming, "plan_pass_b_sweeps", per_tile)


class TestMultiTileSweepParity:
    """Acceptance: with the cap seam shrunk to force >= 4 tiles, pass B
    runs ceil(tiles / tiles_per_sweep) sweeps — strictly fewer than
    tiles — and releases values and kept-partition sets bit-identical
    FOUR ways: unchunked walk = multi-tile XLA = per-tile loop = the
    Pallas multi-tile binner (``kernel_backend=pallas``, interpret
    mode on the CPU proxy)."""

    def _assert_same(self, a, b, tag):
        assert set(a) == set(b), tag  # kept-partition sets
        for k in a:
            for f in _pct_fields(a):
                assert getattr(a[k], f) == getattr(b[k], f), (tag, k, f)

    def _run_pallas(self, run_fn):
        """The fourth implementation, with proof it actually ran the
        Pallas path (a silent XLA fallback would make the parity
        assertion vacuous)."""
        from pipelinedp_tpu import obs
        from pipelinedp_tpu import plan as plan_mod

        obs.reset()
        with plan_mod.seam_override("kernel_backend", "pallas"):
            out, t = run_fn()
        counters = obs.ledger().snapshot()["counters"]
        assert counters.get("kernel.pallas_dispatches", 0) >= 1
        assert not counters.get("kernel.fallbacks")
        return out, t

    def test_single_device(self, monkeypatch):
        ds = _dataset()
        params = _pct_params()  # Q=4; P_pad = 8
        # Private selection at finite eps: the kept SET is part of the
        # parity claim, not just the values.
        full, t_full = _run(ds, params, eps=4.0, monkeypatch=monkeypatch)
        assert t_full["stream_pass_b_sweeps"] == 1
        assert len(full) >= 4
        # Un-chunked (single-full) pass B under pallas: the request
        # routes through the multi-tile kernels as a T=1 pack — served
        # by the binner (or a VISIBLE kernel.fallback), never a silent
        # xla run through the dispatch-less single-tile kernel.
        pallas_full, t_pf = self._run_pallas(
            lambda: _run(ds, params, eps=4.0, monkeypatch=monkeypatch))
        assert t_pf["stream_pass_b_sweeps"] == 1
        self._assert_same(full, pallas_full, "pallas vs unchunked xla")
        monkeypatch.setattr(je, "_SUBHIST_BYTE_CAP", 5 * UNIT)
        multi, t_multi = _run(ds, params, eps=4.0,
                              monkeypatch=monkeypatch)
        assert t_multi["stream_pass_b_tiles"] == 32
        assert t_multi["stream_pass_b_tiles_per_sweep"] == 5
        assert t_multi["stream_pass_b_sweeps"] == 7 == -(
            -t_multi["stream_pass_b_tiles"] //
            t_multi["stream_pass_b_tiles_per_sweep"])
        assert (t_multi["stream_pass_b_sweeps"] <
                t_multi["stream_pass_b_tiles"])
        pallas, t_pal = self._run_pallas(
            lambda: _run(ds, params, eps=4.0, monkeypatch=monkeypatch))
        assert t_pal["stream_pass_b_sweeps"] == 7
        _force_per_tile(monkeypatch)
        per_tile, t_tile = _run(ds, params, eps=4.0,
                                monkeypatch=monkeypatch)
        assert t_tile["stream_pass_b_sweeps"] == 32
        self._assert_same(full, multi, "multi-tile vs unchunked")
        self._assert_same(full, per_tile, "per-tile vs unchunked")
        self._assert_same(full, pallas, "pallas vs unchunked")

    def test_mesh(self, monkeypatch):
        from pipelinedp_tpu.parallel import make_mesh

        ds = _dataset(seed=17)
        params = _pct_params()

        def run(**kw):
            return _run(ds, params, eps=4.0, chunk=499,
                        backend=JaxBackend(mesh=make_mesh(8),
                                           rng_seed=7),
                        monkeypatch=monkeypatch, **kw)

        full, t_full = run()
        assert t_full["stream_pass_b_sweeps"] == 1
        monkeypatch.setattr(je, "_SUBHIST_BYTE_CAP", 5 * UNIT)
        multi, t_multi = run()
        assert (t_multi["stream_pass_b_sweeps"] <
                t_multi["stream_pass_b_tiles"] == 32)
        pallas, _ = self._run_pallas(run)
        _force_per_tile(monkeypatch)
        per_tile, _ = run()
        self._assert_same(full, multi, "mesh multi-tile vs unchunked")
        self._assert_same(full, per_tile, "mesh per-tile vs unchunked")
        self._assert_same(full, pallas, "mesh pallas vs unchunked")

    def test_sweep_counters_reach_ledger(self, monkeypatch):
        from pipelinedp_tpu import obs

        obs.reset()
        ds = _dataset(seed=3)
        params = _pct_params()
        monkeypatch.setattr(je, "_SUBHIST_BYTE_CAP", 5 * UNIT)
        _, t = _run(ds, params, monkeypatch=monkeypatch,
                    public=list(range(5)))
        counters = obs.ledger().snapshot()["counters"]
        assert (counters["stream.pass_b_stream_sweeps"] ==
                t["stream_pass_b_sweeps"])
        assert counters["stream.pass_b_tiles"] == 32


class TestHybridPrefixCache:
    """Cache overflow no longer zeroes the cache: the resident batch
    prefix keeps serving pass B from HBM and only the suffix reships —
    bit-identical to both the all-cached and the all-reshipped runs,
    with strictly fewer reshipped bytes than full reship."""

    def _run_with_cache(self, ds, params, cache, monkeypatch):
        return _run(ds, params, public=list(range(5)),
                    monkeypatch=monkeypatch, stream_cache=cache)

    def test_hybrid_reships_only_the_suffix(self, monkeypatch):
        ds = _dataset(seed=21)
        params = _pct_params(percentiles=(50, 95))
        cached, t_c = self._run_with_cache(ds, params, 1 << 30,
                                           monkeypatch)
        reship, t_r = self._run_with_cache(ds, params, 0, monkeypatch)
        assert t_c["stream_pass_b"] == "device_cache"
        assert t_c["stream_pass_b_reshipped_bytes"] == 0
        assert t_r["stream_pass_b"] == "reship"
        full_bytes = t_r["stream_pass_b_reshipped_bytes"]
        assert full_bytes > 0
        n_batches = t_r["stream_batches"]
        # Budget for ~2.5 batches: the prefix caches, the rest reships.
        per_batch = full_bytes // n_batches
        hybrid, t_h = self._run_with_cache(ds, params,
                                           per_batch * 5 // 2,
                                           monkeypatch)
        assert t_h["stream_pass_b"] == "hybrid"
        assert 1 <= t_h["stream_pass_b_cached_batches"] < n_batches
        assert 0 < t_h["stream_pass_b_reshipped_bytes"] < full_bytes
        for p in range(5):
            for f in _pct_fields(cached):
                v = getattr(cached[p], f)
                assert getattr(hybrid[p], f) == v, (p, f, "hybrid")
                assert getattr(reship[p], f) == v, (p, f, "reship")

    def test_overflow_event_keeps_prefix(self, monkeypatch):
        from pipelinedp_tpu import obs

        obs.reset()
        ds = _dataset(seed=22)
        params = _pct_params(percentiles=(50,))
        _, t_r = self._run_with_cache(ds, params, 0, monkeypatch)
        per_batch = (t_r["stream_pass_b_reshipped_bytes"] //
                     t_r["stream_batches"])
        obs.reset()
        _, t_h = self._run_with_cache(ds, params, per_batch * 3 // 2,
                                      monkeypatch)
        assert t_h["stream_pass_b"] == "hybrid"
        events = [e for e in obs.ledger().snapshot()["events"]
                  if e["name"] == "stream.cache_overflow"]
        assert events and events[0]["prefix_batches"] >= 1

    def test_hybrid_composes_with_multi_tile_sweeps(self, monkeypatch):
        """The two tentpole halves together: shrunken cap (multi-tile
        sweeps) + overflowing cache (hybrid source) still bit-identical
        to the unconstrained run."""
        ds = _dataset(seed=23)
        params = _pct_params()
        full, _ = self._run_with_cache(ds, params, 1 << 30, monkeypatch)
        _, t_r = self._run_with_cache(ds, params, 0, monkeypatch)
        per_batch = (t_r["stream_pass_b_reshipped_bytes"] //
                     t_r["stream_batches"])
        monkeypatch.setattr(je, "_SUBHIST_BYTE_CAP", 5 * UNIT)
        hybrid, t_h = self._run_with_cache(ds, params,
                                           per_batch * 5 // 2,
                                           monkeypatch)
        assert t_h["stream_pass_b"] == "hybrid"
        assert t_h["stream_pass_b_sweeps"] == 7
        for p in range(5):
            for f in _pct_fields(full):
                assert getattr(hybrid[p], f) == getattr(full[p], f), (
                    p, f)


class TestReshipStagingModes:
    """Satellite: reship-only sweeps stage through the rotating
    StagingRing (fresh-copy retention is only needed while feeding the
    cache) — parity across both staging modes and both executors."""

    @pytest.mark.parametrize("executor", [True, False])
    def test_ring_reship_equals_copy_cached(self, executor,
                                            monkeypatch):
        ds = _dataset(seed=31)
        params = _pct_params(percentiles=(50, 90))
        copied, _ = _run(ds, params, public=list(range(5)),
                         monkeypatch=monkeypatch, stream_cache=1 << 30,
                         ingest_executor=executor)
        ringed, t = _run(ds, params, public=list(range(5)),
                         monkeypatch=monkeypatch, stream_cache=0,
                         ingest_executor=executor)
        assert t["stream_pass_b"] == "reship"
        for p in range(5):
            for f in _pct_fields(copied):
                assert getattr(ringed[p], f) == getattr(copied[p], f), (
                    p, f, executor)


class TestPassBFaultDrain:
    """A fault-injected kill DURING a pass-B sweep severs the run at a
    deterministic batch and drains every worker thread — zero orphans
    (the autouse fixture re-asserts after each test)."""

    @pytest.mark.parametrize("executor", [True, False])
    def test_kill_mid_sweep_drains(self, executor, monkeypatch):
        ds = _dataset(seed=41)
        params = _pct_params(percentiles=(50,))
        with injected_faults(FaultPlan(fail_pass_b_chunks=(1,))):
            with pytest.raises(ChunkFailure, match="pass-B"):
                _run(ds, params, public=list(range(5)),
                     monkeypatch=monkeypatch, stream_cache=0,
                     ingest_executor=executor)
        assert not ingest_threads(), "pass-B kill left orphan threads"

    def test_kill_in_cached_sweep_drains(self, monkeypatch):
        """The kill also lands when the sweep reads the device cache
        (no stager running) — same deterministic failure, no orphans."""
        ds = _dataset(seed=42)
        params = _pct_params(percentiles=(50,))
        with injected_faults(FaultPlan(fail_pass_b_chunks=(0,))):
            with pytest.raises(ChunkFailure, match="pass-B"):
                _run(ds, params, public=list(range(5)),
                     monkeypatch=monkeypatch, stream_cache=1 << 30)
        assert not ingest_threads()


class TestNoStagerLint:
    """In-tree twin of ``make nostager``: pass-B restreaming must flow
    through the sweep planner's ONE stream source. Any new
    ``BackgroundStager`` construction in ``streaming.py`` outside pass
    A's overlapped loop or ``run_sweep`` re-introduces per-tile
    restreaming and must fail here."""

    def test_stager_sites_confined(self):
        # The shared AST engine's rule carries BOTH halves: the
        # outside-streaming construction ban and the "exactly the two
        # blessed streaming.py sites" shape check; `make nostager`
        # is the same rule.
        from pipelinedp_tpu import lint
        assert lint.check_tree("nostager") == []

    def test_streaming_still_has_its_two_sites(self):
        """The rule must be testing something: pass A + run_sweep DO
        construct stagers (a rewrite that dropped them would silently
        hollow out the shape check). AST call sites, not text — a
        docstring mention must neither count nor fail."""
        path = os.path.join(REPO, "pipelinedp_tpu", "streaming.py")
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
        sites = [n for n in ast.walk(tree)
                 if isinstance(n, ast.Call)
                 and (getattr(n.func, "id", None) == "BackgroundStager"
                      or getattr(n.func, "attr", None)
                      == "BackgroundStager")]
        assert len(sites) == 2
