"""Exercises the Beam and Spark adapters (``beam_backend.py``,
``SparkRDDBackend``, ``private_beam``, ``private_spark``).

apache_beam / pyspark are not installable in every environment, so the
adapters run against lazy structural fakes (``fake_beam`` /
``fake_spark``) — the adapter code, its closures, stage-label
bookkeeping and the engine graph over it all execute for real. When the
real libraries ARE importable, ``TestRealBeam`` / ``TestRealSpark``
additionally run an op-conformance subset and an E2E flow on the
genuine runners (they skip here).

The fake beam module is registered in ``sys.modules`` only for the
duration of the adapter imports below, then removed: the rest of the
test session sees the unmodified beam-optional behavior (``import
apache_beam`` raising, ``pipeline_backend`` without a ``BeamBackend``
attribute). The already-imported adapter modules keep their references.
"""

import operator
import sys

import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.ops import noise as noise_ops
from pipelinedp_tpu import pipeline_backend as _pb

try:
    import apache_beam as beam
    HAVE_BEAM = True
except ImportError:
    HAVE_BEAM = False

try:
    import pyspark as _real_pyspark  # noqa: F401
    HAVE_SPARK = True
except ImportError:
    HAVE_SPARK = False

if not HAVE_BEAM:
    from tests import fake_beam as _fake_beam_mod
    beam = _fake_beam_mod.build_fake_beam_module()
    _added = {"apache_beam": beam}
    for name in ("apache_beam.combiners", "apache_beam.transforms",
                 "apache_beam.transforms.ptransform"):
        _added[name] = sys.modules[name]  # registered by the builder
    _had_bb = hasattr(_pb, "BeamBackend")
    try:
        sys.modules["apache_beam"] = beam
        from pipelinedp_tpu.beam_backend import BeamBackend
        _pb.BeamBackend = BeamBackend  # as if beam existed at start
        from pipelinedp_tpu import private_beam
    finally:
        for name in _added:
            sys.modules.pop(name, None)
        if not _had_bb:
            del _pb.BeamBackend
else:
    from pipelinedp_tpu.beam_backend import BeamBackend
    from pipelinedp_tpu import private_beam

from pipelinedp_tpu.pipeline_backend import SparkRDDBackend
from pipelinedp_tpu import private_spark
from tests.fake_spark import FakeSparkContext

BIG_EPS = 1e5


# ---------------------------------------------------------------------------
# Harnesses: wrap list -> native collection, collect -> list
# ---------------------------------------------------------------------------


class BeamHarness:
    name = "beam"

    def __init__(self):
        self.backend = BeamBackend()
        self.pipeline = beam.Pipeline()

    def col(self, data):
        return self.pipeline | f"create{id(data)}" >> beam.Create(data)

    def collect(self, col):
        return list(col)


class SparkHarness:
    name = "spark"

    def __init__(self):
        self.sc = FakeSparkContext()
        self.backend = SparkRDDBackend(self.sc)

    def col(self, data):
        return self.sc.parallelize(data)

    def collect(self, col):
        return list(col.collect())


@pytest.fixture(params=["beam", "spark"])
def h(request):
    if request.param == "beam" and HAVE_BEAM:
        # BeamHarness assumes the fake's iterable PCollections; with real
        # beam installed, TestRealBeam covers the adapter instead.
        pytest.skip("real beam installed: fake-backed harness not used")
    # SparkHarness always uses FakeSparkContext (duck-typed RDDs), so it
    # runs whether or not pyspark is installed.
    return BeamHarness() if request.param == "beam" else SparkHarness()


class _SumCombiner:

    def merge_accumulators(self, a, b):
        return a + b


class TestClusterBackendConformance:
    """The op matrix of tests/test_pipeline_backend.py, on the adapters."""

    def test_map(self, h):
        got = h.collect(h.backend.map(h.col([1, 2, 3]), lambda x: 2 * x,
                                      "map"))
        assert sorted(got) == [2, 4, 6]

    def test_flat_map(self, h):
        got = h.collect(h.backend.flat_map(h.col([1, 2]),
                                           lambda x: [x, x], "fm"))
        assert sorted(got) == [1, 1, 2, 2]

    def test_map_tuple(self, h):
        got = h.collect(h.backend.map_tuple(h.col([(1, "a"), (2, "b")]),
                                            lambda k, v: (v, k), "mt"))
        assert sorted(got) == [("a", 1), ("b", 2)]

    def test_map_values(self, h):
        got = h.collect(h.backend.map_values(h.col([(1, 2), (2, 3)]),
                                             lambda v: 2 * v, "mv"))
        assert sorted(got) == [(1, 4), (2, 6)]

    def test_group_by_key(self, h):
        got = dict(h.collect(h.backend.group_by_key(
            h.col([(1, "a"), (2, "b"), (1, "c")]), "gbk")))
        assert sorted(got[1]) == ["a", "c"]
        assert list(got[2]) == ["b"]

    def test_filter(self, h):
        got = h.collect(h.backend.filter(h.col([1, 2, 3, 4]),
                                         lambda x: x % 2 == 0, "f"))
        assert sorted(got) == [2, 4]

    def test_filter_by_key_in_memory(self, h):
        got = h.collect(h.backend.filter_by_key(
            h.col([(1, "a"), (2, "b"), (3, "c")]), [1, 3], "fbk"))
        assert sorted(got) == [(1, "a"), (3, "c")]

    def test_filter_by_key_distributed(self, h):
        keys = h.col([1, 3])
        got = h.collect(h.backend.filter_by_key(
            h.col([(1, "a"), (2, "b"), (3, "c")]), keys, "fbk2"))
        assert sorted(got) == [(1, "a"), (3, "c")]

    def test_keys_values(self, h):
        col = h.col([(1, "a"), (2, "b")])
        assert sorted(h.collect(h.backend.keys(col, "k"))) == [1, 2]
        col2 = h.col([(1, "a"), (2, "b")])
        assert sorted(h.collect(h.backend.values(col2, "v"))) == ["a", "b"]

    def test_sample_fixed_per_key(self, h):
        data = [(1, i) for i in range(10)] + [(2, 99)]
        got = dict(h.collect(h.backend.sample_fixed_per_key(
            h.col(data), 3, "sample")))
        assert len(got[1]) == 3
        assert set(got[1]) <= set(range(10))
        assert list(got[2]) == [99]

    def test_count_per_element(self, h):
        got = dict(h.collect(h.backend.count_per_element(
            h.col(["a", "b", "a"]), "cpe")))
        assert got == {"a": 2, "b": 1}

    def test_sum_per_key(self, h):
        got = dict(h.collect(h.backend.sum_per_key(
            h.col([(1, 2), (1, 3), (2, 5)]), "spk")))
        assert got == {1: 5, 2: 5}

    def test_combine_accumulators_per_key(self, h):
        got = dict(h.collect(h.backend.combine_accumulators_per_key(
            h.col([(1, 2), (1, 3), (2, 5)]), _SumCombiner(), "capk")))
        assert got == {1: 5, 2: 5}

    def test_reduce_per_key(self, h):
        got = dict(h.collect(h.backend.reduce_per_key(
            h.col([(1, 2), (1, 3)]), operator.add, "rpk")))
        assert got == {1: 5}

    def test_flatten(self, h):
        got = h.collect(h.backend.flatten(
            (h.col([1, 2]), h.col([3])), "flat"))
        assert sorted(got) == [1, 2, 3]

    def test_distinct(self, h):
        got = h.collect(h.backend.distinct(h.col([1, 2, 2, 3, 1]), "d"))
        assert sorted(got) == [1, 2, 3]

    def test_to_list(self, h):
        if h.name == "spark":
            # Reference parity: Spark leaves to_list unimplemented
            # (reference pipeline_backend.py:454-455).
            with pytest.raises(NotImplementedError):
                h.backend.to_list(h.col([1, 2, 3]), "tl")
            return
        got = h.collect(h.backend.to_list(h.col([1, 2, 3]), "tl"))
        assert sorted(got[0]) == [1, 2, 3]


class TestBeamStageLabels:

    @pytest.mark.skipif(HAVE_BEAM, reason="fake-specific label check")
    def test_repeated_stage_names_stay_unique(self):
        hn = BeamHarness()
        col = hn.col([1, 2, 3])
        # Same stage name twice: the UniqueLabelsGenerator must suffix
        # them apart or the (fake = real beam semantics) pipeline raises.
        a = hn.backend.map(col, lambda x: x + 1, "stage")
        b = hn.backend.map(a, lambda x: x + 1, "stage")
        assert sorted(hn.collect(b)) == [3, 4, 5]


class TestEngineOnClusterBackends:
    """Full DPEngine aggregation through each adapter (huge eps: results
    pin to the exact aggregates)."""

    def _run_engine(self, h, public=None):
        data = [(u, p, 1.0) for u in range(30) for p in ("x", "y")]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=2,
            max_contributions_per_partition=1,
            min_value=0.0, max_value=1.0)
        ex = pdp.DataExtractors(
            privacy_id_extractor=operator.itemgetter(0),
            partition_extractor=operator.itemgetter(1),
            value_extractor=operator.itemgetter(2))
        acc = pdp.NaiveBudgetAccountant(total_epsilon=BIG_EPS,
                                        total_delta=1e-2)
        engine = pdp.DPEngine(acc, h.backend)
        result = engine.aggregate(h.col(data), params, ex,
                                  public_partitions=public)
        acc.compute_budgets()
        return dict(h.collect(result))

    def test_private_partitions(self, h):
        noise_ops.seed_host_rng(0)
        out = self._run_engine(h)
        assert sorted(out) == ["x", "y"]
        for v in out.values():
            assert v.count == pytest.approx(30, abs=0.5)
            assert v.sum == pytest.approx(30, abs=0.5)

    def test_public_partitions(self, h):
        noise_ops.seed_host_rng(0)
        out = self._run_engine(h, public=["x", "z"])
        assert sorted(out) == ["x", "z"]
        assert out["x"].count == pytest.approx(30, abs=0.5)
        assert out["z"].count == pytest.approx(0, abs=0.5)

    def test_select_partitions(self, h):
        noise_ops.seed_host_rng(0)
        data = [(u, "big") for u in range(1000)] + [(1, "small")]
        ex = pdp.DataExtractors(
            privacy_id_extractor=operator.itemgetter(0),
            partition_extractor=operator.itemgetter(1))
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                        total_delta=1e-6)
        engine = pdp.DPEngine(acc, h.backend)
        result = engine.select_partitions(
            h.col(data), pdp.SelectPartitionsParams(
                max_partitions_contributed=2), ex)
        acc.compute_budgets()
        got = h.collect(result)
        assert "big" in got and "small" not in got


@pytest.mark.skipif(HAVE_BEAM, reason="fluent fake-beam flow")
class TestPrivateBeamOnFake:

    def test_count_flow(self):
        noise_ops.seed_host_rng(0)
        p = beam.Pipeline()
        data = ([(u, "a") for u in range(40)] +
                [(u, "b") for u in range(100, 125)])
        pcol = p | "create" >> beam.Create(data)
        acc = pdp.NaiveBudgetAccountant(total_epsilon=BIG_EPS,
                                        total_delta=1e-2)
        private = pcol | private_beam.MakePrivate(
            budget_accountant=acc,
            privacy_id_extractor=operator.itemgetter(0))
        counts = private | private_beam.Count(
            pdp.CountParams(max_partitions_contributed=1,
                            max_contributions_per_partition=1,
                            partition_extractor=operator.itemgetter(1)))
        acc.compute_budgets()
        got = dict(counts)
        assert got["a"] == pytest.approx(40, abs=0.5)
        assert got["b"] == pytest.approx(25, abs=0.5)

    def test_map_then_sum(self):
        noise_ops.seed_host_rng(0)
        p = beam.Pipeline()
        data = [(u, "a", 2.0) for u in range(30)]
        pcol = p | "create" >> beam.Create(data)
        acc = pdp.NaiveBudgetAccountant(total_epsilon=BIG_EPS,
                                        total_delta=1e-2)
        private = pcol | private_beam.MakePrivate(
            budget_accountant=acc,
            privacy_id_extractor=operator.itemgetter(0))
        doubled = private | private_beam.Map(
            lambda row: (row[1], row[2] * 2))
        sums = doubled | private_beam.Sum(
            pdp.SumParams(max_partitions_contributed=1,
                          max_contributions_per_partition=1,
                          min_value=0.0, max_value=10.0,
                          partition_extractor=operator.itemgetter(0),
                          value_extractor=operator.itemgetter(1)))
        acc.compute_budgets()
        got = dict(sums)
        assert got["a"] == pytest.approx(120, abs=1.0)

    def test_combine_per_key_with_private_combine_fn(self):
        noise_ops.seed_host_rng(0)

        class SumCombineFn(private_beam.PrivateCombineFn):

            def create_accumulator_for_private_output(self):
                return 0.0

            def add_input_for_private_output(self, acc_, v):
                return acc_ + min(v, 5.0)

            def merge_accumulators(self, a, b):
                return a + b

            def extract_private_output(self, accumulator, budget):
                return accumulator + noise_ops.np_laplace(
                    5.0 / budget.eps)

            def request_budget(self, budget_accountant):
                self._budget = budget_accountant.request_budget(
                    pdp.MechanismType.LAPLACE)

            def explain_computation(self):
                return "private sum via CombineFn"

        p = beam.Pipeline()
        data = [(u, ("a", 2.0)) for u in range(30)]
        pcol = p | "create" >> beam.Create(data)
        acc = pdp.NaiveBudgetAccountant(total_epsilon=BIG_EPS,
                                        total_delta=1e-2)
        private = pcol | private_beam.MakePrivate(
            budget_accountant=acc, privacy_id_extractor=lambda row: row[0])
        # CombinePerKey consumes (key, value) elements.
        private = private | private_beam.Map(lambda row: row[1])
        out = private | private_beam.CombinePerKey(
            SumCombineFn(),
            private_beam.CombinePerKeyParams(
                max_partitions_contributed=1,
                max_contributions_per_partition=1))
        acc.compute_budgets()
        got = dict(out)
        # Unnested: the value is the combiner's scalar, not a 1-tuple.
        assert got["a"] == pytest.approx(60, abs=1.0)

        # AggregateParams path: the combine_fn must appear in
        # custom_combiners; a single combiner is unnested the same way.
        fn = SumCombineFn()
        p2 = beam.Pipeline()
        pcol2 = p2 | "create2" >> beam.Create(data)
        acc2 = pdp.NaiveBudgetAccountant(total_epsilon=BIG_EPS,
                                         total_delta=1e-2)
        private2 = pcol2 | private_beam.MakePrivate(
            budget_accountant=acc2, privacy_id_extractor=lambda row: row[0])
        private2 = private2 | private_beam.Map(lambda row: row[1])
        out2 = private2 | private_beam.CombinePerKey(
            fn,
            pdp.AggregateParams(metrics=None,
                                max_partitions_contributed=1,
                                max_contributions_per_partition=1,
                                custom_combiners=[fn]))
        acc2.compute_budgets()
        got2 = dict(out2)
        assert got2["a"] == pytest.approx(60, abs=1.0)

        # A params whose custom_combiners omit the combine_fn is an error.
        other = SumCombineFn()
        with pytest.raises(ValueError, match="combine_fn"):
            bad = private2 | private_beam.CombinePerKey(
                SumCombineFn(),
                pdp.AggregateParams(metrics=None,
                                    max_partitions_contributed=1,
                                    max_contributions_per_partition=1,
                                    custom_combiners=[other]))

        # metrics=None without custom combiners is rejected at
        # construction with a clear message.
        with pytest.raises(ValueError, match="metrics must be set"):
            pdp.AggregateParams(metrics=None,
                                max_partitions_contributed=1,
                                max_contributions_per_partition=1)


@pytest.mark.skipif(HAVE_SPARK, reason="fluent fake-spark flow")
class TestPrivateSparkOnFake:

    def test_count_and_privacy_id_count(self):
        noise_ops.seed_host_rng(0)
        sc = FakeSparkContext()
        data = [(u, "a") for u in range(40)] + [(0, "a"), (0, "a")]
        rdd = sc.parallelize(data)
        acc = pdp.NaiveBudgetAccountant(total_epsilon=BIG_EPS,
                                        total_delta=1e-2)
        prdd = private_spark.make_private(
            rdd, acc, privacy_id_extractor=operator.itemgetter(0))
        counts = prdd.count(pdp.CountParams(
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            partition_extractor=operator.itemgetter(1)))
        pid_counts = prdd.privacy_id_count(pdp.PrivacyIdCountParams(
            max_partitions_contributed=1,
            partition_extractor=operator.itemgetter(1)))
        acc.compute_budgets()
        assert dict(counts.collect())["a"] == pytest.approx(40, abs=0.5)
        assert dict(pid_counts.collect())["a"] == pytest.approx(40,
                                                               abs=0.5)


# ---------------------------------------------------------------------------
# Real-library E2E (skip unless installed)
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not HAVE_BEAM, reason="apache_beam not installed")
class TestRealBeam:
    """Runs on the genuine Beam runner where apache_beam is installed:
    an op-conformance subset (the shuffle-heavy ops whose behavior
    depends on the real runner) plus a fluent E2E flow."""

    def test_op_conformance_subset(self):
        from apache_beam.testing.test_pipeline import TestPipeline
        from apache_beam.testing.util import assert_that, equal_to
        backend = BeamBackend()
        with TestPipeline() as p:
            col = p | "in" >> beam.Create([(1, "a"), (2, "b"), (1, "c")])
            mapped = backend.map_values(col, str.upper, "mv")
            assert_that(mapped, equal_to([(1, "A"), (2, "B"), (1, "C")]),
                        label="check_mv")
            grouped = backend.group_by_key(
                p | "in2" >> beam.Create([(1, "a"), (1, "b")]), "gbk")
            assert_that(grouped | "norm" >> beam.MapTuple(
                lambda k, v: (k, sorted(v))), equal_to([(1, ["a", "b"])]),
                        label="check_gbk")
            combined = backend.combine_accumulators_per_key(
                p | "in3" >> beam.Create([(1, 2), (1, 3), (2, 5)]),
                _SumCombiner(), "capk")
            assert_that(combined, equal_to([(1, 5), (2, 5)]),
                        label="check_capk")
            # The distributed filter_by_key regime (CoGroupByKey join).
            keys_col = p | "keys" >> beam.Create([1])
            filtered = backend.filter_by_key(
                p | "in4" >> beam.Create([(1, "x"), (2, "y")]), keys_col,
                "fbk")
            assert_that(filtered, equal_to([(1, "x")]), label="check_fbk")
            sampled = backend.sample_fixed_per_key(
                p | "in5" >> beam.Create([(1, i) for i in range(10)]), 3,
                "sample")
            assert_that(sampled | "count" >> beam.MapTuple(
                lambda k, v: (k, len(v))), equal_to([(1, 3)]),
                        label="check_sample")

    def test_count_on_test_pipeline(self):
        from apache_beam.testing.test_pipeline import TestPipeline
        from apache_beam.testing.util import assert_that, equal_to
        noise_ops.seed_host_rng(0)
        with TestPipeline() as p:
            data = [(u, "a") for u in range(40)]
            pcol = p | beam.Create(data)
            acc = pdp.NaiveBudgetAccountant(total_epsilon=BIG_EPS,
                                            total_delta=1e-2)
            private = pcol | private_beam.MakePrivate(
                budget_accountant=acc,
                privacy_id_extractor=operator.itemgetter(0))
            counts = private | private_beam.Count(
                pdp.CountParams(max_partitions_contributed=1,
                                max_contributions_per_partition=1,
                                partition_extractor=operator.itemgetter(1)))
            acc.compute_budgets()
            assert_that(counts | beam.Keys(), equal_to(["a"]))


@pytest.mark.skipif(not HAVE_SPARK, reason="pyspark not installed")
class TestRealSpark:

    def test_count_on_local_master(self):
        import pyspark
        noise_ops.seed_host_rng(0)
        conf = pyspark.SparkConf().setMaster("local[1]")
        with pyspark.SparkContext.getOrCreate(conf=conf) as sc:
            data = [(u, "a") for u in range(40)]
            acc = pdp.NaiveBudgetAccountant(total_epsilon=BIG_EPS,
                                            total_delta=1e-2)
            prdd = private_spark.make_private(
                sc.parallelize(data), acc,
                privacy_id_extractor=operator.itemgetter(0))
            counts = prdd.count(pdp.CountParams(
                max_partitions_contributed=1,
                max_contributions_per_partition=1,
                partition_extractor=operator.itemgetter(1)))
            acc.compute_budgets()
            assert dict(counts.collect())["a"] == pytest.approx(40,
                                                                abs=0.5)
