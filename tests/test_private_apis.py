"""Tests for the fluent private-collection API and the peeker package
(mirrors the reference's ``tests/private_spark_test.py`` and
``utility_analysis/tests/`` at the capability level)."""

import operator

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import peeker
from pipelinedp_tpu.backends import JaxBackend
from pipelinedp_tpu.ops import noise as noise_ops

BIG_EPS = 1e5


def movie_rows(n_users=40):
    # (user, movie, rating)
    return [(u, m, 4.0) for u in range(n_users) for m in ("m1", "m2")]


def extractors():
    return pdp.DataExtractors(privacy_id_extractor=operator.itemgetter(0),
                              partition_extractor=operator.itemgetter(1),
                              value_extractor=operator.itemgetter(2))


class TestPrivateCollection:

    def _private(self, backend=None, eps=BIG_EPS):
        backend = backend or pdp.LocalBackend()
        acc = pdp.NaiveBudgetAccountant(total_epsilon=eps,
                                        total_delta=1e-10)
        pcol = pdp.make_private(movie_rows(), backend, acc,
                                operator.itemgetter(0))
        return pcol, acc

    def test_count(self):
        noise_ops.seed_host_rng(0)
        pcol, acc = self._private()
        result = pcol.count(
            pdp.CountParams(max_partitions_contributed=2,
                            max_contributions_per_partition=1,
                            partition_extractor=operator.itemgetter(1)))
        acc.compute_budgets()
        out = dict(result)
        assert out["m1"] == pytest.approx(40, abs=0.5)

    def test_sum_and_mean(self):
        noise_ops.seed_host_rng(0)
        pcol, acc = self._private()
        s = pcol.sum(
            pdp.SumParams(max_partitions_contributed=2,
                          max_contributions_per_partition=1,
                          min_value=0.0, max_value=5.0,
                          partition_extractor=operator.itemgetter(1),
                          value_extractor=operator.itemgetter(2)))
        m = pcol.mean(
            pdp.MeanParams(max_partitions_contributed=2,
                           max_contributions_per_partition=1,
                           min_value=0.0, max_value=5.0,
                           partition_extractor=operator.itemgetter(1),
                           value_extractor=operator.itemgetter(2)))
        acc.compute_budgets()
        assert dict(s)["m1"] == pytest.approx(160.0, rel=0.01)
        assert dict(m)["m2"] == pytest.approx(4.0, abs=0.05)

    def test_privacy_id_count(self):
        noise_ops.seed_host_rng(0)
        pcol, acc = self._private()
        result = pcol.privacy_id_count(
            pdp.PrivacyIdCountParams(
                max_partitions_contributed=2,
                partition_extractor=operator.itemgetter(1)))
        acc.compute_budgets()
        assert dict(result)["m1"] == pytest.approx(40, abs=0.5)

    def test_variance(self):
        noise_ops.seed_host_rng(0)
        backend = pdp.LocalBackend()
        acc = pdp.NaiveBudgetAccountant(total_epsilon=BIG_EPS,
                                        total_delta=1e-10)
        data = [(u, "m", 2.0) for u in range(100)] + [
            (u, "m", 8.0) for u in range(100, 200)
        ]
        pcol = pdp.make_private(data, backend, acc,
                                operator.itemgetter(0))
        result = pcol.variance(
            pdp.VarianceParams(max_partitions_contributed=1,
                               max_contributions_per_partition=1,
                               min_value=0.0, max_value=10.0,
                               partition_extractor=operator.itemgetter(1),
                               value_extractor=operator.itemgetter(2)))
        acc.compute_budgets()
        assert dict(result)["m"] == pytest.approx(9.0, abs=0.3)

    def test_map_flat_map(self):
        noise_ops.seed_host_rng(0)
        pcol, acc = self._private()
        doubled = pcol.map(lambda row: (row[0], row[1], row[2] * 2))
        result = doubled.sum(
            pdp.SumParams(max_partitions_contributed=2,
                          max_contributions_per_partition=1,
                          min_value=0.0, max_value=10.0,
                          partition_extractor=operator.itemgetter(1),
                          value_extractor=operator.itemgetter(2)))
        acc.compute_budgets()
        assert dict(result)["m1"] == pytest.approx(320.0, rel=0.01)

    def test_select_partitions(self):
        noise_ops.seed_host_rng(0)
        backend = pdp.LocalBackend()
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                        total_delta=1e-6)
        data = [(u, "big", 1.0) for u in range(1000)]
        pcol = pdp.make_private(data, backend, acc,
                                operator.itemgetter(0))
        result = pcol.select_partitions(
            pdp.SelectPartitionsParams(max_partitions_contributed=1),
            partition_extractor=operator.itemgetter(1))
        acc.compute_budgets()
        assert "big" in list(result)

    def test_on_jax_backend(self):
        noise_ops.seed_host_rng(0)
        pcol, acc = self._private(backend=JaxBackend(rng_seed=0))
        result = pcol.count(
            pdp.CountParams(max_partitions_contributed=2,
                            max_contributions_per_partition=1,
                            partition_extractor=operator.itemgetter(1)))
        acc.compute_budgets()
        assert dict(result)["m1"] == pytest.approx(40, abs=0.5)


class TestDataPeeker:

    def test_sample_keeps_n_partitions(self):
        noise_ops.seed_host_rng(0)
        data = [(u, f"p{p}", 1.0) for u in range(20) for p in range(10)]
        pk = peeker.DataPeeker(pdp.LocalBackend())
        params = peeker.SampleParams(number_of_sampled_partitions=3)
        out = list(pk.sample(data, params, extractors()))
        pks = {pk for _, pk, _ in out}
        assert len(pks) == 3
        assert all(len(row) == 3 for row in out)

    def test_sketch_count(self):
        noise_ops.seed_host_rng(0)
        data = [(u, "a", 1.0) for u in range(10) for _ in range(3)]
        pk = peeker.DataPeeker(pdp.LocalBackend())
        params = peeker.SampleParams(number_of_sampled_partitions=5,
                                     metrics=[pdp.Metrics.COUNT])
        out = list(pk.sketch(data, params, extractors()))
        # One sketch row per (pk, pid): 10 rows, each count 3, pcount 1.
        assert len(out) == 10
        for pk_, value, pcount in out:
            assert pk_ == "a"
            assert value == 3
            assert pcount == 1

    def test_aggregate_true(self):
        data = [(u, "a", 2.0) for u in range(10)]
        pk = peeker.DataPeeker(pdp.LocalBackend())
        params = peeker.SampleParams(number_of_sampled_partitions=5,
                                     metrics=[pdp.Metrics.SUM])
        out = dict(pk.aggregate_true(data, params, extractors()))
        assert out["a"] == (20.0,)


class TestPeekerEngine:

    def test_aggregate_sketches_count(self):
        noise_ops.seed_host_rng(0)
        # Sketches: (pk, per-user count, partition_count)
        sketches = [("a", 2, 1)] * 500
        acc = pdp.NaiveBudgetAccountant(total_epsilon=BIG_EPS,
                                        total_delta=1e-6)
        engine = peeker.PeekerEngine(acc, pdp.LocalBackend())
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=2)
        result = engine.aggregate_sketches(sketches, params)
        acc.compute_budgets()
        out = dict(result)
        assert out["a"].count == pytest.approx(1000, rel=0.01)

    def test_aggregate_sketch_true(self):
        sketches = [("a", 5.0, 1), ("a", 3.0, 2), ("b", 1.0, 1)]
        out = dict(
            peeker.aggregate_sketch_true(pdp.LocalBackend(), sketches,
                                         pdp.Metrics.SUM))
        assert out["a"] == 8.0
        assert out["b"] == 1.0
