"""Streaming (multi-batch) fused-plane tests.

Strategy: force tiny chunks via the ``PIPELINEDP_TPU_STREAM_CHUNK`` env
knob so ordinary-size datasets stream through many batches, then apply
the same differential discipline as ``test_jax_engine``: at huge eps the
streamed result must match the LocalBackend oracle / exact aggregates
partition by partition, across metric combinations, bounding modes and
selection regimes. The chunked execution must be observable
(``timings["stream_batches"] > 1``) so these tests can't silently pass
through the single-batch path.
"""

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import streaming
from pipelinedp_tpu.backends import JaxBackend

BIG_EPS = 1e12


@pytest.fixture(autouse=True)
def tiny_chunks(monkeypatch):
    monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "997")


def run_streamed(ds, params, public=None, eps=BIG_EPS, delta=1e-2,
                 seed=0):
    acc = pdp.NaiveBudgetAccountant(total_epsilon=eps, total_delta=delta)
    engine = pdp.DPEngine(acc, JaxBackend(rng_seed=seed))
    res = engine.aggregate(ds, params, pdp.DataExtractors(),
                           public_partitions=public)
    acc.compute_budgets()
    got = dict(res)
    assert res.timings.get("stream_batches", 0) > 1, (
        "dataset did not stream — test is not covering the chunked path")
    return got


def make_ds(rng, n=12_000, users=2_000, parts=15):
    return pdp.ArrayDataset(privacy_ids=rng.integers(0, users, n),
                            partition_keys=rng.integers(0, parts, n),
                            values=rng.uniform(0.0, 10.0, n)), parts


class TestStreamedDifferential:
    """Huge-eps, non-binding caps: streamed == exact, per partition."""

    def test_count_sum_mean_variance_pid_count(self):
        rng = np.random.default_rng(1)
        ds, parts = make_ds(rng)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM, pdp.Metrics.MEAN,
                     pdp.Metrics.VARIANCE, pdp.Metrics.PRIVACY_ID_COUNT],
            max_partitions_contributed=parts,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=10.0)
        got = run_streamed(ds, params)
        pk = ds.partition_keys
        vals = ds.values
        pid = ds.privacy_ids
        assert len(got) == parts
        for p in range(parts):
            m = pk == p
            assert got[p].count == pytest.approx(m.sum(), abs=0.5)
            assert got[p].sum == pytest.approx(vals[m].sum(), rel=1e-5)
            assert got[p].mean == pytest.approx(vals[m].mean(), abs=1e-4)
            assert got[p].variance == pytest.approx(vals[m].var(),
                                                    abs=1e-2)
            assert got[p].privacy_id_count == pytest.approx(
                len(np.unique(pid[m])), abs=0.5)

    def test_matches_single_batch_aggregates(self):
        """Same dataset through the single-batch kernel (big chunk) and
        the streamed path: deterministic aggregates identical at huge
        eps, regardless of the different batch structure."""
        rng = np.random.default_rng(2)
        ds, parts = make_ds(rng, n=8_000)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=parts,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=10.0)
        streamed = run_streamed(ds, params, public=list(range(parts)))

        import os
        os.environ["PIPELINEDP_TPU_STREAM_CHUNK"] = str(1 << 26)
        ds.invalidate_cache()
        acc = pdp.NaiveBudgetAccountant(total_epsilon=BIG_EPS,
                                        total_delta=1e-2)
        engine = pdp.DPEngine(acc, JaxBackend(rng_seed=0))
        res = engine.aggregate(ds, params, pdp.DataExtractors(),
                               public_partitions=list(range(parts)))
        acc.compute_budgets()
        single = dict(res)
        for p in range(parts):
            assert streamed[p].count == pytest.approx(single[p].count,
                                                      abs=1e-3)
            assert streamed[p].sum == pytest.approx(single[p].sum,
                                                    rel=1e-5)

    def test_per_partition_bounds_mode(self):
        rng = np.random.default_rng(3)
        ds, parts = make_ds(rng)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.SUM],
            max_partitions_contributed=parts,
            max_contributions_per_partition=50,
            min_sum_per_partition=0.0, max_sum_per_partition=100.0)
        got = run_streamed(ds, params)
        pk, vals = ds.partition_keys, ds.values
        for p in range(parts):
            m = pk == p
            # Quantization grid is bound/2^23 per SEGMENT — keep the
            # clip bound realistic or the grid dominates the check.
            assert got[p].sum == pytest.approx(vals[m].sum(), rel=1e-4)

    def test_total_cap_bounding_invariants(self):
        """max_contributions binding: the per-pid sample differs between
        planes, so check invariants — global kept rows = sum over pids of
        min(rows, cap)."""
        rng = np.random.default_rng(4)
        n = 10_000
        pid = rng.integers(0, 300, n)  # ~33 rows/pid, cap at 10
        ds = pdp.ArrayDataset(privacy_ids=pid,
                              partition_keys=rng.integers(0, 8, n),
                              values=rng.uniform(0, 10, n))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT], max_contributions=10)
        got = run_streamed(ds, params, public=list(range(8)))
        expect = sum(min(c, 10) for c in np.bincount(pid))
        total = sum(m.count for m in got.values())
        assert total == pytest.approx(expect, rel=1e-3)

    def test_bounds_already_enforced(self):
        rng = np.random.default_rng(5)
        n = 9_000
        pk = rng.integers(0, 6, n)
        vals = rng.uniform(0, 5, n)
        ds = pdp.ArrayDataset(privacy_ids=None, partition_keys=pk,
                              values=vals)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=6,
            max_contributions_per_partition=3,
            min_value=0.0, max_value=5.0,
            contribution_bounds_already_enforced=True)
        got = run_streamed(ds, params, public=list(range(6)))
        for p in range(6):
            m = pk == p
            assert got[p].count == pytest.approx(m.sum(), abs=0.5)
            assert got[p].sum == pytest.approx(vals[m].sum(), rel=1e-5)

    def test_vector_sum(self):
        rng = np.random.default_rng(6)
        n = 6_000
        ds = pdp.ArrayDataset(
            privacy_ids=rng.integers(0, 1000, n),
            partition_keys=rng.integers(0, 4, n),
            values=rng.uniform(-1, 1, (n, 3)))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.VECTOR_SUM],
            max_partitions_contributed=4,
            max_contributions_per_partition=20,
            vector_size=3, vector_max_norm=100.0,
            vector_norm_kind=pdp.NormKind.Linf)
        got = run_streamed(ds, params, public=list(range(4)))
        for p in range(4):
            m = ds.partition_keys == p
            np.testing.assert_allclose(np.asarray(got[p].vector_sum),
                                       ds.values[m].sum(axis=0),
                                       rtol=1e-4, atol=1e-3)

    def test_private_selection_drops_small_partitions(self):
        """Selection statistics survive the streamed nseg accumulation:
        big partitions kept, single-user partitions dropped at modest
        eps."""
        rng = np.random.default_rng(7)
        n = 8_000
        pid = np.arange(n)  # every row its own user
        pk = np.where(np.arange(n) < 7_800,
                      rng.integers(0, 4, n), 4 + np.arange(n) % 150)
        ds = pdp.ArrayDataset(privacy_ids=pid, partition_keys=pk,
                              values=None)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PRIVACY_ID_COUNT],
            max_partitions_contributed=1,
            max_contributions_per_partition=1)
        got = run_streamed(ds, params, eps=5.0, delta=1e-5)
        kept = set(got)
        assert {0, 1, 2, 3} <= kept  # ~1950 users each: always kept
        # the ~150 single/double-user partitions are overwhelmingly
        # dropped
        assert len(kept - {0, 1, 2, 3}) < 20


class TestStreamedPercentiles:
    """Percentiles stream in two passes (mid histogram + chosen-subtree
    leaf histograms, both additive across batches); the walk math and
    PRNG node-noise keying are shared with the single-batch kernel."""

    def test_matches_exact_at_huge_eps(self):
        rng = np.random.default_rng(20)
        n = 18_000
        vals = rng.uniform(0, 10, n)
        pk = rng.integers(0, 6, n)
        ds = pdp.ArrayDataset(privacy_ids=rng.integers(0, 4_000, n),
                              partition_keys=pk, values=vals)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50), pdp.Metrics.PERCENTILE(90),
                     pdp.Metrics.VARIANCE, pdp.Metrics.COUNT],
            max_partitions_contributed=6,
            max_contributions_per_partition=30,
            min_value=0.0, max_value=10.0)
        got = run_streamed(ds, params)
        for p in range(6):
            m = pk == p
            e50, e90 = np.percentile(vals[m], [50, 90])
            assert got[p].percentile_50 == pytest.approx(e50, abs=0.15)
            assert got[p].percentile_90 == pytest.approx(e90, abs=0.15)
            assert got[p].variance == pytest.approx(vals[m].var(),
                                                    abs=0.05)
            assert got[p].count == pytest.approx(m.sum(), abs=0.5)

    def test_histograms_are_exactly_additive(self, monkeypatch):
        """The precision claim ("the streamed walk sees the same exact
        histograms") asserted EXACTLY: the mid-level histogram and the
        subtree leaf histograms accumulated over many tiny batches equal
        the single-batch computation bit-for-bit (non-binding caps, so
        bounding keeps every row on both sides)."""
        import jax
        import jax.numpy as jnp
        from pipelinedp_tpu import jax_engine as je
        from pipelinedp_tpu import streaming as sm

        rng = np.random.default_rng(30)
        n = 6_000
        ds = pdp.ArrayDataset(privacy_ids=rng.integers(0, 1_500, n),
                              partition_keys=rng.integers(0, 4, n),
                              values=rng.uniform(0, 10, n))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50),
                     pdp.Metrics.PERCENTILE(90)],
            max_partitions_contributed=4,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=10.0)
        config = je.FusedConfig.from_params(params, public=True)
        encoded = je._encode_arrays(ds, None, list(range(4)),
                                    require_pid=True)
        P_pad = je._pad_pow2(len(encoded.pk_vocab))
        key = jax.random.PRNGKey(5)
        _, _, n_mid, span = sm._tree_consts()
        sub_start = jnp.asarray(
            (np.arange(P_pad)[:, None] % 4 * span * np.ones(
                (1, 2))).astype(np.int32))

        def run_chunks(chunk):
            # batch count derived directly; this test drives the kernels
            # below the engine's env-var chunk mechanism
            n_batches = max(1, -(-n // chunk))
            order, counts = sm._batch_assignment(config, encoded,
                                                 n_batches, 5)
            pad_rows = je._pad_rows(int(counts.max()))
            mid_acc = None
            sub_acc = None
            offset = 0
            for b in range(n_batches):
                cnt = int(counts[b, 0])
                rows = (slice(offset, offset + cnt) if order is None
                        else order[offset:offset + cnt])
                offset += cnt
                pid_b = np.zeros(pad_rows, np.int32)
                pk_b = np.zeros(pad_rows, np.int32)
                pid_b[:cnt] = encoded.pid[rows]
                pk_b[:cnt] = encoded.pk[rows]
                vals_b = np.zeros(pad_rows, np.float32)
                vals_b[:cnt] = encoded.values[rows]
                planes = (je._narrow_ids(pid_b, "u16") +
                          je._narrow_ids(pk_b, "u16"))
                kb = jax.random.fold_in(jax.random.PRNGKey(5), b)
                _, _, mid = sm._partials_kernel(
                    config, P_pad, planes, jnp.asarray(vals_b),
                    jnp.int32(cnt), kb, 12, n_pid_planes=len(planes) - 1)
                sub = sm._pct_sub_kernel(
                    config, P_pad, planes, jnp.asarray(vals_b),
                    jnp.int32(cnt), kb, 12,
                    n_pid_planes=len(planes) - 1, sub_start=sub_start,
                    p_offset=jnp.int32(0), n_block=P_pad)
                mid_acc = mid if mid_acc is None else mid_acc + mid
                sub_acc = sub if sub_acc is None else sub_acc + sub
            return np.asarray(mid_acc), np.asarray(sub_acc)

        # Caps non-binding -> bounding keeps every row regardless of the
        # per-batch sampling keys, so one batch and 10 batches must
        # produce IDENTICAL integer histograms.
        mid_many, sub_many = run_chunks(599)
        mid_one, sub_one = run_chunks(1 << 26)
        np.testing.assert_array_equal(mid_many, mid_one)
        np.testing.assert_array_equal(sub_many, sub_one)
        assert int(mid_one.sum()) == n  # every row counted exactly once

    def test_walk_parity_with_single_batch(self, monkeypatch):
        """Same seed, non-binding caps: the streamed walk sees the same
        exact histograms (pinned bit-exactly by
        ``test_histograms_are_exactly_additive``) and the same
        (pk, node)-keyed noise as the single-batch walk. The two walks
        are separate XLA programs whose codegen (FMA fusion) may differ
        in the last float32 bit; when a noisy rank comparison sits
        within an ulp of a child boundary that last bit can flip the
        picked child — the same tie quirk ``TestFusedPercentile``
        documents — and a flip at the top level diverges by up to a
        4096-leaf parent width (~0.63 on [0, 10]), hence the loose
        value tolerance here; the precision burden lives in the
        histogram test above."""
        rng = np.random.default_rng(21)
        n = 10_000
        ds = pdp.ArrayDataset(privacy_ids=rng.integers(0, 2_500, n),
                              partition_keys=rng.integers(0, 4, n),
                              values=rng.uniform(0, 10, n))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50),
                     pdp.Metrics.PERCENTILE(95)],
            max_partitions_contributed=4,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=10.0)

        def run_with_chunk(chunk):
            monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", str(chunk))
            ds.invalidate_cache()
            acc = pdp.NaiveBudgetAccountant(total_epsilon=3.0,
                                            total_delta=1e-6)
            eng = pdp.DPEngine(acc, JaxBackend(rng_seed=7))
            res = eng.aggregate(ds, params, pdp.DataExtractors(),
                                public_partitions=list(range(4)))
            acc.compute_budgets()
            return dict(res), res.timings.get("stream_batches", 0)

        streamed, nb = run_with_chunk(997)
        single, nb2 = run_with_chunk(1 << 26)
        assert nb > 5 and nb2 == 0
        for p in range(4):
            assert streamed[p].percentile_50 == pytest.approx(
                single[p].percentile_50, abs=0.7)
            assert streamed[p].percentile_95 == pytest.approx(
                single[p].percentile_95, abs=0.7)

    def test_tiny_subhist_cap_chunks_quantiles(self, monkeypatch):
        """Past _SUBHIST_BYTE_CAP, pass B walks quantile GROUPS instead
        of refusing — and because node noise is a pure function of
        (partition, node id), the chunked walk must be BIT-IDENTICAL to
        the unchunked one."""
        from pipelinedp_tpu import jax_engine as je
        rng = np.random.default_rng(88)
        n = 5_000
        ds = pdp.ArrayDataset(
            privacy_ids=rng.integers(0, 1_200, n),
            partition_keys=rng.integers(0, 5, n),
            values=rng.uniform(0.0, 20.0, n))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(25), pdp.Metrics.PERCENTILE(50),
                     pdp.Metrics.PERCENTILE(75), pdp.Metrics.PERCENTILE(95)],
            max_partitions_contributed=5,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=20.0)

        def run(want_rounds):
            ds.invalidate_cache()
            acc = pdp.NaiveBudgetAccountant(total_epsilon=BIG_EPS,
                                            total_delta=1e-2)
            engine = pdp.DPEngine(acc, JaxBackend(rng_seed=7))
            res = engine.aggregate(ds, params, pdp.DataExtractors(),
                                   public_partitions=list(range(5)))
            acc.compute_budgets()
            got = dict(res)
            assert res.timings["stream_batches"] > 1
            # Guard against the test going vacuous: the chunking must
            # actually have happened (or actually not have).
            assert res.timings["stream_pass_b_rounds"] == want_rounds
            return got

        full = run(want_rounds=1)
        # Cap fits exactly ONE quantile's [P_pad, 1, span] block ->
        # 4 pass-B rounds.
        _, _, _, span = streaming._tree_consts()
        monkeypatch.setattr(je, "_SUBHIST_BYTE_CAP", 8 * span * 4)
        chunked = run(want_rounds=4)
        for p in range(5):
            for f in ("percentile_25", "percentile_50", "percentile_75",
                      "percentile_95"):
                assert getattr(chunked[p], f) == getattr(full[p], f), (
                    p, f)
        # A cap below even ONE quantile's [P_pad, 1, span] block now
        # partition-block-chunks instead of refusing: blocks of 4
        # partitions (P_pad = 8) x 4 single-quantile groups = 8 rounds,
        # still bit-identical (node noise is keyed by the GLOBAL
        # partition id).
        monkeypatch.setattr(je, "_SUBHIST_BYTE_CAP", 4 * span * 4)
        p_chunked = run(want_rounds=8)
        for p in range(5):
            for f in ("percentile_25", "percentile_50", "percentile_75",
                      "percentile_95"):
                assert getattr(p_chunked[p], f) == getattr(full[p], f), (
                    p, f)
        # Only a cap below a single [1, 1, span] block is refused.
        monkeypatch.setattr(je, "_SUBHIST_BYTE_CAP", 4)
        with pytest.raises(NotImplementedError, match="subtree block"):
            run(want_rounds=0)

    def test_pass_b_reship_matches_device_cache(self, monkeypatch):
        """Pass B over the device-resident batch cache and pass B
        re-shipping every batch must produce IDENTICAL percentiles
        (same (b, arrays) -> same kernels), and both sources must be
        observable in timings."""
        rng = np.random.default_rng(77)
        n = 6_000
        ds = pdp.ArrayDataset(
            privacy_ids=rng.integers(0, 1_500, n),
            partition_keys=rng.integers(0, 6, n),
            values=rng.uniform(0.0, 50.0, n))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50), pdp.Metrics.PERCENTILE(95)],
            max_partitions_contributed=6,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=50.0)

        def run(cache_bytes):
            monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CACHE",
                               str(cache_bytes))
            ds.invalidate_cache()
            acc = pdp.NaiveBudgetAccountant(total_epsilon=BIG_EPS,
                                            total_delta=1e-2)
            engine = pdp.DPEngine(acc, JaxBackend(rng_seed=3))
            res = engine.aggregate(ds, params, pdp.DataExtractors(),
                                   public_partitions=list(range(6)))
            acc.compute_budgets()
            got = dict(res)
            assert res.timings["stream_batches"] > 1
            return got, res.timings["stream_pass_b"]

        cached, src_c = run(1 << 30)
        reshipped, src_r = run(0)
        assert src_c == "device_cache" and src_r == "reship"
        for p in range(6):
            assert cached[p].percentile_50 == reshipped[p].percentile_50
            assert cached[p].percentile_95 == reshipped[p].percentile_95

    def test_private_selection_with_percentiles(self):
        rng = np.random.default_rng(22)
        n = 8_000
        pk = rng.integers(0, 5, n)
        vals = rng.uniform(0, 10, n)
        ds = pdp.ArrayDataset(privacy_ids=np.arange(n),
                              partition_keys=pk, values=vals)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50)],
            max_partitions_contributed=5,
            max_contributions_per_partition=1,
            min_value=0.0, max_value=10.0)
        got = run_streamed(ds, params, eps=1e6, delta=1e-3)
        assert set(got) == set(range(5))
        for p in range(5):
            assert got[p].percentile_50 == pytest.approx(
                np.percentile(vals[pk == p], 50), abs=0.2)


class TestStreamedSelectPartitions:

    def test_select_partitions_streams(self):
        rng = np.random.default_rng(10)
        n = 9_000
        pk = np.concatenate([rng.integers(0, 6, n - 60),
                             6 + np.arange(60) % 30])
        ds = pdp.ArrayDataset(privacy_ids=np.arange(n),
                              partition_keys=pk, values=None)
        acc = pdp.NaiveBudgetAccountant(total_epsilon=5.0,
                                        total_delta=1e-6)
        engine = pdp.DPEngine(acc, JaxBackend(rng_seed=0))
        res = engine.select_partitions(
            ds, pdp.SelectPartitionsParams(max_partitions_contributed=3),
            pdp.DataExtractors())
        acc.compute_budgets()
        kept = set(res)
        # ~1500-user partitions always keep; 1-2-user tails drop.
        assert {0, 1, 2, 3, 4, 5} <= kept
        assert len(kept - {0, 1, 2, 3, 4, 5}) < 10


class TestStreamedFuzz:
    """Randomized parameter points through the streamed path (the
    streaming analogue of test_differential_fuzz): huge eps, non-binding
    caps, public partitions — streamed results must equal the exact
    aggregates partition by partition."""

    @pytest.mark.parametrize("seed", range(6))
    def test_random_config(self, seed):
        rng = np.random.default_rng(2000 + seed)
        n = int(rng.integers(4_000, 15_000))
        users = int(rng.integers(200, 3_000))
        parts = int(rng.integers(3, 25))
        ds = pdp.ArrayDataset(
            privacy_ids=rng.integers(0, users, n),
            partition_keys=rng.integers(0, parts, n),
            values=rng.uniform(0.0, 10.0, n))
        combos = [
            [pdp.Metrics.COUNT],
            [pdp.Metrics.SUM, pdp.Metrics.COUNT],
            [pdp.Metrics.MEAN, pdp.Metrics.VARIANCE],
            [pdp.Metrics.PRIVACY_ID_COUNT, pdp.Metrics.SUM],
        ]
        metrics = combos[int(rng.integers(0, len(combos)))]
        params = pdp.AggregateParams(
            metrics=metrics,
            noise_kind=(pdp.NoiseKind.LAPLACE if rng.random() < 0.5
                        else pdp.NoiseKind.GAUSSIAN),
            max_partitions_contributed=parts,
            max_contributions_per_partition=200,
            min_value=0.0, max_value=10.0)
        got = run_streamed(ds, params, public=list(range(parts)),
                           seed=seed)
        pk, vals = ds.partition_keys, ds.values
        for p in range(parts):
            m = pk == p
            if pdp.Metrics.COUNT in metrics:
                assert got[p].count == pytest.approx(m.sum(), abs=0.5)
            if pdp.Metrics.SUM in metrics:
                assert got[p].sum == pytest.approx(vals[m].sum(),
                                                   rel=1e-4, abs=0.1)
            if pdp.Metrics.MEAN in metrics:
                assert got[p].mean == pytest.approx(vals[m].mean(),
                                                    abs=1e-3)
            if pdp.Metrics.VARIANCE in metrics:
                assert got[p].variance == pytest.approx(vals[m].var(),
                                                        abs=0.05)
            if pdp.Metrics.PRIVACY_ID_COUNT in metrics:
                assert got[p].privacy_id_count == pytest.approx(
                    len(np.unique(ds.privacy_ids[m])), abs=0.5)


class TestStreamedOnMesh:
    """Streaming composed with the device mesh (VERDICT r4 #3): chunks
    shard over the 8-device CPU mesh, owner-block partials fold into
    the same host accumulators, results match the oracle."""

    def _mesh_backend(self, seed=0):
        from pipelinedp_tpu.parallel import make_mesh
        return JaxBackend(rng_seed=seed, mesh=make_mesh())

    def run_mesh_streamed(self, ds, params, public=None, eps=BIG_EPS,
                          min_batches=3):
        acc = pdp.NaiveBudgetAccountant(total_epsilon=eps,
                                        total_delta=1e-2)
        engine = pdp.DPEngine(acc, self._mesh_backend())
        res = engine.aggregate(ds, params, pdp.DataExtractors(),
                               public_partitions=public)
        acc.compute_budgets()
        got = dict(res)
        assert res.timings.get("stream_batches", 0) >= min_batches, (
            "dataset did not stream over enough chunks on the mesh")
        return got

    def test_matches_exact_on_mesh(self, monkeypatch):
        """≥3 chunks over the 8-device mesh match the exact aggregates
        (the verdict's Done criterion)."""
        # Mesh chunk budget is per device: 8 devices x 500 rows/chunk
        # over 23k rows -> >= 5 batches.
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "500")
        rng = np.random.default_rng(40)
        n, parts = 23_000, 15
        ds = pdp.ArrayDataset(
            privacy_ids=rng.integers(0, 3_000, n),
            partition_keys=rng.integers(0, parts, n),
            values=rng.uniform(0.0, 10.0, n))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM, pdp.Metrics.MEAN,
                     pdp.Metrics.PRIVACY_ID_COUNT],
            max_partitions_contributed=parts,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=10.0)
        got = self.run_mesh_streamed(ds, params)
        pk, vals, pid = ds.partition_keys, ds.values, ds.privacy_ids
        assert len(got) == parts
        for p in range(parts):
            m = pk == p
            assert got[p].count == pytest.approx(m.sum(), abs=0.5)
            assert got[p].sum == pytest.approx(vals[m].sum(), rel=1e-5)
            assert got[p].mean == pytest.approx(vals[m].mean(), abs=1e-4)
            assert got[p].privacy_id_count == pytest.approx(
                len(np.unique(pid[m])), abs=0.5)

    def test_percentiles_stream_on_mesh(self, monkeypatch):
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "400")
        rng = np.random.default_rng(41)
        n = 10_000
        ds = pdp.ArrayDataset(
            privacy_ids=rng.integers(0, 2_000, n),
            partition_keys=rng.integers(0, 4, n),
            values=rng.uniform(0.0, 100.0, n))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50), pdp.Metrics.PERCENTILE(90)],
            max_partitions_contributed=4,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=100.0)
        got = self.run_mesh_streamed(ds, params, public=list(range(4)))
        for p in range(4):
            true = np.percentile(ds.values[ds.partition_keys == p],
                                 [50, 90])
            assert got[p].percentile_50 == pytest.approx(true[0], abs=0.5)
            assert got[p].percentile_90 == pytest.approx(true[1], abs=0.5)

    def test_private_selection_with_percentiles_on_mesh(self,
                                                        monkeypatch):
        """PRIVATE selection + two-pass percentiles, streamed over the
        mesh: the selection kernel runs (not the public bypass) and the
        kept partitions carry accurate medians. At huge eps selection
        keeps everything it sees — the DROPPING behavior on the mesh
        stream is pinned at moderate eps by
        ``TestStreamedOnMesh.test_select_partitions_streams_on_mesh``."""
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "400")
        rng = np.random.default_rng(45)
        n = 9_000
        pid = rng.integers(0, 2_500, n)
        pk = np.where(np.arange(n) % 20 < 19, rng.integers(0, 4, n),
                      4 + (np.arange(n) % 150))
        ds = pdp.ArrayDataset(privacy_ids=pid,
                              partition_keys=pk.astype(np.int64),
                              values=rng.uniform(0.0, 40.0, n))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.PERCENTILE(50)],
            max_partitions_contributed=4,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=40.0)
        got = self.run_mesh_streamed(ds, params, eps=1e6)
        assert set(range(4)) <= set(got)
        for p in range(4):
            m = pk == p
            true = float(np.percentile(ds.values[m], 50))
            assert got[p].percentile_50 == pytest.approx(true, abs=1.0)

    def test_vector_sum_streams_on_mesh(self, monkeypatch):
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "300")
        rng = np.random.default_rng(42)
        n, d = 6_000, 3
        ds = pdp.ArrayDataset(
            privacy_ids=rng.integers(0, 1_500, n),
            partition_keys=rng.integers(0, 5, n),
            values=rng.normal(0.0, 1.0, (n, d)).astype(np.float32))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.VECTOR_SUM], vector_size=d,
            vector_max_norm=1e9,
            vector_norm_kind=pdp.NormKind.Linf,
            max_partitions_contributed=5,
            max_contributions_per_partition=50)
        got = self.run_mesh_streamed(ds, params, public=list(range(5)))
        for p in range(5):
            true = ds.values[ds.partition_keys == p].sum(axis=0)
            np.testing.assert_allclose(got[p].vector_sum, true,
                                       rtol=1e-4, atol=1e-2)

    def test_select_partitions_streams_on_mesh(self, monkeypatch):
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "500")
        rng = np.random.default_rng(43)
        n = 12_000
        # 5 heavy partitions + a long tail of SINGLE-user partitions
        # (every tail row is its own partition): selection at moderate
        # eps must keep the heavy ones AND drop the tail — both sides
        # asserted, so a selection regression that keeps everything
        # cannot pass.
        pid = rng.integers(0, 4_000, n)
        pk = np.where(np.arange(n) % 10 < 9, rng.integers(0, 5, n),
                      5 + np.arange(n))
        ds = pdp.ArrayDataset(privacy_ids=pid,
                              partition_keys=pk.astype(np.int64),
                              values=None)
        acc = pdp.NaiveBudgetAccountant(total_epsilon=10.0,
                                        total_delta=1e-4)
        engine = pdp.DPEngine(acc, self._mesh_backend())
        params = pdp.SelectPartitionsParams(max_partitions_contributed=3)
        res = engine.select_partitions(ds, params, pdp.DataExtractors())
        acc.compute_budgets()
        kept = set(res)
        assert set(range(5)) <= kept
        tail_kept = [p for p in kept if p >= 5]
        # ~1200 single-user partitions; DP selection at eps=10 keeps a
        # single-user partition with vanishing probability (measured: 0
        # kept for this seed; allow a handful of probabilistic strays).
        assert len(tail_kept) <= 5, tail_kept

    def test_mesh_streamed_matches_single_device_streamed(self,
                                                          monkeypatch):
        """Same seed, same dataset: mesh streaming and single-device
        streaming agree exactly on the deterministic aggregates at huge
        eps with non-binding caps (different bounding subsample is
        irrelevant when nothing is dropped)."""
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "600")
        rng = np.random.default_rng(44)
        n, parts = 11_000, 8
        ds = pdp.ArrayDataset(
            privacy_ids=rng.integers(0, 2_500, n),
            partition_keys=rng.integers(0, parts, n),
            values=rng.uniform(0.0, 10.0, n))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=parts,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=10.0)
        mesh_got = self.run_mesh_streamed(ds, params,
                                          public=list(range(parts)))
        ds.invalidate_cache()
        single_got = run_streamed(ds, params, public=list(range(parts)))
        for p in range(parts):
            assert mesh_got[p].count == pytest.approx(
                single_got[p].count, abs=1e-3)
            assert mesh_got[p].sum == pytest.approx(
                single_got[p].sum, rel=1e-5)


class TestStreamingInternals:

    def test_pid_batches_are_disjoint(self):
        """Every privacy unit's rows land in exactly one batch."""
        from pipelinedp_tpu import jax_engine as je
        rng = np.random.default_rng(8)
        n = 5_000
        pid = rng.integers(0, 400, n)
        enc = je.EncodedData(pid=pid.astype(np.int32),
                             pk=np.zeros(n, np.int32),
                             values=np.zeros(n, np.float32),
                             pk_vocab=[0], n_rows=n)
        config = je.FusedConfig.from_params(
            pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                max_partitions_contributed=1,
                                max_contributions_per_partition=1),
            public=True)
        for n_dev in (1, 8):
            order, counts = streaming._batch_assignment(config, enc, 7,
                                                        123, n_dev)
            assert counts.shape == (7, n_dev)
            seen = {}
            offset = 0
            for cell, c in enumerate(counts.ravel()):
                # A unit's rows must stay within ONE (batch, shard) cell.
                cell_pids = set(
                    pid[order[offset:offset + int(c)]].tolist())
                for u in cell_pids:
                    assert seen.setdefault(u, cell) == cell
                offset += int(c)
            assert offset == n

    def test_wide_id_space_streams_exactly(self):
        """Privacy ids >= 2^24 force the "i32" plane spec, whose narrow
        planes ARE the reused staging buffer — the ship path must copy
        them (the delayed fold means the previous batch's kernel may
        still be reading its input when the next batch stages)."""
        from pipelinedp_tpu import jax_engine as je
        rng = np.random.default_rng(55)
        n = 9_000
        pid = rng.integers((1 << 24) + 1, 1 << 30, n)
        ds = pdp.ArrayDataset(
            privacy_ids=pid,
            partition_keys=rng.integers(0, 10, n),
            values=rng.uniform(0.0, 10.0, n))
        enc = je.encode(ds, pdp.DataExtractors(), None, None)
        # The guard must hold on the ENCODED ids (what ships): if a
        # future encode densifies pids this test must fail loudly
        # rather than silently stop covering the i32 path.
        assert je._plane_spec(int(enc.pid.max())) == "i32"
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=10,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=10.0)
        got = run_streamed(ds, params, public=list(range(10)))
        for p in range(10):
            m = ds.partition_keys == p
            assert got[p].count == pytest.approx(m.sum(), abs=0.5)
            assert got[p].sum == pytest.approx(ds.values[m].sum(),
                                               rel=1e-5)

    def test_chunk_target_capped_by_lane_capacity(self, monkeypatch):
        """A big mesh must not scale value-config batches past the
        global fixed-point lane capacity (the psum makes lane capacity
        a per-batch GLOBAL bound) — and the capped target must itself
        be plannable."""
        from pipelinedp_tpu import jax_engine as je
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", str(1 << 26))
        value_config = je.FusedConfig.from_params(
            pdp.AggregateParams(
                metrics=[pdp.Metrics.SUM], max_partitions_contributed=1,
                max_contributions_per_partition=1, min_value=0.0,
                max_value=1.0), public=True)
        count_config = je.FusedConfig.from_params(
            pdp.AggregateParams(
                metrics=[pdp.Metrics.COUNT],
                max_partitions_contributed=1,
                max_contributions_per_partition=1), public=True)
        capped = streaming.chunk_target_rows(value_config, 8)
        assert capped <= je._fx_max_rows() < (1 << 26) * 8
        je._fx_plan(capped)  # must not raise
        assert streaming.chunk_target_rows(count_config, 8) == (1 << 26) * 8
        # Count columns are int32 psums: a giant mesh must not form a
        # batch that could wrap them.
        assert streaming.chunk_target_rows(count_config, 64) < (1 << 31)
        # And therefore: every row count above the single-batch lane cap
        # streams on a mesh — no dead zone between the caps.
        class _FakeMesh:
            class devices:
                size = 8
        assert streaming.should_stream(value_config,
                                       je._fx_max_rows() + 1, _FakeMesh)

    def test_exact_lane_accumulation_across_batches(self):
        """Adversarial equal values summed across many batches stay
        exact (float32 single-batch accumulation would drift)."""
        n = 30_000
        ds = pdp.ArrayDataset(
            privacy_ids=np.arange(n) % 5_000,
            partition_keys=np.zeros(n, np.int64),
            values=np.full(n, 7.25))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.SUM], max_partitions_contributed=1,
            max_contributions_per_partition=10,
            min_value=0.0, max_value=10.0)
        got = run_streamed(ds, params, public=[0])
        assert got[0].sum == pytest.approx(7.25 * n, rel=1e-6)

    def test_count_only_streams_past_lane_plan(self, monkeypatch):
        """Streaming must never consult the single-batch lane plan for
        pipelines with no fixed-point columns."""
        from pipelinedp_tpu import jax_engine as je
        monkeypatch.setattr(
            je, "_fx_plan",
            lambda n: (_ for _ in ()).throw(AssertionError("no plan")))
        rng = np.random.default_rng(9)
        ds = pdp.ArrayDataset(privacy_ids=rng.integers(0, 500, 4_000),
                              partition_keys=rng.integers(0, 5, 4_000),
                              values=None)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT], max_partitions_contributed=5,
            max_contributions_per_partition=20)
        got = run_streamed(ds, params, public=list(range(5)))
        assert len(got) == 5
