"""Backend conformance suite: every op of every backend is tested against
the same expected collections (the reference's pattern,
``tests/pipeline_backend_test.py:31-614``). Multiprocessing functions live
at module level so they pickle into worker processes."""

import operator

import pytest

from pipelinedp_tpu import pipeline_backend
from pipelinedp_tpu.ops import noise as noise_ops


def double(x):
    return 2 * x


def explode(x):
    return [x, x]


def add_pair(a, b):
    return a + b


def is_even(x):
    return x % 2 == 0


def kv_swap(k, v):
    return (v, k)


class _SumCombiner:

    def merge_accumulators(self, a, b):
        return a + b


def _run(col):
    """Materializes any backend collection (element order is not part of
    the op contract, so results are sorted)."""
    return sorted(list(col))


BACKENDS = [
    pipeline_backend.LocalBackend(),
    pipeline_backend.MultiProcLocalBackend(n_jobs=2, chunk_size=4),
]
IDS = ["local", "multiproc"]


@pytest.mark.parametrize("backend", BACKENDS, ids=IDS)
class TestBackendConformance:

    def test_map(self, backend):
        assert _run(backend.map([1, 2, 3], double, "map")) == [2, 4, 6]

    def test_flat_map(self, backend):
        assert _run(backend.flat_map([1, 2], explode,
                                     "fm")) == [1, 1, 2, 2]

    def test_map_tuple(self, backend):
        got = _run(backend.map_tuple([(1, "a"), (2, "b")], kv_swap, "mt"))
        assert got == [("a", 1), ("b", 2)]

    def test_map_values(self, backend):
        got = _run(backend.map_values([(1, 2), (2, 3)], double, "mv"))
        assert got == [(1, 4), (2, 6)]

    def test_group_by_key(self, backend):
        got = dict(backend.group_by_key([(1, "a"), (2, "b"), (1, "c")],
                                        "gbk"))
        assert sorted(got[1]) == ["a", "c"]
        assert got[2] == ["b"]

    def test_filter(self, backend):
        assert _run(backend.filter([1, 2, 3, 4], is_even, "f")) == [2, 4]

    def test_filter_by_key(self, backend):
        col = [(1, "a"), (2, "b"), (3, "c")]
        got = _run(backend.filter_by_key(col, [1, 3], "fbk"))
        assert got == [(1, "a"), (3, "c")]

    def test_keys_values(self, backend):
        col = [(1, "a"), (2, "b")]
        assert _run(backend.keys(col, "k")) == [1, 2]
        assert _run(backend.values(col, "v")) == ["a", "b"]

    def test_sample_fixed_per_key(self, backend):
        noise_ops.seed_host_rng(0)
        col = [(1, i) for i in range(100)] + [(2, 0)]
        got = dict(backend.sample_fixed_per_key(col, 5, "sample"))
        assert len(got[1]) == 5
        assert set(got[1]) <= set(range(100))
        assert got[2] == [0]

    def test_count_per_element(self, backend):
        got = dict(backend.count_per_element(["a", "b", "a"], "cpe"))
        assert got == {"a": 2, "b": 1}

    def test_sum_per_key(self, backend):
        got = dict(backend.sum_per_key([(1, 2), (1, 3), (2, 5)], "spk"))
        assert got == {1: 5, 2: 5}

    def test_combine_accumulators_per_key(self, backend):
        got = dict(
            backend.combine_accumulators_per_key(
                [(1, 2), (1, 3), (2, 5)], _SumCombiner(), "combine"))
        assert got == {1: 5, 2: 5}

    def test_reduce_per_key(self, backend):
        got = dict(
            backend.reduce_per_key([(1, 2), (1, 3)], add_pair, "reduce"))
        assert got == {1: 5}

    def test_flatten(self, backend):
        got = _run(backend.flatten(([1, 2], [3]), "flat"))
        assert got == [1, 2, 3]

    def test_distinct(self, backend):
        assert _run(backend.distinct([1, 2, 1, 3], "d")) == [1, 2, 3]

    def test_to_list(self, backend):
        got = list(backend.to_list([1, 2, 3], "tl"))
        assert got == [[1, 2, 3]]

    def test_laziness_chain(self, backend):
        # A multi-stage chain end-to-end.
        col = backend.map([1, 2, 3, 4], double, "m")  # 2,4,6,8
        col = backend.filter(col, is_even, "f")  # all
        col = backend.map(col, double, "m2")  # 4,8,12,16
        assert _run(col) == [4, 8, 12, 16]


class TestLocalBackendLaziness:

    def test_generators_are_lazy(self):
        calls = []

        def track(x):
            calls.append(x)
            return x

        backend = pipeline_backend.LocalBackend()
        col = backend.map([1, 2, 3], track, "m")
        assert calls == []  # nothing executed yet
        list(col)
        assert calls == [1, 2, 3]

    def test_to_multi_transformable(self):
        backend = pipeline_backend.LocalBackend()
        col = backend.map([1, 2], double, "m")
        col = backend.to_multi_transformable_collection(col)
        assert list(col) == [2, 4]
        assert list(col) == [2, 4]  # second pass works


class TestUniqueLabels:

    def test_unique_labels(self):
        gen = pipeline_backend.UniqueLabelsGenerator("sfx")
        a = gen.unique("stage")
        b = gen.unique("stage")
        c = gen.unique("")
        assert a == "stage_sfx"
        assert b == "stage_1_sfx"
        assert "UNDEFINED" in c
        assert len({a, b, c}) == 3


class TestAnnotators:

    def test_annotator_applied(self):

        class Recorder(pipeline_backend.Annotator):

            def __init__(self):
                self.calls = []

            def annotate(self, col, params=None, budget=None):
                self.calls.append((params, budget))
                return col

        rec = Recorder()
        pipeline_backend.register_annotator(rec)
        try:
            backend = pipeline_backend.LocalBackend()
            col = backend.annotate([1, 2], "ann", params="p", budget="b")
            assert list(col) == [1, 2]
            assert rec.calls == [("p", "b")]
        finally:
            pipeline_backend._annotators.remove(rec)


def _draw_worker_noise(_):
    """Module-level (picklable) helper: draws from the worker's host RNG.
    The sleep keeps each worker busy long enough that no single worker can
    drain the whole task queue — every worker must participate, otherwise
    the test could pass trivially (8 sequential draws from ONE shared RNG
    state are also distinct)."""
    import os
    import time
    from pipelinedp_tpu.ops import noise as noise_ops
    draw = tuple(noise_ops.np_laplace(1.0, shape=4).tolist())
    time.sleep(0.2)
    return os.getpid(), draw


class TestMultiProcWorkerSeeding:

    def test_workers_draw_distinct_noise(self):
        """Forked pool workers must NOT inherit identical RNG state:
        identical noise streams across workers cancel in pairwise partition
        differences, voiding DP (advisor finding, round 1)."""
        backend = pipeline_backend.MultiProcLocalBackend(n_jobs=4)
        try:
            results = backend._pool().map(_draw_worker_noise, range(8),
                                          chunksize=1)
        finally:
            backend.close()
        first_draw_per_pid = {}
        for pid, draw in results:
            first_draw_per_pid.setdefault(pid, draw)
        assert len(first_draw_per_pid) >= 2, (
            "need at least two workers to exercise the regression")
        draws = list(first_draw_per_pid.values())
        assert len(set(draws)) == len(draws), (
            "two pool workers produced identical noise streams")
