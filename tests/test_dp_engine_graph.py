"""Graph-shape tests: assert WHICH nodes the engine builds and with what
arguments, using mocked bounders/combiners/selection — the reference's
``tests/dp_engine_test.py:209-389`` pattern (mock.patch over node
factories, deterministic mock selection strategies, annotator budgets)
without depending on DP randomness."""

import operator
from unittest import mock

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import dp_engine as dp_engine_mod
from pipelinedp_tpu import pipeline_backend


def extractors():
    return pdp.DataExtractors(privacy_id_extractor=operator.itemgetter(0),
                              partition_extractor=operator.itemgetter(1),
                              value_extractor=operator.itemgetter(2))


def data(n_users=10, n_parts=4, rows_per=3):
    return [(u, p, 1.0) for u in range(n_users) for p in range(n_parts)
            for _ in range(rows_per)]


def count_params(**kw):
    base = dict(metrics=[pdp.Metrics.COUNT], max_partitions_contributed=4,
                max_contributions_per_partition=4)
    base.update(kw)
    return pdp.AggregateParams(**base)


def make_engine(eps=1e5, delta=1e-2):
    acc = pdp.NaiveBudgetAccountant(total_epsilon=eps, total_delta=delta)
    return pdp.DPEngine(acc, pdp.LocalBackend()), acc


class TestGraphShape:

    def test_bounder_receives_graph_arguments(self):
        """The engine hands the bounder (col, params, backend, report,
        create_accumulator) — reference dp_engine_test.py:209-241."""
        engine, acc = make_engine()
        params = count_params()
        bounder = mock.MagicMock()
        bounder.bound_contributions.return_value = []
        with mock.patch.object(pdp.DPEngine, "_create_contribution_bounder",
                               return_value=bounder):
            engine.aggregate(data(), params, extractors())
        acc.compute_budgets()
        assert bounder.bound_contributions.call_count == 1
        args = bounder.bound_contributions.call_args[0]
        assert list(args[0]) == [(u, p, 1.0) for (u, p, _) in data()]
        assert args[1] is params
        assert isinstance(args[2], pdp.LocalBackend)
        assert callable(args[4])  # combiner.create_accumulator

    def test_bounder_choice_depends_on_params(self):
        engine, _ = make_engine()
        from pipelinedp_tpu import contribution_bounders as cb
        assert isinstance(
            engine._create_contribution_bounder(count_params()),
            cb.SamplingCrossAndPerPartitionContributionBounder)
        assert isinstance(
            engine._create_contribution_bounder(
                count_params(max_contributions=4,
                             max_partitions_contributed=None,
                             max_contributions_per_partition=None)),
            cb.SamplingPerPrivacyIdContributionBounder)

    def test_public_partitions_drop_node_built(self):
        """Public partitions insert the drop node before extraction —
        reference dp_engine_test.py:243-266."""
        engine, acc = make_engine()
        original = pdp.DPEngine._drop_not_public_partitions
        with mock.patch.object(pdp.DPEngine, "_drop_not_public_partitions",
                               side_effect=original,
                               autospec=True) as drop:
            out = engine.aggregate(data(), count_params(),
                                   extractors(),
                                   public_partitions=[0, 1, 99])
            acc.compute_budgets()
            result = dict(out)
        assert drop.call_count == 1
        assert drop.call_args[0][2] == [0, 1, 99]
        # Non-public partitions 2, 3 dropped; missing public 99 injected.
        assert sorted(result) == [0, 1, 99]

    def test_public_partitions_already_filtered_skips_drop(self):
        engine, acc = make_engine()
        with mock.patch.object(pdp.DPEngine,
                               "_drop_not_public_partitions") as drop:
            out = engine.aggregate(
                data(), count_params(public_partitions_already_filtered=True),
                extractors(), public_partitions=[0, 1, 2, 3])
            acc.compute_budgets()
            list(out)
        drop.assert_not_called()

    def test_mock_selection_strategy_controls_kept_partitions(self):
        """Deterministic partition selection via a mocked strategy object —
        reference dp_engine_test.py:290-315."""

        class MockStrategy:
            def should_keep(self, num_users):
                return num_users >= 8

        # 10 users hit partitions 0..3; partition 3 additionally loses
        # users (only 5 contribute).
        rows = [(u, p, 1.0) for u in range(10) for p in range(3)]
        rows += [(u, 3, 1.0) for u in range(5)]
        engine, acc = make_engine()
        with mock.patch.object(dp_engine_mod,
                               "_cached_partition_selection_strategy",
                               return_value=MockStrategy()):
            out = engine.aggregate(rows, count_params(), extractors())
            acc.compute_budgets()
            result = dict(out)
        assert sorted(result) == [0, 1, 2]  # partition 3: 5 users < 8

    def test_custom_combiner_factory_node(self):
        """custom_combiners route through the dedicated factory —
        reference dp_engine_test.py:757-780."""
        from pipelinedp_tpu import combiners as combiners_mod

        class Custom(combiners_mod.CustomCombiner):
            def create_accumulator(self, values):
                return len(list(values))

            def merge_accumulators(self, a, b):
                return a + b

            def compute_metrics(self, acc):
                return {"n": acc}

            def metrics_names(self):
                return ["n"]

            def request_budget(self, budget_accountant):
                self._budget = budget_accountant.request_budget(
                    pdp.MechanismType.LAPLACE)

            def explain_computation(self):
                return lambda: "custom"

        engine, acc = make_engine()
        custom = Custom()
        params = pdp.AggregateParams(max_partitions_contributed=2,
                                     max_contributions_per_partition=2,
                                     custom_combiners=[custom])
        with mock.patch.object(
                combiners_mod, "create_compound_combiner_with_custom_combiners",
                side_effect=combiners_mod.
                create_compound_combiner_with_custom_combiners) as factory:
            out = engine.aggregate(data(), params, extractors())
            acc.compute_budgets()
            list(out)
        assert factory.call_count == 1
        assert factory.call_args[0][2] == [custom]

    def test_annotators_receive_per_aggregation_budget(self):
        """Annotators get (params, per-aggregation Budget) at each
        aggregation — reference dp_engine_test.py:782-808."""
        seen = []

        class Recorder(pipeline_backend.Annotator):
            def annotate(self, col, params=None, budget=None):
                seen.append((params, budget))
                return col

        rec = Recorder()
        pipeline_backend.register_annotator(rec)
        try:
            # Declared pipeline shape makes per-aggregation budgets
            # knowable at aggregation time (reference semantics).
            acc = pdp.NaiveBudgetAccountant(total_epsilon=3.0,
                                            total_delta=3e-6,
                                            aggregation_weights=[1, 2])
            engine = pdp.DPEngine(acc, pdp.LocalBackend())
            p1 = count_params(budget_weight=1)
            p2 = count_params(budget_weight=2)
            r1 = engine.aggregate(data(), p1, extractors())
            r2 = engine.aggregate(data(), p2, extractors())
            acc.compute_budgets()
            list(r1), list(r2)
        finally:
            pipeline_backend._annotators.remove(rec)
        assert len(seen) == 2
        (params1, b1), (params2, b2) = seen
        assert params1 is p1 and params2 is p2
        # Weighted split of the total (ε, δ): 1:2.
        assert b1.epsilon == pytest.approx(1.0)
        assert b2.epsilon == pytest.approx(2.0)
        assert b1.delta == pytest.approx(1e-6)
        assert b2.delta == pytest.approx(2e-6)

    def test_budget_annotation_none_without_declared_shape(self):
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1.0, total_delta=1e-6)
        assert acc._compute_budget_for_aggregation(1.0) is None

    def test_selection_budget_requested_only_for_private(self):
        engine, acc = make_engine()
        out = engine.aggregate(data(), count_params(), extractors(),
                               public_partitions=[0, 1])
        n_public = len(acc._mechanisms)
        engine2, acc2 = make_engine()
        out2 = engine2.aggregate(data(), count_params(), extractors())
        n_private = len(acc2._mechanisms)
        # Private selection adds exactly one GENERIC mechanism request.
        assert n_private == n_public + 1

    def test_bounds_already_enforced_skips_bounder(self):
        engine, acc = make_engine()
        rows = [(0, 1.0), (0, 2.0), (1, 1.0)]
        ex = pdp.DataExtractors(partition_extractor=operator.itemgetter(0),
                                value_extractor=operator.itemgetter(1))
        with mock.patch.object(pdp.DPEngine,
                               "_create_contribution_bounder") as bound:
            out = engine.aggregate(
                rows,
                count_params(contribution_bounds_already_enforced=True),
                ex)
            acc.compute_budgets()
            dict(out)
        bound.assert_not_called()
