"""Differential fuzzing: randomized datasets and parameter combinations,
fused JAX plane vs the LocalBackend oracle.

Strategy: at huge epsilon the noise vanishes, and with contribution caps
chosen to never bind, the bounded aggregates are a deterministic function
of the data — the two planes must agree partition by partition. Each case
draws a random point from the full parameter space (metric combinations,
noise kind, bounding mode, selection strategy / public partitions,
bounds-already-enforced). Fixed seeds keep failures reproducible; a
failing case prints its spec.

When caps DO bind, outputs legitimately differ (each plane samples its
own rows), so binding-cap cases check invariants instead: per-partition
counts respect linf*l0 and the global row count is conserved or reduced.
"""

import operator

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.backends import JaxBackend

# Huge enough that even the Gaussian mechanism's noise vanishes: the
# analytic-Gaussian sigma only decays as Delta2/sqrt(2*eps) (not 1/eps),
# so eps=1e7 still leaves sigma ~ 0.1 at the sensitivities drawn here.
BIG_EPS = 1e12

SCALAR_COMBOS = [
    [pdp.Metrics.COUNT],
    [pdp.Metrics.PRIVACY_ID_COUNT],
    [pdp.Metrics.SUM],
    [pdp.Metrics.COUNT, pdp.Metrics.SUM],
    [pdp.Metrics.MEAN],
    [pdp.Metrics.VARIANCE],
    [pdp.Metrics.MEAN, pdp.Metrics.COUNT, pdp.Metrics.SUM],
    [pdp.Metrics.VARIANCE, pdp.Metrics.MEAN, pdp.Metrics.COUNT],
    [pdp.Metrics.COUNT, pdp.Metrics.PERCENTILE(50), pdp.Metrics.PERCENTILE(90)],
    [pdp.Metrics.SUM, pdp.Metrics.PRIVACY_ID_COUNT],
]


def make_dataset(rng, n_rows, n_users, n_parts):
    return pdp.ArrayDataset(
        privacy_ids=rng.integers(0, n_users, n_rows),
        partition_keys=rng.integers(0, n_parts, n_rows),
        values=rng.uniform(0.0, 10.0, n_rows))


def run_engine(backend, ds, params, public, ext=None):
    acc = pdp.NaiveBudgetAccountant(total_epsilon=BIG_EPS, total_delta=1e-2)
    engine = pdp.DPEngine(acc, backend)
    res = engine.aggregate(ds, params, ext or pdp.DataExtractors(),
                           public_partitions=public)
    acc.compute_budgets()
    return dict(res)


def assert_fields_close(fused_row, local_row, context, skip=()):
    """The per-field fused-vs-local comparison contract shared by the
    fuzz tests; ``skip`` names fields checked separately (percentiles
    get an order-statistic envelope instead of plane equality)."""
    for field in fused_row._fields:
        if field in skip:
            continue
        assert getattr(fused_row, field) == pytest.approx(
            getattr(local_row, field), rel=2e-3, abs=2e-2), (
                context, field, fused_row, local_row)


def case_spec(seed):
    """Draws one random parameter-space point (deterministic per seed)."""
    rng = np.random.default_rng(seed)
    n_parts = int(rng.integers(3, 40))
    n_users = int(rng.integers(5, 300))
    n_rows = int(rng.integers(50, 3000))
    metrics = SCALAR_COMBOS[int(rng.integers(0, len(SCALAR_COMBOS)))]
    noise = (pdp.NoiseKind.LAPLACE
             if rng.random() < 0.5 else pdp.NoiseKind.GAUSSIAN)
    public = rng.random() < 0.5
    strategy = list(pdp.PartitionSelectionStrategy)[
        int(rng.integers(0, len(pdp.PartitionSelectionStrategy)))]
    needs_values = any(
        m.is_percentile or m.name in ("SUM", "MEAN", "VARIANCE")
        for m in metrics)
    return dict(n_parts=n_parts, n_users=n_users, n_rows=n_rows,
                metrics=metrics, noise=noise, public=public,
                strategy=strategy, needs_values=needs_values, rng=rng)


class TestDifferentialFuzz:

    @pytest.mark.parametrize("seed", range(20))
    def test_nonbinding_caps_match_oracle(self, seed):
        spec = case_spec(seed)
        rng = spec["rng"]
        ds = make_dataset(rng, spec["n_rows"], spec["n_users"],
                          spec["n_parts"])
        # Caps that can never bind: every pid's rows fit under linf and
        # every pid's partition spread fits under l0.
        counts_per_pair = {}
        for u, p in zip(ds.privacy_ids.tolist(),
                        ds.partition_keys.tolist()):
            counts_per_pair[(u, p)] = counts_per_pair.get((u, p), 0) + 1
        linf = max(counts_per_pair.values()) + 1
        l0 = spec["n_parts"] + 1
        kw = dict(metrics=spec["metrics"], noise_kind=spec["noise"],
                  max_partitions_contributed=l0,
                  max_contributions_per_partition=linf,
                  partition_selection_strategy=spec["strategy"])
        if spec["needs_values"]:
            kw.update(min_value=0.0, max_value=10.0)
        params = pdp.AggregateParams(**kw)
        public = (sorted(np.unique(ds.partition_keys).tolist())
                  if spec["public"] else None)

        fused = run_engine(JaxBackend(rng_seed=seed), ds, params, public)
        local = run_engine(pdp.LocalBackend(), ds, params, public)

        if public:
            assert set(fused) == set(local) == set(public), spec
        # Private selection keeps/drops randomly per plane: compare the
        # intersection (dropping small partitions is legitimate).
        common = set(fused) & set(local)
        users_per_part = {}
        for u, p in zip(ds.privacy_ids.tolist(),
                        ds.partition_keys.tolist()):
            users_per_part.setdefault(p, set()).add(u)
        # Private selection may legitimately drop every small partition;
        # only a partition with plenty of users is guaranteed kept at
        # huge eps on both planes.
        if public or max(len(s) for s in users_per_part.values()) >= 20:
            assert common, (spec, len(fused), len(local))
        values_per_part = {}
        for p, v in zip(ds.partition_keys.tolist(), ds.values.tolist()):
            values_per_part.setdefault(p, []).append(v)
        for k in common:
            f, l = fused[k], local[k]
            pct_fields = tuple(fl for fl in f._fields
                               if fl.startswith("percentile_"))
            assert_fields_close(f, l, (spec, k), skip=pct_fields)
            for field in pct_fields:
                # At an exact rank boundary (e.g. the median of an even
                # count) the tree walk's child choice is decided by
                # vanishing noise, and ANY point between the two adjacent
                # order statistics is a valid quantile estimate — the
                # reference's C++ tree behaves the same. Check both
                # planes against the order-statistic envelope instead of
                # each other.
                q = float(field.split("_", 1)[1].replace("_", ".")) / 100
                s = sorted(values_per_part[k])
                m = len(s)
                kf = q * m
                lw = 10.0 / 16**4  # leaf width of the [0,10] tree
                lo = s[max(int(np.floor(kf)) - 1, 0)] - lw - 1e-3
                hi = s[min(int(np.ceil(kf)), m - 1)] + lw + 1e-3
                for plane, val in (("fused", getattr(f, field)),
                                   ("local", getattr(l, field))):
                    assert lo <= val <= hi, (
                        spec, k, field, plane, val, (lo, hi))

    @pytest.mark.parametrize("seed", range(20, 28))
    def test_binding_caps_invariants(self, seed):
        spec = case_spec(seed)
        rng = spec["rng"]
        ds = make_dataset(rng, spec["n_rows"], spec["n_users"],
                          spec["n_parts"])
        linf = int(rng.integers(1, 3))
        l0 = int(rng.integers(1, 4))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT], noise_kind=spec["noise"],
            max_partitions_contributed=l0,
            max_contributions_per_partition=linf)
        public = sorted(np.unique(ds.partition_keys).tolist())
        fused = run_engine(JaxBackend(rng_seed=seed), ds, params, public)
        # Per-partition: at most (users contributing) * linf rows; global:
        # bounding only removes rows.
        users_per_part = {}
        for u, p in zip(ds.privacy_ids.tolist(),
                        ds.partition_keys.tolist()):
            users_per_part.setdefault(p, set()).add(u)
        total = 0.0
        for k, v in fused.items():
            cap = len(users_per_part.get(k, ())) * linf
            assert v.count <= cap + 0.5, (spec, k, v.count, cap)
            total += v.count
        assert total <= spec["n_rows"] + 0.5, spec

    @pytest.mark.parametrize("seed", range(50, 56))
    def test_max_contributions_nonbinding_matches_oracle(self, seed):
        # Total-cap mode with a cap no unit ever reaches: fused and local
        # must agree exactly at huge eps.
        spec = case_spec(seed)
        rng = spec["rng"]
        ds = make_dataset(rng, spec["n_rows"], spec["n_users"],
                          spec["n_parts"])
        rows_per_user = {}
        for u in ds.privacy_ids.tolist():
            rows_per_user[u] = rows_per_user.get(u, 0) + 1
        metrics = [[pdp.Metrics.COUNT],
                   [pdp.Metrics.COUNT, pdp.Metrics.SUM],
                   [pdp.Metrics.PRIVACY_ID_COUNT],
                   [pdp.Metrics.MEAN, pdp.Metrics.VARIANCE]][seed % 4]
        kw = dict(metrics=metrics, noise_kind=spec["noise"],
                  max_contributions=max(rows_per_user.values()) + 1)
        if any(m.name != "COUNT" and m.name != "PRIVACY_ID_COUNT"
               for m in metrics):
            kw.update(min_value=0.0, max_value=10.0)
        params = pdp.AggregateParams(**kw)
        public = sorted(np.unique(ds.partition_keys).tolist())
        fused = run_engine(JaxBackend(rng_seed=seed), ds, params, public)
        local = run_engine(pdp.LocalBackend(), ds, params, public)
        assert set(fused) == set(local) == set(public)
        for k in public:
            assert_fields_close(fused[k], local[k], (spec, k))

    @pytest.mark.parametrize("seed", [30, 31, 32])
    def test_bounds_already_enforced(self, seed):
        spec = case_spec(seed)
        rng = spec["rng"]
        ds = pdp.ArrayDataset(
            privacy_ids=None,
            partition_keys=rng.integers(0, spec["n_parts"], spec["n_rows"]),
            values=rng.uniform(0.0, 10.0, spec["n_rows"]))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            noise_kind=spec["noise"],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0.0, max_value=10.0,
            contribution_bounds_already_enforced=True)
        public = sorted(np.unique(ds.partition_keys).tolist())
        ext = pdp.DataExtractors()
        fused = run_engine(JaxBackend(rng_seed=seed), ds, params, public,
                           ext=ext)
        local = run_engine(pdp.LocalBackend(), ds, params, public, ext=ext)
        assert set(fused) == set(local)
        for k in fused:
            assert fused[k].count == pytest.approx(local[k].count,
                                                   abs=2e-2), (spec, k)
            assert fused[k].sum == pytest.approx(local[k].sum,
                                                 rel=2e-3, abs=5e-2), (
                                                     spec, k)

    @pytest.mark.parametrize("seed,norm", [
        (40, pdp.NormKind.Linf), (41, pdp.NormKind.L1), (42, pdp.NormKind.L2)])
    def test_vector_sum(self, seed, norm):
        rng = np.random.default_rng(seed)
        n_rows, n_parts, dim = 400, 6, 3
        ds = pdp.ArrayDataset(
            privacy_ids=rng.integers(0, 100, n_rows),
            partition_keys=rng.integers(0, n_parts, n_rows),
            values=rng.uniform(-1.0, 1.0, (n_rows, dim)))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.VECTOR_SUM],
            max_partitions_contributed=n_parts + 1,
            max_contributions_per_partition=50,
            vector_size=dim, vector_max_norm=100.0, vector_norm_kind=norm)
        public = sorted(np.unique(ds.partition_keys).tolist())
        fused = run_engine(JaxBackend(rng_seed=seed), ds, params, public)
        local = run_engine(pdp.LocalBackend(), ds, params, public)
        assert set(fused) == set(local)
        for k in fused:
            np.testing.assert_allclose(
                np.asarray(fused[k].vector_sum),
                np.asarray(local[k].vector_sum), rtol=1e-3, atol=5e-2)
