"""Sketch-first ingest + DP heavy hitters (``pipelinedp_tpu/sketch``).

Covers the ISSUE-15 acceptance surface: seeded stable-hash round-trips
(including collision-prone bucket counts), matmul-vs-scatter sketch
bit-parity (PARITY row 36), per-user pre-sketch bounding invariance,
sketch-vs-exact candidate recall on a power-law key space, the
cap≥universe bit-parity with the dense path on single device AND the
8-device mesh (PARITY row 37), the phase-1 budget audit record + the
schema-v5 run-report ``sketch`` section, kill-mid-sketch drain with
zero orphan ``pdp-*`` threads, and the sketch knob registrations.
"""

import threading

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import obs
from pipelinedp_tpu.backends import JaxBackend
from pipelinedp_tpu.sketch import (SketchParams, bucket_ids,
                                   stable_hash64, stable_hash_any)
from pipelinedp_tpu.sketch import device as sketch_device
from pipelinedp_tpu.sketch import engine as sketch_engine
from pipelinedp_tpu.sketch import hashing


@pytest.fixture(autouse=True)
def _fresh_obs():
    obs.reset()
    yield
    obs.reset()


def _params(noise=None, l0=3, linf=2):
    return pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
        noise_kind=noise or pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=l0,
        max_contributions_per_partition=linf,
        min_value=0.0, max_value=10.0)


def _string_dataset(n=8000, n_users=600, n_keys=80, seed=1, zipf=1.4):
    rng = np.random.default_rng(seed)
    raw = rng.zipf(zipf, n) % n_keys
    return pdp.ArrayDataset(
        privacy_ids=rng.integers(0, n_users, n),
        partition_keys=np.char.add("key/", raw.astype("U6")),
        values=rng.uniform(0.0, 10.0, n))


def _run(backend, ds, params, sketch=None, eps=1.0, delta=1e-6):
    acc = pdp.NaiveBudgetAccountant(total_epsilon=eps, total_delta=delta)
    engine = pdp.DPEngine(acc, backend)
    res = engine.aggregate(ds, params, pdp.DataExtractors(),
                           sketch_first=sketch)
    acc.compute_budgets()
    return dict(res), res


#: Generous phase-1 budget + sub-unit threshold + cap >= buckets: every
#: populated bucket is selected, so the candidate set IS the key
#: universe — the PARITY row 37 regime.
def _keep_all_sketch(**kw):
    base = dict(eps=1e6, delta=1e-6, width=2048, depth=2,
                candidate_cap=2048, threshold=0.5)
    base.update(kw)
    return SketchParams(**base)


class TestHashing:

    def test_container_invariance_str(self):
        keys = ["alpha", "beta", "a longer key with spaces", "ß∂ƒ©"]
        arr = np.asarray(keys)
        vec = stable_hash64(arr)
        for k, h in zip(keys, vec):
            assert stable_hash_any(k) == int(h)

    def test_container_invariance_bytes_and_int(self):
        barr = np.asarray([b"x", b"yz", b"abc"], dtype="S3")
        for k, h in zip([b"x", b"yz", b"abc"], stable_hash64(barr)):
            assert stable_hash_any(k) == int(h)
        iarr = np.asarray([0, 1, -5, 2**40], dtype=np.int64)
        for k, h in zip(iarr.tolist(), stable_hash64(iarr)):
            assert stable_hash_any(int(k)) == int(h)

    def test_itemsize_invariance(self):
        # The same string must hash identically whether it sits in a
        # <U1 or a <U16 array (NumPy NUL-padding must not leak in).
        a = stable_hash64(np.asarray(["a"]))
        b = stable_hash64(np.asarray(["a", "0123456789abcdef"]))
        assert int(a[0]) == int(b[0])

    def test_embedded_nuls_are_content(self):
        # Only TRAILING NULs are padding; embedded/leading NULs must
        # hash (else distinct binary-id keys silently merge in EVERY
        # depth row and count-min cannot separate them).
        assert stable_hash_any("a\x00b") != stable_hash_any("ab")
        assert stable_hash_any(b"\x00a") != stable_hash_any(b"a")
        assert stable_hash_any("a\x00") == stable_hash_any("a")  # np's
        # own U/S cells cannot represent trailing NULs either
        arr = np.asarray(["a\x00b", "ab"])
        h = stable_hash64(arr)
        assert int(h[0]) == stable_hash_any("a\x00b")
        assert int(h[0]) != int(h[1])

    def test_seed_changes_everything(self):
        keys = np.asarray([f"k{i}" for i in range(64)])
        h1 = stable_hash64(keys, seed=1)
        h2 = stable_hash64(keys, seed=2)
        assert (h1 != h2).all()

    def test_distinct_keys_distinct_hashes(self):
        keys = np.asarray([f"url/{i}" for i in range(10_000)])
        assert len(np.unique(stable_hash64(keys))) == 10_000

    def test_bucket_round_trip_collision_prone(self):
        # Collision-prone: 10k keys into 256 buckets. Selecting a
        # bucket subset must recover EXACTLY the keys hashing into it.
        keys = np.asarray([f"q{i}" for i in range(10_000)])
        h = stable_hash64(keys)
        rows = bucket_ids(h, 256, 3)
        assert rows.shape == (3, 10_000)
        assert rows.min() >= 0 and rows.max() < 256
        # every bucket populated at this load factor
        assert len(np.unique(rows[0])) == 256
        selected = np.zeros(256, bool)
        selected[[3, 17, 200]] = True
        cand, table = hashing.build_candidate_table(
            keys, selected[rows[0]])
        expect = {k for k, b in zip(keys.tolist(), rows[0])
                  if selected[b]}
        assert set(cand) == expect == set(table)
        assert sorted(table.values()) == list(range(len(cand)))

    def test_rows_independent(self):
        keys = np.asarray([f"r{i}" for i in range(4096)])
        rows = bucket_ids(stable_hash64(keys), 1024, 2)
        # depth rows are distinct remixes: colliding in row 0 must not
        # imply colliding in row 1 (the count-min property).
        same0 = rows[0][:-1] == rows[0][1:]
        same1 = rows[1][:-1] == rows[1][1:]
        assert not (same0 & same1).any()


class TestDeviceSketch:

    @pytest.mark.parametrize("n", [1, 511, 512, 1300])
    def test_matmul_equals_scatter_and_bincount(self, n):
        rng = np.random.default_rng(n)
        width = 512
        bk = rng.integers(0, width, size=(3, n)).astype(np.int32)
        pad = sketch_device.pad_chunk(bk)
        m = np.asarray(sketch_device.sketch_chunk_program(
            pad, width=width, backend="matmul"))
        x = np.asarray(sketch_device.sketch_chunk_program(
            pad, width=width, backend="xla"))
        assert (m == x).all()
        for d in range(3):
            assert (m[d] == np.bincount(bk[d], minlength=width)).all()
        assert m.sum() == 3 * n  # padding (-1) counted nowhere

    def test_chunked_accumulation_exact(self):
        rng = np.random.default_rng(7)
        bk = rng.integers(0, 256, size=(2, 5000)).astype(np.int32)
        whole = np.zeros((2, 256), np.int64)
        sketch_device.accumulate_chunk(
            whole, sketch_device.sketch_chunk_program(
                sketch_device.pad_chunk(bk), width=256,
                backend="matmul"))
        parts = np.zeros((2, 256), np.int64)
        for lo in range(0, 5000, 700):
            chunk = sketch_device.pad_chunk(
                np.ascontiguousarray(bk[:, lo:lo + 700]))
            sketch_device.accumulate_chunk(
                parts, sketch_device.sketch_chunk_program(
                    chunk, width=256, backend="matmul"))
        assert (whole == parts).all()


class TestBounding:

    def test_l0_bound_holds(self):
        # one heavy user touching 50 keys, bounded to 3
        pid = np.zeros(50, np.int64)
        keys = np.asarray([f"k{i}" for i in range(50)])
        uniq, inv = sketch_engine._factorize_keys(keys)
        h = stable_hash64(uniq)
        kept = sketch_engine.bound_pairs(pid, inv, h, 3, 0)
        assert len(kept) == 3

    def test_neighbor_sensitivity_bound_string_pids(self):
        # The L1 <= l0 sensitivity bound must hold for FACTORIZED pid
        # types too: removing one user may change only that user's
        # <= l0 kept pairs, never reshuffle other users' samples (the
        # user salt is a content hash, not a dataset-relative rank).
        rng = np.random.default_rng(11)
        l0 = 3
        pids, keys = [], []
        for u in range(40):
            for k in rng.choice(200, size=10, replace=False):
                pids.append(f"user-{u}")
                keys.append(f"key-{k}")
        pid_arr, key_arr = np.asarray(pids), np.asarray(keys)
        uniq, inv = sketch_engine._factorize_keys(key_arr)
        h = stable_hash64(uniq)

        def kept_multiset(mask):
            # key indices stay in the FULL table's space (inv indexes
            # uniq), so kept sets compare across neighbors directly
            kept = sketch_engine.bound_pairs(
                pid_arr[mask], inv[mask], h, l0, 0)
            return sorted(kept.tolist())

        full = kept_multiset(np.ones(len(pid_arr), bool))
        for victim in ("user-0", "user-17", "user-39"):
            neighbor = kept_multiset(pid_arr != victim)
            # symmetric difference is ONLY the victim's <= l0 pairs
            from collections import Counter
            diff = Counter(full) - Counter(neighbor)
            gained = Counter(neighbor) - Counter(full)
            assert sum(diff.values()) <= l0, victim
            assert sum(gained.values()) == 0, victim

    def test_row_order_and_duplication_invariant(self):
        rng = np.random.default_rng(5)
        pid = rng.integers(0, 30, 2000)
        keys = np.asarray([f"k{i}" for i in rng.integers(0, 200, 2000)])
        uniq, inv = sketch_engine._factorize_keys(keys)
        h = stable_hash64(uniq)
        kept_a = sketch_engine.bound_pairs(pid, inv, h, 4, 9)
        perm = rng.permutation(2000)
        uniq2, inv2 = sketch_engine._factorize_keys(keys[perm])
        assert (uniq2 == uniq).all()
        kept_b = sketch_engine.bound_pairs(pid[perm], inv2,
                                           stable_hash64(uniq2), 4, 9)
        # kept PAIR SETS are identical regardless of row order (and of
        # (pid, key) duplication, which the pair dedup removes first)
        assert sorted(kept_a.tolist()) == sorted(kept_b.tolist())
        # and every user keeps at most l0 keys
        pairs = {}
        pid_sorted = np.sort(np.unique(pid))
        del pid_sorted, pairs


class TestEndToEnd:

    def test_recall_on_power_law(self):
        ds = _string_dataset(n=30_000, n_users=3000, n_keys=2000,
                             seed=3, zipf=1.2)
        sk = SketchParams(eps=30.0, delta=1e-6, width=1 << 14, depth=2,
                          candidate_cap=1 << 14)
        out, res = _run(JaxBackend(rng_seed=5), ds, _params(),
                        sk, eps=30.0)
        # exact top-20 keys by distinct-user count
        import collections
        users_of = collections.defaultdict(set)
        for u, k in zip(ds.privacy_ids.tolist(),
                        ds.partition_keys.tolist()):
            users_of[k].add(u)
        top = sorted(users_of, key=lambda k: -len(users_of[k]))[:20]
        recall = sum(1 for k in top if k in out) / 20
        assert recall >= 0.8, (recall, len(out))

    def test_parity_with_dense_single_device(self):
        ds = _string_dataset()
        params = _params(noise=pdp.NoiseKind.GAUSSIAN)
        dense, _ = _run(JaxBackend(rng_seed=11), ds, params)
        ds2 = _string_dataset()
        sketchy, res = _run(JaxBackend(rng_seed=11), ds2, params,
                            _keep_all_sketch())
        assert set(dense) == set(sketchy)
        for k in dense:
            assert tuple(dense[k]) == tuple(sketchy[k])
        assert res.timings["sketch_candidates"] == len(
            np.unique(ds.partition_keys))

    def test_parity_with_dense_8_device_mesh(self):
        from pipelinedp_tpu.parallel import make_mesh
        ds = _string_dataset(seed=2)
        params = _params()
        dense, _ = _run(JaxBackend(mesh=make_mesh(8), rng_seed=13),
                        ds, params)
        sketchy, _ = _run(JaxBackend(mesh=make_mesh(8), rng_seed=13),
                          _string_dataset(seed=2), params,
                          _keep_all_sketch())
        assert set(dense) == set(sketchy) and len(dense) > 0
        for k in dense:
            assert tuple(dense[k]) == tuple(sketchy[k])

    def test_sketch_backend_parity(self):
        params = _params()
        sk = dict(eps=4.0, delta=1e-7, width=1024, depth=2,
                  candidate_cap=64)
        a, _ = _run(JaxBackend(rng_seed=3), _string_dataset(), params,
                    SketchParams(backend="matmul", **sk))
        b, _ = _run(JaxBackend(rng_seed=3), _string_dataset(), params,
                    SketchParams(backend="xla", **sk))
        assert set(a) == set(b) and len(a) > 0
        for k in a:
            assert tuple(a[k]) == tuple(b[k])

    def test_audit_record_and_report_section(self):
        out, _ = _run(JaxBackend(rng_seed=7), _string_dataset(),
                      _params(), _keep_all_sketch())
        rep = obs.build_run_report()
        assert rep["schema_version"] == 6
        runs = rep["sketch"]["runs"]
        assert len(runs) == 1
        rec = runs[0]
        assert rec["width"] == 2048 and rec["depth"] == 2
        assert rec["buckets_selected"] >= rec["candidates"] > 0
        # the phase-1 selection budget is audited like any accountant
        metrics = [m["metric"] for acc in rep["privacy"]["accountants"]
                   for m in acc["mechanisms"]]
        assert "sketch_candidate_selection" in metrics
        sel = [m for acc in rep["privacy"]["accountants"]
               for m in acc["mechanisms"]
               if m["metric"] == "sketch_candidate_selection"][0]
        assert sel["eps"] == pytest.approx(1e6)

    def test_empty_selection_releases_nothing(self):
        sk = SketchParams(eps=0.5, delta=1e-9, width=1024, depth=1,
                          candidate_cap=16, threshold=1e9)
        out, _ = _run(JaxBackend(rng_seed=1), _string_dataset(),
                      _params(), sk)
        assert out == {}
        counters = obs.ledger().snapshot()["counters"]
        assert counters.get("sketch.runs") == 1

    def test_candidate_cap_is_a_bucket_cap(self):
        # cap=4 with hundreds of populated buckets: at most 4 buckets
        # survive, and every candidate hashes into a selected bucket.
        ds = _string_dataset(n_keys=500, seed=6)
        sk = SketchParams(eps=50.0, delta=1e-6, width=4096, depth=1,
                          candidate_cap=4)
        out, res = _run(JaxBackend(rng_seed=2), ds, _params(), sk,
                        eps=50.0)
        rep = obs.build_run_report()
        rec = rep["sketch"]["runs"][0]
        assert rec["buckets_selected"] <= 4
        assert rec["candidates"] <= rec["universe_keys"]
        assert set(out) <= set(res._candidate_table)

    def test_requires_privacy_ids_and_private_selection(self):
        ds = _string_dataset()
        acc = pdp.NaiveBudgetAccountant(1.0, 1e-6)
        engine = pdp.DPEngine(acc, JaxBackend(rng_seed=0))
        with pytest.raises(ValueError, match="public_partitions"):
            engine.aggregate(ds, _params(), pdp.DataExtractors(),
                             public_partitions=["key/1"],
                             sketch_first=_keep_all_sketch())
        with pytest.raises(TypeError, match="SketchParams"):
            engine.aggregate(ds, _params(), pdp.DataExtractors(),
                             sketch_first={"eps": 1.0})
        with pytest.raises(NotImplementedError, match="fused"):
            pdp.DPEngine(pdp.NaiveBudgetAccountant(1.0, 1e-6),
                         pdp.LocalBackend()).aggregate(
                ds, _params(), pdp.DataExtractors(),
                sketch_first=_keep_all_sketch())


class TestFaults:

    def test_kill_mid_sketch_drains_to_zero_orphans(self):
        from pipelinedp_tpu.resilience import faults
        ds = _string_dataset(n=20_000, n_users=4000, n_keys=1500)
        # tiny chunks force a multi-chunk stream; the kill lands on
        # chunk 1's dispatch, after chunk 2 may already be staging
        sk = _keep_all_sketch(chunk_rows=512)
        before = {t.name for t in threading.enumerate()
                  if t.name.startswith("pdp-")}
        with faults.injected_faults(
                faults.FaultPlan(fail_sketch_chunks=(1,))):
            with pytest.raises(faults.ChunkFailure, match="sketch"):
                _run(JaxBackend(rng_seed=0), ds, _params(), sk)
        for t in threading.enumerate():
            if (t.name.startswith("pdp-") and t.name not in before
                    and t.is_alive()):
                t.join(timeout=5.0)
                assert not t.is_alive(), f"orphan thread {t.name}"
        counters = obs.ledger().snapshot()["counters"]
        assert counters.get("faults.injected", 0) >= 1
        # a later run in the same process is healthy
        out, _ = _run(JaxBackend(rng_seed=0), ds, _params(),
                      _keep_all_sketch())
        assert len(out) > 0


class TestKnobs:

    def test_sketch_knobs_registered(self):
        from pipelinedp_tpu.plan import knobs
        for name, dp_safe in (("sketch_width", False),
                              ("sketch_depth", False),
                              ("sketch_candidate_cap", False),
                              ("sketch_backend", True)):
            spec = knobs.BY_NAME[name]
            assert spec.dp_safe is dp_safe, name
            assert spec.seam is None  # SketchParams is the injection
            assert spec.doc and spec.unit

    def test_env_override_resolves(self, monkeypatch):
        from pipelinedp_tpu.plan import knobs
        monkeypatch.setenv("PIPELINEDP_TPU_SKETCH_WIDTH", "1000")
        v, src = knobs.resolve_value(knobs.BY_NAME["sketch_width"], None)
        assert (v, src) == (1000, "env")
        # SketchParams rounds the resolved width to the radix multiple
        assert SketchParams(eps=1.0, delta=0.0).resolved_width() == 1024
        monkeypatch.setenv("PIPELINEDP_TPU_SKETCH_BACKEND", "xla")
        assert SketchParams(eps=1.0, delta=0.0).resolved_backend() == \
            "xla"

    def test_explicit_params_outrank_env(self, monkeypatch):
        monkeypatch.setenv("PIPELINEDP_TPU_SKETCH_DEPTH", "7")
        assert SketchParams(eps=1.0, delta=0.0,
                            depth=3).resolved_depth() == 3

    def test_autotune_sweeps_sketch_backend(self):
        from pipelinedp_tpu import plan as plan_mod
        cands = plan_mod.autotune_candidates()
        assert {"sketch_backend": "xla"}.items() <= cands[-1].items()
        assert all("sketch_width" not in c for c in cands)

    def test_params_validation(self):
        with pytest.raises(ValueError, match="eps"):
            SketchParams(eps=0.0, delta=0.0)
        with pytest.raises(ValueError, match="width"):
            SketchParams(eps=1.0, delta=0.0, width=-5)
        with pytest.raises(ValueError, match="backend"):
            SketchParams(eps=1.0, delta=0.0, backend="pallas")


class TestPeekerShim:

    def test_data_peeker_sketch_routes_through_sketch_peek(self):
        from pipelinedp_tpu import peeker
        rows = [(u, f"p{u % 3}", 1.0) for u in range(30)]
        pk = peeker.DataPeeker(pdp.LocalBackend())
        params = peeker.SampleParams(number_of_sampled_partitions=3,
                                     metrics=[pdp.Metrics.COUNT])
        ex = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                partition_extractor=lambda r: r[1],
                                value_extractor=lambda r: r[2])
        out = list(pk.sketch(rows, params, ex))
        # one row per (pk, pid); COUNT child accumulator == 1 row each
        assert len(out) == 30
        assert all(v == 1 and pcount == 1 for _, v, pcount in out)
