"""Topology-aware collectives: the two-axis ("dcn", "ici") mesh view.

The hierarchical exchange (`mesh_topology=hier`) must be BIT-IDENTICAL
to the flat one for every released value and kept set — that is the
knob's dp-safety contract (PARITY row 43) — while moving strictly fewer
bytes across the host (DCN) boundary. This file is the in-process half
of that proof, on the 8-device CPU mesh with simulated hosts
(``PIPELINEDP_TPU_MESH_HOSTS``); ``test_multihost.py`` repeats the
parity and byte assertions across a real two-process gloo boundary.
``make topocheck`` runs this file plus the collective-confinement lint.
"""

import contextlib
import os

import numpy as np
import pytest

import jax

import pipelinedp_tpu as pdp
from pipelinedp_tpu import obs
from pipelinedp_tpu.backends import JaxBackend
from pipelinedp_tpu.ops import noise as noise_ops
from pipelinedp_tpu.parallel import sharded as psh
from pipelinedp_tpu.resilience import (CheckpointStore, FaultPlan,
                                       injected_faults)

BIG_EPS = 1e5

TOPOLOGY_ENV = "PIPELINEDP_TPU_MESH_TOPOLOGY"
HOSTS_ENV = psh._MESH_HOSTS_ENV


@pytest.fixture(autouse=True)
def _isolated_topology_registry():
    """Meshes registered by a test (notably a flat topology with
    simulated hosts, whose device order — and registry key — collides
    with the plain flat mesh) must not leak into other files."""
    saved = dict(psh._TOPOLOGIES)
    yield
    psh._TOPOLOGIES.clear()
    psh._TOPOLOGIES.update(saved)


@contextlib.contextmanager
def topology_env(mode=None, hosts=None):
    """Pin the mesh_topology knob (env outranks seam and plan) and the
    simulated host count for the duration — make_mesh reads both; the
    registered topology is what the kernels consult afterwards."""
    pairs = ((TOPOLOGY_ENV, mode),
             (HOSTS_ENV, None if hosts is None else str(hosts)))
    saved = {k: os.environ.get(k) for k, _ in pairs}
    for k, v in pairs:
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def device_ids(mesh):
    return [int(d.id) for d in mesh.devices.reshape(-1)]


def require_8():
    assert len(jax.devices()) >= 8, (
        "conftest must provide 8 virtual devices")


# ---------------------------------------------------------------------------
# The registry: interleave order, fallbacks, reform preservation
# ---------------------------------------------------------------------------

class TestTopologyRegistry:

    def test_default_mesh_is_flat_in_natural_order(self):
        require_8()
        mesh = psh.make_mesh(8)
        topo = psh.topology_of(mesh)
        assert topo.mode == "flat"
        assert not topo.hierarchical
        assert not topo.multi_host
        assert device_ids(mesh) == list(range(8))

    def test_hier_interleaves_simulated_hosts(self):
        require_8()
        with topology_env("hier", 2):
            mesh = psh.make_mesh(8)
        topo = psh.topology_of(mesh)
        assert (topo.mode, topo.n_hosts, topo.per_host) == ("hier", 2, 4)
        assert topo.simulated and topo.hierarchical and topo.multi_host
        # Position p = j*H + h holds host h's j-th device: hosts are
        # the contiguous id halves [0..3] and [4..7], interleaved.
        assert device_ids(mesh) == [0, 4, 1, 5, 2, 6, 3, 7]
        assert psh._ici_groups(topo) == [[0, 2, 4, 6], [1, 3, 5, 7]]
        assert psh._dcn_groups(topo) == [[0, 1], [2, 3], [4, 5], [6, 7]]

    def test_hier_on_single_host_degrades_to_flat(self):
        require_8()
        with topology_env("hier", None):
            mesh = psh.make_mesh(8)
        topo = psh.topology_of(mesh)
        assert topo.mode == "flat" and not topo.hierarchical
        assert device_ids(mesh) == list(range(8))

    def test_auto_resolves_by_host_count(self):
        require_8()
        with topology_env("auto", 2):
            assert psh.topology_of(psh.make_mesh(8)).mode == "hier"
        with topology_env("auto", None):
            assert psh.topology_of(psh.make_mesh(8)).mode == "flat"

    def test_ragged_hosts_fall_back_with_event(self, monkeypatch):
        require_8()
        devices = jax.devices()[:8]
        monkeypatch.setattr(
            psh, "_host_groups",
            lambda d: ([list(d[:3]), list(d[3:])], True))
        obs.reset()
        with topology_env("hier", None):
            mesh = psh.make_mesh(8)
        topo = psh.topology_of(mesh)
        assert topo.mode == "flat"
        assert device_ids(mesh) == [int(d.id) for d in devices]
        events = [e for e in obs.ledger().snapshot()["events"]
                  if e["name"] == "mesh.topology_fallback"]
        assert events and events[0]["reason"] == "ragged_hosts"

    def test_plain_mesh_built_elsewhere_is_flat(self):
        require_8()
        mesh = psh.Mesh(np.asarray(jax.devices()[:8]), ("data",))
        topo = psh.topology_of(mesh)
        assert topo.mode == "flat" and topo.n_devices == 8
        assert psh.topology_of(None).n_devices == 1

    def test_reform_preserves_hier_within_hosts(self):
        """8 -> 4 under hier(2,4): the divisor prefix of the interleave
        is [0,4,1,5] — each host sheds its highest-slot devices and the
        survivors regroup within their host as hier(2,2)."""
        require_8()
        with topology_env("hier", 2):
            mesh = psh.make_mesh(8)
        obs.reset()
        half = psh.reform_mesh(mesh)
        topo = psh.topology_of(half)
        assert device_ids(half) == [0, 4, 1, 5]
        assert (topo.mode, topo.n_hosts, topo.per_host) == ("hier", 2, 2)
        ev = [e for e in obs.ledger().snapshot()["events"]
              if e["name"] == "mesh.reformed"]
        assert ev and ev[0]["topology"] == "hier" and ev[0]["hosts"] == 2
        # 4 -> 2: still a valid hier interleave (one device per host,
        # exchange degenerates but the grouping stays host-aligned).
        quarter = psh.reform_mesh(half)
        assert device_ids(quarter) == [0, 4]
        t2 = psh.topology_of(quarter)
        assert (t2.mode, t2.n_hosts, t2.per_host) == ("hier", 2, 1)
        assert not t2.hierarchical
        # 2 -> 1: the host count no longer divides — degrade to flat.
        last = psh.reform_mesh(quarter)
        assert device_ids(last) == [0]
        assert psh.topology_of(last).mode == "flat"


# ---------------------------------------------------------------------------
# Collective-level parity + the comms byte meter
# ---------------------------------------------------------------------------

def _run_collective(mesh, x_global, body):
    """shard_map `body(local_vec, axis, topo)` over dim 0 of
    ``x_global`` (one row per mesh position), owner-sharded output."""
    axis = mesh.axis_names[0]
    topo = psh.topology_of(mesh)
    fn = psh.shard_map(
        lambda v: body(v[0], axis, topo),
        mesh=mesh, in_specs=psh.PSpec(axis), out_specs=psh.PSpec(axis),
        **{psh._CHECK_KW: False})
    return np.asarray(jax.jit(fn)(x_global))


def _run_replicated(mesh, x_global, body):
    axis = mesh.axis_names[0]
    topo = psh.topology_of(mesh)
    fn = psh.shard_map(
        lambda v: body(v[0], axis, topo),
        mesh=mesh, in_specs=psh.PSpec(axis), out_specs=psh.PSpec(),
        **{psh._CHECK_KW: False})
    return np.asarray(jax.jit(fn)(x_global))


def _meshes_flat_and_hier(n=8, hosts=2):
    """(flat mesh, hier mesh) over the same devices; the flat one is
    built WITH simulated hosts so its exchange bytes are attributed to
    DCN — the apples-to-apples byte comparison of the two policies."""
    with topology_env("flat", hosts):
        flat = psh.make_mesh(n)
    with topology_env("hier", hosts):
        hier = psh.make_mesh(n)
    return flat, hier


class TestCollectiveParity:

    def _data(self, cols, seed=3):
        rng = np.random.default_rng(seed)
        return rng.integers(0, 1 << 20, (8, cols)).astype(np.int32)

    def test_owner_scatter_bit_identical_and_fewer_dcn_bytes(self):
        """The acceptance pair in one trace: hier == flat bitwise on
        integer payloads, and the hier two-stage scatter crosses the
        host boundary with strictly fewer (estimated) bytes."""
        require_8()
        x = self._data(8 * 288)  # distinctive width: fresh jit traces
        flat_mesh, hier_mesh = _meshes_flat_and_hier()
        want = x.sum(axis=0, dtype=np.int32)

        scatter = lambda v, axis, topo: psh.scatter_to_owner(
            v, axis, dim=0, topo=topo)
        obs.reset()
        got_flat = _run_collective(flat_mesh, x, scatter)
        flat_c = dict(obs.ledger().snapshot()["counters"])
        obs.reset()
        got_hier = _run_collective(hier_mesh, x, scatter)
        hier_c = dict(obs.ledger().snapshot()["counters"])

        np.testing.assert_array_equal(got_flat, want)
        np.testing.assert_array_equal(got_hier, got_flat)
        assert flat_c.get("comms.dcn_bytes", 0) > 0
        assert hier_c.get("comms.dcn_bytes", 0) > 0
        assert hier_c["comms.dcn_bytes"] < flat_c["comms.dcn_bytes"]
        assert hier_c.get("comms.ici_bytes", 0) > 0
        assert hier_c.get("comms.collectives", 0) >= 2

    def test_replicating_psum_bit_identical(self):
        require_8()
        x = self._data(8 * 160, seed=4)
        flat_mesh, hier_mesh = _meshes_flat_and_hier()
        body = lambda v, axis, topo: psh.combine_shards(
            v, axis, 0, True, topo=topo)
        got_flat = _run_replicated(flat_mesh, x, body)
        got_hier = _run_replicated(hier_mesh, x, body)
        np.testing.assert_array_equal(got_flat,
                                      x.sum(axis=0, dtype=np.int32))
        np.testing.assert_array_equal(got_hier, got_flat)

    def test_replicate_indivisible_block_falls_back_flat(self):
        """Payload the per-host split cannot tile (size % per_host != 0)
        keeps the flat psum — the pass-B tile-block contract."""
        require_8()
        x = self._data(42, seed=5)  # 42 % 4 != 0
        _, hier_mesh = _meshes_flat_and_hier()
        body = lambda v, axis, topo: psh.combine_shards(
            v, axis, 0, True, topo=topo)
        got = _run_replicated(hier_mesh, x, body)
        np.testing.assert_array_equal(got, x.sum(axis=0, dtype=np.int32))

    def test_gather_blocks_byte_identical(self):
        require_8()
        x = self._data(8 * 64, seed=6)
        flat_mesh, hier_mesh = _meshes_flat_and_hier()
        body = lambda v, axis, topo: psh.gather_blocks(
            v, axis, dim=0, topo=topo)
        got_flat = _run_replicated(flat_mesh, x, body)
        got_hier = _run_replicated(hier_mesh, x, body)
        np.testing.assert_array_equal(got_flat, x.reshape(-1))
        np.testing.assert_array_equal(got_hier, got_flat)

    def test_single_host_flat_records_no_dcn(self):
        require_8()
        x = self._data(8 * 96, seed=7)
        mesh = psh.make_mesh(8)  # flat, one (real) host
        obs.reset()
        _run_collective(mesh, x, lambda v, axis, topo:
                        psh.scatter_to_owner(v, axis, dim=0, topo=topo))
        c = obs.ledger().snapshot()["counters"]
        assert c.get("comms.dcn_bytes", 0) == 0
        assert c.get("comms.ici_bytes", 0) > 0


class TestCommsSurfaces:

    def test_metrics_endpoint_renders_comms_counters(self):
        from pipelinedp_tpu.obs import metrics
        text = metrics.render_prometheus(
            {"comms.collectives": 3, "comms.ici_bytes": 128,
             "comms.dcn_bytes": 64})
        assert "pdp_comms_ici_bytes_total 128" in text
        assert "pdp_comms_dcn_bytes_total 64" in text
        assert "pdp_comms_collectives_total 3" in text

    def test_heartbeat_carries_comms_section(self, tmp_path):
        from pipelinedp_tpu.obs import monitor as obs_monitor
        mon = obs_monitor.Monitor(
            heartbeat_path=str(tmp_path / "hb.json"), run_name="t")
        counters = {"comms.collectives": 5, "comms.ici_bytes": 1024,
                    "comms.dcn_bytes": 256}
        hb = mon._build_heartbeat(mon._t_start + 1.0, [], [], counters,
                                  False, 0.0)
        assert hb["comms"] == {"collectives": 5, "ici_bytes": 1024,
                               "dcn_bytes": 256}
        hb2 = mon._build_heartbeat(mon._t_start + 1.0, [], [], {},
                                   False, 0.0)
        assert "comms" not in hb2


# ---------------------------------------------------------------------------
# End-to-end engine parity: hier vs flat release bit-identity
# ---------------------------------------------------------------------------

def extractors():
    import operator
    return pdp.DataExtractors(
        privacy_id_extractor=operator.itemgetter(0),
        partition_extractor=operator.itemgetter(1),
        value_extractor=operator.itemgetter(2))


def run(backend, data, params, eps=5.0, delta=1e-6):
    noise_ops.seed_host_rng(0)
    acc = pdp.NaiveBudgetAccountant(total_epsilon=eps, total_delta=delta)
    engine = pdp.DPEngine(acc, backend)
    result = engine.aggregate(data, params, extractors())
    acc.compute_budgets()
    return dict(result)


def assert_bit_identical(got_a, got_b):
    """EXACT equality of every released metric — noisy floats included —
    and of the kept-partition sets: the bit-parity contract."""
    assert set(got_a) == set(got_b), (
        f"kept sets differ: {sorted(set(got_a) ^ set(got_b))}")
    for k in got_a:
        ta, tb = got_a[k], got_b[k]
        assert ta._fields == tb._fields
        for f in ta._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(ta, f)), np.asarray(getattr(tb, f)),
                err_msg=f"partition {k}.{f}")


class TestEngineBitParity:
    """Real noise, real private selection, MODERATE eps: any grouping
    drift in the two-stage exchange shows up as a float mismatch."""

    def _data(self, n=3000, parts=6, seed=5):
        rng = np.random.default_rng(seed)
        return [(u, f"p{u % parts}", float(v))
                for u, v in enumerate(rng.uniform(0, 100, n))]

    def _params(self):
        return pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM,
                     pdp.Metrics.PERCENTILE(50)],
            max_partitions_contributed=2,
            max_contributions_per_partition=4,
            min_value=0.0, max_value=100.0)

    def test_hier_matches_flat_on_8_device_mesh(self):
        require_8()
        data, params = self._data(), self._params()
        flat_mesh, hier_mesh = _meshes_flat_and_hier()
        got_flat = run(JaxBackend(mesh=flat_mesh, rng_seed=20), data,
                       params)
        got_hier = run(JaxBackend(mesh=hier_mesh, rng_seed=20), data,
                       params)
        assert_bit_identical(got_flat, got_hier)

    def test_hier_knob_is_noop_on_single_device(self):
        data, params = self._data(n=800), self._params()
        got_plain = run(JaxBackend(rng_seed=20), data, params)
        with topology_env("hier", None):
            mesh = psh.make_mesh(1)
        assert psh.topology_of(mesh).mode == "flat"
        got_hier = run(JaxBackend(mesh=mesh, rng_seed=20), data, params)
        assert_bit_identical(got_plain, got_hier)


# ---------------------------------------------------------------------------
# Streamed elastic shrink under hier
# ---------------------------------------------------------------------------

def run_streamed(ds, params, seed=0, eps=5.0, delta=1e-6,
                 checkpoint=None, mesh=None):
    ds.invalidate_cache()
    acc = pdp.NaiveBudgetAccountant(total_epsilon=eps, total_delta=delta)
    engine = pdp.DPEngine(acc, JaxBackend(rng_seed=seed, mesh=mesh,
                                          checkpoint=checkpoint))
    res = engine.aggregate(ds, params, pdp.DataExtractors())
    acc.compute_budgets()
    got = dict(res)
    assert res.timings.get("stream_batches", 0) > 1
    return got, res.timings


class TestElasticShrinkUnderHier:

    def test_8_to_4_loss_preserves_hier_and_bit_parity(self, tmp_path,
                                                       monkeypatch):
        """Device loss mid-stream on a hier(2,4) mesh: the survivors
        regroup within their host to hier(2,2), the resume adopts the
        checkpoint, and the release is bit-identical to a clean FLAT
        run at the surviving shape — elastic shrink and the topology
        knob compose without touching the released values."""
        require_8()
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "500")
        rng = np.random.default_rng(8)
        n, users, parts = 14_000, 2_000, 12
        ds = pdp.ArrayDataset(
            privacy_ids=rng.integers(0, users, n),
            partition_keys=rng.integers(0, parts, n),
            values=rng.uniform(0.0, 10.0, n))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=parts,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=10.0)
        baseline, _ = run_streamed(ds, params, seed=21,
                                   mesh=psh.make_mesh(4))

        obs.reset()
        store = CheckpointStore(str(tmp_path / "topo.ckpt"))
        with topology_env("hier", 2):
            mesh = psh.make_mesh(8)
        assert psh.topology_of(mesh).hierarchical
        with injected_faults(FaultPlan(lose_device_chunks=(2,))):
            survived, timings = run_streamed(ds, params, seed=21,
                                             mesh=mesh,
                                             checkpoint=store)
        assert timings["stream_mesh_reshards"] == 1
        hist = timings["stream_reshard_history"]
        assert hist[0]["old_devices"] == 8
        assert hist[0]["new_devices"] == 4
        snap = obs.ledger().snapshot()
        reformed = [e for e in snap["events"]
                    if e["name"] == "mesh.reformed"]
        assert reformed and reformed[0]["topology"] == "hier"
        assert reformed[0]["hosts"] == 2
        assert reformed[0]["per_host"] == 2
        assert snap["counters"]["checkpoint.elastic_adoptions"] >= 1
        assert_bit_identical(baseline, survived)
        assert not store.exists()


# ---------------------------------------------------------------------------
# Sharded sketch accumulation parity
# ---------------------------------------------------------------------------

class TestShardedSketchParity:

    def _buckets(self, depth=3, n=5000, width=512, seed=9):
        rng = np.random.default_rng(seed)
        return rng.integers(0, width, (depth, n)).astype(np.int32)

    @pytest.mark.parametrize("backend", ["matmul", "scatter"])
    def test_chunk_program_matches_single_device(self, backend):
        require_8()
        from pipelinedp_tpu.sketch import device as sk_dev
        width = 512
        raw = self._buckets(width=width)
        flat_mesh, hier_mesh = _meshes_flat_and_hier()
        padded = sk_dev.pad_chunk(raw, n_shards=8)
        single = np.asarray(sk_dev._sketch_chunk(padded, width, backend))
        for mesh in (flat_mesh, hier_mesh):
            got = np.asarray(sk_dev.sharded_sketch_chunk_program(
                width, backend, mesh, padded))
            np.testing.assert_array_equal(got, single)

    def test_accumulate_stream_matches_single_device(self):
        require_8()
        from pipelinedp_tpu.sketch import engine as sk_engine
        width = 512
        raw = self._buckets(n=7000, width=width, seed=10)
        tr = obs.tracer()
        want, chunks = sk_engine._accumulate_stream(
            raw, width, "scatter", 1500, tr, mesh=None)
        assert chunks > 1
        flat_mesh, hier_mesh = _meshes_flat_and_hier()
        for mesh in (flat_mesh, hier_mesh):
            got, got_chunks = sk_engine._accumulate_stream(
                raw, width, "scatter", 1500, tr, mesh=mesh)
            assert got_chunks == chunks
            np.testing.assert_array_equal(got, want)

    def test_pad_chunk_aligns_to_shard_blocks(self):
        from pipelinedp_tpu.sketch import device as sk_dev
        raw = self._buckets(n=1000)
        out = sk_dev.pad_chunk(raw, n_shards=8)
        unit = sk_dev.ROW_BLOCK * 8
        assert out.shape[1] % unit == 0
        np.testing.assert_array_equal(out[:, :1000], raw)
        assert (out[:, 1000:] == -1).all()


# ---------------------------------------------------------------------------
# Planner-driven sweep chunk sizing
# ---------------------------------------------------------------------------

class TestPlannedSweepChunk:

    def test_lane_align(self):
        from pipelinedp_tpu.analysis import jax_sweep as js
        assert js._lane_align(4096) == 4096
        assert js._lane_align(133) == 128
        assert js._lane_align(100) == 64
        assert js._lane_align(1) == 1
        assert js._lane_align(0) == 1

    def test_no_plan_keeps_static_formula(self, monkeypatch):
        from pipelinedp_tpu.analysis import jax_sweep as js
        from pipelinedp_tpu.plan import planner
        monkeypatch.setattr(planner, "current_cost_model", lambda: None)
        assert js._plan_chunk(4096, 10_000, 128) == (4096, "static")

    def test_fitted_model_scales_chunk(self, monkeypatch):
        from pipelinedp_tpu.analysis import jax_sweep as js
        from pipelinedp_tpu.plan import planner

        class FakeModel:
            def predict_hbm_peak(self, dk, phase, rows, parts, q):
                assert phase == "sweep"
                return js._SWEEP_HBM_BUDGET * 2  # peak 2x over budget

        monkeypatch.setattr(planner, "current_cost_model", FakeModel)
        chunk, source = js._plan_chunk(512, 10_000, 128)
        assert source == "model"
        assert chunk == 256  # halved, already lane-aligned
        # The static cap still binds when the model would widen.
        chunk_hi, _ = js._plan_chunk(js._CHUNK_CAP * 8, 10_000, 128)
        assert 1 <= chunk_hi <= js._CHUNK_CAP

    def test_poisoned_history_fits_empty_model_and_falls_back(
            self, monkeypatch):
        """A ledger of degraded runs and foreign fingerprints fits an
        EMPTY cost model (plan/model.py skips both), whose predictions
        are all None — the chunk sizing must degrade to the static
        formula, never to a fit over poisoned samples."""
        from pipelinedp_tpu.analysis import jax_sweep as js
        from pipelinedp_tpu.plan import model, planner
        entries = [
            {"fingerprint": "me", "degraded": True, "device_costs": [
                {"phase": "sweep", "rows": 10_000, "partitions": 128,
                 "quantiles": 0, "hbm_peak": 123456}]},
            {"fingerprint": "someone-else", "device_costs": [
                {"phase": "sweep", "rows": 10_000, "partitions": 128,
                 "quantiles": 0, "hbm_peak": 123456}]},
        ]
        poisoned = model.fit(entries, fingerprint="me")
        assert poisoned.predict_hbm_peak(
            None, "sweep", 10_000, 128, 0) is None
        monkeypatch.setattr(planner, "current_cost_model",
                            lambda: poisoned)
        assert js._plan_chunk(4096, 10_000, 128) == (4096, "static")
