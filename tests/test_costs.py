"""Device cost observatory tests (``pipelinedp_tpu/obs/costs``,
``make costcheck``).

Coverage contract:

* roofline math — verdicts flip at the device ridge point exactly;
  unknown device kinds / missing analyses stay ``unknown`` (never a
  made-up ceiling);
* ``instrumented_jit`` — off it dispatches through plain ``jax.jit``
  and records nothing; on it captures exactly ONE compile per
  (function, abstract-shape signature) — the wrapped Python body
  traces once across repeat calls (the compile-count assertion: cost
  capture never pays a second XLA compile) — with flops/bytes,
  memory stats, compile wall time and a persistent-cache verdict in
  the cost table, a ``compile.program`` span under tracing, and new
  signatures creating new entries;
* analysis tolerance — every known shape of ``cost_analysis()`` /
  ``memory_analysis()`` across jax versions (dict, one-element list,
  None, raise, missing fields) degrades to a ``cost.unavailable``
  event, never an error;
* HBM watermark sampling — gated by ``PIPELINEDP_TPU_COSTS``, fills
  the ``hbm.live_bytes`` gauge / ``hbm.watermark`` running max / the
  ledger series behind the Chrome-trace counter track;
* store schema tolerance v1→v2→v3 — a synthetic mixed-schema ledger
  round-trips through ``last_known_good``, ``--summarize`` (text,
  ``--json`` and ``--csv``) and ``bench.py --compare`` without error;
* Chrome-trace counter tracks — sampled series export as ``ph: "C"``
  events; cumulative progress counters differentiate into rows/s;
* the e2e acceptance shape — a traced streamed run on the CPU backend
  lands a ``device_costs`` section with >= 1 program carrying flops,
  compile wall time and cache verdict, plus a roofline verdict per
  recorded phase (``unknown`` only where witnessed by a
  ``cost.unavailable`` event);
* lint twin — AST-precise ban on ``cost_analysis(`` /
  ``memory_analysis(`` / ``live_arrays(`` calls outside
  ``pipelinedp_tpu/obs/`` (``make nocost`` runs the grep twin).

The DP-output bit-parity of costs on vs off (PARITY row 31) lives in
``tests/test_obs.py::TestParity``, extending the trace/audit pattern.
"""

import ast
import csv
import io
import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

import pipelinedp_tpu as pdp
from pipelinedp_tpu import obs
from pipelinedp_tpu.backends import JaxBackend
from pipelinedp_tpu.obs import costs
from pipelinedp_tpu.obs import report as obs_report
from pipelinedp_tpu.obs import store as obs_store
from pipelinedp_tpu.obs.costs import instrumented_jit
from pipelinedp_tpu.obs.tracer import RunLedger
from pipelinedp_tpu.resilience.clock import FakeClock

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ENV_A = {"jax_version": "0.4", "platform": "cpu", "device_kind": "cpu",
         "device_count": 1, "process_count": 1, "git_sha": "aaa"}


@pytest.fixture(autouse=True)
def fresh_state(monkeypatch, tmp_path):
    """Every test starts with capture OFF, a fresh ledger/cost table,
    and an isolated store dir."""
    monkeypatch.delenv(costs.ENV_VAR, raising=False)
    monkeypatch.delenv(obs.ENV_VAR, raising=False)
    monkeypatch.setenv(obs_store.ENV_VAR, str(tmp_path / "ledger"))
    monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "997")
    obs.reset()
    yield
    obs.reset()


class TestRoofline:
    """The static peak table and the verdict math."""

    def test_device_peaks_matching(self):
        assert costs.device_peaks("TPU v5 lite")["kind"] == "tpu_v5e"
        assert costs.device_peaks("TPU v4")["kind"] == "tpu_v4"
        cpu = costs.device_peaks("cpu")
        assert cpu["kind"] == "cpu_proxy" and cpu["proxy"] is True
        assert costs.device_peaks("TPU v9000") is None
        assert costs.device_peaks(None) is None

    def test_verdict_flips_exactly_at_the_ridge(self):
        peaks = {"flops_per_s": 100.0, "hbm_bytes_per_s": 10.0}
        # ridge = 10 flop/byte: at it -> compute, just under -> bandwidth
        at = costs.roofline_verdict(1000.0, 100.0, peaks)
        assert at["verdict"] == "compute_bound"
        assert at["intensity"] == 10.0 and at["ridge"] == 10.0
        under = costs.roofline_verdict(999.0, 100.0, peaks)
        assert under["verdict"] == "bandwidth_bound"

    def test_unknown_when_analysis_or_peaks_missing(self):
        peaks = {"flops_per_s": 100.0, "hbm_bytes_per_s": 10.0}
        assert costs.roofline_verdict(None, 8.0, peaks)[
            "verdict"] == "unknown"
        assert costs.roofline_verdict(8.0, None, peaks)[
            "verdict"] == "unknown"
        assert costs.roofline_verdict(8.0, 0.0, peaks)[
            "verdict"] == "unknown"
        no_peaks = costs.roofline_verdict(8.0, 2.0, None)
        assert no_peaks["verdict"] == "unknown"
        assert no_peaks["ridge"] is None
        # Intensity is a property of the PROGRAM: it must survive a
        # missing peak row (only the verdict needs the ridge).
        assert no_peaks["intensity"] == 4.0

    def test_wide_d_matmul_classifies_compute_bound_on_v5e(self):
        """ISSUE-17 satellite: the wide-D segment-sum program's shape
        on the v5e row. HBM traffic is one pass over the [N, D] lanes
        + pk plus the [P, D] result (the [P, Dt] accumulator slab is
        VMEM-resident across row blocks — the kernel's whole point),
        while the one-hot contraction does 2*N*P*D flops: intensity
        ~P/2 flop/byte, so at P >= ~512 partitions the program clears
        the v5e ridge (~240) and classifies compute_bound — the one
        workload in the repo that saturates the MXU instead of the
        memory system."""
        peaks = costs.device_peaks("TPU v5e")
        assert peaks is not None and peaks["kind"] == "tpu_v5e"
        N, P, D = 200_000, 1024, 256
        flops = 2.0 * N * P * D
        bytes_accessed = 4.0 * (N * D + N + P * D)
        got = costs.roofline_verdict(flops, bytes_accessed, peaks)
        assert got["intensity"] > got["ridge"] > 100
        assert got["verdict"] == "compute_bound"
        # The scalar-lane shape (C ~ a handful of metric columns)
        # stays bandwidth_bound on the same row — wide D is what
        # changes the regime, exactly the ISSUE's motivation.
        C = 4
        scalar = costs.roofline_verdict(
            2.0 * N * 64 * C, 4.0 * (N * C + N + 64 * C), peaks)
        assert scalar["verdict"] == "bandwidth_bound"


class FakeCompiled:
    """Stand-in for a jax Compiled with configurable analyses."""

    def __init__(self, cost=None, memory=None, cost_raises=False,
                 memory_raises=False):
        self._cost, self._memory = cost, memory
        self._cr, self._mr = cost_raises, memory_raises

    def cost_analysis(self):
        if self._cr:
            raise NotImplementedError("no analysis on this backend")
        return self._cost

    def memory_analysis(self):
        if self._mr:
            raise NotImplementedError("no analysis on this backend")
        return self._memory


class FakeMemStats:
    argument_size_in_bytes = 100
    output_size_in_bytes = 40
    temp_size_in_bytes = 60
    alias_size_in_bytes = 20
    generated_code_size_in_bytes = 8


class TestAnalysisTolerance:
    """Every known backend shape degrades gracefully, never raises."""

    def test_cost_analysis_shapes(self):
        d = {"flops": 7.0, "bytes accessed": 3.0}
        got, err = costs._cost_analysis(FakeCompiled(cost=d))
        assert err is None and got == {"flops": 7.0,
                                       "bytes_accessed": 3.0}
        # Older jax wraps the dict in a one-element list.
        got, err = costs._cost_analysis(FakeCompiled(cost=[d]))
        assert err is None and got["flops"] == 7.0
        got, err = costs._cost_analysis(FakeCompiled(cost=None))
        assert got is None and "cost_analysis" in err
        got, err = costs._cost_analysis(FakeCompiled(cost={}))
        assert got is None and "no fields" in err
        got, err = costs._cost_analysis(FakeCompiled(cost_raises=True))
        assert got is None and "NotImplementedError" in err

    def test_memory_analysis_shapes(self):
        got, err = costs._memory_analysis(
            FakeCompiled(memory=FakeMemStats()))
        assert err is None
        # peak = args + outputs + temps + code - aliased
        assert got["peak_bytes"] == 100 + 40 + 60 + 8 - 20
        got, err = costs._memory_analysis(FakeCompiled(memory=None))
        assert got is None and "memory_analysis" in err
        got, err = costs._memory_analysis(
            FakeCompiled(memory_raises=True))
        assert got is None and "NotImplementedError" in err


class TestInstrumentedJit:
    """The seam itself: off = jax.jit; on = capture-once dispatch."""

    def test_off_records_nothing(self):
        traces = {"n": 0}

        @instrumented_jit(phase="t", static_argnames=("k",))
        def f(x, k):
            traces["n"] += 1
            return x * k

        assert float(f(jnp.float32(3.0), k=2)) == 6.0
        assert float(f(jnp.float32(4.0), k=2)) == 8.0
        assert costs.TABLE.snapshot()["programs"] == {}
        assert traces["n"] == 1  # plain jit cache still deduplicates

    def test_on_captures_once_per_signature(self, monkeypatch):
        """THE compile-count assertion: with capture on, two calls at
        the same signature trace (= compile) the wrapped body exactly
        once — dispatch goes through the captured executable, never a
        second XLA compile."""
        monkeypatch.setenv(costs.ENV_VAR, "1")
        monkeypatch.setenv(obs.ENV_VAR, "1")
        traces = {"n": 0}

        @instrumented_jit(phase="walk", static_argnames=("k",))
        def g(x, k):
            traces["n"] += 1
            return x * jnp.float32(k)

        r1 = g(jnp.arange(8, dtype=jnp.float32), k=3)
        r2 = g(jnp.arange(8, dtype=jnp.float32), k=3)
        np.testing.assert_array_equal(np.asarray(r1), np.asarray(r2))
        assert traces["n"] == 1, "same signature recompiled"
        snap = costs.TABLE.snapshot()
        assert len(snap["programs"]) == 1
        (entry,) = snap["programs"].values()
        assert entry["program"] == "g" and entry["phase"] == "walk"
        assert entry["compile_s"] > 0.0
        assert entry["compile_cache"] in ("hit", "miss", "disabled",
                                          "unknown")
        assert entry["calls"] == 2
        # CPU exposes both analyses: flops/bytes and a verdict land.
        assert entry["flops"] is not None
        assert entry["bytes_accessed"] is not None
        assert entry["verdict"] in ("compute_bound", "bandwidth_bound")
        assert entry["memory"]["peak_bytes"] >= 0
        led = obs.ledger().snapshot()
        compile_spans = [s for s in led["spans"]
                         if s.name == "compile.program"]
        assert len(compile_spans) == 1
        assert led["counters"]["cost.programs_captured"] == 1
        # A NEW static value is a new program: second capture.
        g(jnp.arange(8, dtype=jnp.float32), k=4)
        assert traces["n"] == 2
        assert len(costs.TABLE.snapshot()["programs"]) == 2

    def test_phase_aggregates_roll_up(self, monkeypatch):
        monkeypatch.setenv(costs.ENV_VAR, "1")

        @instrumented_jit(phase="pass_a")
        def h1(x):
            return x + 1

        @instrumented_jit(phase="pass_a")
        def h2(x):
            return x * 2

        h1(jnp.arange(4.0))
        h2(jnp.arange(4.0))
        snap = costs.TABLE.snapshot()
        ph = snap["phases"]["pass_a"]
        assert ph["programs"] == 2 and ph["calls"] == 2
        assert ph["verdict"] in ("compute_bound", "bandwidth_bound")
        assert snap["peaks"]["kind"] == "cpu_proxy"

    def test_unavailable_backend_records_event_not_error(
            self, monkeypatch):
        monkeypatch.setenv(costs.ENV_VAR, "1")
        monkeypatch.setattr(
            costs, "_cost_analysis",
            lambda c: (None, "cost_analysis: NotImplementedError"))
        monkeypatch.setattr(
            costs, "_memory_analysis",
            lambda c: (None, "memory_analysis: NotImplementedError"))

        @instrumented_jit(phase="t")
        def f(x):
            return x - 1

        out = f(jnp.arange(4.0))
        np.testing.assert_array_equal(np.asarray(out),
                                      np.arange(4.0) - 1)
        (entry,) = costs.TABLE.snapshot()["programs"].values()
        assert entry["verdict"] == "unknown"
        assert entry["flops"] is None and entry["memory"] is None
        assert entry["unavailable"] and len(entry["unavailable"]) == 2
        led = obs.ledger().snapshot()
        assert led["counters"]["cost.unavailable"] == 1
        ev = next(e for e in led["events"]
                  if e["name"] == "cost.unavailable")
        assert ev["program"] == "f"

    def test_exotic_signature_falls_back_to_plain_jit(
            self, monkeypatch):
        monkeypatch.setenv(costs.ENV_VAR, "1")

        @instrumented_jit(phase="t")
        def f(*xs):
            return sum(xs)

        assert float(f(jnp.float32(1.0), jnp.float32(2.0))) == 3.0
        assert costs.TABLE.snapshot()["programs"] == {}

    def test_dispatch_fallback_on_executable_rejection(
            self, monkeypatch):
        """The signature key sees abstract shapes, not sharding or
        placement — when the AOT executable rejects a call jax.jit
        would have accepted, dispatch falls back to the traced path
        (capture must never take an aggregation down) and records a
        ``cost.dispatch_fallback`` event."""
        monkeypatch.setenv(costs.ENV_VAR, "1")

        @instrumented_jit(phase="t")
        def f(x):
            return x + 2

        x = jnp.arange(4.0)
        expected = np.arange(4.0) + 2
        np.testing.assert_array_equal(np.asarray(f(x)), expected)
        ((key, (_, table_key)),) = f._compiled.items()

        def rejecting_executable(*a, **k):
            raise ValueError("sharding mismatch")

        f._compiled[key] = (rejecting_executable, table_key)
        np.testing.assert_array_equal(np.asarray(f(x)), expected)
        led = obs.ledger().snapshot()
        assert led["counters"]["cost.dispatch_fallbacks"] == 1
        ev = next(e for e in led["events"]
                  if e["name"] == "cost.dispatch_fallback")
        assert ev["program"] == "f" and "ValueError" in ev["error"]

    def test_jit_attributes_pass_through(self):
        @instrumented_jit(phase="t")
        def f(x):
            return x + 1

        lowered = f.lower(jnp.arange(4.0))
        assert lowered is not None
        assert f.__name__ == "f"


class TestHbmSampling:
    """The monitor-beat hook: live-array bytes -> gauges + series."""

    def test_off_is_noop(self):
        assert costs.sample_live_bytes() is None
        assert costs.hbm_snapshot() is None

    def test_on_fills_gauges_watermark_and_series(self, monkeypatch):
        monkeypatch.setenv(costs.ENV_VAR, "1")
        keep = jnp.arange(1024, dtype=jnp.float32)  # noqa: F841
        n = costs.sample_live_bytes()
        assert n is not None and n >= 1024 * 4
        snap = costs.hbm_snapshot()
        assert snap["live_bytes"] == n
        assert snap["watermark"] >= n
        led = obs.ledger().snapshot()
        assert led["counters"]["hbm.live_bytes"] == n
        assert led["counters"]["hbm.watermark"] >= n
        # The time series feeds only the Chrome-trace counter track:
        # it accumulates under tracing, not on the bare heartbeat.
        assert "hbm.live_bytes" not in led["series"]
        monkeypatch.setenv(obs.ENV_VAR, "1")
        costs.sample_live_bytes()
        led = obs.ledger().snapshot()
        assert led["series"]["hbm.live_bytes"], "no series sample"
        # The watermark never comes back down when live bytes do.
        del keep
        costs.sample_live_bytes()
        snap2 = costs.hbm_snapshot()
        assert snap2["watermark"] >= snap["watermark"] or (
            snap2["watermark"] >= snap2["live_bytes"])

    def test_reset_clears_table_and_watermark(self, monkeypatch):
        monkeypatch.setenv(costs.ENV_VAR, "1")
        costs.sample_live_bytes()
        assert costs.hbm_snapshot() is not None
        obs.reset()
        assert costs.hbm_snapshot() is None
        assert costs.TABLE.snapshot()["programs"] == {}


def _mixed_schema_store(tmp_path, fp_env=ENV_A):
    """A synthetic ledger holding one v1, one v2 and one v3 entry for
    the same fingerprint — the store file a long-lived install accretes
    across upgrades."""
    s = obs_store.LedgerStore(str(tmp_path / "mixed"))
    fp = obs_store.fingerprint_key(fp_env)
    v1 = {"schema_version": 1, "name": "run_report", "fingerprint": fp,
          "payload": {"run_report": {
              "schema_version": 1,
              "spans": {"pass_a": {"count": 1, "total_s": 1.0}}}}}
    v2 = {"schema_version": 2, "name": "run_report", "fingerprint": fp,
          "payload": {"run_report": {
              "schema_version": 2,
              "privacy": {"enabled": False},
              "spans": {"pass_a": {"count": 1, "total_s": 0.9}}}}}
    with open(s.path, "w", encoding="utf-8") as f:
        f.write(json.dumps(v1) + "\n")
        f.write(json.dumps(v2) + "\n")
    # The v3 entry goes through the real writer.
    s.append("run_report", {"run_report": {
        "schema_version": 3,
        "spans": {"pass_a": {"count": 1, "total_s": 0.8}},
        "device_costs": {
            "platform": "cpu", "device_kind": "cpu",
            "peaks": {"kind": "cpu_proxy", "flops_per_s": 1e11,
                      "hbm_bytes_per_s": 5e10, "proxy": True},
            "programs": {"_partials_kernel#0001": {
                "program": "_partials_kernel", "phase": "pass_a",
                "compile_s": 0.25, "compile_cache": "miss",
                "flops": 1e6, "bytes_accessed": 1e7,
                "intensity": 0.1, "verdict": "bandwidth_bound",
                "memory": {"peak_bytes": 4096}, "calls": 3}},
            "phases": {"pass_a": {"programs": 1, "calls": 3,
                                  "compile_s": 0.25, "flops": 1e6,
                                  "bytes_accessed": 1e7, "analyzed": 1,
                                  "verdict": "bandwidth_bound",
                                  "intensity": 0.1, "ridge": 2.0}}}}},
        env=fp_env)
    return s, fp


class TestSchemaToleranceV1V2V3:
    """Satellite: a mixed-schema ledger round-trips through every
    reader — ``last_known_good``, ``--summarize`` (all three output
    modes) and ``bench.py --compare`` — without error."""

    def test_entries_and_last_known_good(self, tmp_path):
        s, fp = _mixed_schema_store(tmp_path)
        entries = s.entries()
        assert [e["schema_version"] for e in entries] == [1, 2, 6]
        lkg = s.last_known_good("run_report", fp)
        assert lkg["schema_version"] == 6

    def test_summarize_mixes_all_schemas(self, tmp_path):
        s, fp = _mixed_schema_store(tmp_path)
        summary = obs_store.summarize_entries(s.entries())
        agg = summary[fp]
        assert agg["runs"] == 3
        # All three reports' pass_a spans feed the phase table...
        assert agg["phases"]["pass_a"]["reports"] == 3
        # ...but only the v3 entry contributes cost/roofline columns.
        prog = agg["programs"]["_partials_kernel"]
        assert prog["samples"] == 1
        assert prog["compile_s_latest"] == 0.25
        assert prog["compile_cache"] == "miss"
        assert prog["verdict"] == "bandwidth_bound"
        assert prog["hbm_peak_bytes"] == 4096

    def test_summarize_cli_text_json_csv(self, tmp_path, capsys,
                                         monkeypatch):
        s, fp = _mixed_schema_store(tmp_path)
        base = ["--summarize", "--dir", os.path.dirname(s.path)]
        assert obs_store.main(base) == 0
        text = capsys.readouterr().out
        assert "_partials_kernel" in text
        assert "bandwidth_bound" in text
        assert obs_store.main(base + ["--json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["fingerprints"][fp]["programs"][
            "_partials_kernel"]["flops"] == 1e6
        assert obs_store.main(base + ["--csv"]) == 0
        rows = list(csv.DictReader(io.StringIO(
            capsys.readouterr().out)))
        kinds = {r["kind"] for r in rows}
        assert kinds == {"phase", "program"}
        prog_row = next(r for r in rows if r["kind"] == "program")
        assert prog_row["name"] == "_partials_kernel"
        assert prog_row["verdict"] == "bandwidth_bound"
        assert float(prog_row["flops"]) == 1e6

    def test_program_rows_key_per_signature(self):
        """Two shape signatures of one kernel aggregate as separate
        rows (distinct XLA programs must not share a compile-trend
        series); signature-less entries keep the bare name."""
        p1 = {"program": "k", "signature": "P=16, f32[16]"}
        p2 = {"program": "k", "signature": "P=32, f32[32]"}
        k1, k2 = (obs_store._program_row_key(p1),
                  obs_store._program_row_key(p2))
        assert k1 != k2
        assert k1.startswith("k@") and k2.startswith("k@")
        assert obs_store._program_row_key(p1) == k1  # stable
        assert obs_store._program_row_key({"program": "k"}) == "k"

    def test_json_and_csv_are_mutually_exclusive(self, tmp_path):
        with pytest.raises(SystemExit):
            obs_store.main(["--summarize", "--dir", str(tmp_path),
                            "--json", "--csv"])

    def test_bench_compare_tolerates_mixed_schemas(self, monkeypatch,
                                                   tmp_path):
        """``bench.py --compare`` against a store whose baseline
        entries span v1..v3: no error, and the span comparison still
        works off whichever schemas carry spans."""
        monkeypatch.setenv(obs_store.ENV_VAR, str(tmp_path / "mixed"))
        monkeypatch.syspath_prepend(REPO)
        import bench
        bench.reset_run_state()
        _mixed_schema_store(tmp_path, fp_env=bench.env_fingerprint())
        bench.reset_run_state()  # re-reads baselines incl. the mix
        report = {"schema_version": 3,
                  "spans": {"pass_a": {"count": 1, "total_s": 0.7}}}
        reg = bench.compare_to_baseline(records=[], run_report=report)
        span = next((p for p in reg["spans"]
                     if p["span"] == "pass_a"), None)
        assert span is not None and span["baseline_total_s"] == 0.8
        assert reg["regressed"] == []


class TestChromeCounterTracks:
    """Satellite: sampled series export as ``ph: "C"`` counter events —
    rows/s differentiated from the cumulative progress counter, raw
    values for live-HBM bytes."""

    def test_counter_track_export(self):
        clock = FakeClock(100.0)
        led = RunLedger(clock=clock)
        led.sample("hbm.live_bytes", 1000.0)
        clock.sleep(1.0)
        led.sample("hbm.live_bytes", 3000.0)
        events = obs_report.chrome_trace_events(led.snapshot())
        cs = [e for e in events if e["ph"] == "C"]
        assert [e["args"]["value"] for e in cs] == [1000.0, 3000.0]
        assert cs[0]["name"] == "hbm.live_bytes"
        assert cs[1]["ts"] - cs[0]["ts"] == pytest.approx(1e6)

    def test_progress_counter_differentiates_to_rate(self):
        clock = FakeClock(10.0)
        led = RunLedger(clock=clock)
        # Cumulative rows-staged samples: 0 -> 997 over 1s -> 997 rows/s
        led.sample("progress.rows_staged", 0.0)
        clock.sleep(1.0)
        led.sample("progress.rows_staged", 997.0)
        clock.sleep(2.0)
        led.sample("progress.rows_staged", 997.0 + 4000.0)
        events = obs_report.chrome_trace_events(led.snapshot())
        cs = [e for e in events if e["ph"] == "C"]
        assert all(e["name"] == "rows/s" for e in cs)
        assert [e["args"]["value"] for e in cs] == [997.0, 2000.0]

    def test_traced_inc_feeds_the_series(self, monkeypatch):
        monkeypatch.setenv(obs.ENV_VAR, "1")
        obs.inc("progress.rows_staged", 997)
        obs.inc("progress.rows_staged", 997)
        snap = obs.ledger().snapshot()
        assert [v for _, v in snap["series"][
            "progress.rows_staged"]] == [997.0, 1994.0]


def run_streamed(seed=31, chunk_env="PIPELINEDP_TPU_STREAM_CHUNK"):
    rng = np.random.default_rng(seed)
    n, users, parts = 9_000, 2_000, 12
    ds = pdp.ArrayDataset(privacy_ids=rng.integers(0, users, n),
                          partition_keys=rng.integers(0, parts, n),
                          values=rng.uniform(0.0, 10.0, n))
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM,
                 pdp.Metrics.PERCENTILE(50)],
        max_partitions_contributed=parts,
        max_contributions_per_partition=50,
        min_value=0.0, max_value=10.0)
    acc = pdp.NaiveBudgetAccountant(total_epsilon=1e12,
                                    total_delta=1e-2)
    engine = pdp.DPEngine(acc, JaxBackend(rng_seed=17))
    res = engine.aggregate(ds, params, pdp.DataExtractors())
    acc.compute_budgets()
    return dict(res)


class TestAcceptanceEndToEnd:
    """The ISSUE acceptance shape on the CPU backend: a traced run with
    ``PIPELINEDP_TPU_COSTS=1`` produces a run report whose
    ``device_costs`` section carries >= 1 program with flops, compile
    wall time and cache verdict, plus a roofline verdict for every
    recorded phase — ``unknown`` only when witnessed by a
    ``cost.unavailable`` event."""

    def test_traced_streamed_run_lands_device_costs(self, monkeypatch):
        monkeypatch.setenv(costs.ENV_VAR, "1")
        monkeypatch.setenv(obs.ENV_VAR, "1")
        # A chunk size unique to this test: the kernels' abstract
        # shapes must be fresh so capture fires even after other tests
        # compiled the default-chunk programs.
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "983")
        run_streamed()
        report = obs.build_run_report()
        assert report["schema_version"] == 6
        dc = report["device_costs"]
        assert len(dc["programs"]) >= 1
        assert dc["device_kind"], "device kind not captured"
        for entry in dc["programs"].values():
            assert entry["compile_s"] > 0.0
            assert entry["compile_cache"] in ("hit", "miss",
                                              "disabled", "unknown")
        events = obs.ledger().snapshot()["events"]
        unavailable = {e["program"] for e in events
                      if e["name"] == "cost.unavailable"}
        for key, entry in dc["programs"].items():
            if entry["program"] not in unavailable:
                assert entry["flops"] is not None, key
        for name, ph in dc["phases"].items():
            if ph["verdict"] == "unknown":
                assert ph["analyzed"] == 0
                assert unavailable, (
                    f"phase {name} unknown without a cost.unavailable "
                    "witness")
            else:
                assert ph["verdict"] in ("compute_bound",
                                         "bandwidth_bound")
        # The streamed phases all surfaced.
        assert {"pass_a", "pass_b", "walk", "select"} <= set(
            dc["phases"])


class TestNoDirectAnalysisCalls:
    """AST-precise twin of ``make nocost``: ``cost_analysis(`` /
    ``memory_analysis(`` / ``live_arrays(`` calls are banned outside
    ``pipelinedp_tpu/obs/`` — device-cost capture must flow through the
    observatory so every measurement lands in the versioned report."""

    def test_analysis_calls_only_under_obs(self):
        # Delegates to the shared AST engine; `make nocost` is the
        # same rule.
        from pipelinedp_tpu import lint
        assert lint.check_tree("nocost") == []
