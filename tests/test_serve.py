"""Resident multi-tenant serve acceptance suite.

The ISSUE-12 acceptance criteria, end to end:

* a resident ``serve.Service`` handles >= 3 tenants' interleaved
  requests through warm programs — the second same-signature request
  is a registry hit AND captures no new ``compile.program`` span;
* an overdrawing request is refused BEFORE any compute runs, with the
  shortfall named;
* two threads racing ``submit()`` against a tenant whose remaining
  budget covers only one request: exactly one succeeds, and the
  durable ledger after a kill-and-restart replays to exactly one
  debit and the same remaining (eps, delta);
* serve-path outputs are bit-identical to the direct ``DPEngine``
  path (PARITY row 34);
* admission control refuses malformed params / queue-full /
  per-tenant in-flight overflow as structured responses, and a
  drained service leaves zero orphan ``pdp-*`` threads;
* the heartbeat snapshots every live request in one document, at a
  run-namespaced path;
* the ``noserve`` AST twins: budget-ledger writes confined to
  ``serve/`` + ``budget_accounting.py``, and no batch-engine module
  imports ``pipelinedp_tpu.serve``.
"""

import ast
import json
import os
import threading

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import obs, serve
from pipelinedp_tpu.backends import JaxBackend
from pipelinedp_tpu.budget_accounting import Budget
from pipelinedp_tpu.obs import monitor as obs_monitor
from pipelinedp_tpu.resilience import faults
from pipelinedp_tpu.resilience.clock import FakeClock
from pipelinedp_tpu.serve.budget_ledger import (DuplicateRequest,
                                                Overdraw,
                                                TenantBudgetLedger,
                                                TenantMismatch)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BIG_EPS = 1e6


@pytest.fixture(autouse=True)
def fresh_state(monkeypatch, tmp_path):
    """Fresh obs state, isolated ledger dir, heartbeat off — and a
    zero-orphan-thread assertion over EVERY test in this file (the
    ingest-executor drain discipline, applied to pdp-serve-*)."""
    monkeypatch.setenv("PIPELINEDP_TPU_LEDGER_DIR",
                       str(tmp_path / "obs_ledger"))
    monkeypatch.delenv(obs_monitor.ENV_VAR, raising=False)
    obs.reset()
    yield
    obs_monitor.stop()
    obs.reset()
    orphans = [t.name for t in threading.enumerate()
               if t.name.startswith("pdp-serve") and t.is_alive()]
    assert not orphans, f"orphan serve threads: {orphans}"


def make_ds(seed=0, n=6_000, users=1_500, parts=10):
    rng = np.random.default_rng(seed)
    return pdp.ArrayDataset(privacy_ids=rng.integers(0, users, n),
                            partition_keys=rng.integers(0, parts, n),
                            values=rng.uniform(0.0, 10.0, n))


def count_params(parts=10):
    return pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
        max_partitions_contributed=parts,
        max_contributions_per_partition=20,
        min_value=0.0, max_value=10.0)


def request(tenant, ds, eps=1.0, delta=1e-8, seed=7, rid=None,
            params=None):
    return serve.ServeRequest(tenant=tenant,
                              params=params or count_params(),
                              dataset=ds, epsilon=eps, delta=delta,
                              rng_seed=seed, request_id=rid)


# ---------------------------------------------------------------------
# durable budget ledger
# ---------------------------------------------------------------------


class TestBudgetLedger:

    def test_reserve_commit_remaining_and_restart_replay(self, tmp_path):
        led = TenantBudgetLedger(str(tmp_path))
        rem = led.open_tenant("acme", 4.0, 1e-6)
        assert rem.epsilon == 4.0 and rem.delta == 1e-6
        lease = led.reserve("acme", "r1", 1.5, 2e-7)
        assert lease.state == "reserved"
        led.commit("acme", "r1")
        rem = led.remaining("acme")
        assert rem.epsilon == pytest.approx(2.5)
        assert rem.delta == pytest.approx(8e-7)
        # Kill-and-restart: a fresh instance over the same directory
        # replays to the same remaining (eps, delta).
        led2 = TenantBudgetLedger(str(tmp_path))
        rem2 = led2.remaining("acme")
        assert rem2.epsilon == pytest.approx(rem.epsilon)
        assert rem2.delta == pytest.approx(rem.delta)
        assert led2.debits("acme")["r1"]["state"] == "committed"

    def test_reserve_is_exactly_once_per_request_id(self, tmp_path):
        led = TenantBudgetLedger(str(tmp_path))
        led.open_tenant("t", 2.0, 0.0)
        led.reserve("t", "r1", 1.5, 0.0)
        # Same id again: the SAME lease comes back, no second debit —
        # even though a fresh 1.5 would overdraw the remaining 0.5.
        again = led.reserve("t", "r1", 1.5, 0.0)
        assert again.epsilon == 1.5 and again.state == "reserved"
        assert led.remaining("t").epsilon == pytest.approx(0.5)

    def test_replay_retry_must_match_reserved_amounts(self, tmp_path):
        """The restart-replay dedup hands back the original lease ONLY
        to a retry carrying the original (eps, delta) — a different
        demand under the same id must not silently run at amounts the
        caller never asked for."""
        led = TenantBudgetLedger(str(tmp_path))
        led.open_tenant("t", 2.0, 0.0)
        led.reserve("t", "r1", 1.5, 0.0)
        with pytest.raises(serve.LedgerError, match="must carry"):
            led.reserve("t", "r1", 0.5, 0.0)
        # The refused mismatch touched nothing.
        assert led.debits("t")["r1"]["epsilon"] == 1.5
        assert led.remaining("t").epsilon == pytest.approx(0.5)

    def test_committed_id_refuses_re_reserve(self, tmp_path):
        """A committed debit's output was RELEASED: re-running the id
        would publish a second noisy view on one charge — refused."""
        led = TenantBudgetLedger(str(tmp_path))
        led.open_tenant("t", 5.0, 0.0)
        led.reserve("t", "r1", 1.0, 0.0)
        led.commit("t", "r1")
        with pytest.raises(DuplicateRequest):
            led.reserve("t", "r1", 1.0, 0.0)
        assert led.remaining("t").epsilon == pytest.approx(4.0)

    def test_released_id_may_retry_as_fresh_debit(self, tmp_path):
        """A released debit was refunded (clean pre-release failure):
        the retry is a fresh debit at the NEW amounts, overdraw-checked
        like any other."""
        led = TenantBudgetLedger(str(tmp_path))
        led.open_tenant("t", 2.0, 0.0)
        led.reserve("t", "r1", 1.5, 0.0)
        led.release("t", "r1")
        lease = led.reserve("t", "r1", 1.0, 0.0)
        assert lease.epsilon == 1.0 and lease.state == "reserved"
        assert led.remaining("t").epsilon == pytest.approx(1.0)
        assert len(led.debits("t")) == 1

    def test_overdraw_refused_without_writing(self, tmp_path):
        led = TenantBudgetLedger(str(tmp_path))
        led.open_tenant("t", 1.0, 1e-8)
        before = open(led.path_for("t"), "rb").read()
        with pytest.raises(Overdraw) as ei:
            led.reserve("t", "r1", 3.0, 0.0)
        assert ei.value.shortfall.epsilon == pytest.approx(2.0)
        assert "shortfall" in str(ei.value)
        assert open(led.path_for("t"), "rb").read() == before
        assert led.remaining("t").epsilon == pytest.approx(1.0)

    def test_reserved_but_uncommitted_stays_spent_on_replay(
            self, tmp_path):
        """The kill-mid-request window: a reserve with no commit and
        no release must count as SPENT after restart (noise may have
        been drawn) — the DP-conservative direction."""
        led = TenantBudgetLedger(str(tmp_path))
        led.open_tenant("t", 2.0, 0.0)
        led.reserve("t", "dead", 1.5, 0.0)
        led2 = TenantBudgetLedger(str(tmp_path))
        assert led2.remaining("t").epsilon == pytest.approx(0.5)
        assert led2.debits("t")["dead"]["state"] == "reserved"

    def test_release_refunds_clean_failures(self, tmp_path):
        led = TenantBudgetLedger(str(tmp_path))
        led.open_tenant("t", 2.0, 0.0)
        led.reserve("t", "r1", 1.5, 0.0)
        led.release("t", "r1")
        assert led.remaining("t").epsilon == pytest.approx(2.0)
        # A committed debit can never be released back.
        led.reserve("t", "r2", 1.0, 0.0)
        led.commit("t", "r2")
        with pytest.raises(serve.LedgerError):
            led.release("t", "r2")

    def test_totals_mismatch_refused(self, tmp_path):
        led = TenantBudgetLedger(str(tmp_path))
        led.open_tenant("t", 2.0, 0.0)
        led.open_tenant("t", 2.0, 0.0)  # idempotent re-open
        with pytest.raises(TenantMismatch):
            TenantBudgetLedger(str(tmp_path)).open_tenant("t", 3.0, 0.0)

    def test_failed_durable_write_leaves_cache_on_disk_state(
            self, tmp_path, monkeypatch):
        """A durable-write failure (disk full, I/O error) must not
        leave the in-memory cache ahead of disk: the exception
        propagates AND the cached doc stays on the last durable state,
        so memory and disk never diverge for the rest of the process."""
        from pipelinedp_tpu.serve import budget_ledger as bl
        led = TenantBudgetLedger(str(tmp_path))
        led.open_tenant("t", 2.0, 0.0)
        led.reserve("t", "r1", 0.5, 0.0)
        real_write = bl.atomic_write_json

        def full_disk(path, doc):
            raise OSError("disk full")

        monkeypatch.setattr(bl, "atomic_write_json", full_disk)
        with pytest.raises(OSError):
            led.reserve("t", "r2", 0.5, 0.0)
        with pytest.raises(OSError):
            led.commit("t", "r1")
        # In-memory state is exactly the last durable state...
        assert led.remaining("t").epsilon == pytest.approx(1.5)
        assert "r2" not in led.debits("t")
        assert led.debits("t")["r1"]["state"] == "reserved"
        # ...and a disk replay agrees with it to the byte.
        monkeypatch.setattr(bl, "atomic_write_json", real_write)
        assert TenantBudgetLedger(str(tmp_path)).debits(
            "t") == led.debits("t")
        # The healed ledger proceeds normally.
        led.commit("t", "r1")
        assert led.remaining("t").epsilon == pytest.approx(1.5)


# ---------------------------------------------------------------------
# the resident service
# ---------------------------------------------------------------------


class TestServiceAcceptance:

    def test_three_tenants_interleaved_warm_no_new_compiles(
            self, tmp_path, monkeypatch):
        """>= 3 tenants' requests interleave through one resident
        service; each tenant's SECOND same-signature request is a warm
        registry hit and — with the cost observatory watching every
        jitted entry — captures zero new ``compile.program`` spans."""
        monkeypatch.setenv("PIPELINEDP_TPU_COSTS", "1")
        tenants = {f"t{i}": (10.0, 1e-6) for i in range(3)}
        ds = make_ds()
        with serve.Service(str(tmp_path / "svc"),
                           tenants=tenants) as svc:
            first = {}
            for tenant in tenants:  # round 1: cold registry builds
                ds.invalidate_cache()
                out = svc.submit(request(tenant, ds, eps=1.0))
                assert out.ok, out
                assert out.warm is False
                first[tenant] = dict(out.results)
            captured = obs.ledger().snapshot()["counters"].get(
                "cost.programs_captured", 0)
            for tenant in tenants:  # round 2: warm, zero new programs
                ds.invalidate_cache()
                out = svc.submit(request(tenant, ds, eps=1.0))
                assert out.ok, out
                assert out.warm is True
                # Same seed + same data -> the warm program replays
                # the identical release.
                assert dict(out.results) == first[tenant]
                assert out.remaining.epsilon == pytest.approx(8.0)
            after = obs.ledger().snapshot()["counters"].get(
                "cost.programs_captured", 0)
            assert after == captured, (
                "second same-signature requests captured new "
                "compile.program spans")

    def test_overdraw_refused_before_any_compute(self, tmp_path):
        ds = make_ds()
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"t": (1.0, 1e-8)}) as svc:
            out = svc.submit(request("t", ds, eps=5.0))
            assert not out.ok
            assert out.reason == "overdraw"
            assert "shortfall" in out.detail
            assert out.remaining.epsilon == pytest.approx(1.0)
            counters = obs.ledger().snapshot()["counters"]
            # Nothing ran: no engine was ever built for the request.
            assert counters.get("serve.cold_builds", 0) == 0
            assert counters.get("serve.requests_admitted", 0) == 0
            # And the durable ledger still holds the full budget.
            assert svc.budgets.remaining("t").epsilon == pytest.approx(
                1.0)

    def test_serve_path_bit_identical_to_direct_engine(self, tmp_path):
        """PARITY row 34: same params, data and seed through the
        resident service and through a hand-built DPEngine release
        bit-identical outputs — twice, so the WARM program is also in
        scope."""
        ds = make_ds(seed=3)
        params = count_params()
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"t": (10.0, 1e-6)}) as svc:
            served = []
            for _ in range(2):
                ds.invalidate_cache()
                out = svc.submit(request("t", ds, eps=0.8, delta=1e-8,
                                         seed=11, params=params))
                assert out.ok, out
                served.append(dict(out.results))
        acc = pdp.NaiveBudgetAccountant(total_epsilon=0.8,
                                        total_delta=1e-8)
        engine = pdp.DPEngine(acc, JaxBackend(rng_seed=11))
        ds.invalidate_cache()
        res = engine.aggregate(ds, params, pdp.DataExtractors())
        acc.compute_budgets()
        direct = dict(res)
        assert served[0] == direct
        assert served[1] == direct

    def test_malformed_refusals(self, tmp_path):
        ds = make_ds()
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"t": (5.0, 1e-6)}) as svc:
            not_a_request = svc.submit({"tenant": "t"})
            assert not not_a_request.ok
            assert not_a_request.reason == "malformed"
            assert "ServeRequest" in not_a_request.detail
            bad_params = svc.submit(serve.ServeRequest(
                tenant="t", params="not-params", dataset=ds,
                epsilon=1.0))
            assert bad_params.reason == "malformed"
            empty = svc.submit(request("t", pdp.ArrayDataset(
                privacy_ids=np.array([], dtype=np.int64),
                partition_keys=np.array([], dtype=np.int64),
                values=np.array([]))))
            assert empty.reason == "malformed"
            unknown = svc.submit(request("ghost", ds))
            assert unknown.reason == "malformed"
            # Refusals naming unknown tenants never grow per-tenant
            # state in a resident process: no books dir, no in-flight
            # slot, no ledger lock entry.
            assert not os.path.exists(svc.books_dir("ghost"))
            assert "ghost" not in svc._inflight
            assert "ghost" not in svc.budgets._tenant_locks
            nonpos = svc.submit(request("t", ds, eps=0.0))
            assert nonpos.reason == "malformed"
            # None of it burned budget.
            assert svc.budgets.remaining("t").epsilon == pytest.approx(
                5.0)

    def test_duplicate_request_id_refused_after_success(self, tmp_path):
        """Resubmitting a SERVED request id is a structured
        'duplicate' refusal — never a silent second release."""
        ds = make_ds(n=800, parts=4)
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"t": (5.0, 1e-6)}) as svc:
            first = svc.submit(request("t", ds, eps=1.0, rid="dup"))
            assert first.ok
            again = svc.submit(request("t", ds, eps=1.0, rid="dup"))
            assert not again.ok and again.reason == "duplicate"
            assert svc.budgets.remaining("t").epsilon == pytest.approx(
                4.0)

    def test_duplicate_request_id_refused_while_in_flight(
            self, tmp_path, monkeypatch):
        """A retry of an id whose ORIGINAL IS STILL RUNNING (a client
        re-sending a slow request) is refused at admission — without
        this, both copies would execute against the ledger's one
        reserved debit and release two noisy views on one charge. The
        ledger's reserved-dedup lease is for restart replay only."""
        gate = threading.Event()
        started = threading.Event()
        real_execute = serve.Service._execute

        def gated_execute(self, pending):
            started.set()
            gate.wait(timeout=30)
            real_execute(self, pending)

        monkeypatch.setattr(serve.Service, "_execute", gated_execute)
        ds = make_ds(n=800, parts=4)
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"t": (5.0, 1e-6)},
                           workers=1) as svc:
            outs = {}

            def bg():
                outs["first"] = svc.submit(
                    request("t", ds, eps=1.0, rid="dup"))

            t1 = threading.Thread(target=bg)
            t1.start()
            assert started.wait(timeout=30)
            retry = svc.submit(request("t", ds, eps=1.0, rid="dup"))
            assert not retry.ok and retry.reason == "duplicate"
            assert "in flight" in retry.detail
            gate.set()
            t1.join(timeout=120)
            assert outs["first"].ok
            # Exactly one debit, one charge, one released output.
            debits = svc.budgets.debits("t")
            assert list(debits) == ["dup"]
            assert debits["dup"]["state"] == "committed"
            assert svc.budgets.remaining("t").epsilon == pytest.approx(
                4.0)

    def test_same_request_id_across_tenants_never_collides(
            self, tmp_path, monkeypatch):
        """The in-flight guard is scoped per tenant, like the ledger's
        debits: tenant b reusing tenant a's request id (both clients
        numbering their own requests) must be admitted, not refused as
        a duplicate of a's still-running request."""
        gate = threading.Event()
        started = threading.Event()
        real_execute = serve.Service._execute

        def gated_execute(self, pending):
            if pending.request.tenant == "a":
                started.set()
                gate.wait(timeout=30)
            real_execute(self, pending)

        monkeypatch.setattr(serve.Service, "_execute", gated_execute)
        ds = make_ds(n=800, parts=4)
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"a": (5.0, 1e-6),
                                    "b": (5.0, 1e-6)},
                           workers=2) as svc:
            outs = {}
            t1 = threading.Thread(
                target=lambda: outs.setdefault("a", svc.submit(
                    request("a", ds, eps=1.0, rid="same"))))
            t1.start()
            assert started.wait(timeout=30)
            got_b = svc.submit(request("b", ds, eps=1.0, rid="same"))
            assert got_b.ok, got_b
            gate.set()
            t1.join(timeout=120)
            assert outs["a"].ok
            assert svc.budgets.debits("a")["same"]["state"] == "committed"
            assert svc.budgets.debits("b")["same"]["state"] == "committed"

    def test_replayed_lease_never_refunded_on_clean_failure(
            self, tmp_path):
        """A restart replay whose retry fails CLEANLY must leave the
        debit SPENT: the pre-restart attempt may have drawn noise
        before dying, so refunding would be the unsafe direction —
        unlike a fresh reserve, which a clean failure refunds."""
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"t": (5.0, 1e-6)}) as svc:
            # The restart-replay state: a reserved debit with no live
            # request, then a retry whose rows no extractor can pull
            # apart (fails inside the engine, before any DP output).
            svc.budgets.reserve("t", "replay", 1.0, 1e-8)
            out = svc.submit(request("t", [1, 2, 3], eps=1.0,
                                     rid="replay"))
            assert not out.ok and out.reason == "error"
            assert svc.budgets.debits("t")["replay"][
                "state"] == "reserved"
            assert svc.budgets.remaining("t").epsilon == pytest.approx(
                4.0)

    def test_clean_failure_heals_engine_for_stale_entry_holders(
            self, tmp_path, monkeypatch):
        """A failure AFTER the accountant registered mechanisms (but
        before finalize) must leave the warm engine rebindable before
        the entry lock releases: a same-signature waiter that fetched
        the entry before the failure dropped it from the registry is
        served on a fresh accountant, not refused over leftovers."""
        ds = make_ds(n=500, parts=4)
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"t": (5.0, 1e-6)}) as svc:
            ok = svc.submit(request("t", ds, eps=1.0))
            assert ok.ok
            (entry,) = list(svc._registry.values())
            real = pdp.NaiveBudgetAccountant.compute_budgets

            def boom(self):
                raise RuntimeError("post-registration failure")

            monkeypatch.setattr(pdp.NaiveBudgetAccountant,
                                "compute_budgets", boom)
            ds.invalidate_cache()
            bad = svc.submit(request("t", ds, eps=1.0))
            assert not bad.ok and bad.reason == "error"
            monkeypatch.setattr(pdp.NaiveBudgetAccountant,
                                "compute_budgets", real)
            # The stale entry's engine rebinds cleanly — the failure
            # path cleared its half-run accountant under the lock.
            entry.engine.rebind_budget_accountant(
                pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                          total_delta=0.0))
            # And the failed FRESH reserve was refunded.
            assert svc.budgets.remaining("t").epsilon == pytest.approx(
                4.0)

    def test_replay_with_mismatched_amounts_refused(self, tmp_path):
        """A restart replay must carry the reserved debit's original
        (eps, delta): a different demand under the same id is refused
        as malformed instead of silently running at the old amounts;
        the matching retry dedupes onto the debit and serves."""
        ds = make_ds(n=800, parts=4)
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"t": (5.0, 1e-6)}) as svc:
            # The restart-replay state: a reserved debit with no live
            # request (the previous process died mid-compute).
            svc.budgets.reserve("t", "replay", 1.0, 1e-9)
            bad = svc.submit(request("t", ds, eps=0.5, delta=1e-9,
                                     rid="replay"))
            assert not bad.ok and bad.reason == "malformed"
            assert "must carry" in bad.detail
            good = svc.submit(request("t", ds, eps=1.0, delta=1e-9,
                                      rid="replay"))
            assert good.ok
            assert svc.budgets.remaining("t").epsilon == pytest.approx(
                4.0)
            assert svc.budgets.debits("t")["replay"][
                "state"] == "committed"

    def test_non_string_request_id_never_ghosts_the_live_set(
            self, tmp_path):
        """A non-string request_id is normalized to str at admission,
        so the worker's teardown key matches and the id never sticks
        in the live set refusing later submits as phantom duplicates."""
        ds = make_ds(n=800, parts=4)
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"t": (5.0, 1e-6)}) as svc:
            first = svc.submit(request("t", ds, eps=1.0, rid=7))
            assert first.ok and first.request_id == "7"
            assert not svc._live
            # The committed id refuses a re-run (ledger, not a ghost).
            again = svc.submit(request("t", ds, eps=1.0, rid=7))
            assert not again.ok and again.reason == "duplicate"
            assert "committed" in again.detail
            # A FALSY id like 0 is a real id, not "absent": its second
            # submit must hit the same exactly-once refusal, never a
            # fresh generated id (which would charge twice and release
            # two noisy views of one logical request).
            ds.invalidate_cache()
            zero = svc.submit(request("t", ds, eps=1.0, rid=0))
            assert zero.ok and zero.request_id == "0"
            zero_again = svc.submit(request("t", ds, eps=1.0, rid=0))
            assert not zero_again.ok and zero_again.reason == "duplicate"

    def test_slot_and_live_id_freed_before_submit_returns(
            self, tmp_path):
        """finish() runs the worker's teardown BEFORE unblocking the
        submitter: the moment submit() returns, an immediate same-id
        retry of a cleanly-failed (refunded) request is admitted, and
        the in-flight slot is free — no racing the worker's cleanup."""
        ds = make_ds(n=800, parts=4)
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"t": (5.0, 1e-6)},
                           max_inflight_per_tenant=1) as svc:
            failed = svc.submit(request("t", [1, 2, 3], eps=1.0,
                                        rid="retry-me"))
            assert not failed.ok and failed.reason == "error"
            # Immediately: slot free, id free, fresh debit admitted.
            assert svc._inflight.get("t", 0) == 0
            assert not svc._live
            retried = svc.submit(request("t", ds, eps=1.0,
                                         rid="retry-me"))
            assert retried.ok, retried
            assert svc.budgets.remaining("t").epsilon == pytest.approx(
                4.0)

    def test_engine_error_releases_the_reserve(self, tmp_path):
        """A request that fails CLEANLY inside the engine (no DP
        output ever existed) refunds its reserve and comes back as a
        structured 'error' refusal."""
        # Rows that no extractor can pull apart: AggregateParams
        # validation passes at admission, but the engine's own checks
        # reject the request once the worker runs it.
        broken_rows = [1, 2, 3]
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"t": (5.0, 1e-6)}) as svc:
            out = svc.submit(request("t", broken_rows, eps=1.0))
            assert not out.ok and out.reason == "error"
            assert svc.budgets.remaining("t").epsilon == pytest.approx(
                5.0)
            assert svc.budgets.debits("t")[out.request_id][
                "state"] == "released"

    def test_queue_full_and_tenant_busy_backpressure(self, tmp_path,
                                                     monkeypatch):
        """Admission control under load: a gated worker holds the one
        queue slot + the in-flight cap, and further submits come back
        as structured queue_full / tenant_busy refusals — budget
        untouched."""
        gate = threading.Event()
        started = threading.Event()
        real_execute = serve.Service._execute

        def gated_execute(self, pending):
            started.set()
            gate.wait(timeout=30)
            real_execute(self, pending)

        monkeypatch.setattr(serve.Service, "_execute", gated_execute)
        ds = make_ds(n=800, parts=4)
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"a": (50.0, 1e-5),
                                    "b": (50.0, 1e-5),
                                    "c": (50.0, 1e-5)},
                           max_queue=1, max_inflight_per_tenant=1,
                           workers=1) as svc:
            outs = {}

            def bg(name, req):
                outs[name] = svc.submit(req)

            t1 = threading.Thread(target=bg, args=(
                "first", request("a", ds, eps=1.0)))
            t1.start()
            assert started.wait(timeout=30)
            # Worker busy with tenant a; same tenant again -> the
            # per-tenant in-flight cap refuses first.
            busy = svc.submit(request("a", ds, eps=1.0))
            assert busy.reason == "tenant_busy"
            # Another tenant fills the one queue slot...
            t2 = threading.Thread(target=bg, args=(
                "second", request("b", ds, eps=1.0)))
            t2.start()
            deadline = [svc._q.full()]
            for _ in range(500):
                if deadline[-1]:
                    break
                threading.Event().wait(0.01)
                deadline.append(svc._q.full())
            assert deadline[-1], "queued request never landed"
            # ...so a THIRD tenant sees pure queue-full backpressure
            # (its own in-flight count is zero).
            full = svc.submit(request("c", ds, eps=1.0))
            assert full.reason == "queue_full"
            gate.set()
            t1.join(timeout=60)
            t2.join(timeout=60)
            assert outs["first"].ok and outs["second"].ok
            # Refused requests burned nothing; served ones debited.
            assert svc.budgets.remaining("a").epsilon == pytest.approx(
                49.0)
            assert svc.budgets.remaining("b").epsilon == pytest.approx(
                49.0)
            assert svc.budgets.remaining("c").epsilon == pytest.approx(
                50.0)

    def test_shutdown_refusal_after_close(self, tmp_path):
        svc = serve.Service(str(tmp_path / "svc"),
                            tenants={"t": (5.0, 1e-6)})
        ds = make_ds(n=500, parts=4)
        first = svc.submit(request("t", ds, eps=1.0))
        assert first.ok
        svc.close()
        out = svc.submit(request("t", ds, eps=1.0))
        assert not out.ok and out.reason == "shutdown"
        svc.close()  # idempotent


# ---------------------------------------------------------------------
# concurrent overdraw + kill-and-restart (satellite 3)
# ---------------------------------------------------------------------


class TestConcurrentOverdraw:

    def test_racing_submits_exactly_one_debit_and_restart_replay(
            self, tmp_path):
        """Two threads race submit() against one tenant whose budget
        covers only ONE request: exactly one succeeds, the refusal
        names the shortfall, and after a kill-and-restart the durable
        ledger replays to exactly one debit."""
        ds = make_ds(n=1_000, parts=4)
        ledger_dir = str(tmp_path / "svc")
        with serve.Service(ledger_dir,
                           tenants={"t": (1.0, 1e-7)},
                           workers=2) as svc:
            barrier = threading.Barrier(2)
            outs = [None, None]

            def racer(i):
                req = request("t", ds, eps=0.8, delta=1e-8,
                              rid=f"race-{i}")
                barrier.wait(timeout=30)
                outs[i] = svc.submit(req)

            threads = [threading.Thread(target=racer, args=(i,))
                       for i in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            oks = [o for o in outs if o.ok]
            refusals = [o for o in outs if not o.ok]
            assert len(oks) == 1 and len(refusals) == 1
            assert refusals[0].reason == "overdraw"
            assert "shortfall" in refusals[0].detail
            assert refusals[0].remaining.epsilon <= 0.2 + 1e-9
        # Kill-and-restart: the durable per-tenant ledger replays to
        # the SAME remaining (eps, delta), with exactly one debit.
        led = TenantBudgetLedger(os.path.join(ledger_dir, "budgets"))
        debits = led.debits("t")
        assert len(debits) == 1
        (debit,) = debits.values()
        assert debit["state"] == "committed"
        assert led.remaining("t").epsilon == pytest.approx(0.2)
        # And a restarted SERVICE over the same books agrees.
        with serve.Service(ledger_dir,
                           tenants={"t": (1.0, 1e-7)}) as svc2:
            again = svc2.submit(request("t", ds, eps=0.8, delta=1e-8))
            assert not again.ok and again.reason == "overdraw"

    def test_kill_mid_request_leaves_reserve_spent(self, tmp_path):
        """The faults seam kills request 0 between reserve and commit
        (the process-death window): the caller sees the crash, the
        reserve is neither committed nor released, and a restarted
        service counts it as spent."""
        ds = make_ds(n=1_000, parts=4)
        ledger_dir = str(tmp_path / "svc")
        with faults.injected_faults(
                faults.FaultPlan(fail_serve_requests=(0,))):
            with serve.Service(ledger_dir,
                               tenants={"t": (1.0, 0.0)}) as svc:
                with pytest.raises(faults.ServeKill):
                    svc.submit(request("t", ds, eps=0.8, delta=0.0,
                                       rid="killed"))
        led = TenantBudgetLedger(os.path.join(ledger_dir, "budgets"))
        assert led.debits("t")["killed"]["state"] == "reserved"
        assert led.remaining("t").epsilon == pytest.approx(0.2)
        # Restarted service: the dead request's budget stays spent, so
        # a same-size follow-up is refused...
        with serve.Service(ledger_dir, tenants={"t": (1.0, 0.0)}) as s2:
            out = s2.submit(request("t", ds, eps=0.8, delta=0.0))
            assert not out.ok and out.reason == "overdraw"
            # ...and a RETRY of the killed id dedupes onto the
            # existing debit instead of double-spending.
            lease = s2.budgets.reserve("t", "killed", 0.8, 0.0)
            assert lease.epsilon == 0.8
            assert len(s2.budgets.debits("t")) == 1


# ---------------------------------------------------------------------
# per-tenant books + live-request heartbeat
# ---------------------------------------------------------------------


class TestBooksAndHeartbeat:

    def test_books_appended_under_each_tenant(self, tmp_path):
        ds = make_ds(n=1_000, parts=4)
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"a": (5.0, 1e-6),
                                    "b": (5.0, 1e-6)}) as svc:
            ra = svc.submit(request("a", ds, eps=1.0))
            ds.invalidate_cache()
            rb = svc.submit(request("b", ds, eps=1.0))
            refused = svc.submit(request("a", ds, eps=99.0))
            assert ra.ok and rb.ok and refused.reason == "overdraw"
            for tenant, resp in (("a", ra), ("b", rb)):
                path = os.path.join(svc.books_dir(tenant),
                                    "run_ledger.jsonl")
                entries = [json.loads(line) for line in
                           open(path, encoding="utf-8")]
                served = [e for e in entries
                          if e["name"] == "serve.request"]
                assert len(served) == 1
                book = served[0]["payload"]["serve"]
                assert book["tenant"] == tenant
                assert book["request_id"] == resp.request_id
                assert book["audit"]["books"]["tenant"] == tenant
                assert book["remaining_epsilon"] == pytest.approx(4.0)
            refusals = [json.loads(line) for line in
                        open(os.path.join(svc.books_dir("a"),
                                          "run_ledger.jsonl"),
                             encoding="utf-8")
                        if json.loads(line)["name"] == "serve.refusal"]
            assert refusals and refusals[0]["payload"]["serve"][
                "reason"] == "overdraw"

    def test_books_store_built_once_per_tenant_under_concurrency(
            self, tmp_path, monkeypatch):
        """Concurrent appends for one tenant must share a single
        LedgerStore instance (the store's one-lock-per-file contract):
        a slowed constructor + a thread barrier would race the old
        unguarded creation into duplicate stores."""
        from pipelinedp_tpu.obs import store as obs_store
        builds = []
        real_store = obs_store.LedgerStore

        class SlowStore(real_store):
            def __init__(self, *a, **k):
                builds.append(threading.current_thread().name)
                threading.Event().wait(0.05)
                super().__init__(*a, **k)

        monkeypatch.setattr(obs_store, "LedgerStore", SlowStore)
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"t": (5.0, 1e-6)}) as svc:
            n = 6
            barrier = threading.Barrier(n)

            def append(i):
                barrier.wait(timeout=30)
                svc._append_books("t", "serve.test", {"i": i})

            threads = [threading.Thread(target=append, args=(i,))
                       for i in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert len(builds) == 1, builds
            assert len(svc._books_stores) == 1
            path = os.path.join(svc.books_dir("t"), "run_ledger.jsonl")
            entries = [json.loads(line) for line in
                       open(path, encoding="utf-8")
                       if json.loads(line)["name"] == "serve.test"]
            assert len(entries) == n

    def test_heartbeat_snapshots_all_live_requests_one_document(
            self, tmp_path):
        """The monitor satellite: a resident process's heartbeat names
        EVERY live request (tenant + phase) in one document, at a
        run-namespaced path — no per-request clobbering."""
        clk = FakeClock()
        mon = obs_monitor.Monitor(
            clock=clk, interval_s=1.0, stall_s=60.0,
            heartbeat_path=str(tmp_path / "hb.json"),
            run_name="svc").start_inline()
        try:
            obs_monitor.register_request("r1", tenant="a",
                                         phase="queued")
            obs_monitor.register_request("r2", tenant="b",
                                         phase="running")
            obs_monitor.update_request("r1", phase="running")
            hb = mon.poll_once()
            reqs = {r["request_id"]: r for r in hb["requests"]}
            assert set(reqs) == {"r1", "r2"}
            assert reqs["r1"]["tenant"] == "a"
            assert reqs["r1"]["phase"] == "running"
            on_disk = json.load(open(mon.heartbeat_path,
                                     encoding="utf-8"))
            assert len(on_disk["requests"]) == 2
            obs_monitor.unregister_request("r1")
            obs_monitor.unregister_request("r2")
            hb = mon.poll_once()
            assert "requests" not in hb
        finally:
            obs_monitor.reset_requests()
            from pipelinedp_tpu.obs.tracer import ACTIVITY
            ACTIVITY.reset(enabled=False)

    def test_heartbeat_path_namespaced_by_run(self, monkeypatch,
                                              tmp_path):
        monkeypatch.setenv("PIPELINEDP_TPU_LEDGER_DIR",
                           str(tmp_path / "led"))
        monkeypatch.delenv(obs_monitor.ENV_VAR, raising=False)
        dest = obs_monitor.heartbeat_destination(run="bench-7")
        assert dest.endswith(os.path.join("led",
                                          "heartbeat-bench-7.json"))
        # Unsafe characters in a run name never escape the directory.
        weird = obs_monitor.heartbeat_destination(run="a/../b c")
        assert os.path.dirname(weird) == str(tmp_path / "led")
        # Explicit env paths still win verbatim.
        monkeypatch.setenv(obs_monitor.ENV_VAR,
                           str(tmp_path / "x.json"))
        assert obs_monitor.heartbeat_destination(
            run="r") == str(tmp_path / "x.json")
        mon = obs_monitor.Monitor(clock=FakeClock(), run_name="r7")
        assert mon.heartbeat_path == str(tmp_path / "x.json")
        monkeypatch.delenv(obs_monitor.ENV_VAR)
        mon = obs_monitor.Monitor(clock=FakeClock(), run_name="r7")
        assert mon.heartbeat_path.endswith("heartbeat-r7.json")


# ---------------------------------------------------------------------
# tune requests: the utility-analysis megasweep behind the serve door
# ---------------------------------------------------------------------


def tune_request(tenant, ds, eps=1.0, delta=1e-8, rid=None, parts=6):
    params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                 max_partitions_contributed=parts,
                                 max_contributions_per_partition=4)
    return serve.ServeRequest(tenant=tenant, params=params, dataset=ds,
                              epsilon=eps, delta=delta, rng_seed=7,
                              request_id=rid, kind="tune")


class TestTuneRequests:
    """``kind="tune"`` serve requests: admitted through the same
    admission control as aggregates (quota'd, structurally refused,
    books-stamped) but debiting ZERO (ε, δ) — utility analysis releases
    error estimates of hypothetical mechanisms, never private data."""

    def test_tune_served_zero_budget_debited_books_stamped(
            self, tmp_path):
        ds = make_ds(n=2_000, parts=6)
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"t": (5.0, 1e-6)}) as svc:
            out = svc.submit(tune_request("t", ds, eps=1.0, rid="tu1"))
            assert out.ok, out
            assert out.audit["kind"] == "tune"
            assert out.audit["budget_debited"] is False
            assert out.audit["candidates"] > 1
            assert "max_partitions_contributed" in out.audit["best"]
            (label, tune_result), = out.results
            assert label == "tune"
            assert tune_result.index_best == out.audit["index_best"]
            # The balance is untouched — in the response AND on disk.
            assert out.remaining.epsilon == pytest.approx(5.0)
            assert svc.budgets.remaining("t").epsilon == pytest.approx(
                5.0)
            assert svc.budgets.remaining("t").delta == pytest.approx(
                1e-6)
            # Books: stamped like any request, with kind="tune" and
            # zero (eps, delta).
            path = os.path.join(svc.books_dir("t"), "run_ledger.jsonl")
            entries = [json.loads(line) for line in
                       open(path, encoding="utf-8")]
            served = [e for e in entries if e["name"] == "serve.request"]
            assert len(served) == 1
            book = served[0]["payload"]["serve"]
            assert book["kind"] == "tune"
            assert book["epsilon"] == 0.0 and book["delta"] == 0.0
            assert book["audit"]["budget_debited"] is False
            assert book["audit"]["simulated_epsilon"] == 1.0

    def test_tune_second_same_signature_warm_zero_new_compiles(
            self, tmp_path, monkeypatch):
        """The second same-signature tune is a warm registry hit and —
        with the cost observatory watching — captures zero new
        ``compile.program`` spans (one compiled megasweep serves every
        config batch)."""
        monkeypatch.setenv("PIPELINEDP_TPU_COSTS", "1")
        ds = make_ds(n=2_000, parts=6)
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"t": (5.0, 1e-6)}) as svc:
            ds.invalidate_cache()
            first = svc.submit(tune_request("t", ds, rid="tu-a"))
            assert first.ok and first.warm is False
            captured = obs.ledger().snapshot()["counters"].get(
                "cost.programs_captured", 0)
            ds.invalidate_cache()
            second = svc.submit(tune_request("t", ds, rid="tu-b"))
            assert second.ok and second.warm is True
            assert second.audit["index_best"] == first.audit[
                "index_best"]
            after = obs.ledger().snapshot()["counters"].get(
                "cost.programs_captured", 0)
            assert after == captured, (
                "second same-signature tune captured new "
                "compile.program spans")

    def test_tune_refusals_structural_and_free(self, tmp_path):
        ds = make_ds(n=2_000, parts=6)
        with serve.Service(str(tmp_path / "svc")) as svc:
            svc.register_tenant("t", 5.0, 1e-6,
                                max_rows_per_request=100)
            # Unknown kinds are malformed before any compute.
            bogus = svc.submit(serve.ServeRequest(
                tenant="t", params=count_params(), dataset=ds,
                epsilon=1.0, kind="optimize"))
            assert not bogus.ok and bogus.reason == "malformed"
            assert "kind" in bogus.detail
            # Tune analyzes exactly one metric.
            multi = tune_request("t", ds)
            multi.params = count_params()  # COUNT + SUM
            multi.kind = "tune"
            out = svc.submit(multi)
            assert not out.ok and out.reason == "malformed"
            assert "one metric" in out.detail
            # Unknown tenants never grow state, tune or not.
            ghost = svc.submit(tune_request("ghost", ds))
            assert ghost.reason == "malformed"
            assert not os.path.exists(svc.books_dir("ghost"))
            # Tunes ride the same per-tenant row quota.
            quota = svc.submit(tune_request("t", ds))
            assert not quota.ok and quota.reason == "quota"
            assert "row quota" in quota.detail
            # None of it burned budget.
            assert svc.budgets.remaining("t").epsilon == pytest.approx(
                5.0)


# ---------------------------------------------------------------------
# the noserve lint, AST-precise (twin of ``make noserve``)
# ---------------------------------------------------------------------


class TestNoServeLint:

    def test_serve_confinement(self):
        """Both halves — no serve imports outside serve/ (the service
        depends on the engine, never the reverse) and
        TenantBudgetLedger construction confined to serve/ +
        budget_accounting.py — are one rule in the shared AST engine;
        `make noserve` is the same rule."""
        from pipelinedp_tpu import lint
        assert lint.check_tree("noserve") == []


# ---------------------------------------------------------------------
# degraded mode: structured refusal before any reserve
# ---------------------------------------------------------------------


class TestDegradedMode:

    def test_degraded_refuses_before_reserve_and_clears(self, tmp_path):
        """A degraded service refuses EVERY submit with the structured
        "degraded" reason BEFORE any budget reserve — the ledger still
        holds the full budget afterwards — and clear_degraded()
        restores normal admission."""
        ds = make_ds()
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"t": (5.0, 1e-6)}) as svc:
            svc.set_degraded("mesh lost its last participant")
            out = svc.submit(request("t", ds, eps=1.0))
            assert not out.ok
            assert out.reason == "degraded"
            assert "participant" in out.detail
            counters = obs.ledger().snapshot()["counters"]
            assert counters.get("serve.requests_admitted", 0) == 0
            assert counters.get("serve.refusals.degraded", 0) == 1
            # No reserve ever hit the durable ledger.
            assert svc.budgets.remaining("t").epsilon == pytest.approx(
                5.0)
            # The heartbeat says WHY traffic is bouncing.
            health = obs_monitor.serve_health_snapshot()
            assert health == {"state": "degraded",
                              "detail": "mesh lost its last participant"}
            mon = obs_monitor.Monitor(clock=FakeClock(), run_name="dg")
            hb = mon.poll_once()
            assert hb["serve"]["health"]["state"] == "degraded"
            svc.clear_degraded()
            assert obs_monitor.serve_health_snapshot() == {"state": "ok"}
            ok = svc.submit(request("t", ds, eps=1.0))
            assert ok.ok, ok
        events = [e["name"] for e in obs.ledger().snapshot()["events"]]
        assert "serve.degraded" in events
        assert "serve.degraded_cleared" in events

    def test_degraded_env_arms_at_construction(self, tmp_path,
                                               monkeypatch):
        """A process that came up degraded (resilience.health set
        PIPELINEDP_TPU_DEGRADED) starts its service refusing."""
        from pipelinedp_tpu.resilience.health import DEGRADED_ENV
        monkeypatch.setenv(DEGRADED_ENV, "1")
        ds = make_ds()
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"t": (5.0, 1e-6)}) as svc:
            out = svc.submit(request("t", ds, eps=1.0))
            assert out.reason == "degraded"
            assert DEGRADED_ENV in out.detail
