"""Live-telemetry tests (``pipelinedp_tpu/obs/monitor.py``) —
``make watchcheck``.

Coverage contract:

* heartbeat — atomically replaced (a concurrent reader loop never sees
  a torn file), carries phase / batches-sweeps done vs planned /
  rows-per-second / active-span ages, and an on-pace/behind verdict
  with projected ETA when the ledger store holds a same-fingerprint
  baseline;
* stall watchdog — fires at the EXACT FakeClock deadline (no real
  sleeps), re-arms on new span activity, emits ``watchdog.stalled``
  and a flight record, and invokes the pluggable action (an action
  that raises is recorded, never fatal);
* the acceptance wedge — a seeded fault holding a staged fetch: the
  heartbeat shows the stalled phase, the ledger carries
  ``watchdog.stalled``, the flight record names the blocked
  ``pdp-*`` worker with its stack, and the drained run leaves zero
  orphan threads;
* flight record — round-trips the last-N completed-span ring and
  names every live ``pdp-*`` worker;
* parity — DP outputs bit-identical with heartbeat on vs off
  (PARITY row 30);
* ledger analytics — ``python -m pipelinedp_tpu.obs.store
  --summarize`` aggregates a synthetic two-run ledger into
  per-(fingerprint, phase) cost tables with trend deltas;
* probe watchdog — a wedged-hold device probe is cancelled by the
  stall action instead of waiting out its timeout;
* lint twin — ``obs/monitor.py`` never calls into the ``time`` module
  directly (AST-precise; the deadline story must ride the injectable
  clock).
"""

import ast
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import obs
from pipelinedp_tpu.backends import JaxBackend
from pipelinedp_tpu.obs import monitor as obs_monitor
from pipelinedp_tpu.obs import store as obs_store
from pipelinedp_tpu.obs.tracer import ACTIVITY, FLIGHT_RING_SPANS
from pipelinedp_tpu.resilience import FaultPlan, injected_faults
from pipelinedp_tpu.resilience import faults
from pipelinedp_tpu.resilience.clock import FakeClock

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BIG_EPS = 1e12

ENV_A = {"jax_version": "0.4", "platform": "cpu", "device_kind": "cpu",
         "device_count": 1, "process_count": 1, "git_sha": "aaa"}


@pytest.fixture(autouse=True)
def fresh_state(monkeypatch, tmp_path):
    """Fresh ledger/activity registry, isolated store dir, heartbeat
    OFF unless a test opts in — and a guaranteed monitor stop so no
    test leaks an armed registry or a pdp-monitor thread."""
    monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "997")
    monkeypatch.delenv(obs_monitor.ENV_VAR, raising=False)
    monkeypatch.setenv(obs_store.ENV_VAR, str(tmp_path / "ledger"))
    monkeypatch.delenv(obs.ENV_VAR, raising=False)
    obs.reset()
    yield
    obs_monitor.stop()
    ACTIVITY.reset(enabled=False)
    obs.reset()


def make_ds(seed=1, n=9_000, users=2_000, parts=12):
    rng = np.random.default_rng(seed)
    return pdp.ArrayDataset(privacy_ids=rng.integers(0, users, n),
                            partition_keys=rng.integers(0, parts, n),
                            values=rng.uniform(0.0, 10.0, n)), parts


def count_params(parts):
    return pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM, pdp.Metrics.MEAN],
        max_partitions_contributed=parts,
        max_contributions_per_partition=50,
        min_value=0.0, max_value=10.0)


def run_streamed(ds, params, seed=0):
    ds.invalidate_cache()
    acc = pdp.NaiveBudgetAccountant(total_epsilon=BIG_EPS, total_delta=1e-2)
    engine = pdp.DPEngine(acc, JaxBackend(rng_seed=seed))
    res = engine.aggregate(ds, params, pdp.DataExtractors())
    acc.compute_budgets()
    got = dict(res)
    assert res.timings.get("stream_batches", 0) > 1
    return got


def inline_monitor(tmp_path, clk, **kw):
    kw.setdefault("stall_s", 30.0)
    kw.setdefault("interval_s", 1.0)
    kw.setdefault("heartbeat_path", str(tmp_path / "heartbeat.json"))
    kw.setdefault("run_name", "t")
    return obs_monitor.Monitor(clock=clk, **kw).start_inline()


def wait_activity_quiesce(timeout_s=30.0, stable_beats=3):
    """Wait (real time, short beats) until no span opens/closes — the
    held pipeline has fully backed up and only virtual time remains."""
    gate = threading.Event()
    deadline = time.monotonic() + timeout_s
    last, stable = -1, 0
    while time.monotonic() < deadline:
        cur = ACTIVITY.seq
        if cur == last:
            stable += 1
            if stable >= stable_beats:
                return
        else:
            last, stable = cur, 0
        gate.wait(0.05)
    raise AssertionError("pipeline activity never quiesced")


class TestHeartbeat:
    def test_atomic_replace_under_concurrent_reader(self, tmp_path):
        """A reader polling the heartbeat while the monitor rewrites it
        never observes a torn file: every read either hits the previous
        beat or the new one, always valid JSON."""
        clk = FakeClock()
        mon = inline_monitor(tmp_path, clk)
        mon.poll_once()  # the file exists before the reader starts
        done = threading.Event()
        errors = []

        def writer():
            try:
                for _ in range(300):
                    clk.sleep(1.0)
                    mon.poll_once()
            except BaseException as e:  # surfaced by the main thread
                errors.append(e)
            finally:
                done.set()

        t = threading.Thread(target=writer)
        t.start()
        reads = 0
        beats = set()
        while not done.is_set() or reads == 0:
            with open(mon.heartbeat_path, encoding="utf-8") as f:
                hb = json.loads(f.read())  # a torn write would raise
            assert hb["run"] == "t"
            beats.add(hb["beat"])
            reads += 1
        t.join()
        assert not errors, errors
        assert reads > 0 and len(beats) >= 1
        mon.stop()

    def test_progress_phase_rate_and_active_spans(self, tmp_path):
        clk = FakeClock()
        mon = inline_monitor(tmp_path, clk)
        obs.inc("progress.batches_staged", 3)
        obs.inc("progress.batches_planned", 10)
        obs.inc("stream.pass_b_stream_sweeps", 1)
        obs.inc("progress.sweeps_planned", 4)
        obs.inc("progress.rows_staged", 5_000)
        obs.inc("ingest.rows_ingested", 20_000)
        tr = obs.tracer()  # measuring tracer: the monitor is armed
        span = tr.span("ingest.pass_a", cat="ingest")
        span.__enter__()
        clk.sleep(2.0)
        hb = mon.poll_once()
        assert hb["phase"] == "ingest.pass_a"
        assert hb["progress"] == {
            "batches_done": 3, "batches_planned": 10,
            "sweeps_done": 1, "sweeps_planned": 4,
            "rows_done": 5_000, "rows_planned": 20_000,
            "rows_per_s": 2_500.0}
        (active,) = hb["active_spans"]
        assert active["name"] == "ingest.pass_a"
        assert active["age_s"] == pytest.approx(2.0)
        assert hb["stalled"] is False
        span.__exit__(None, None, None)
        mon.stop()

    def test_pace_verdict_vs_baseline(self, tmp_path):
        """With a same-fingerprint baseline run report in the store the
        heartbeat carries on-pace/behind + a projected ETA; a run at
        half the baseline rate is still on pace (slack), one far below
        is behind."""
        store = obs_store.LedgerStore(str(tmp_path / "ledger"))
        fp = obs_store.fingerprint_key(ENV_A)
        store.append("run_report", {
            "run_report": {
                "counters": {"progress.rows_staged": 10_000},
                "spans": {"ingest.pass_a": {"count": 1,
                                            "total_s": 10.0}}}},
            env=ENV_A)  # baseline: 1000 rows/s
        clk = FakeClock()
        mon = inline_monitor(tmp_path, clk, fingerprint=fp,
                             store_dir=str(tmp_path / "ledger"))
        obs.inc("progress.rows_staged", 1_600)
        obs.inc("ingest.rows_ingested", 20_000)
        clk.sleep(2.0)  # 800 rows/s >= 0.5 * 1000
        hb = mon.poll_once()
        assert hb["pace"]["verdict"] == "on_pace"
        assert hb["pace"]["baseline_rows_per_s"] == pytest.approx(1000.0)
        assert hb["pace"]["projected_eta_s"] == pytest.approx(
            (20_000 - 1_600) / 800.0, rel=1e-3)
        mon.stop()
        obs.reset()
        mon2 = inline_monitor(tmp_path, clk, fingerprint=fp,
                              store_dir=str(tmp_path / "ledger"))
        obs.inc("progress.rows_staged", 100)
        obs.inc("ingest.rows_ingested", 20_000)
        clk.sleep(10.0)  # 10 rows/s < 0.5 * 1000
        hb2 = mon2.poll_once()
        assert hb2["pace"]["verdict"] == "behind"
        mon2.stop()

    def test_pace_anchor_excludes_pre_ingest_wall(self, tmp_path):
        """A long pre-ingest prelude (the bench arms the monitor
        BEFORE the device probe and the cold compiles) must not dilute
        the pace verdict: the rate anchors at the first beat that saw
        staged rows, so a run at baseline speed reads on-pace even
        after a 60s silent prelude."""
        store = obs_store.LedgerStore(str(tmp_path / "ledger"))
        fp = obs_store.fingerprint_key(ENV_A)
        store.append("run_report", {
            "run_report": {
                "counters": {"progress.rows_staged": 10_000},
                "spans": {"ingest.pass_a": {"count": 1,
                                            "total_s": 10.0}}}},
            env=ENV_A)  # baseline: 1000 rows/s
        clk = FakeClock()
        mon = inline_monitor(tmp_path, clk, fingerprint=fp,
                             store_dir=str(tmp_path / "ledger"))
        clk.sleep(60.0)  # probe + compile: a minute of zero rows
        mon.poll_once()
        obs.inc("progress.rows_staged", 1_600)
        obs.inc("ingest.rows_ingested", 20_000)
        mon.poll_once()  # the anchor beat
        clk.sleep(2.0)
        obs.inc("progress.rows_staged", 1_600)
        hb = mon.poll_once()
        # 1600 rows over the 2s since the anchor — NOT 3200/64s.
        assert hb["progress"]["rows_per_s"] == pytest.approx(800.0)
        assert hb["pace"]["verdict"] == "on_pace"
        mon.stop()

    def test_degraded_baseline_never_paces(self, tmp_path):
        """A degraded capture can't set the pace bar (last_known_good
        discipline carries over to the live view)."""
        store = obs_store.LedgerStore(str(tmp_path / "ledger"))
        fp = obs_store.fingerprint_key(ENV_A)
        store.append("run_report", {
            "run_report": {"counters": {"progress.rows_staged": 10},
                           "spans": {"ingest.pass_a": {
                               "total_s": 10.0}}}},
            env=ENV_A, degraded=True)
        clk = FakeClock()
        mon = inline_monitor(tmp_path, clk, fingerprint=fp,
                             store_dir=str(tmp_path / "ledger"))
        obs.inc("progress.rows_staged", 100)
        clk.sleep(1.0)
        assert "pace" not in mon.poll_once()
        mon.stop()

    def test_off_is_zero_overhead(self):
        assert obs_monitor.maybe_start() is None
        assert not obs_monitor.heartbeat_enabled()
        assert ACTIVITY.enabled is False
        assert obs.tracer() is obs.NOOP_TRACER

    def test_maybe_start_global_lifecycle(self, tmp_path, monkeypatch):
        hb_path = str(tmp_path / "hb.json")
        monkeypatch.setenv(obs_monitor.ENV_VAR, hb_path)
        mon = obs_monitor.maybe_start(run_name="glob")
        assert mon is not None
        assert obs_monitor.maybe_start() is mon  # idempotent
        assert mon.heartbeat_path == hb_path
        assert ACTIVITY.enabled is True
        assert any(t.name == "pdp-monitor"
                   for t in threading.enumerate())
        obs_monitor.stop()
        assert obs_monitor.active_monitor() is None
        assert not any(t.name == "pdp-monitor" and t.is_alive()
                       for t in threading.enumerate())
        # The final beat on stop left a parseable heartbeat behind.
        hb = json.load(open(hb_path, encoding="utf-8"))
        assert hb["run"] == "glob"


class TestWatchdog:
    def test_fires_at_exact_fake_clock_deadline_and_rearms(self,
                                                           tmp_path):
        clk = FakeClock()
        mon = inline_monitor(tmp_path, clk, stall_s=30.0)
        with obs.tracer().span("phase.a", cat="t"):
            clk.sleep(0.5)
        mon.poll_once()  # baseline beat
        clk.sleep(29.99)
        assert mon.poll_once()["stalled"] is False
        assert mon.stalls == []
        clk.sleep(0.01)  # exactly 30.0s of silence
        hb = mon.poll_once()
        assert hb["stalled"] is True
        assert hb["stall"]["deadline_s"] == 30.0
        assert len(mon.stalls) == 1
        events = [e for e in obs.ledger().snapshot()["events"]
                  if e["name"] == "watchdog.stalled"]
        assert len(events) == 1
        assert events[0]["phase"] == "phase.a"
        assert events[0]["flight_record"] == mon.flight_path
        # The episode fires ONCE: more silence, no duplicate event.
        clk.sleep(100.0)
        mon.poll_once()
        assert len(mon.stalls) == 1
        # New span activity re-arms; the next silence fires again.
        with obs.tracer().span("phase.b", cat="t"):
            clk.sleep(0.1)
        assert mon.poll_once()["stalled"] is False
        clk.sleep(30.0)
        mon.poll_once()
        assert len(mon.stalls) == 2
        assert obs.ledger().snapshot()["counters"][
            "watchdog.stalls"] == 2
        mon.stop()

    def test_flight_record_ring_and_thread_stacks(self, tmp_path):
        """The flight record carries exactly the last-N completed
        spans and a stack summary for every live pdp-* worker."""
        from pipelinedp_tpu.ingest.executor import _CaptureThread
        clk = FakeClock()
        mon = inline_monitor(tmp_path, clk, stall_s=10.0)
        tr = obs.tracer()
        n_over = FLIGHT_RING_SPANS + 17
        for i in range(n_over):
            with tr.span(f"s{i}", cat="t"):
                clk.sleep(0.001)
        held = threading.Event()
        entered = threading.Event()

        def body():
            with tr.span("worker.hold", cat="t"):
                entered.set()
                held.wait(30)

        worker = _CaptureThread(body, "wedge")
        worker.start()
        assert entered.wait(10)
        mon.poll_once()
        clk.sleep(10.0)
        mon.poll_once()
        assert len(mon.stalls) == 1
        rec = json.load(open(mon.flight_path, encoding="utf-8"))
        names = [s["name"] for s in rec["recent_spans"]]
        assert len(names) == FLIGHT_RING_SPANS
        assert names == [f"s{i}" for i in
                         range(n_over - FLIGHT_RING_SPANS, n_over)]
        (active,) = rec["active_spans"]
        assert active["name"] == "worker.hold"
        assert active["thread"] == "pdp-ingest-wedge"
        stacks = {v["name"]: v["stack"] for v in rec["threads"].values()}
        assert "pdp-ingest-wedge" in stacks
        assert any("body" in frame for frame in
                   stacks["pdp-ingest-wedge"])
        assert rec["stall"]["phase"] == "worker.hold"
        held.set()
        worker.join(10)
        assert not worker.is_alive()
        mon.stop()

    def test_on_stall_action_runs_and_errors_are_contained(self,
                                                           tmp_path):
        clk = FakeClock()
        seen = []
        mon = inline_monitor(tmp_path, clk, stall_s=5.0,
                             on_stall=seen.append)
        mon.poll_once()
        clk.sleep(5.0)
        mon.poll_once()
        assert len(seen) == 1
        assert seen[0]["flight_record"] == mon.flight_path
        assert "no span opened or closed" in seen[0]["diagnosis"]
        mon.stop()

        def boom(info):
            raise RuntimeError("action failed")

        clk2 = FakeClock()
        mon2 = inline_monitor(tmp_path, clk2, stall_s=5.0,
                              on_stall=boom, run_name="t2")
        mon2.poll_once()
        clk2.sleep(5.0)
        mon2.poll_once()  # must not raise
        assert len(mon2.stalls) == 1
        assert any(e["name"] == "watchdog.action_error"
                   for e in obs.ledger().snapshot()["events"])
        mon2.stop()

    def test_wedged_staged_fetch_end_to_end(self, tmp_path):
        """THE acceptance wedge: a seeded fault holds batch 2's staged
        fetch mid-stream. Before the run can exit, the monitor (on a
        FakeClock, zero real sleeps) produces a heartbeat showing the
        stalled phase, a ``watchdog.stalled`` ledger event, and a
        flight record naming the blocked pdp-* worker — then the
        released run completes and drains to zero orphan threads."""
        ds, parts = make_ds(seed=5)
        params = count_params(parts)
        clk = FakeClock()
        mon = inline_monitor(tmp_path, clk, stall_s=30.0,
                             run_name="wedged")
        results = {}

        def run():
            results["out"] = run_streamed(ds, params, seed=7)

        with injected_faults(FaultPlan(hold_fetch_batches=(2,))):
            t = threading.Thread(target=run)
            t.start()
            try:
                assert faults.hold_started().wait(60), (
                    "the injected hold never engaged")
                wait_activity_quiesce()
                mon.poll_once()  # baseline beat at virtual t
                clk.sleep(29.99)
                assert mon.poll_once()["stalled"] is False
                clk.sleep(0.01)
                hb = mon.poll_once()
                assert hb["stalled"] is True
                assert hb["phase"] == "ingest.fetch"
                assert hb["stall"]["flight_record"] == mon.flight_path
                held = [a for a in hb["active_spans"]
                        if a["name"] == "ingest.fetch"]
                assert held and held[0]["thread"] == "pdp-ingest-fold"
                ev = [e for e in obs.ledger().snapshot()["events"]
                      if e["name"] == "watchdog.stalled"]
                assert ev and ev[0]["phase"] == "ingest.fetch"
                rec = json.load(open(mon.flight_path,
                                     encoding="utf-8"))
                blocked = [a for a in rec["active_spans"]
                           if a["name"] == "ingest.fetch"]
                assert blocked
                assert blocked[0]["thread"] == "pdp-ingest-fold"
                stacks = {v["name"]: v["stack"]
                          for v in rec["threads"].values()}
                assert "pdp-ingest-fold" in stacks
                assert any("check_fetch_hold" in frame
                           for frame in stacks["pdp-ingest-fold"])
            finally:
                faults.release_holds()
                t.join(120)
        assert not t.is_alive()
        assert results["out"], "the released run never completed"
        mon.stop()
        orphans = [th for th in threading.enumerate()
                   if th.name.startswith("pdp-") and th.is_alive()]
        assert orphans == [], f"orphan worker threads: {orphans}"


class TestParityHeartbeat:
    def test_outputs_bit_identical_heartbeat_on_off(self, tmp_path,
                                                    monkeypatch):
        """PARITY row 30: PIPELINEDP_TPU_HEARTBEAT changes ONLY the
        telemetry — DP outputs are bit-identical with the monitor on
        vs off, and only the 'on' run leaves a heartbeat file."""
        ds, parts = make_ds(seed=9)
        params = count_params(parts)
        hb_path = str(tmp_path / "hb.json")
        results = {}
        for mode in ("off", "on"):
            obs.reset()
            obs_monitor.stop()
            if mode == "on":
                monkeypatch.setenv(obs_monitor.ENV_VAR, hb_path)
            else:
                monkeypatch.delenv(obs_monitor.ENV_VAR, raising=False)
            results[mode] = run_streamed(ds, params, seed=17)
            obs_monitor.stop()
        assert os.path.exists(hb_path)
        assert set(results["off"]) == set(results["on"])
        for k in results["off"]:
            ta, tb = results["off"][k], results["on"][k]
            assert ta._fields == tb._fields
            for f in ta._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(ta, f)),
                    np.asarray(getattr(tb, f)),
                    err_msg=f"partition {k}.{f}")


class TestLedgerAnalytics:
    def _seed_two_runs(self, directory):
        store = obs_store.LedgerStore(directory)
        for total, rate in ((10.0, 100.0), (15.0, 120.0)):
            store.append("run_report", {
                "run_report": {
                    "counters": {"progress.rows_staged": 1000},
                    "spans": {"ingest.pass_a": {"count": 1,
                                                "total_s": total},
                              "walk.top": {"count": 1,
                                           "total_s": 0.5}}}},
                env=ENV_A)
            store.append("dp_rate", {"record": {
                "metric": "dp_rate", "value": rate,
                "unit": "rows/s"}}, env=ENV_A)
        return obs_store.fingerprint_key(ENV_A)

    def test_summarize_entries_trends(self, tmp_path):
        d = str(tmp_path / "led")
        fp = self._seed_two_runs(d)
        summary = obs_store.summarize_entries(
            obs_store.LedgerStore(d).entries())
        agg = summary[fp]
        assert agg["runs"] == 2 and agg["degraded_runs"] == 0
        pa = agg["phases"]["ingest.pass_a"]
        assert pa["reports"] == 2
        assert pa["mean_s"] == pytest.approx(12.5)
        assert pa["latest_s"] == pytest.approx(15.0)
        assert pa["trend"] == pytest.approx(0.5)  # 15 vs prior mean 10
        assert agg["phases"]["walk.top"]["trend"] == pytest.approx(0.0)
        m = agg["metrics"]["dp_rate"]
        assert m["samples"] == 2 and m["best"] == 120.0
        assert m["trend"] == pytest.approx(0.2)

    def test_summarize_cli_smoke(self, tmp_path):
        """The CLI end to end on a synthetic two-run ledger: human
        table by default, machine-readable under --json."""
        d = str(tmp_path / "led")
        fp = self._seed_two_runs(d)
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)
        proc = subprocess.run(
            [sys.executable, "-m", "pipelinedp_tpu.obs.store",
             "--summarize", "--dir", d],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=120)
        assert proc.returncode == 0, proc.stderr
        assert f"fingerprint {fp}" in proc.stdout
        assert "ingest.pass_a" in proc.stdout
        assert "+50%" in proc.stdout  # the pass-A cost trend
        assert "dp_rate" in proc.stdout
        proc2 = subprocess.run(
            [sys.executable, "-m", "pipelinedp_tpu.obs.store",
             "--summarize", "--dir", d, "--json"],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=120)
        assert proc2.returncode == 0, proc2.stderr
        payload = json.loads(proc2.stdout)
        assert payload["entries"] == 4
        assert payload["fingerprints"][fp]["phases"][
            "ingest.pass_a"]["trend"] == pytest.approx(0.5)


class TestProbeWatchdog:
    def test_wedged_hold_probe_is_cancellable(self):
        """The injected wedge with ``wedged_hold`` burns the probe
        window on the injectable clock and aborts as soon as the
        watchdog-cancel lands — never the full timeout."""
        from pipelinedp_tpu.resilience import health

        class CancelAfter(FakeClock):
            def sleep(self, seconds):
                super().sleep(seconds)
                if len(self.sleeps) == 4:
                    health.cancel_active_probe()

        clk = CancelAfter()
        with injected_faults(FaultPlan(wedged_init=1, wedged_hold=True)):
            ok, detail = health.probe_devices(timeout_s=300.0, clock=clk)
        assert ok is False
        assert "cancelled by the stall watchdog" in detail
        # 4 beats of 0.05s, not 300s of virtual waiting.
        assert sum(clk.sleeps) == pytest.approx(0.2)

    def test_probe_stall_cancelled_by_live_monitor(self, tmp_path):
        """End to end on the real clock (sub-second knobs): the armed
        monitor's stall action cancels a wedged-hold probe, the health
        layer degrades with the cancellation as its detail, and the
        flight record exists — seconds, not the 300s probe wall."""
        from pipelinedp_tpu.resilience import RetryPolicy, health
        mon = obs_monitor.Monitor(
            stall_s=0.2, interval_s=0.05,
            heartbeat_path=str(tmp_path / "hb.json"),
            run_name="probe",
            on_stall=lambda info: health.cancel_active_probe()).start()
        env = {}
        try:
            with injected_faults(FaultPlan(wedged_init=99,
                                           wedged_hold=True)):
                report = health.ensure_device_or_degrade(
                    policy=RetryPolicy(max_attempts=1, base_delay_s=0.0,
                                       seed=0),
                    timeout_s=30.0, env=env)
        finally:
            mon.stop()
        assert report.degraded
        assert "cancelled by the stall watchdog" in report.detail
        assert mon.stalls, "the watchdog never fired"
        assert os.path.exists(mon.flight_path)
        rec = json.load(open(mon.flight_path, encoding="utf-8"))
        active = [a["name"] for a in rec["active_spans"]]
        assert "health.device_probe" in active


class TestSatellites:
    def test_chrome_trace_names_live_pdp_threads(self, tmp_path):
        """A pdp-* worker that completed no span still gets a Perfetto
        thread-name metadata row and an ``otherData.thread_names``
        entry — the tid→name map flight-record stacks key on."""
        from pipelinedp_tpu.ingest.executor import _CaptureThread
        from pipelinedp_tpu.obs import report as obs_report
        held = threading.Event()
        t = _CaptureThread(lambda: held.wait(30), "lurker")
        t.start()
        try:
            path = str(tmp_path / "trace.json")
            obs_report.write_chrome_trace(path, obs.ledger().snapshot())
            payload = json.load(open(path, encoding="utf-8"))
            names = payload["otherData"]["thread_names"]
            assert "pdp-ingest-lurker" in names.values()
            metas = [e for e in payload["traceEvents"]
                     if e["ph"] == "M"]
            assert any(m["args"]["name"] == "pdp-ingest-lurker"
                       for m in metas)
        finally:
            held.set()
            t.join(10)
        assert not t.is_alive()

    def test_bench_compare_verdict_line(self, monkeypatch):
        """The --compare stdout one-liner: on-pace and regressed forms
        (the interactive view of the gate, no JSON spelunking)."""
        monkeypatch.syspath_prepend(REPO)
        import bench
        ok = {"regressed": [], "threshold": 0.10, "fingerprint": "f00",
              "rates": [{"metric": "a", "baseline": 5.0},
                        {"metric": "b", "baseline": None}]}
        line = bench.compare_verdict_line(ok)
        assert line.startswith("COMPARE: on pace")
        assert "1 rate(s)" in line and "f00" in line
        bad = {"regressed": ["dp_rate"], "threshold": 0.10,
               "fingerprint": "f00", "rates": []}
        line2 = bench.compare_verdict_line(bad)
        assert line2.startswith("COMPARE: REGRESSED")
        assert "dp_rate" in line2 and ">10%" in line2
        # First run / fresh fingerprint: nothing was gated — the line
        # must say so, not claim "on pace".
        none = {"regressed": [], "threshold": 0.10,
                "fingerprint": "f00",
                "rates": [{"metric": "a", "baseline": None}]}
        line3 = bench.compare_verdict_line(none)
        assert line3.startswith("COMPARE: no baseline")
        assert "f00" in line3


class TestMonitorClockLint:
    """In-tree twin of the ``make noperf``/``nosleep`` extension: the
    monitor must use the injectable clock — no direct call into the
    ``time`` module anywhere in ``obs/monitor.py`` (AST-precise, so a
    ``time.monotonic`` would be caught too, not just the two names the
    greps know)."""

    def test_monitor_never_calls_time_module(self):
        # The monitor's no-time-module check is part of the shared
        # engine's noperf rule (`make noperf`).
        from pipelinedp_tpu import lint
        assert lint.check_tree("noperf") == []
