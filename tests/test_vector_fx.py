"""Fixed-point vector aggregation + device vector noise (ISSUE 17).

PARITY row 39: under the ``fx`` vector accumulator, VECTOR_SUM's
coordinates quantize against the static norm clip bound into 24-bit
fixed-point int32 lanes and reduce as exact integer sums — so released
vectors are bit-identical across kernel backends (pallas vs xla), on a
single device AND the 8-device mesh, and through the streamed pass-A
path; the wide-D Pallas kernel dispatches on the int32 operand.

PARITY row 40: per-coordinate vector noise draws on device through
``ops/counter_rng.py`` keyed by (partition vocab index, coordinate).
This is a seeded SEAM, not a bit-twin of the numpy reference — the
draw order and generator differ — so the assertions are key-
determinism (same (seed, partition, coordinate) -> same draw on every
release path) plus released-value distribution checks against the
calibrated per-coordinate scale, not bit-parity against numpy.
"""

import operator

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import jax_engine as je
from pipelinedp_tpu import obs
from pipelinedp_tpu import plan as plan_mod
from pipelinedp_tpu.aggregate_params import NoiseKind
from pipelinedp_tpu.backends import JaxBackend
from pipelinedp_tpu.ops import noise as noise_ops
from pipelinedp_tpu.ops import vector_noise
from pipelinedp_tpu.plan import knobs as knobs_mod

ACC_SPEC = knobs_mod.BY_NAME["vector_accumulator"]
TILE_SPEC = knobs_mod.BY_NAME["segsum_wide_d_block"]

D = 64
PARTS = 5


def extractors():
    return pdp.DataExtractors(privacy_id_extractor=operator.itemgetter(0),
                              partition_extractor=operator.itemgetter(1),
                              value_extractor=operator.itemgetter(2))


def vec_params(d=D, norm=4.0, noise=pdp.NoiseKind.GAUSSIAN):
    return pdp.AggregateParams(
        noise_kind=noise, metrics=[pdp.Metrics.VECTOR_SUM],
        max_partitions_contributed=2,
        max_contributions_per_partition=1,
        vector_size=d, vector_max_norm=norm,
        vector_norm_kind=pdp.NormKind.L2)


def make_data(n_users=400, d=D, seed=0):
    rng = np.random.default_rng(seed)
    return [(u, f"p{u % PARTS}", rng.normal(size=d))
            for u in range(n_users)]


def run_vector(data, params, accum, backend="xla", mesh=None,
               chunk=None, seed=7, eps=1e5, public=True):
    """One aggregation under (accumulator, kernel backend, mesh,
    stream chunk); returns {pk: released [D] float64 vector}."""
    import os
    old = os.environ.get("PIPELINEDP_TPU_STREAM_CHUNK")
    if chunk is not None:
        os.environ["PIPELINEDP_TPU_STREAM_CHUNK"] = str(chunk)
    try:
        with plan_mod.seam_override("vector_accumulator", accum), \
             plan_mod.seam_override("kernel_backend", backend):
            noise_ops.seed_host_rng(0)
            kw = {}
            if mesh:
                from pipelinedp_tpu.parallel import make_mesh
                kw["mesh"] = make_mesh(mesh)
            acc = pdp.NaiveBudgetAccountant(total_epsilon=eps,
                                            total_delta=1e-6)
            engine = pdp.DPEngine(acc, JaxBackend(rng_seed=seed, **kw))
            pub = ([f"p{i}" for i in range(PARTS)] if public else None)
            res = engine.aggregate(data, params, extractors(),
                                   public_partitions=pub)
            acc.compute_budgets()
            out = dict(res)
    finally:
        if old is None:
            os.environ.pop("PIPELINEDP_TPU_STREAM_CHUNK", None)
        else:
            os.environ["PIPELINEDP_TPU_STREAM_CHUNK"] = old
    return {k: np.asarray(v.vector_sum) for k, v in out.items()}


class TestVectorFxParity:
    """PARITY row 39: one set of released bits for every execution
    geometry of the same fx request."""

    def _assert_same(self, base, other, label):
        assert set(base) == set(other), label
        for k in base:
            np.testing.assert_array_equal(base[k], other[k],
                                          err_msg=f"{label} pk={k}")

    def test_pallas_bit_identical_and_dispatches(self):
        data = make_data()
        params = vec_params()
        base = run_vector(data, params, "fx", "xla")
        obs.reset()
        pal = run_vector(data, params, "fx", "pallas")
        counters = obs.ledger().snapshot()["counters"]
        assert counters.get("kernel.pallas_dispatches", 0) >= 1
        self._assert_same(base, pal, "pallas")

    def test_mesh_bit_identical_both_backends(self):
        data = make_data()
        params = vec_params()
        base = run_vector(data, params, "fx", "xla")
        self._assert_same(base, run_vector(data, params, "fx", "xla",
                                           mesh=8), "mesh/xla")
        self._assert_same(base, run_vector(data, params, "fx", "pallas",
                                           mesh=8), "mesh/pallas")

    def test_streamed_bit_identical_both_backends(self):
        data = make_data()
        params = vec_params()
        base = run_vector(data, params, "fx", "xla")
        self._assert_same(base, run_vector(data, params, "fx", "xla",
                                           chunk=50), "stream/xla")
        self._assert_same(base, run_vector(data, params, "fx", "pallas",
                                           chunk=50), "stream/pallas")

    def test_private_selection_paths_agree(self):
        """The compact release path (private selection keeps a subset
        of rows) must key vector noise by the GLOBAL vocab index, so
        pallas/xla stay bit-identical there too."""
        data = make_data(n_users=800)
        params = vec_params()
        base = run_vector(data, params, "fx", "xla", public=False,
                          eps=50.0)
        assert base  # selection keeps a non-empty set
        pal = run_vector(data, params, "fx", "pallas", public=False,
                         eps=50.0)
        self._assert_same(base, pal, "private/pallas")

    def test_fx_tracks_f32_within_quantization_error(self):
        """The accumulators are different mechanisms (fx clamps each
        coordinate at +-bound while quantizing), but on data inside
        the bound they agree to quantization error — the retired
        'Scaling limits' caveat's replacement property."""
        rng = np.random.default_rng(3)
        data = [(u, f"p{u % PARTS}", rng.uniform(-0.3, 0.3, D))
                for u in range(400)]
        params = vec_params()
        f32 = run_vector(data, params, "f32", "xla")
        fx = run_vector(data, params, "fx", "xla")
        for k in f32:
            np.testing.assert_allclose(fx[k], f32[k], atol=1e-3)

    def test_laplace_noise_kind_also_bit_identical(self):
        data = make_data(n_users=200)
        params = vec_params(noise=pdp.NoiseKind.LAPLACE)
        base = run_vector(data, params, "fx", "xla")
        pal = run_vector(data, params, "fx", "pallas")
        self._assert_same(base, pal, "laplace/pallas")


class TestVectorKnobs:
    """The two ISSUE-17 knobs ride the registry like every other."""

    def test_vector_accumulator_is_dp_unsafe(self):
        assert not ACC_SPEC.dp_safe
        assert ACC_SPEC.kind is str
        assert ACC_SPEC.default == "f32"
        assert ACC_SPEC.choices == ("f32", "fx")
        assert ACC_SPEC.env_var == "PIPELINEDP_TPU_VECTOR_ACCUMULATOR"

    def test_plan_cannot_flip_the_accumulator(self, monkeypatch):
        """fx and f32 release DIFFERENT floats (fx quantizes at the
        clip bound): a plan file must never flip the accumulator, only
        env/seam (the operator's explicit hand) can."""
        monkeypatch.delenv(ACC_SPEC.env_var, raising=False)
        got = knobs_mod.resolve_value(ACC_SPEC,
                                      {"vector_accumulator": "fx"})
        assert got == ("f32", "default")

    def test_env_flips_the_accumulator(self, monkeypatch):
        monkeypatch.setenv(ACC_SPEC.env_var, "fx")
        assert knobs_mod.resolve_value(ACC_SPEC, None) == ("fx", "env")

    def test_wide_d_block_is_dp_safe_int(self):
        assert TILE_SPEC.dp_safe
        assert TILE_SPEC.kind is int
        assert TILE_SPEC.default == 0
        assert TILE_SPEC.env_var == "PIPELINEDP_TPU_SEGSUM_WIDE_D_BLOCK"

    def test_autotune_sweeps_the_tile_width(self):
        cands = plan_mod.autotune_candidates()
        pinned = {vec.get("segsum_wide_d_block") for vec in cands}
        assert {256, 128} <= pinned

    def test_config_resolves_accumulator_only_for_vector_requests(self):
        with plan_mod.seam_override("vector_accumulator", "fx"):
            scalar = je.FusedConfig.from_params(
                pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                    max_partitions_contributed=1,
                                    max_contributions_per_partition=1),
                public=True)
            vector = je.FusedConfig.from_params(vec_params(), public=True)
        # Scalar configs stay byte-identical to the pre-ISSUE shape —
        # the knob never perturbs their compile cache keys.
        assert scalar.vector_accumulator == "f32"
        assert scalar.wide_d_block == 0
        assert vector.vector_accumulator == "fx"


class TestDeviceVectorNoise:
    """PARITY row 40: the seeded vector-noise seam."""

    def test_draws_keyed_by_content_not_position(self):
        """Row i's noise depends on pk_index[i], not i: a compact
        release (kept subset) draws exactly the rows the full release
        would — the property every execution geometry stands on."""
        full = vector_noise.unit_noise_block(
            NoiseKind.GAUSSIAN, 5, np.arange(10), 16)
        sub = vector_noise.unit_noise_block(
            NoiseKind.GAUSSIAN, 5, np.array([3, 7]), 16)
        np.testing.assert_array_equal(sub, full[[3, 7]])

    def test_streams_are_label_separated(self):
        """The vector stream (0x7ec) must not collide with the raw
        engine key or the quantile-tree stream — same seed, different
        draws per kind as well (laplace and gaussian transform the
        same counters differently)."""
        g = vector_noise.unit_noise_block(NoiseKind.GAUSSIAN, 5,
                                          np.arange(8), 8)
        l = vector_noise.unit_noise_block(NoiseKind.LAPLACE, 5,
                                          np.arange(8), 8)
        assert np.abs(g - l).max() > 1e-6

    def test_seeds_decorrelate(self):
        a = vector_noise.unit_noise_block(NoiseKind.GAUSSIAN, 0,
                                          np.arange(64), 64)
        b = vector_noise.unit_noise_block(NoiseKind.GAUSSIAN, 1,
                                          np.arange(64), 64)
        assert np.abs(a - b).max() > 1e-3
        corr = np.corrcoef(a.ravel(), b.ravel())[0, 1]
        assert abs(corr) < 0.05

    def test_gaussian_unit_distribution(self):
        block = vector_noise.unit_noise_block(
            NoiseKind.GAUSSIAN, 11, np.arange(400), 256)
        draws = block.ravel()  # 102,400 draws
        assert abs(draws.mean()) < 0.02
        assert abs(draws.std() - 1.0) < 0.02
        # Tail sanity: a gaussian, not something bounded.
        assert (np.abs(draws) > 3).mean() == pytest.approx(0.0027,
                                                           abs=0.0015)

    def test_laplace_unit_distribution(self):
        block = vector_noise.unit_noise_block(
            NoiseKind.LAPLACE, 12, np.arange(400), 256)
        draws = block.ravel()
        assert abs(draws.mean()) < 0.02
        # Unit-scale Laplace: variance 2.
        assert draws.std() == pytest.approx(np.sqrt(2.0), abs=0.05)

    def test_released_noise_matches_calibrated_sigma(self):
        """End to end: empty public partitions release pure noise, so
        their released vectors sample the calibrated per-coordinate
        gaussian directly — mean 0, std gaussian_sigma(eps/D, delta/D,
        l2_sens)."""
        eps, delta, d = 2.0, 1e-6, 32
        params = vec_params(d=d)
        data = [(u, "live", np.ones(d) * 0.01) for u in range(20)]
        noise_ops.seed_host_rng(0)
        acc = pdp.NaiveBudgetAccountant(total_epsilon=eps,
                                        total_delta=delta)
        engine = pdp.DPEngine(acc, JaxBackend(rng_seed=3))
        public = ["live"] + [f"empty{i}" for i in range(300)]
        res = engine.aggregate(data, params, extractors(),
                               public_partitions=public)
        acc.compute_budgets()
        out = dict(res)
        draws = np.concatenate(
            [np.asarray(out[k].vector_sum) for k in public[1:]])
        sigma = noise_ops.gaussian_sigma(
            eps / d, delta / d,
            noise_ops.compute_l2_sensitivity(
                params.max_partitions_contributed,
                params.max_contributions_per_partition))
        assert draws.shape == (300 * d,)
        assert abs(draws.mean()) < 0.1 * sigma
        assert draws.std() == pytest.approx(sigma, rel=0.05)

    def test_released_laplace_noise_matches_calibrated_scale(self):
        eps, d = 2.0, 32
        params = vec_params(d=d, noise=pdp.NoiseKind.LAPLACE)
        data = [(u, "live", np.ones(d) * 0.01) for u in range(20)]
        noise_ops.seed_host_rng(0)
        acc = pdp.NaiveBudgetAccountant(total_epsilon=eps,
                                        total_delta=1e-6)
        engine = pdp.DPEngine(acc, JaxBackend(rng_seed=4))
        public = ["live"] + [f"empty{i}" for i in range(300)]
        res = engine.aggregate(data, params, extractors(),
                               public_partitions=public)
        acc.compute_budgets()
        out = dict(res)
        draws = np.concatenate(
            [np.asarray(out[k].vector_sum) for k in public[1:]])
        scale = noise_ops.laplace_scale(
            eps / d,
            noise_ops.compute_l1_sensitivity(
                params.max_partitions_contributed,
                params.max_contributions_per_partition))
        assert draws.std() == pytest.approx(scale * np.sqrt(2.0),
                                            rel=0.05)

    def test_release_deterministic_in_engine_seed(self):
        data = make_data(n_users=100)
        params = vec_params()
        a = run_vector(data, params, "fx", seed=21, eps=2.0)
        b = run_vector(data, params, "fx", seed=21, eps=2.0)
        c = run_vector(data, params, "fx", seed=22, eps=2.0)
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])
        assert any(np.abs(a[k] - c[k]).max() > 1e-9 for k in a)

    def test_secure_host_noise_keeps_the_numpy_path(self, monkeypatch):
        """The hardened release never enters the device seam: with
        secure host noise on (and no explicit rng — the same
        ``secure and rng is None`` convention as the scalar
        mechanisms), VECTOR_SUM still flows through
        dp_computations.add_noise_vector."""
        from pipelinedp_tpu import dp_computations
        calls = []

        def spy(vec, params, rng):
            calls.append(np.shape(vec))
            return np.asarray(vec, dtype=np.float64)

        monkeypatch.setattr(dp_computations, "add_noise_vector", spy)
        monkeypatch.setattr(noise_ops, "_secure_host_noise", True)
        data = make_data(n_users=50)
        run_vector(data, vec_params(), "f32", seed=None)
        assert calls
