"""Quantile-walk fast-path tests (PR 3).

Covers the counter-based node-noise generator (``ops/counter_rng.py``):
correctness against JAX's own threefry, purity in the (partition, node)
indices, calibrated statistical moments; the three-way bit-parity of
the single-batch, owner-sharded-mesh and streamed walks; the
partition-block-chunked walks (single-batch and streamed, straddling a
shrunken ``_SUBHIST_BYTE_CAP``); the extreme-scale guard cliffs at
their EXACT boundaries via the injectable cap seams; and the lint
banning new ``vmap(...fold_in...)`` per-element key constructions.
"""

import os
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import pipelinedp_tpu as pdp
from pipelinedp_tpu import jax_engine as je
from pipelinedp_tpu import streaming
from pipelinedp_tpu.aggregate_params import NoiseKind
from pipelinedp_tpu.backends import JaxBackend
from pipelinedp_tpu.ops import counter_rng

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestCounterRng:
    """The counter-based generator itself."""

    def test_threefry_matches_jax_internal(self):
        """Our batched Threefry-2x32 must be the SAME cipher JAX's own
        key system uses (same rotation schedule, same key injection) —
        pinned against the internal reference implementation."""
        from jax._src import prng as jax_prng

        rng = np.random.default_rng(0)
        k = rng.integers(0, 2**32, 2, dtype=np.uint32)
        c = rng.integers(0, 2**32, 64, dtype=np.uint32)
        ref = np.asarray(jax_prng.threefry_2x32(jnp.asarray(k),
                                                jnp.asarray(c)))
        h0, h1 = counter_rng.threefry2x32(
            jnp.uint32(k[0]), jnp.uint32(k[1]),
            jnp.asarray(c[:32]), jnp.asarray(c[32:]))
        np.testing.assert_array_equal(
            np.concatenate([np.asarray(h0), np.asarray(h1)]), ref)

    def test_node_noise_pure_in_indices(self):
        """The memoization contract: a (partition, node) pair draws the
        same noise wherever and however often it appears — sliced
        blocks, duplicated node ids across quantiles, and the root
        broadcast are all bit-exact restructurings."""
        key = jax.random.PRNGKey(3)
        P, Q, b = 32, 3, 16
        rng = np.random.default_rng(1)
        node_ids = jnp.asarray(
            rng.integers(0, 69904, (P, Q, b)).astype(np.int32))
        full = np.asarray(je._node_noise(NoiseKind.LAPLACE, key,
                                         node_ids))
        # Partition blocks with explicit global pk_index == full slice.
        for p0 in (0, 8, 24):
            blk = np.asarray(je._node_noise(
                NoiseKind.LAPLACE, key, node_ids[p0:p0 + 8],
                pk_index=jnp.arange(p0, p0 + 8, dtype=jnp.uint32)))
            np.testing.assert_array_equal(blk, full[p0:p0 + 8])
        # Duplicated node ids across the Q axis draw identical noise.
        dup = jnp.broadcast_to(node_ids[:, :1, :], node_ids.shape)
        out = np.asarray(je._node_noise(NoiseKind.LAPLACE, key, dup))
        np.testing.assert_array_equal(out, np.broadcast_to(
            out[:, :1, :], out.shape))

    @pytest.mark.parametrize("kind,var", [(NoiseKind.LAPLACE, 2.0),
                                          (NoiseKind.GAUSSIAN, 1.0)])
    def test_unit_moments(self, kind, var):
        """The generator's raw draws are unit-scale: Laplace(b=1) has
        variance 2, the Gaussian variance 1."""
        key = jax.random.PRNGKey(11)
        ids = jnp.arange(1 << 19, dtype=jnp.int32).reshape(1 << 15, 1, 16)
        draws = np.asarray(je._node_noise(kind, key, ids)).ravel()
        assert abs(draws.mean()) < 0.01
        assert draws.var() == pytest.approx(var, rel=0.02)

    def test_walk_noise_matches_calibrated_scale(self):
        """Through ``_noise_scales`` + the walk's ``raw + noise * scale``
        arithmetic, per-node noise must still carry the calibrated
        per-level scale (the statistical-moments acceptance check)."""
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50)],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=4,
            max_contributions_per_partition=2,
            min_value=0.0, max_value=10.0)
        config = je.FusedConfig.from_params(params, public=True)

        class _Spec:
            eps, delta = 0.5, 1e-6

        scale = float(je._noise_scales(config, {"percentile": _Spec})[0])
        # eps/level = 0.5/4, l1 sensitivity = l0 * linf = 8 -> b = 64.
        assert scale == pytest.approx(8 / (0.5 / 4), rel=1e-5)
        key = jax.random.PRNGKey(4)
        ids = jnp.arange(1 << 19, dtype=jnp.int32).reshape(1 << 15, 1, 16)
        draws = np.asarray(
            je._node_noise(NoiseKind.LAPLACE, key, ids)).ravel() * scale
        assert draws.var() == pytest.approx(2.0 * scale**2, rel=0.02)


def _walk_params(percentiles=(50, 90), hi=10.0, **kw):
    kw.setdefault("max_partitions_contributed", 40)
    kw.setdefault("max_contributions_per_partition", 200)
    return pdp.AggregateParams(
        metrics=[pdp.Metrics.PERCENTILE(p) for p in percentiles] +
        [pdp.Metrics.COUNT],
        noise_kind=pdp.NoiseKind.LAPLACE,
        min_value=0.0, max_value=hi, **kw)


def _percentile_fields(got):
    return [f for f in got[next(iter(got))]._fields
            if f.startswith("percentile_") or f == "count"]


class TestThreeWayBitParity:
    """Single-batch, 8-device owner-sharded mesh and streamed quantile
    walks must produce BIT-IDENTICAL released values and kept-partition
    sets for the same seed: the counter-based node noise is keyed by
    the GLOBAL (partition, node id), the mesh/streamed key splits now
    mirror the single-chip 3-way split, and the streamed host release
    draws over the kept set in the same order as the single-batch
    compact fetch. Caps are non-binding so bounding keeps every row on
    all three paths (binding caps legitimately sample per-path)."""

    def _dataset(self):
        rng = np.random.default_rng(42)
        n = 20_000
        return pdp.ArrayDataset(
            privacy_ids=rng.integers(0, 2_000, n),
            partition_keys=(rng.zipf(1.6, n) % 40).astype(np.int64),
            values=rng.uniform(0, 10, n))

    def _run(self, ds, backend, chunk=None, monkeypatch=None):
        if chunk is not None:
            monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", str(chunk))
        else:
            monkeypatch.delenv("PIPELINEDP_TPU_STREAM_CHUNK",
                               raising=False)
        ds.invalidate_cache()
        acc = pdp.NaiveBudgetAccountant(total_epsilon=4.0,
                                        total_delta=1e-4)
        engine = pdp.DPEngine(acc, backend)
        res = engine.aggregate(ds, _walk_params(), pdp.DataExtractors())
        acc.compute_budgets()
        return dict(res), res.timings

    def test_three_way_bit_identical(self, monkeypatch):
        from pipelinedp_tpu.parallel import make_mesh

        ds = self._dataset()
        single, _ = self._run(ds, JaxBackend(rng_seed=11),
                              monkeypatch=monkeypatch)
        mesh, _ = self._run(ds, JaxBackend(mesh=make_mesh(8),
                                           rng_seed=11),
                            monkeypatch=monkeypatch)
        streamed, t = self._run(ds, JaxBackend(rng_seed=11), chunk=997,
                                monkeypatch=monkeypatch)
        assert t["stream_batches"] > 5  # really streamed
        assert len(single) > 5  # non-trivial kept set
        assert set(single) == set(mesh) == set(streamed)
        for k in single:
            for f in _percentile_fields(single):
                v = getattr(single[k], f)
                assert getattr(mesh[k], f) == v, (k, f, "mesh")
                assert getattr(streamed[k], f) == v, (k, f, "streamed")


class TestPartitionBlockChunkedWalk:
    """Past ``_SUBHIST_BYTE_CAP`` the bottom walk chunks the partition
    axis into blocks — bit-identical to the unchunked walk (node noise
    is a pure function of the GLOBAL (partition, node id))."""

    def _run_public(self, ds, params, parts, backend=None, chunk=None,
                    monkeypatch=None):
        if monkeypatch is not None:
            if chunk is not None:
                monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK",
                                   str(chunk))
            else:
                monkeypatch.delenv("PIPELINEDP_TPU_STREAM_CHUNK",
                                   raising=False)
        ds.invalidate_cache()
        je.fused_aggregate_kernel.clear_cache()
        acc = pdp.NaiveBudgetAccountant(total_epsilon=3.0,
                                        total_delta=1e-6)
        engine = pdp.DPEngine(acc, backend or JaxBackend(rng_seed=9))
        res = engine.aggregate(ds, params, pdp.DataExtractors(),
                               public_partitions=list(range(parts)))
        acc.compute_budgets()
        return dict(res), res.timings

    def test_single_batch_blocks_bit_identical(self, monkeypatch):
        """The single-batch walk no longer degrades to per-level row
        scatters past the cap: it partition-block-chunks, and the
        blocked walk is bit-identical to the one-block walk."""
        rng = np.random.default_rng(5)
        n = 8_000
        ds = pdp.ArrayDataset(privacy_ids=rng.integers(0, 2_000, n),
                              partition_keys=rng.integers(0, 6, n),
                              values=rng.uniform(0, 20, n))
        params = _walk_params(percentiles=(25, 50, 95), hi=20.0,
                              max_partitions_contributed=6,
                              max_contributions_per_partition=50)
        full, _ = self._run_public(ds, params, 6,
                                   monkeypatch=monkeypatch)
        # P_pad = 8, Q = 3: cap sized for 2-partition blocks -> the
        # bottom walk runs as 4 blocks, each built with the compacted
        # sub-histogram machinery. Spy on the builder to prove the
        # chunked path actually traced.
        _, _, _, span = streaming._tree_consts()
        monkeypatch.setattr(je, "_SUBHIST_BYTE_CAP", 2 * 3 * span * 4)
        block_sizes = []
        orig = je._build_sub_hist

        def spy(qpk, leaf, kept, sub_start, P, *a, **kw):
            block_sizes.append(P)
            return orig(qpk, leaf, kept, sub_start, P, *a, **kw)

        monkeypatch.setattr(je, "_build_sub_hist", spy)
        chunked, _ = self._run_public(ds, params, 6,
                                      monkeypatch=monkeypatch)
        assert block_sizes == [2, 2, 2, 2]
        for p in range(6):
            for f in _percentile_fields(full):
                assert getattr(chunked[p], f) == getattr(full[p], f), (
                    p, f)

    def test_streamed_single_quantile_over_cap_completes(self,
                                                         monkeypatch):
        """The acceptance case: a streamed percentile run whose SINGLE-
        quantile [P_pad, 1, span] block exceeds a test-shrunken cap
        completes via partition-block chunking (no NotImplementedError)
        and matches the uncapped run bit-for-bit."""
        rng = np.random.default_rng(88)
        n = 6_000
        ds = pdp.ArrayDataset(privacy_ids=rng.integers(0, 1_500, n),
                              partition_keys=rng.integers(0, 5, n),
                              values=rng.uniform(0.0, 20.0, n))
        params = _walk_params(percentiles=(50, 95), hi=20.0,
                              max_partitions_contributed=5,
                              max_contributions_per_partition=50)
        full, t_full = self._run_public(ds, params, 5, chunk=997,
                                        monkeypatch=monkeypatch)
        assert t_full["stream_batches"] > 1
        assert t_full["stream_pass_b_rounds"] == 1
        # P_pad = 8: a cap of two partitions' single-quantile blocks is
        # BELOW one quantile's [8, 1, span] block -> partition-block
        # mode: 2 q-groups x 4 p-blocks = 8 rounds.
        _, _, _, span = streaming._tree_consts()
        monkeypatch.setattr(je, "_SUBHIST_BYTE_CAP", 2 * span * 4)
        chunked, t_chunk = self._run_public(ds, params, 5, chunk=997,
                                            monkeypatch=monkeypatch)
        assert t_chunk["stream_pass_b_rounds"] == 8
        for p in range(5):
            for f in _percentile_fields(full):
                assert getattr(chunked[p], f) == getattr(full[p], f), (
                    p, f)

    def test_streamed_blocks_on_mesh_bit_identical(self, monkeypatch):
        """Partition-block chunking composes with the 8-device mesh
        (block rounds combine shards with a replicating psum instead of
        the owner-block scatter) — still bit-identical."""
        from pipelinedp_tpu.parallel import make_mesh

        rng = np.random.default_rng(17)
        n = 6_000
        ds = pdp.ArrayDataset(privacy_ids=rng.integers(0, 1_500, n),
                              partition_keys=rng.integers(0, 5, n),
                              values=rng.uniform(0.0, 10.0, n))
        params = _walk_params(percentiles=(50, 90),
                              max_partitions_contributed=5,
                              max_contributions_per_partition=50)
        mesh = make_mesh(8)
        # The per-batch target scales with the mesh size: 8 x 499 rows
        # per batch still splits 6,000 rows into > 1 batch.
        full, t_full = self._run_public(
            ds, params, 5, backend=JaxBackend(mesh=mesh, rng_seed=3),
            chunk=499, monkeypatch=monkeypatch)
        assert t_full["stream_batches"] > 1
        _, _, _, span = streaming._tree_consts()
        monkeypatch.setattr(je, "_SUBHIST_BYTE_CAP", 4 * span * 4)
        chunked, t_chunk = self._run_public(
            ds, params, 5, backend=JaxBackend(mesh=mesh, rng_seed=3),
            chunk=499, monkeypatch=monkeypatch)
        assert t_chunk["stream_pass_b_rounds"] > 1
        for p in range(5):
            for f in _percentile_fields(full):
                assert getattr(chunked[p], f) == getattr(full[p], f), (
                    p, f)


class TestGuardBoundaries:
    """The extreme-scale guard cliffs (VERDICT r5 "What's weak" #6),
    pinned at their EXACT boundaries via the injectable cap seams —
    the way ``test_jax_engine`` pins the lane-plan boundary at
    524,417 rows exactly."""

    def test_lane_plan_boundary_at_true_cap(self):
        """The 2^27-row per-batch unit-skew cliff, at its real
        constant: the narrowest (4-bit) lane plan accumulates exactly
        up to floor((2^31 - 1) / 15) = 143,165,576 rows."""
        boundary = (je._LANE_SUM_CAP - 1) // 15
        assert boundary == 143_165_576 == je._fx_max_rows()
        assert je._fx_plan(boundary) == (4, 6)
        with pytest.raises(NotImplementedError, match="privacy unit"):
            je._fx_plan(boundary + 1)

    def test_unit_skew_guard_exact_boundary(self, monkeypatch):
        """The streamed guard for one privacy unit owning more rows
        than a batch can hold, at the exact injected boundary: with
        ``_LANE_SUM_CAP = 1501`` the cliff is at 100 rows — a unit
        owning exactly 100 streams fine, 101 raises the skew message."""
        monkeypatch.setattr(je, "_LANE_SUM_CAP", 1501)
        assert je._fx_max_rows() == 100
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "50")
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.SUM], noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=1,
            max_contributions_per_partition=200,
            min_value=0.0, max_value=1.0)

        def run(n_rows_of_one_unit):
            ds = pdp.ArrayDataset(
                privacy_ids=np.zeros(n_rows_of_one_unit, np.int64),
                partition_keys=np.zeros(n_rows_of_one_unit, np.int64),
                values=np.ones(n_rows_of_one_unit, np.float32))
            acc = pdp.NaiveBudgetAccountant(total_epsilon=1e6,
                                            total_delta=1e-2)
            engine = pdp.DPEngine(acc, JaxBackend(rng_seed=0))
            res = engine.aggregate(ds, params, pdp.DataExtractors(),
                                   public_partitions=[0])
            acc.compute_budgets()
            return dict(res)

        got = run(100)  # exactly at capacity: completes
        assert got[0].sum == pytest.approx(100.0, abs=0.5)
        with pytest.raises(NotImplementedError,
                           match="privacy unit owns"):
            run(101)

    def test_select_units_guard_exact_boundary(self, monkeypatch):
        """The >2^31-privacy-units-per-partition selection guard at an
        injected cap of 64: 63 units in one partition selects fine, 64
        raises."""
        monkeypatch.setattr(streaming, "_SELECT_UNITS_CAP", 64)
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "29")
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=1,
            max_contributions_per_partition=1)

        def run(n_units):
            ds = pdp.ArrayDataset(
                privacy_ids=np.arange(n_units, dtype=np.int64),
                partition_keys=np.zeros(n_units, np.int64),
                values=np.zeros(n_units, np.float32))
            acc = pdp.NaiveBudgetAccountant(total_epsilon=1e6,
                                            total_delta=1e-2)
            engine = pdp.DPEngine(acc, JaxBackend(rng_seed=0))
            res = engine.aggregate(ds, params, pdp.DataExtractors())
            acc.compute_budgets()
            return dict(res)

        got = run(63)  # one below the cap: completes and keeps pk 0
        assert 0 in got
        with pytest.raises(NotImplementedError, match="privacy units"):
            run(64)

    def test_tree_rows_guard_exact_boundary(self, monkeypatch):
        """The >2^31-kept-rows-per-partition streamed-percentile guard
        at an injected cap of 256: a partition holding 255 kept rows
        walks fine, 256 raises."""
        monkeypatch.setattr(streaming, "_TREE_ROWS_CAP", 256)
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "61")
        params = _walk_params(percentiles=(50,),
                              max_partitions_contributed=1,
                              max_contributions_per_partition=300)

        def run(n_rows):
            rng = np.random.default_rng(1)
            ds = pdp.ArrayDataset(
                privacy_ids=np.arange(n_rows, dtype=np.int64),
                partition_keys=np.zeros(n_rows, np.int64),
                values=rng.uniform(0, 10, n_rows))
            acc = pdp.NaiveBudgetAccountant(total_epsilon=1e6,
                                            total_delta=1e-2)
            engine = pdp.DPEngine(acc, JaxBackend(rng_seed=0))
            res = engine.aggregate(ds, params, pdp.DataExtractors(),
                                   public_partitions=[0])
            acc.compute_budgets()
            return dict(res)

        got = run(255)
        assert got[0].percentile_50 == pytest.approx(5.0, abs=1.0)
        with pytest.raises(NotImplementedError, match="2\\^31 kept"):
            run(256)


class TestFoldInKeyLint:
    """Per-element ``vmap(fold_in)`` key constructions rebuild a full
    threefry key schedule per element — the cost the counter-based
    generator removed. New ones are banned outside the one blessed
    helper module (``ops/counter_rng.py``); ``make nofoldin`` enforces
    the same rule at the Makefile level."""

    def test_no_vmap_fold_in_outside_blessed_helper(self):
        # Delegates to the shared AST engine; `make nofoldin` is the
        # same rule.
        from pipelinedp_tpu import lint
        assert lint.check_tree("nofoldin") == []
