"""A lazy structural stand-in for the RDD API slice that
``SparkRDDBackend`` and ``private_spark`` consume (map, flatMap,
mapValues, flatMapValues, groupByKey, reduceByKey, filter, join, union,
keys, values, distinct, collect). Same rationale as ``fake_beam``:
execute the adapter code where pyspark is not installable. Laziness via
composed thunks preserves the two-phase budget protocol."""

from __future__ import annotations

import itertools


class FakeRDD:

    def __init__(self, thunk, context=None):
        self._thunk = thunk
        self._cache = None
        #: mirrors pyspark's RDD.context (used by private_spark)
        self.context = context

    # -- materialization --
    def collect(self):
        if self._cache is None:
            self._cache = list(self._thunk())
        return self._cache

    def __iter__(self):
        return iter(self.collect())

    # -- transformations (all lazy) --
    def map(self, fn):
        return FakeRDD(lambda: [fn(x) for x in self.collect()],
                       self.context)

    def flatMap(self, fn):
        return FakeRDD(lambda: list(
            itertools.chain.from_iterable(fn(x) for x in self.collect())),
                       self.context)

    def mapValues(self, fn):
        return FakeRDD(lambda: [(k, fn(v)) for k, v in self.collect()],
                       self.context)

    def flatMapValues(self, fn):
        return FakeRDD(lambda: [(k, v2) for k, v in self.collect()
                                for v2 in fn(v)], self.context)

    def filter(self, fn):
        return FakeRDD(lambda: [x for x in self.collect() if fn(x)],
                       self.context)

    def _grouped(self):
        out = {}
        for k, v in self.collect():
            out.setdefault(k, []).append(v)
        return out

    def groupByKey(self):
        return FakeRDD(lambda: list(self._grouped().items()),
                       self.context)

    def reduceByKey(self, fn):
        def thunk():
            out = {}
            for k, v in self.collect():
                out[k] = fn(out[k], v) if k in out else v
            return list(out.items())
        return FakeRDD(thunk, self.context)

    def join(self, other):
        def thunk():
            right = other._grouped()
            return [(k, (v, w)) for k, v in self.collect()
                    for w in right.get(k, [])]
        return FakeRDD(thunk, self.context)

    def union(self, other):
        return FakeRDD(lambda: self.collect() + other.collect(),
                       self.context)

    def keys(self):
        return FakeRDD(lambda: [k for k, _ in self.collect()],
                       self.context)

    def values(self):
        return FakeRDD(lambda: [v for _, v in self.collect()],
                       self.context)

    def distinct(self):
        def thunk():
            seen, out = set(), []
            for x in self.collect():
                if x not in seen:
                    seen.add(x)
                    out.append(x)
            return out
        return FakeRDD(thunk, self.context)


class FakeSparkContext:

    def parallelize(self, data):
        data = list(data)
        return FakeRDD(lambda: list(data), self)

    def union(self, rdds):
        return FakeRDD(lambda: list(
            itertools.chain.from_iterable(r.collect() for r in rdds)),
                       self)
