"""Worker process for the elastic reshard-resume parity test (ISSUE 16).

Two ``jax.distributed`` gloo processes form an 8-device global mesh and
stream the same aggregation. Process 1 (the NON-coordinator, so the
coordinator service survives) carries an injected ``fail_chunks`` fault
in its env and dies mid-stream. Process 0's mesh supervisor (armed via
``PIPELINEDP_TPU_MESH_DIR``) detects the death at its next collective
dispatch — BEFORE enqueueing the collective that would wedge on the
dead peer — raises ``MeshParticipantLost``, and the elastic wrapper in
``streaming.py`` re-forms the mesh over the survivor's 4 local devices,
resumes from the checkpoint, and finishes. The survivor then proves the
recovery:

* released values BIT-IDENTICAL to a clean run at the surviving shape;
* ``stream_mesh_reshards == 1`` with the 8 -> 4 ``participant_lost``
  record in the timings' reshard history;
* the ``mesh.reshard`` event on the run ledger;
* the resume started from a checkpoint, not from scratch.

Both processes exit via ``os._exit(0)`` after printing their marker —
the distributed atexit barrier would otherwise hang on the dead peer.

Not a pytest file — invoked directly with (process_id, n_processes,
rendezvous_file) argv; see ``tests/test_multihost.py``.
"""

import os
import sys

from multihost_worker import rendezvous_port


def main() -> None:
    proc_id = int(sys.argv[1])
    n_proc = int(sys.argv[2])
    rendezvous = sys.argv[3]

    # Self-deadline: an orphaned worker spinning in a gloo collective
    # must never outlive the suite (same discipline as
    # multihost_worker.py).
    import threading
    watchdog = threading.Timer(480.0, lambda: os._exit(3))
    watchdog.daemon = True
    watchdog.start()

    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    # Synchronous dispatch: see multihost_worker.py — keeps the two
    # processes' gloo collectives paired in program order.
    jax.config.update("jax_cpu_enable_async_dispatch", False)
    port = rendezvous_port(proc_id, rendezvous)
    from pipelinedp_tpu.resilience import (CheckpointStore, RetryPolicy,
                                           resilient_distributed_initialize)
    resilient_distributed_initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=n_proc, process_id=proc_id,
        policy=RetryPolicy(max_attempts=2, base_delay_s=1.0,
                           multiplier=2.0, max_delay_s=10.0,
                           jitter=0.25, seed=proc_id),
        # The coordination service's default reaction to a peer that
        # stops heartbeating is to FATALLY terminate every surviving
        # client after ~100s — the exact recovery this test exists to
        # prove. Stretch the tolerance past the harness deadline so
        # OUR supervisor, not jax's, owns death detection here.
        service_max_missing_heartbeats=1000,
        client_max_missing_heartbeats=1000)
    assert len(jax.devices()) == 4 * n_proc, jax.devices()
    assert len(jax.local_devices()) == 4

    import numpy as np

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import obs
    from pipelinedp_tpu.backends import JaxBackend
    from pipelinedp_tpu.parallel import make_mesh
    from pipelinedp_tpu.parallel import sharded
    from pipelinedp_tpu.resilience import faults

    mesh = make_mesh()  # all 8 global devices
    assert mesh.devices.size == 4 * n_proc
    assert os.environ.get("PIPELINEDP_TPU_MESH_DIR"), (
        "the parent must arm the mesh supervisor")

    rng = np.random.default_rng(0)  # identical data on every process
    n = 20_000
    pid = rng.integers(0, 2_000, n)
    pk = rng.integers(0, 40, n)
    vals = rng.uniform(0.0, 10.0, n)
    ds = pdp.ArrayDataset(privacy_ids=pid, partition_keys=pk,
                          values=vals)
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
        max_partitions_contributed=50,
        max_contributions_per_partition=50,
        min_value=0.0, max_value=10.0)
    public = list(range(40))

    def run(backend):
        ds.invalidate_cache()
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1e8,
                                        total_delta=1e-6)
        engine = pdp.DPEngine(acc, backend)
        res = engine.aggregate(ds, params, pdp.DataExtractors(),
                               public_partitions=public)
        acc.compute_budgets()
        return dict(res), res.timings

    if proc_id != 0:
        # The victim: its own env carries fail_chunks=2, so its stream
        # dies at chunk 2 — from the survivor's side, indistinguishable
        # from this host dropping out. The quiesce path inside
        # streaming drains the in-flight collective first, so the
        # SURVIVOR's matching dispatch completes instead of wedging.
        assert faults.active() is not None, (
            "victim worker expected an injected fault plan")
        try:
            run(JaxBackend(mesh=mesh, rng_seed=11))
        except faults.FaultInjected:
            print(f"proc {proc_id}: dying (injected fault mid-stream)",
                  flush=True)
            os._exit(0)
        print(f"proc {proc_id}: fault never fired", flush=True)
        os._exit(4)

    # The survivor (and coordinator): checkpointed elastic run. The
    # wrapper re-forms onto the 4 local devices when the supervisor
    # reports the peer dead, resumes from the checkpoint, completes.
    assert faults.active() is None, (
        "survivor must not inherit the victim's fault plan")
    store = CheckpointStore(os.path.join(
        os.environ["PDP_TEST_CKPT_DIR"], "elastic.ckpt"))
    survived, timings = run(JaxBackend(mesh=mesh, rng_seed=11,
                                       checkpoint=store))
    assert timings.get("stream_batches", 0) >= 3, timings
    assert timings.get("stream_mesh_reshards") == 1, timings
    (reshard,) = timings["stream_reshard_history"]
    assert reshard["old_devices"] == 8, reshard
    assert reshard["new_devices"] == 4, reshard
    assert reshard["reason"] == "participant_lost", reshard
    assert timings.get("stream_resumed_from", 0) >= 1, (
        "recovery restarted from scratch instead of the checkpoint")
    events = [e for e in obs.ledger().snapshot()["events"]
              if e["name"] == "mesh.reshard"]
    assert len(events) == 1, events
    assert events[0]["old_devices"] == 8, events
    assert events[0]["new_devices"] == 4, events

    # Bit-parity oracle: a CLEAN run at the surviving shape — the same
    # local mesh the wrapper re-formed onto.
    survivor_mesh = sharded.reform_mesh(mesh)
    assert survivor_mesh is not None
    assert survivor_mesh.devices.size == 4
    baseline, base_timings = run(JaxBackend(mesh=survivor_mesh,
                                            rng_seed=11))
    assert base_timings.get("stream_batches", 0) >= 3, base_timings
    assert set(survived) == set(baseline), (
        sorted(set(survived) ^ set(baseline)))
    for k in survived:
        for f in survived[k]._fields:
            va = np.asarray(getattr(survived[k], f))
            vb = np.asarray(getattr(baseline[k], f))
            assert np.array_equal(va, vb), (k, f, va, vb)

    print(f"proc {proc_id}: OK (reshard "
          f"{reshard['old_devices']} -> {reshard['new_devices']}, "
          f"resumed from batch {timings['stream_resumed_from']}, "
          f"{len(survived)} partitions bit-identical)", flush=True)
    # Skip the distributed atexit barrier — the peer is dead.
    os._exit(0)


if __name__ == "__main__":
    main()
