"""Tests for dp_computations — mirrors the reference's statistical test
strategy (``tests/dp_computations_test.py``): calibration identities and
moment checks, plus vectorized-path equivalence (ours accepts arrays)."""

import math

import numpy as np
import pytest

from pipelinedp_tpu import dp_computations as dpc
from pipelinedp_tpu.aggregate_params import NoiseKind, NormKind
from pipelinedp_tpu.ops import noise as noise_ops


def scalar_params(eps=2e5, delta=1e-10, min_value=0.0, max_value=10.0,
                  min_sum=None, max_sum=None, l0=2, linf=3,
                  noise_kind=NoiseKind.LAPLACE):
    return dpc.ScalarNoiseParams(
        eps=eps, delta=delta, min_value=min_value, max_value=max_value,
        min_sum_per_partition=min_sum, max_sum_per_partition=max_sum,
        max_partitions_contributed=l0,
        max_contributions_per_partition=linf, noise_kind=noise_kind)


class TestHelpers:

    def test_middle_and_squares(self):
        assert dpc.compute_middle(2, 10) == 6
        assert dpc.compute_squares_interval(-3, 2) == (0, 9)
        assert dpc.compute_squares_interval(1, 4) == (1, 16)
        assert dpc.compute_squares_interval(-5, -2) == (25, 4)

    def test_equally_split_budget_sums_exactly(self):
        budgets = dpc.equally_split_budget(1.0, 1e-6, 3)
        assert len(budgets) == 3
        assert sum(b[0] for b in budgets) == 1.0
        assert sum(b[1] for b in budgets) == 1e-6
        with pytest.raises(ValueError):
            dpc.equally_split_budget(1.0, 0.0, 0)


class TestApplyMechanisms:
    """Public single-release helpers (reference dp_computations.py:111-143)."""

    def test_laplace_big_eps_near_identity(self):
        noise_ops.seed_host_rng(0)
        assert dpc.apply_laplace_mechanism(42.0, 1e6, 3.0) == pytest.approx(
            42.0, abs=1e-3)

    def test_laplace_std(self):
        noise_ops.seed_host_rng(0)
        draws = np.array([
            dpc.apply_laplace_mechanism(0.0, 1.0, 2.0) for _ in range(20000)
        ])
        # b = l1/eps = 2 -> std = 2*sqrt(2).
        assert np.std(draws) == pytest.approx(2 * math.sqrt(2), rel=0.05)

    def test_gaussian_std_matches_compute_sigma(self):
        noise_ops.seed_host_rng(0)
        sigma = dpc.compute_sigma(1.0, 1e-6, 2.0)
        draws = np.array([
            dpc.apply_gaussian_mechanism(0.0, 1.0, 1e-6, 2.0)
            for _ in range(20000)
        ])
        assert np.std(draws) == pytest.approx(sigma, rel=0.05)

    def test_batched(self):
        noise_ops.seed_host_rng(0)
        vals = np.zeros(5000)
        got = dpc.apply_laplace_mechanism(vals, 1.0, 1.0)
        assert got.shape == (5000,)
        assert np.std(got) == pytest.approx(math.sqrt(2), rel=0.1)


class TestCount:

    def test_big_eps_deterministic(self):
        p = scalar_params()
        assert dpc.compute_dp_count(42, p) == pytest.approx(42, abs=0.01)

    def test_vectorized(self):
        p = scalar_params()
        counts = np.array([1.0, 10.0, 100.0])
        got = dpc.compute_dp_count(counts, p)
        assert got.shape == (3,)
        np.testing.assert_allclose(got, counts, atol=0.01)

    def test_noise_std_laplace(self):
        # linf=3, l0=2 -> L1=6; eps=1 -> b=6 -> std = 6*sqrt(2).
        p = scalar_params(eps=1.0, noise_kind=NoiseKind.LAPLACE)
        noise_ops.seed_host_rng(0)
        draws = np.array([dpc.compute_dp_count(0, p) for _ in range(20000)])
        assert np.std(draws) == pytest.approx(6 * math.sqrt(2), rel=0.05)
        assert dpc.compute_dp_count_noise_std(p) == pytest.approx(
            6 * math.sqrt(2))

    def test_noise_std_gaussian(self):
        p = scalar_params(eps=1.0, delta=1e-6,
                          noise_kind=NoiseKind.GAUSSIAN)
        expected = noise_ops.gaussian_sigma(1.0, 1e-6,
                                            math.sqrt(2) * 3)
        assert dpc.compute_dp_count_noise_std(p) == pytest.approx(expected)


class TestSum:

    def test_per_value_bounds(self):
        p = scalar_params()
        assert dpc.compute_dp_sum(100.0, p) == pytest.approx(100, abs=0.01)

    def test_per_partition_bounds(self):
        p = scalar_params(min_value=None, max_value=None, min_sum=0.0,
                          max_sum=5.0)
        assert dpc.compute_dp_sum(4.0, p) == pytest.approx(4.0, abs=0.01)
        assert dpc.compute_dp_sum_noise_std(p) > 0

    def test_zero_sensitivity_returns_zero_exactly(self):
        p = scalar_params(min_value=0.0, max_value=0.0)
        assert dpc.compute_dp_sum(123.0, p) == 0


class TestMeanVariance:

    def test_mean_big_eps(self):
        p = scalar_params(min_value=0.0, max_value=10.0, linf=1)
        count, total, mean = dpc.compute_dp_mean(
            100, 100 * (7.0 - 5.0), p)  # normalized sum: values at 7
        assert count == pytest.approx(100, abs=0.01)
        assert mean == pytest.approx(7.0, abs=0.01)
        assert total == pytest.approx(700.0, rel=0.001)

    def test_mean_degenerate_interval(self):
        p = scalar_params(min_value=5.0, max_value=5.0, linf=1)
        _, _, mean = dpc.compute_dp_mean(10, 0.0, p)
        assert mean == pytest.approx(5.0)

    def test_var_big_eps(self):
        # Values: half at 2, half at 8 in [0,10]: mean 5, var 9.
        p = scalar_params(min_value=0.0, max_value=10.0, linf=1)
        n = 100
        normalized = (2 - 5) * 50 + (8 - 5) * 50  # 0
        normalized_sq = 9 * 50 + 9 * 50
        count, total, mean, var = dpc.compute_dp_var(
            n, normalized, normalized_sq, p)
        assert count == pytest.approx(100, abs=0.01)
        assert mean == pytest.approx(5.0, abs=0.01)
        assert var == pytest.approx(9.0, abs=0.1)

    def test_vectorized_mean(self):
        p = scalar_params(min_value=0.0, max_value=10.0, linf=1)
        counts = np.array([10.0, 20.0])
        nsums = np.array([10 * 2.0, 20 * -1.0])
        count, total, mean = dpc.compute_dp_mean(counts, nsums, p)
        np.testing.assert_allclose(mean, [7.0, 4.0], atol=0.01)
        np.testing.assert_allclose(count, counts, atol=0.01)


class TestVectorSum:

    def _params(self, norm_kind, max_norm=10.0, eps=1e6):
        return dpc.AdditiveVectorNoiseParams(
            eps_per_coordinate=eps, delta_per_coordinate=0.0,
            max_norm=max_norm, l0_sensitivity=1, linf_sensitivity=1,
            norm_kind=norm_kind, noise_kind=NoiseKind.LAPLACE)

    def test_linf_clipping(self):
        got = dpc.add_noise_vector(
            np.array([5.0, -20.0, 15.0]), self._params(NormKind.Linf))
        np.testing.assert_allclose(got, [5.0, -10.0, 10.0], atol=0.01)

    def test_l2_clipping(self):
        vec = np.array([30.0, 40.0])  # norm 50, clip to 10 -> [6, 8]
        got = dpc.add_noise_vector(vec, self._params(NormKind.L2))
        np.testing.assert_allclose(got, [6.0, 8.0], atol=0.01)

    def test_l1_clipping(self):
        vec = np.array([15.0, 5.0])  # l1 20, clip to 10 -> [7.5, 2.5]
        got = dpc.add_noise_vector(vec, self._params(NormKind.L1))
        np.testing.assert_allclose(got, [7.5, 2.5], atol=0.01)

    def test_zero_vector_unchanged(self):
        got = dpc.add_noise_vector(
            np.zeros(3), self._params(NormKind.L2))
        np.testing.assert_allclose(got, np.zeros(3), atol=0.01)
