"""Execution-planner tests (``pipelinedp_tpu/plan``, ``make plancheck``).

Coverage contract:

* knob registry — cold-start resolution (no plan file, no env, no
  ledger history) is BYTE-IDENTICAL to the hardcoded defaults; env
  overrides outrank test seams outrank plan files outrank defaults;
  dp-unsafe knobs (``stream_chunk_rows``, the int32 guard caps) are
  never applied from a plan (``plan.skipped_dp_unsafe``);
* poisoned history — an empty ledger, a degraded-only ledger and a
  mixed-fingerprint ledger all fit an EMPTY model (predict None) and
  resolve to the defaults byte-for-byte;
* plan file — atomic write/load round-trip; a plan written under a
  DIFFERENT fingerprint hash is ignored with a ``plan.stale`` event;
  ``PIPELINEDP_TPU_PLAN_DIR=0`` disables loading entirely;
* cost model — least-squares fit from synthetic trials predicts
  through the samples, serializes through the plan file, and the
  roofline fallback floors at bytes over the static peak bandwidth;
* pass-B q_chunk pin — a pinned quantile-group width constrains the
  sweep planner's tiling; an infeasible pin falls back to the search;
* PARITY row 32 — planner on (a plan file moving every dp-safe knob)
  vs off (no plan): DP outputs bit-identical, because plans only
  select among already-parity-tested execution paths;
* ``--since-run-id`` — the store's run-windowed reads (module helper,
  incremental ``read_from`` offsets, and the CLI flag);
* bench provenance — every bench record carries ``plan_source`` /
  ``plan_hash``, and ``--compare`` refuses to gate a rate against a
  baseline recorded under a different plan (``COMPARE: plan
  mismatch``, never a false regression);
* the autotune acceptance flow — ``run_autotune`` writes a plan file
  a subsequent plain streamed run resolves (``plan.applied`` events
  with ``source: "plan"``);
* lint twin — AST-precise ban on direct reads of the registered knob
  constants outside ``pipelinedp_tpu/plan/`` (``make noknobs`` runs
  the grep twin).
"""

import argparse
import ast
import json
import os

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import jax_engine as je
from pipelinedp_tpu import obs
from pipelinedp_tpu import plan as plan_pkg
from pipelinedp_tpu import streaming
from pipelinedp_tpu.backends import JaxBackend
from pipelinedp_tpu.obs import store as obs_store
from pipelinedp_tpu.plan import knobs as plan_knobs
from pipelinedp_tpu.plan import model as plan_model
from pipelinedp_tpu.plan import planner as plan_planner

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BIG_EPS = 1e12

#: Today's hardcoded defaults, restated literally: the cold-start
#: acceptance criterion is byte-identity against THESE values, so the
#: test must not derive them from the registry it is checking.
HARDCODED_DEFAULTS = {
    "subhist_byte_cap": 600 << 20,
    "stream_chunk_rows": 1 << 26,
    "stream_cache_bytes": 4 << 30,
    "ingest_executor": True,
    "q_chunk": 0,
    "kernel_backend": "xla",
    "segsum_wide_d_block": 0,
    "sweep_config_batch": 0,
    "vector_accumulator": "f32",
    "serve_fusion": False,
    "serve_fuse_window_ms": 8,
    "serve_fuse_batch": 8,
    "serve_fuse_rows_floor": 8192,
    "sketch_width": 1 << 16,
    "sketch_depth": 2,
    "sketch_candidate_cap": 4096,
    "sketch_backend": "matmul",
    "mesh_topology": "flat",
    "select_units_cap": int(np.iinfo(np.int32).max),
    "tree_rows_cap": int(np.iinfo(np.int32).max),
}


@pytest.fixture(autouse=True)
def fresh_plan_state(monkeypatch):
    """Isolate every test: no ambient plan file/env, fresh applied
    state, and a fresh obs ledger so event assertions see only this
    test's emissions."""
    for var in (plan_planner.ENV_DIR, "PIPELINEDP_TPU_SUBHIST_CAP",
                "PIPELINEDP_TPU_Q_CHUNK", "PIPELINEDP_TPU_STREAM_CHUNK",
                "PIPELINEDP_TPU_STREAM_CACHE",
                "PIPELINEDP_TPU_INGEST_EXECUTOR",
                "PIPELINEDP_TPU_SERVE_FUSION",
                "PIPELINEDP_TPU_SERVE_FUSE_WINDOW_MS",
                "PIPELINEDP_TPU_SERVE_FUSE_BATCH",
                "PIPELINEDP_TPU_SERVE_FUSE_ROWS_FLOOR",
                "PIPELINEDP_TPU_SEGSUM_WIDE_D_BLOCK",
                "PIPELINEDP_TPU_VECTOR_ACCUMULATOR",
                "PIPELINEDP_TPU_COMPILE_CACHE"):
        monkeypatch.delenv(var, raising=False)
    obs.reset()
    plan_pkg.set_default_dir(None)
    yield
    obs.reset()
    plan_pkg.set_default_dir(None)


def _events(name):
    return [e for e in obs.ledger().snapshot()["events"]
            if e["name"] == name]


def _write_plan_file(directory, knobs, fingerprint=None, model=None):
    plan = {"schema_version": plan_planner.PLAN_SCHEMA,
            "fingerprint": (plan_planner.fingerprint()
                            if fingerprint is None else fingerprint),
            "device_kind": "cpu", "created_by": "test", "trials": 1,
            "knobs": {"default": dict(knobs)},
            "model": (model or plan_model.CostModel()).to_dict()}
    plan_planner.write_plan(plan, str(directory))
    return plan


class TestKnobRegistry:
    """Resolution precedence and the cold-start contract."""

    def test_cold_start_is_byte_identical_to_defaults(self):
        resolved = plan_knobs.resolve_all(None)
        assert {k: v for k, (v, _) in resolved.items()} == (
            HARDCODED_DEFAULTS)
        assert {s for _, (_, s) in resolved.items()} == {"default"}
        assert plan_knobs.defaults() == HARDCODED_DEFAULTS

    def test_env_outranks_plan_and_default(self, monkeypatch):
        monkeypatch.setenv("PIPELINEDP_TPU_SUBHIST_CAP", "1048576")
        v, s = plan_knobs.resolve_value(
            plan_knobs.BY_NAME["subhist_byte_cap"],
            {"subhist_byte_cap": 2048})
        assert (v, s) == (1 << 20, "env")

    def test_seam_outranks_plan(self, monkeypatch):
        monkeypatch.setattr(je, "_SUBHIST_BYTE_CAP", 4096)
        v, s = plan_knobs.resolve_value(
            plan_knobs.BY_NAME["subhist_byte_cap"],
            {"subhist_byte_cap": 2048})
        assert (v, s) == (4096, "seam")

    def test_plan_outranks_default_for_dp_safe(self):
        v, s = plan_knobs.resolve_value(
            plan_knobs.BY_NAME["stream_cache_bytes"],
            {"stream_cache_bytes": 0})
        assert (v, s) == (0, "plan")

    def test_dp_unsafe_knob_never_applied_from_plan(self):
        v, s = plan_knobs.resolve_value(
            plan_knobs.BY_NAME["stream_chunk_rows"],
            {"stream_chunk_rows": 1234})
        assert (v, s) == (1 << 26, "default")
        ev = _events("plan.skipped_dp_unsafe")
        assert ev and ev[-1]["knob"] == "stream_chunk_rows"
        for guard in ("select_units_cap", "tree_rows_cap"):
            v, s = plan_knobs.resolve_value(plan_knobs.BY_NAME[guard],
                                            {guard: 7})
            assert (v, s) == (HARDCODED_DEFAULTS[guard], "default")

    def test_seam_override_restores(self):
        before = streaming._Q_CHUNK
        with plan_pkg.seam_override("q_chunk", 3):
            assert streaming._Q_CHUNK == 3
            assert plan_knobs.resolve_value(
                plan_knobs.BY_NAME["q_chunk"], None) == (3, "seam")
        assert streaming._Q_CHUNK == before

    def test_bool_parsing(self, monkeypatch):
        monkeypatch.setenv("PIPELINEDP_TPU_INGEST_EXECUTOR", "off")
        v, s = plan_knobs.resolve_value(
            plan_knobs.BY_NAME["ingest_executor"], None)
        assert (v, s) == (False, "env")


class TestPlanFile:
    """Atomic persistence, fingerprint keying, stale rejection."""

    def test_round_trip_and_resolution(self, tmp_path, monkeypatch):
        d = tmp_path / "plan"
        monkeypatch.setenv(plan_planner.ENV_DIR, str(d))
        _write_plan_file(d, {"subhist_byte_cap": 12345678,
                             "ingest_executor": 0})
        resolved = plan_pkg.resolve(emit=True)
        assert resolved.values["subhist_byte_cap"] == 12345678
        assert resolved.sources["subhist_byte_cap"] == "plan"
        assert resolved.values["ingest_executor"] is False
        assert resolved.plan_source == "autotuned"
        assert resolved.plan_hash
        # plan.applied events carry (knob, value, source).
        applied = {e["knob"]: e for e in _events("plan.applied")}
        assert applied["subhist_byte_cap"]["source"] == "plan"
        assert applied["subhist_byte_cap"]["value"] == 12345678
        assert applied["stream_chunk_rows"]["source"] == "default"
        # ... and the run report grows the schema-v4 plan section.
        report = obs.build_run_report()
        assert report["schema_version"] == 6
        assert report["plan"]["knobs"]["subhist_byte_cap"] == {
            "value": 12345678, "source": "plan"}
        assert report["plan"]["plan_hash"] == resolved.plan_hash

    def test_stale_fingerprint_ignored_with_event(self, tmp_path,
                                                  monkeypatch):
        d = tmp_path / "plan"
        monkeypatch.setenv(plan_planner.ENV_DIR, str(d))
        _write_plan_file(d, {"subhist_byte_cap": 999},
                         fingerprint="deadbeefdeadbeef")
        resolved = plan_pkg.resolve()
        assert resolved.values == {
            k: v for k, v in HARDCODED_DEFAULTS.items()}
        assert resolved.plan_hash is None
        ev = _events("plan.stale")
        assert ev and ev[-1]["plan_fingerprint"] == "deadbeefdeadbeef"

    def test_stale_event_emitted_once_per_observation(self, tmp_path,
                                                      monkeypatch):
        # load_plan runs on EVERY knob read; a stale plan must not
        # flood the bounded obs event ring with one plan.stale per
        # read. A rewrite of the file is a new observation.
        d = tmp_path / "plan"
        monkeypatch.setenv(plan_planner.ENV_DIR, str(d))
        _write_plan_file(d, {"subhist_byte_cap": 999},
                         fingerprint="deadbeefdeadbeef")
        for _ in range(4):
            plan_pkg.resolve(emit=False)
            plan_pkg.knob_value("subhist_byte_cap")
        assert len(_events("plan.stale")) == 1
        _write_plan_file(d, {"subhist_byte_cap": 998},
                         fingerprint="feedfacefeedface")
        plan_pkg.resolve(emit=False)
        assert len(_events("plan.stale")) == 2

    def test_single_batch_request_resolves_plan(self, tmp_path,
                                                monkeypatch):
        # Non-streamed requests never reach streaming's resolve; the
        # single-batch path must resolve too, so its plan.applied
        # events and run-report plan section exist and mid-request
        # knob reads bucket at THIS request's shape, not a stale one.
        d = tmp_path / "plan"
        monkeypatch.setenv(plan_planner.ENV_DIR, str(d))
        _write_plan_file(d, {"stream_cache_bytes": 0})
        ds = _dataset(n=2_000, parts=4)
        acc = pdp.NaiveBudgetAccountant(total_epsilon=BIG_EPS,
                                        total_delta=1e-2)
        engine = pdp.DPEngine(acc, JaxBackend(rng_seed=7))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=2,
            max_contributions_per_partition=3)
        res = engine.aggregate(ds, params, pdp.DataExtractors())
        acc.compute_budgets()
        dict(res)
        assert "stream_batches" not in res.timings  # single batch
        applied = [e for e in _events("plan.applied")
                   if e["source"] == "plan"]
        assert applied, "single-batch request resolved no plan"
        assert plan_planner.last_resolved_shape() == {
            "rows": 2_000, "partitions": 4, "quantiles": 0}

    def test_disabled_dir_loads_nothing(self, tmp_path, monkeypatch):
        monkeypatch.setenv(plan_planner.ENV_DIR, "0")
        assert plan_planner.plan_dir() is None
        assert plan_planner.load_plan() is None

    def test_atomic_replace_no_torn_read(self, tmp_path, monkeypatch):
        d = tmp_path / "plan"
        monkeypatch.setenv(plan_planner.ENV_DIR, str(d))
        _write_plan_file(d, {"subhist_byte_cap": 1})
        _write_plan_file(d, {"subhist_byte_cap": 2})
        plan = plan_planner.load_plan()
        assert plan["knobs"]["default"]["subhist_byte_cap"] == 2
        # Only the one file: tmp files never survive the replace.
        assert os.listdir(d) == [plan_planner.PLAN_FILENAME]

    def test_corrupt_plan_file_resolves_defaults(self, tmp_path,
                                                 monkeypatch):
        d = tmp_path / "plan"
        d.mkdir()
        (d / plan_planner.PLAN_FILENAME).write_text("{torn", "utf-8")
        monkeypatch.setenv(plan_planner.ENV_DIR, str(d))
        resolved = plan_pkg.resolve()
        assert resolved.values == HARDCODED_DEFAULTS
        assert resolved.plan_source == "default"

    def test_plan_hash_keys_on_knobs_only(self):
        # A re-autotune that lands on the SAME knob vector must keep
        # the same identity: the write timestamp and the re-fit model
        # blob change every sweep, and hashing them would trip the
        # --compare plan-mismatch refusal forever after the first
        # rewrite.
        base = {"schema_version": plan_planner.PLAN_SCHEMA,
                "fingerprint": "f" * 16, "device_kind": "cpu",
                "created_by": "test", "ts": 1.0, "trials": 5,
                "knobs": {"default": {"q_chunk": 2}},
                "model": plan_model.CostModel().to_dict()}
        rewrite = dict(base, ts=999.0, trials=7,
                       model={"schema": 1, "tables": {"x": [1, 2]}})
        assert plan_planner.plan_hash(base) == (
            plan_planner.plan_hash(rewrite))
        moved = dict(base, knobs={"default": {"q_chunk": 4}})
        assert plan_planner.plan_hash(moved) != (
            plan_planner.plan_hash(base))

    def test_mid_request_knob_read_uses_resolved_shape_bucket(
            self, tmp_path, monkeypatch):
        # The walk resolves subhist_byte_cap shape-blind at jit-trace
        # time (plan.knob_value with no shape argument); it must
        # bucket against the vector the REQUEST resolved, not
        # whichever vector the 'default' bucket happens to carry.
        d = tmp_path / "plan"
        monkeypatch.setenv(plan_planner.ENV_DIR, str(d))
        bucket = plan_model.bucket_key(1_000, 10, 3)
        plan = {"schema_version": plan_planner.PLAN_SCHEMA,
                "fingerprint": plan_planner.fingerprint(),
                "device_kind": "cpu", "created_by": "test",
                "trials": 1,
                "knobs": {bucket: {"subhist_byte_cap": 111 << 20},
                          "default": {"subhist_byte_cap": 222 << 20}},
                "model": plan_model.CostModel().to_dict()}
        plan_planner.write_plan(plan, str(d))
        resolved = plan_pkg.resolve(
            shape={"rows": 1_000, "partitions": 10, "quantiles": 3})
        assert resolved.values["subhist_byte_cap"] == 111 << 20
        # The shape-blind read now follows the request's bucket...
        assert plan_pkg.knob_value("subhist_byte_cap") == 111 << 20
        # ...and falls back to the default bucket with no resolution
        # in force.
        plan_planner.reset()
        assert plan_pkg.knob_value("subhist_byte_cap") == 222 << 20


class TestPoisonedHistory:
    """Cold start and bad ledgers must leave the defaults in force."""

    FP = "aaaaaaaaaaaaaaaa"

    def _entry(self, name, payload, degraded=False, fp=None):
        return {"schema_version": 4, "name": name, "degraded": degraded,
                "fingerprint": fp or self.FP, "ts": 0.0,
                "payload": payload}

    def _trial(self, total_s, degraded=False, fp=None, rows=1000):
        return self._entry("autotune.trial", {"trial": {
            "knobs": {"subhist_byte_cap": 1}, "total_s": total_s,
            "shape": {"rows": rows, "partitions": 8, "quantiles": 3},
            "device_kind": "cpu",
            "phases": {"pass_a": total_s}}}, degraded, fp)

    def test_empty_ledger_fits_empty_model(self):
        model = plan_model.fit([], fingerprint=self.FP)
        assert model.samples == 0
        assert model.predict_seconds("cpu", "pass_a", 1000) is None
        assert plan_model.choose_best_trial([], self.FP) is None

    def test_degraded_only_entries_are_ignored(self):
        entries = [self._trial(1.0, degraded=True) for _ in range(4)]
        model = plan_model.fit(entries, fingerprint=self.FP)
        assert model.samples == 0
        assert plan_model.choose_best_trial(entries, self.FP) is None

    def test_mixed_fingerprints_do_not_cross_pollute(self):
        entries = [self._trial(1.0, fp="bbbbbbbbbbbbbbbb"),
                   self._trial(2.0)]
        model = plan_model.fit(entries, fingerprint=self.FP)
        assert model.samples == 1  # only the matching-fingerprint row
        best = plan_model.choose_best_trial(entries, self.FP)
        assert best[plan_model.bucket_key(1000, 8, 3)]["total_s"] == 2.0

    def test_poisoned_history_resolves_hardcoded_defaults(self):
        # No plan file was (or could be) written from the histories
        # above — resolution must be the identity on the defaults.
        resolved = plan_pkg.resolve()
        assert resolved.values == HARDCODED_DEFAULTS
        assert set(resolved.sources.values()) == {"default"}


class TestCostModel:
    """Fit/predict/serialize + the static roofline fallback."""

    def test_run_report_fits_request_shape_and_hbm_peak(self):
        # Report-derived samples must bucket at the REQUEST's shape
        # (the v4 plan section) so predictions hit the cell directly,
        # and the observatory's program memory stats must feed
        # predict_hbm_peak — not stay permanently None.
        rr = {"schema_version": 4,
              "env": {"device_kind": "cpu"},
              "counters": {"ingest.rows_ingested": 4096},
              "spans": {"ingest.pass_a": {"total_s": 2.0},
                        "ingest.pass_b_sweep": {"total_s": 1.0}},
              "plan": {"shape": {"rows": 4096, "partitions": 32,
                                 "quantiles": 3}},
              "device_costs": {"programs": {
                  "k1": {"phase": "pass_a",
                         "memory": {"peak_bytes": 5_000_000}},
                  "k2": {"phase": "pass_b",
                         "memory": {"peak_bytes": 9_000_000}},
                  "k3": {"phase": "pass_b",
                         "memory": {"peak_bytes": 7_000_000}}}}}
        entry = {"schema_version": 4, "name": "run_report",
                 "degraded": False, "fingerprint": "f", "ts": 0.0,
                 "payload": {"run_report": rr}}
        model = plan_model.fit([entry], fingerprint="f")
        bucket = plan_model.bucket_key(4096, 32, 3)
        assert ("cpu", "pass_a", bucket) in model.cells
        assert model.predict_seconds(
            "cpu", "pass_a", 4096, 32, 3) == pytest.approx(2.0)
        assert model.predict_hbm_peak(
            "cpu", "pass_b", 4096, 32, 3) == 9_000_000

    def test_least_squares_prediction(self):
        entries = []
        for rows, secs in ((1000, 1.0), (2000, 2.0), (4000, 4.0)):
            entries.append({
                "schema_version": 4, "name": "autotune.trial",
                "degraded": False, "fingerprint": "f", "ts": 0.0,
                "payload": {"trial": {
                    "knobs": {"q_chunk": 0}, "total_s": secs,
                    "shape": {"rows": rows, "partitions": 8,
                              "quantiles": 3},
                    "device_kind": "cpu",
                    "phases": {"pass_a": secs}}}})
        model = plan_model.fit(entries, fingerprint="f")
        # Same bucket (log2(rows) equal for 1000..1024? no — 1000 and
        # 2000 land in different buckets), so prediction goes through
        # the phase-wide pooled ratio: seconds/rows == 1e-3.
        pred = model.predict_seconds("cpu", "pass_a", 8000, 8, 3)
        assert pred == pytest.approx(8.0, rel=0.3)
        # Round trip through the plan-file serialization.
        again = plan_model.CostModel.from_dict(model.to_dict())
        assert again.predict_seconds("cpu", "pass_a", 8000, 8, 3) == (
            pytest.approx(pred))

    def test_roofline_fallback_uses_static_peaks(self):
        model = plan_model.CostModel()
        model.bytes_per_unit[("cpu", "pass_a")] = 16.0
        # cpu proxy peak bandwidth is 5e10 B/s (obs.costs.DEVICE_PEAKS)
        floor = model.roofline_floor("cpu", "pass_a", 1_000_000)
        assert floor == pytest.approx(16.0 * 1_000_000 / 5e10)
        assert model.predict_seconds("cpu", "pass_a",
                                     1_000_000) == pytest.approx(floor)
        # Unknown device kind: an honest None, never a made-up floor.
        assert model.roofline_floor("quantum9", "pass_a", 10) is None


class TestQChunkPin:
    """The planner's q_chunk knob constrains the pass-B tiling."""

    def test_pin_constrains_tiling(self):
        _, _, _, span = streaming._tree_consts()
        plan = streaming.plan_pass_b_sweeps(1 << 10, 4, span,
                                            600 << 20, q_chunk=1)
        assert plan.q_chunk == 1
        assert all(qn == 1 for _, qn, _ in plan.tiles)
        # Unpinned, the same under-budget shape is one full-grid tile.
        free = streaming.plan_pass_b_sweeps(1 << 10, 4, span, 600 << 20)
        assert free.n_tiles == 1 and free.q_chunk == 4

    def test_infeasible_pin_falls_back_to_search(self):
        _, _, _, span = streaming._tree_consts()
        unit = span * 4
        # Budget of 2 blocks: qc=3 fits no partition block -> fallback.
        pinned = streaming.plan_pass_b_sweeps(8, 4, span, 2 * unit,
                                              q_chunk=3)
        free = streaming.plan_pass_b_sweeps(8, 4, span, 2 * unit)
        assert pinned == free
        assert _events("plan.q_chunk_infeasible")


def _pct_params():
    return pdp.AggregateParams(
        metrics=[pdp.Metrics.PERCENTILE(p) for p in (25, 50, 75, 95)] +
        [pdp.Metrics.COUNT],
        noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=5,
        max_contributions_per_partition=50,
        min_value=0.0, max_value=20.0)


def _dataset(seed=88, n=6_000, parts=5):
    rng = np.random.default_rng(seed)
    return pdp.ArrayDataset(privacy_ids=rng.integers(0, 1_500, n),
                            partition_keys=rng.integers(0, parts, n),
                            values=rng.uniform(0.0, 20.0, n))


def _run_streamed(ds, params, monkeypatch, chunk=997):
    monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", str(chunk))
    ds.invalidate_cache()
    acc = pdp.NaiveBudgetAccountant(total_epsilon=BIG_EPS,
                                    total_delta=1e-2)
    engine = pdp.DPEngine(acc, JaxBackend(rng_seed=7))
    res = engine.aggregate(ds, params, pdp.DataExtractors())
    acc.compute_budgets()
    got = dict(res)
    assert res.timings["stream_batches"] > 1
    return got


class TestParityPlannerOnOff:
    """PARITY row 32: a plan file moving EVERY dp-safe knob produces
    bit-identical DP outputs to the no-plan defaults — plans only
    select among already-parity-tested execution paths (multi-tile =
    per-tile = unchunked; hybrid = device_cache = reship; overlapped =
    serial)."""

    def test_planner_on_off_outputs_bit_identical(self, tmp_path,
                                                  monkeypatch):
        _, _, _, span = streaming._tree_consts()
        ds, params = _dataset(), _pct_params()
        off = _run_streamed(ds, params, monkeypatch)
        d = tmp_path / "plan"
        monkeypatch.setenv(plan_planner.ENV_DIR, str(d))
        # Move every dp-safe knob off its default: shrunken subhist
        # cap (forces the multi-tile sweep), pinned q_chunk, serial
        # executor, cache off.
        _write_plan_file(d, {"subhist_byte_cap": 5 * span * 4,
                             "q_chunk": 1,
                             "ingest_executor": 0,
                             "stream_cache_bytes": 0})
        on = _run_streamed(ds, params, monkeypatch)
        assert set(on) == set(off)
        applied = {e["knob"]: e["source"]
                   for e in _events("plan.applied")}
        assert applied["subhist_byte_cap"] == "plan"
        assert applied["q_chunk"] == "plan"
        fields = [f for f in off[next(iter(off))]._fields
                  if f.startswith("percentile_") or f == "count"]
        for pk in off:
            for f in fields:
                assert getattr(on[pk], f) == getattr(off[pk], f), (
                    f"planner on/off diverged at {pk}.{f}")


class TestSinceRunId:
    """Run-windowed ledger reads: the autotune fitter's linearity."""

    def _store(self, tmp_path):
        s = obs_store.LedgerStore(str(tmp_path / "ledger"))
        env = {"device_kind": "cpu"}
        s.append("m", {"record": {"value": 1}}, env=env, run_id="r1")
        s.append("m", {"record": {"value": 2}}, env=env, run_id="r2")
        s.append("m", {"record": {"value": 3}}, env=env, run_id="r2")
        return s

    def test_window_module_helper(self, tmp_path):
        s = self._store(tmp_path)
        entries = s.entries()
        win = obs_store.entries_since_run_id(entries, "r2")
        assert [e["payload"]["record"]["value"] for e in win] == [2, 3]
        assert obs_store.entries_since_run_id(entries, "nope") == []

    def test_read_from_is_incremental(self, tmp_path):
        s = self._store(tmp_path)
        first, offset = s.read_from(0)
        assert len(first) == 3
        env = {"device_kind": "cpu"}
        s.append("m", {"record": {"value": 4}}, env=env, run_id="r3")
        tail, end = s.read_from(offset)
        assert [e["payload"]["record"]["value"] for e in tail] == [4]
        assert end > offset
        assert s.read_from(end)[0] == []

    def test_cli_since_run_id(self, tmp_path, capsys):
        s = self._store(tmp_path)
        rc = obs_store.main(["--summarize", "--dir", s.directory,
                             "--since-run-id", "r2", "--json"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["entries"] == 2


def _import_bench(monkeypatch):
    monkeypatch.syspath_prepend(REPO)
    import bench
    return bench


class TestBenchPlanProvenance:
    """Bench records carry the plan identity; --compare refuses to
    gate across plan changes."""

    def _one_rate(self, bench, name="plan_rate"):
        ds = bench.zipf_dataset(8_000, 1_000, 50, seed=3)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM,
                     pdp.Metrics.MEAN],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=4,
            max_contributions_per_partition=2,
            min_value=0.0, max_value=10.0)
        return bench.bench_config(name, params, ds, 4_000, repeats=1)

    def test_records_and_compare_mismatch(self, tmp_path, monkeypatch):
        monkeypatch.setenv(obs_store.ENV_VAR, str(tmp_path / "ledger"))
        bench = _import_bench(monkeypatch)
        # Run 1: default knobs -> baseline.
        bench.reset_run_state()
        rec1 = self._one_rate(bench)
        assert rec1["plan_source"] == "default"
        assert rec1["plan_hash"] is None
        bench.record_run_report()
        # Run 2: a plan file is in force -> provenance changes, and
        # --compare must refuse the gate instead of crying regression.
        bench.reset_run_state()
        d = tmp_path / "plan"
        monkeypatch.setenv(plan_planner.ENV_DIR, str(d))
        plan = _write_plan_file(d, {"stream_cache_bytes": 0})
        rec2 = self._one_rate(bench)
        assert rec2["plan_source"] == "autotuned"
        assert rec2["plan_hash"] == plan_planner.plan_hash(plan)
        regressions = bench.compare_to_baseline()
        assert regressions["plan_mismatches"] == 1
        assert regressions["regressed"] == []
        entry = [r for r in regressions["rates"]
                 if r["metric"] == "plan_rate"][0]
        assert entry["plan_mismatch"] is True
        assert entry["baseline_plan"]["plan_source"] == "default"
        line = bench.compare_verdict_line(regressions)
        assert line.startswith("COMPARE: plan mismatch")

    def test_provenance_snapshot_ignores_bench_internal_env(
            self, tmp_path, monkeypatch):
        # Bench's own records inject measurement scaffolding (the
        # streamed record's chunk env, the capped probes' seams) AFTER
        # the provenance snapshot; a plain default-knob run must stay
        # labeled 'default', not 'env-override'.
        monkeypatch.setenv(obs_store.ENV_VAR, str(tmp_path / "ledger"))
        bench = _import_bench(monkeypatch)
        bench.reset_run_state()
        assert bench.plan_provenance()["plan_source"] == "default"
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "997")
        rec = self._one_rate(bench)
        assert rec["plan_source"] == "default"
        # A fresh run that LAUNCHES under the override is labeled so.
        bench.reset_run_state()
        rec2 = self._one_rate(bench)
        assert rec2["plan_source"] == "env-override"
        # ...and --compare refuses to gate the env-override run
        # against the default-knob baseline (both plan hashes are
        # None, so the SOURCE label is the only tell).
        regressions = bench.compare_to_baseline()
        assert regressions["plan_mismatches"] >= 1
        assert regressions["regressed"] == []
        entry = [r for r in regressions["rates"]
                 if r["metric"] == "plan_rate"][0]
        assert entry["plan_mismatch"] is True
        assert entry["baseline_plan"]["plan_source"] == "default"

    def test_matching_plans_still_gate(self, tmp_path, monkeypatch):
        monkeypatch.setenv(obs_store.ENV_VAR, str(tmp_path / "ledger"))
        d = tmp_path / "plan"
        monkeypatch.setenv(plan_planner.ENV_DIR, str(d))
        _write_plan_file(d, {"stream_cache_bytes": 0})
        bench = _import_bench(monkeypatch)
        bench.reset_run_state()
        self._one_rate(bench)
        bench.reset_run_state()
        self._one_rate(bench)
        regressions = bench.compare_to_baseline()
        assert regressions["plan_mismatches"] == 0
        entry = [r for r in regressions["rates"]
                 if r["metric"] == "plan_rate"][0]
        assert entry["baseline"] is not None


class TestAutotuneAcceptance:
    """The measure→decide→apply loop, in process: ``--autotune``
    writes a plan file; a subsequent plain streamed run loads it,
    witnessed by ``plan.applied`` events with ``source: "plan"``."""

    def test_autotune_writes_plan_and_next_run_loads_it(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv(obs_store.ENV_VAR, str(tmp_path / "ledger"))
        monkeypatch.setenv(plan_planner.ENV_DIR, str(tmp_path / "plan"))
        bench = _import_bench(monkeypatch)
        bench.reset_run_state()
        args = argparse.Namespace(rows=3_000, smoke=True)
        rc = bench.run_autotune(args)
        assert rc == 0
        path = plan_planner.plan_path(str(tmp_path / "plan"))
        assert os.path.exists(path)
        with open(path, encoding="utf-8") as f:
            plan = json.load(f)
        assert plan["fingerprint"] == plan_planner.fingerprint()
        assert plan["knobs"]["default"]
        # Trials landed in the ledger for future (windowed) fits.
        store = obs_store.LedgerStore(str(tmp_path / "ledger"))
        trials = [e for e in store.entries()
                  if e["name"] == "autotune.trial"]
        assert len(trials) == len(plan_pkg.autotune_candidates())
        # The follow-up plain run resolves the plan (source: "plan").
        obs.reset()
        _run_streamed(_dataset(n=3_000, parts=50), _pct_params(),
                      monkeypatch)
        applied = [e for e in _events("plan.applied")
                   if e["source"] == "plan"]
        assert applied, "plain run after --autotune resolved no plan"

    def test_sweep_trials_never_steered_by_preexisting_plan(
            self, tmp_path, monkeypatch):
        # A prior autotune's plan file must not steer this sweep's
        # trials: a seam pinned AT the registry default falls through
        # the precedence, so without isolation the plan would silently
        # win while the ledger labels the trial with its own knobs.
        monkeypatch.setenv(obs_store.ENV_VAR, str(tmp_path / "ledger"))
        d = tmp_path / "plan"
        monkeypatch.setenv(plan_planner.ENV_DIR, str(d))
        _write_plan_file(d, {"q_chunk": 1, "subhist_byte_cap": 1 << 20,
                             "ingest_executor": 0})
        bench = _import_bench(monkeypatch)
        bench.reset_run_state()
        obs.reset()
        rc = bench.run_autotune(
            argparse.Namespace(rows=3_000, smoke=True))
        assert rc == 0
        steered = [e for e in _events("plan.applied")
                   if e["source"] == "plan"]
        assert steered == [], (
            "autotune trials resolved the pre-existing plan file")
        # The sweep still wrote a fresh plan over the old one.
        with open(plan_planner.plan_path(str(d)),
                  encoding="utf-8") as f:
            plan = json.load(f)
        assert plan["created_by"] == "bench --autotune"
        # ...and the plan-dir env survived for the follow-up run.
        assert os.environ[plan_planner.ENV_DIR] == str(d)


class TestNoDirectKnobReads:
    """AST-precise twin of ``make noknobs``: the registered knob
    constants may be READ only inside ``pipelinedp_tpu/plan/`` (the
    registry's seam layer); the defining modules keep the names as
    assignable test seams but must route their own reads through
    ``plan.knobs``. Tests and the seam-override context are exempt."""

    def test_knob_reads_only_under_plan(self):
        # Delegates to the shared AST engine (which owns the
        # KNOB_CONSTANTS/DEFINING tables); `make noknobs` is the
        # same rule.
        from pipelinedp_tpu import lint
        assert lint.check_tree("noknobs") == []

    def test_registry_knows_every_registered_knob_constant(self):
        """The rule's constant table must track the knob registry —
        a knob added to plan/ without a lint constant would silently
        escape the read ban."""
        from pipelinedp_tpu.lint.rules.confinement import NoKnobsRule
        assert NoKnobsRule.KNOB_CONSTANTS == {
            "_SUBHIST_BYTE_CAP", "_SELECT_UNITS_CAP",
            "_TREE_ROWS_CAP", "_Q_CHUNK"}
