"""Privacy audit record + durable run-ledger store + bench regression
gate (``make ledgercheck``).

Coverage contract:

* store semantics — fsync'd JSONL appends keyed by the environment
  fingerprint hash, schema v1→v2 reader tolerance, truncated-trailing-
  line recovery (reads skip the torn line; later appends re-establish
  line-start), concurrent appends from >= 3 threads with zero lost
  records, and ``last_known_good`` NEVER returning a degraded entry;
* directory resolution — ``PIPELINEDP_TPU_LEDGER_DIR`` wins, else a
  ``pdp_run_ledger`` sibling of the compile cache, else the caller's
  default;
* the privacy audit section — a real engine run populates schema-v2
  reports with every mechanism's metric label, (eps, delta) split and
  noise stddev, plus selection pre/post counts (the DP-output
  bit-parity of audit on vs off lives in ``tests/test_obs.py``,
  extending the trace on/off pattern);
* the acceptance flow — two in-process bench-config invocations: run 1
  appends schema-v2 reports to the store, run 2 ``--compare``s against
  them and emits a ``regressions`` section keyed to the same
  fingerprint; degraded captures are excluded from baselines with a
  ``bench.compare_skipped_degraded`` event on the record;
* lint twin — AST-precise ban on ``json.dump(`` artifact writes outside
  ``pipelinedp_tpu/obs/`` (``make noartifacts`` runs the grep twin).
"""

import ast
import json
import os
import sys
import threading

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import obs
from pipelinedp_tpu.backends import JaxBackend
from pipelinedp_tpu.obs import store as obs_store

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ENV_A = {"jax_version": "0.4", "platform": "cpu", "device_kind": "cpu",
         "device_count": 1, "process_count": 1, "git_sha": "aaa"}


@pytest.fixture(autouse=True)
def fresh_state(monkeypatch, tmp_path):
    """Fresh obs ledger/audit registry and an isolated store dir; the
    engine's traced appends (and bench's default) land in tmp."""
    monkeypatch.setenv(obs_store.ENV_VAR, str(tmp_path / "ledger"))
    monkeypatch.delenv(obs.ENV_VAR, raising=False)
    monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "997")
    obs.reset()
    yield
    obs.reset()


class TestStoreCore:
    """Append/read semantics of the JSONL store."""

    def test_append_read_round_trip_and_fingerprint(self, tmp_path):
        s = obs_store.LedgerStore(str(tmp_path / "s"))
        fp = obs_store.fingerprint_key(ENV_A)
        entry = s.append("m1", {"record": {"value": 100}}, env=ENV_A)
        assert entry["fingerprint"] == fp
        assert entry["schema_version"] == obs.SCHEMA_VERSION == 6
        got = s.entries()
        assert len(got) == 1
        assert got[0]["payload"]["record"]["value"] == 100
        # The key ignores volatile fields: flags and degraded must not
        # split baselines across runs of the same build.
        noisy = dict(ENV_A, degraded=True,
                     flags={"PIPELINEDP_TPU_TRACE": "1"})
        assert obs_store.fingerprint_key(noisy) == fp
        # ...but a code change (incl. -dirty) re-keys.
        assert obs_store.fingerprint_key(
            dict(ENV_A, git_sha="aaa-dirty")) != fp

    def test_v1_entry_tolerance(self, tmp_path):
        """A pre-privacy-section (schema v1) line — and one with no
        schema field at all — reads back with v1 defaults and still
        serves as a baseline."""
        s = obs_store.LedgerStore(str(tmp_path / "s"))
        fp = obs_store.fingerprint_key(ENV_A)
        with open(s.path, "w", encoding="utf-8") as f:
            f.write(json.dumps({"schema_version": 1, "name": "old",
                                "fingerprint": fp,
                                "payload": {"record": {"value": 7}}}) +
                    "\n")
            f.write(json.dumps({"name": "older", "fingerprint": fp,
                                "payload": {}}) + "\n")
        s.append("new", {"record": {"value": 9}}, env=ENV_A)
        entries = s.entries()
        assert [e["schema_version"] for e in entries] == [1, 1, 6]
        assert all(e["degraded"] is False for e in entries)
        lkg = s.last_known_good("old", fp)
        assert lkg is not None and (
            lkg["payload"]["record"]["value"] == 7)

    def test_truncated_trailing_line_recovery(self, tmp_path):
        """A crash mid-write leaves a torn tail: reads leave it
        UNCONSUMED (it may be an entry still being written — consuming
        it would split the entry across two incremental reads and drop
        it), the cursor stops before it, and the next append repairs
        it into a counted skip."""
        s = obs_store.LedgerStore(str(tmp_path / "s"))
        for v in (1, 2):
            s.append("m", {"record": {"value": v}}, env=ENV_A)
        clean_end = os.path.getsize(s.path)
        with open(s.path, "ab") as f:
            f.write(b'{"schema_version": 2, "name": "m", "payl')
        got, end = s.read_from(0)
        assert len(got) == 2
        assert s.skipped_lines == 0  # tail not consumed, not "corrupt"
        assert end == clean_end      # cursor stops BEFORE the tail
        s.append("m", {"record": {"value": 3}}, env=ENV_A)
        entries, end2 = s.read_from(end)
        assert [e["payload"]["record"]["value"] for e in entries] == [3]
        assert s.skipped_lines == 1  # repaired torn line now skips
        assert end2 == os.path.getsize(s.path)

    def test_concurrent_appends_lose_nothing(self, tmp_path):
        """>= 3 threads appending concurrently: every record lands,
        every line parses."""
        s = obs_store.LedgerStore(str(tmp_path / "s"))
        n_threads, per_thread = 4, 40
        errors = []

        def writer(i):
            try:
                for j in range(per_thread):
                    s.append(f"t{i}", {"record": {"j": j}}, env=ENV_A)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(i,))
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        entries = s.entries()
        assert s.skipped_lines == 0
        assert len(entries) == n_threads * per_thread
        for i in range(n_threads):
            js = sorted(e["payload"]["record"]["j"] for e in entries
                        if e["name"] == f"t{i}")
            assert js == list(range(per_thread))

    def test_last_known_good_never_degraded(self, tmp_path):
        """The wedged-run-masquerade guard: a degraded capture is never
        a baseline, even when it is the newest entry."""
        s = obs_store.LedgerStore(str(tmp_path / "s"))
        fp = obs_store.fingerprint_key(ENV_A)
        s.append("m", {"record": {"value": 100}}, env=ENV_A)
        s.append("m", {"record": {"value": 5}}, env=ENV_A,
                 degraded=True)
        assert s.latest("m", fp)["degraded"] is True
        lkg = s.last_known_good("m", fp)
        assert lkg["payload"]["record"]["value"] == 100
        assert s.last_known_good_map(fp)["m"] is not None
        # All-degraded history: no baseline at all, rather than a bad one.
        s2 = obs_store.LedgerStore(str(tmp_path / "s2"))
        s2.append("m", {"record": {"value": 5}}, env=ENV_A,
                  degraded=True)
        assert s2.last_known_good("m", fp) is None


class TestLedgerDirResolution:
    def test_env_var_wins(self, monkeypatch, tmp_path):
        monkeypatch.setenv(obs_store.ENV_VAR, str(tmp_path / "explicit"))
        monkeypatch.setenv("PIPELINEDP_TPU_COMPILE_CACHE",
                           str(tmp_path / "cc"))
        assert obs_store.ledger_dir() == str(tmp_path / "explicit")

    def test_compile_cache_sibling_default(self, monkeypatch, tmp_path):
        monkeypatch.delenv(obs_store.ENV_VAR, raising=False)
        monkeypatch.setenv("PIPELINEDP_TPU_COMPILE_CACHE",
                           str(tmp_path / "cc"))
        assert obs_store.ledger_dir() == str(tmp_path / "pdp_run_ledger")

    def test_unset_returns_callers_default(self, monkeypatch):
        monkeypatch.delenv(obs_store.ENV_VAR, raising=False)
        monkeypatch.delenv("PIPELINEDP_TPU_COMPILE_CACHE", raising=False)
        assert obs_store.ledger_dir() is None
        assert obs_store.ledger_dir(default="/x") == "/x"


class TestReportCursorPerDirectory:
    """Regression for the resident multi-tenant service: the per-
    request delta cursor behind ``maybe_append_run_report`` must key
    by resolved directory — a process-wide cursor lets tenant A's
    append swallow the audit records tenant B's ledger never saw."""

    @staticmethod
    def _accountant_ids(entry):
        priv = entry["payload"]["run_report"]["privacy"]
        return [a["books"]["request_id"] for a in priv["accountants"]]

    def _push(self, request_id):
        from pipelinedp_tpu.obs import audit as obs_audit
        with obs_audit.books_context("t", request_id):
            obs_audit.record_accountant({
                "accountant": "NaiveBudgetAccountant",
                "total_epsilon": 1.0, "total_delta": 0.0,
                "finalized": True, "mechanisms": []})

    def test_interleaved_directories_each_get_complete_deltas(
            self, monkeypatch, tmp_path):
        monkeypatch.delenv(obs_store.ENV_VAR, raising=False)
        obs.reset()
        dir_a, dir_b = str(tmp_path / "a"), str(tmp_path / "b")
        self._push("r1")
        assert obs_store.maybe_append_run_report(
            "serve.request", directory=dir_a) is not None
        self._push("r2")
        # Directory B starts its own cursor: its first entry carries
        # BOTH records — r1 was never persisted to B's books.
        entry_b = obs_store.maybe_append_run_report(
            "serve.request", directory=dir_b)
        assert self._accountant_ids(entry_b) == ["r1", "r2"]
        # Directory A's next entry carries ONLY the new record.
        entry_a = obs_store.maybe_append_run_report(
            "serve.request", directory=dir_a)
        assert self._accountant_ids(entry_a) == ["r2"]
        # On-disk stores agree entry for entry.
        a_entries = obs_store.LedgerStore(dir_a).entries()
        assert [self._accountant_ids(e) for e in a_entries] == [
            ["r1"], ["r2"]]

    def test_directory_param_overrides_env(self, monkeypatch, tmp_path):
        monkeypatch.setenv(obs_store.ENV_VAR, str(tmp_path / "env_dir"))
        obs.reset()
        self._push("r1")
        pinned = str(tmp_path / "pinned")
        entry = obs_store.maybe_append_run_report("serve.request",
                                                  directory=pinned)
        assert entry is not None
        assert obs_store.LedgerStore(pinned).entries()
        assert not os.path.exists(
            os.path.join(str(tmp_path / "env_dir"),
                         obs_store.LEDGER_FILENAME))


def run_engine(seed=0, eps=1.0, n=6_000, parts=10):
    rng = np.random.default_rng(5)
    ds = pdp.ArrayDataset(privacy_ids=rng.integers(0, 1_500, n),
                          partition_keys=rng.integers(0, parts, n),
                          values=rng.uniform(0.0, 10.0, n))
    acc = pdp.NaiveBudgetAccountant(total_epsilon=eps, total_delta=1e-6)
    engine = pdp.DPEngine(acc, JaxBackend(rng_seed=seed))
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM, pdp.Metrics.MEAN],
        noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=4, max_contributions_per_partition=2,
        min_value=0.0, max_value=10.0)
    res = engine.aggregate(ds, params, pdp.DataExtractors())
    acc.compute_budgets()
    return dict(res), engine


class TestAuditSection:
    """Schema-v2 ``privacy`` section contents after a real run."""

    def test_every_mechanism_carries_eps_delta_and_stddev(self):
        run_engine()
        priv = obs.build_run_report()["privacy"]
        assert priv["accountants"], "compute_budgets did not record"
        acct = priv["accountants"][0]
        assert acct["accountant"] == "NaiveBudgetAccountant"
        assert acct["total_epsilon"] == 1.0 and acct["finalized"]
        by_metric = {m["metric"]: m for m in acct["mechanisms"]}
        assert {"mean", "partition_selection"} <= set(by_metric)
        mean = by_metric["mean"]
        assert mean["mechanism_type"] == "Laplace"
        assert mean["eps"] > 0 and mean["delta"] == 0.0
        assert mean["internal_splits"] == 2
        # Laplace unit-sensitivity calibration of the eps/k sub-split.
        assert mean["noise_standard_deviation"] == pytest.approx(
            np.sqrt(2.0) * 2 / mean["eps"])
        sel = by_metric["partition_selection"]
        assert sel["mechanism_type"] == "Generic"
        assert sel["eps"] > 0 and sel["delta"] > 0
        assert sel["noise_standard_deviation"] is None

    def test_pld_accountant_publishes_granted_stddev(self):
        acc = pdp.PLDBudgetAccountant(total_epsilon=1.0, total_delta=1e-6)
        spec = acc.request_budget(
            pdp.aggregate_params.MechanismType.GAUSSIAN, metric="count")
        acc.compute_budgets()
        priv = obs.build_run_report()["privacy"]
        rec = priv["accountants"][-1]
        m = rec["mechanisms"][0]
        assert m["metric"] == "count"
        # The audit carries the PLD-granted stddev verbatim.
        assert m["noise_standard_deviation"] == pytest.approx(
            spec.noise_standard_deviation)

    def test_selection_counts_and_expected_errors(self):
        out, _ = run_engine(eps=1e6)
        priv = obs.build_run_report()["privacy"]
        sel = priv["partition_selection"]
        assert sel["strategies"] == ["Truncated Geometric"]
        assert sel["partitions_pre"] == 10
        assert sel["partitions_post"] == len(out)
        errs = {e["metric"]: e for e in priv["expected_errors"]}
        assert {"count", "mean", "sum"} <= set(errs)
        count = errs["count"]
        assert count["noise_stddev"] > 0
        assert count["aggregate_scale"] > 0
        assert count["expected_relative_error"] == pytest.approx(
            count["noise_stddev"] / count["aggregate_scale"])

    def test_structured_stages_keep_string_view(self):
        _, engine = run_engine()
        text = engine.explain_computations_report()[0]
        structured = engine.explain_computations_structured()[0]
        assert structured["method"] == "aggregate"
        assert structured["stages"], "no stages recorded"
        for stage in structured["stages"]:
            # The string view renders the same evaluated text with its
            # 1-based stage number.
            assert f" {stage['stage']}. {stage['text']}" in text

    def test_traced_run_appends_versioned_report(self, monkeypatch,
                                                 tmp_path):
        monkeypatch.setenv(obs.ENV_VAR, "1")
        run_engine()
        s = obs_store.LedgerStore(obs_store.ledger_dir())
        entries = [e for e in s.entries()
                   if e["name"] == "engine.aggregate"]
        assert entries, "traced engine run did not append to the store"
        report = entries[-1]["payload"]["run_report"]
        assert report["schema_version"] == 6
        mechs = report["privacy"]["accountants"][0]["mechanisms"]
        assert all("eps" in m and "delta" in m and
                   "noise_standard_deviation" in m for m in mechs)

    def test_traced_appends_are_per_request_deltas(self, monkeypatch):
        """Entry k carries ONLY request k's audit records — a traced
        process running N aggregations must not grow the ledger
        quadratically by re-appending requests 1..k-1 each time."""
        monkeypatch.setenv(obs.ENV_VAR, "1")
        run_engine(seed=0)
        run_engine(seed=1)
        s = obs_store.LedgerStore(obs_store.ledger_dir())
        entries = [e for e in s.entries()
                   if e["name"] == "engine.aggregate"]
        assert len(entries) == 2
        for e in entries:
            priv = e["payload"]["run_report"]["privacy"]
            assert len(priv["accountants"]) == 1
        # Cumulative views (counters) stay whole; record lists do not.
        ev0 = entries[0]["payload"]["run_report"]["events"]
        ev1 = entries[1]["payload"]["run_report"]["events"]
        assert not (ev0 and ev0[0] in ev1)

    def test_untraced_run_appends_nothing(self):
        run_engine()
        s = obs_store.LedgerStore(obs_store.ledger_dir())
        assert s.entries() == []


def _import_bench(monkeypatch):
    monkeypatch.syspath_prepend(REPO)
    import bench
    return bench


def bench_one_run(bench, name="t_rate", seed=3):
    ds = bench.zipf_dataset(8_000, 1_000, 50, seed=seed)
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM, pdp.Metrics.MEAN],
        noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=4, max_contributions_per_partition=2,
        min_value=0.0, max_value=10.0)
    rec = bench.bench_config(name, params, ds, 4_000, repeats=1)
    report = bench.record_run_report()
    return rec, report


class TestBenchCompareAcceptance:
    """The ISSUE acceptance flow, in process: a traced bench-config run
    appends schema-versioned reports to the ledger store; a second run with
    --compare reads them back and emits a ``regressions`` section keyed
    to the same fingerprint."""

    def test_two_runs_compare(self, monkeypatch):
        monkeypatch.setenv(obs.ENV_VAR, "1")
        bench = _import_bench(monkeypatch)
        # Run 1: records + run report land in the store.
        bench.reset_run_state()
        rec1, rep1 = bench_one_run(bench)
        assert rep1["schema_version"] == 6
        mechs = rep1["privacy"]["accountants"][0]["mechanisms"]
        assert mechs and all(
            "eps" in m and "delta" in m and
            "noise_standard_deviation" in m for m in mechs)
        store = obs_store.LedgerStore(obs_store.ledger_dir())
        names = {e["name"] for e in store.entries()}
        assert {"t_rate", "run_report"} <= names
        fp = obs_store.fingerprint_key(bench.env_fingerprint())
        # Run 2: fresh run state, same store — compare against run 1.
        bench.reset_run_state()
        rec2, rep2 = bench_one_run(bench)
        regressions = bench.compare_to_baseline(run_report=rep2)
        assert regressions["fingerprint"] == fp
        rate = next(r for r in regressions["rates"]
                    if r["metric"] == "t_rate")
        assert rate["baseline"] == rec1["value"]
        assert rate["current"] == rec2["value"]
        assert rate["ratio"] == pytest.approx(
            rec2["value"] / rec1["value"], rel=1e-3)
        # Traced both runs: span totals diff too.
        assert regressions["spans"]
        assert {s["span"] for s in regressions["spans"]} & {
            "bench.aggregate", "engine.encode"}

    def test_regression_detected_and_degraded_skipped(self, monkeypatch):
        """A >10% rate drop lands in ``regressed`` (the --strict exit
        condition), and a NEWER degraded capture is skipped as baseline
        with a bench.compare_skipped_degraded event on the record."""
        bench = _import_bench(monkeypatch)
        bench.reset_run_state()
        env = bench.env_fingerprint()
        store = obs_store.LedgerStore(obs_store.ledger_dir())
        store.append("m", {"record": {"metric": "m", "value": 1000,
                                      "unit": "rows/s"}}, env=env)
        store.append("m", {"record": {"metric": "m", "value": 10,
                                      "unit": "rows/s"}}, env=env,
                     degraded=True)
        bench.reset_run_state()  # re-snapshot baselines incl. the above
        # Synthetic records carry an explicit plan_source: the ambient
        # chunk-env override the fixture sets would otherwise read as a
        # knob-regime change and refuse the gate (tested in test_plan).
        current = [{"metric": "m", "value": 500, "unit": "rows/s",
                    "plan_source": "default"}]
        regressions = bench.compare_to_baseline(records=current)
        # The degraded 10-rows/s capture neither became the baseline
        # (masking the regression) nor poisoned the ratio.
        assert regressions["skipped_degraded_baselines"] == 1
        rate = regressions["rates"][0]
        assert rate["baseline"] == 1000 and rate["regressed"] is True
        assert regressions["regressed"] == ["m"]
        events = [e for e in obs.ledger().snapshot()["events"]
                  if e["name"] == "bench.compare_skipped_degraded"]
        assert events and events[0]["metric"] == "m"
        # Within tolerance: no regression flagged.
        ok = bench.compare_to_baseline(
            records=[{"metric": "m", "value": 950, "unit": "rows/s",
                      "plan_source": "default"}])
        assert ok["regressed"] == []

    def test_baseline_is_best_sample_of_last_run(self, monkeypatch):
        """A run re-samples the flagship (slow-window guard) and emits
        the metric twice; the baseline must be that run's BEST sample —
        a slow re-sample stored last must not lower the bar."""
        bench = _import_bench(monkeypatch)
        bench.reset_run_state()
        env = bench.env_fingerprint()
        store = obs_store.LedgerStore(obs_store.ledger_dir())
        for v in (1000, 400):  # main pass, then slow-window re-sample
            store.append("m", {"record": {"metric": "m", "value": v,
                                          "unit": "rows/s"}}, env=env,
                         run_id="runA")
        bench.reset_run_state()
        reg = bench.compare_to_baseline(
            records=[{"metric": "m", "value": 500, "unit": "rows/s",
                      "plan_source": "default"}])
        rate = reg["rates"][0]
        assert rate["baseline"] == 1000
        assert reg["regressed"] == ["m"]

    def test_gate_failed_run_never_becomes_baseline(self, monkeypatch):
        """A run that failed the --strict gate marks itself
        (bench.gate_failed); its regressed numbers must not become the
        next run's baseline — the gate stays red until fixed."""
        bench = _import_bench(monkeypatch)
        bench.reset_run_state()
        env = bench.env_fingerprint()
        store = obs_store.LedgerStore(obs_store.ledger_dir())
        store.append("m", {"record": {"metric": "m", "value": 1000,
                                      "unit": "rows/s"}}, env=env,
                     run_id="good")
        store.append("m", {"record": {"metric": "m", "value": 500,
                                      "unit": "rows/s"}}, env=env,
                     run_id="bad")
        store.append("bench.gate_failed", {"regressed": ["m"]}, env=env,
                     run_id="bad")
        bench.reset_run_state()
        reg = bench.compare_to_baseline(
            records=[{"metric": "m", "value": 500, "unit": "rows/s",
                      "plan_source": "default"}])
        rate = reg["rates"][0]
        assert rate["baseline"] == 1000
        assert reg["regressed"] == ["m"]

    def test_degraded_skip_detected_behind_gate_failed_run(
            self, monkeypatch):
        """The skip notification fires for ANY newer degraded capture
        passed over — even when a non-degraded (but gate-failed) run
        landed after it, so the degraded entry is not the newest."""
        bench = _import_bench(monkeypatch)
        bench.reset_run_state()
        env = bench.env_fingerprint()
        store = obs_store.LedgerStore(obs_store.ledger_dir())
        store.append("m", {"record": {"metric": "m", "value": 1000,
                                      "unit": "rows/s"}}, env=env,
                     run_id="good")
        store.append("m", {"record": {"metric": "m", "value": 10,
                                      "unit": "rows/s"}}, env=env,
                     degraded=True, run_id="wedged")
        store.append("m", {"record": {"metric": "m", "value": 500,
                                      "unit": "rows/s"}}, env=env,
                     run_id="bad")
        store.append("bench.gate_failed", {"regressed": ["m"]}, env=env,
                     run_id="bad")
        bench.reset_run_state()
        reg = bench.compare_to_baseline(
            records=[{"metric": "m", "value": 990, "unit": "rows/s"}])
        assert reg["rates"][0]["baseline"] == 1000
        assert reg["skipped_degraded_baselines"] == 1
        events = [e for e in obs.ledger().snapshot()["events"]
                  if e["name"] == "bench.compare_skipped_degraded"]
        assert events and events[0]["metric"] == "m"

    def test_vector_accumulator_mismatch_refuses_gate(self,
                                                      monkeypatch):
        """An ``fx`` vector rate never gates against an ``f32``
        baseline (the kernel-backend refusal's twin): the mismatch is
        recorded and the verdict line says so — while a matching-
        accumulator pair still gates normally, including the
        ``coord-bytes/s`` unit the wide-D vector bench emits."""
        bench = _import_bench(monkeypatch)
        bench.reset_run_state()
        env = bench.env_fingerprint()
        store = obs_store.LedgerStore(obs_store.ledger_dir())
        store.append("v", {"record": {
            "metric": "v", "value": 1000, "unit": "coord-bytes/s",
            "vector_accumulator": "f32"}}, env=env)
        bench.reset_run_state()
        reg = bench.compare_to_baseline(records=[
            {"metric": "v", "value": 500, "unit": "coord-bytes/s",
             "plan_source": "default", "kernel_backend": "xla",
             "vector_accumulator": "fx"}])
        rate = reg["rates"][0]
        assert rate.get("vector_accumulator_mismatch") is True
        assert rate["baseline_vector_accumulator"] == "f32"
        assert reg["regressed"] == []
        assert reg["vector_accumulator_mismatches"] == 1
        assert "vector-accumulator mismatch" in \
            bench.compare_verdict_line(reg)
        events = [e for e in obs.ledger().snapshot()["events"]
                  if e["name"] ==
                  "bench.compare_vector_accumulator_mismatch"]
        assert events and events[0]["metric"] == "v"
        # Same accumulator on both sides: the coord-bytes/s rate
        # gates exactly like rows/s — a >10% drop is a regression.
        reg2 = bench.compare_to_baseline(records=[
            {"metric": "v", "value": 500, "unit": "coord-bytes/s",
             "plan_source": "default", "kernel_backend": "xla",
             "vector_accumulator": "f32"}])
        assert reg2["rates"][0].get("regressed") is True
        assert reg2["regressed"] == ["v"]

    def test_sweep_config_batch_mismatch_refuses_gate(self,
                                                      monkeypatch):
        """A width-256 ``configs/s`` rate never gates against a
        width-16 baseline (the kernel-backend / vector-accumulator
        refusals' megasweep twin): ceil(K/width) dispatches per grid
        are different dispatch regimes, so only matching widths
        compare — the mismatch is recorded, counted and named in the
        verdict line, while a matching-width pair still gates."""
        bench = _import_bench(monkeypatch)
        bench.reset_run_state()
        env = bench.env_fingerprint()
        store = obs_store.LedgerStore(obs_store.ledger_dir())
        store.append("utility_megasweep_configs_per_sec", {"record": {
            "metric": "utility_megasweep_configs_per_sec",
            "value": 400, "unit": "configs/s",
            "sweep_config_batch": 16}}, env=env)
        bench.reset_run_state()
        reg = bench.compare_to_baseline(records=[
            {"metric": "utility_megasweep_configs_per_sec",
             "value": 200, "unit": "configs/s",
             "plan_source": "default", "kernel_backend": "xla",
             "sweep_config_batch": 256}])
        rate = reg["rates"][0]
        assert rate.get("sweep_config_batch_mismatch") is True
        assert rate["baseline_sweep_config_batch"] == 16
        assert reg["regressed"] == []
        assert reg["sweep_config_batch_mismatches"] == 1
        assert "sweep-config-batch mismatch" in \
            bench.compare_verdict_line(reg)
        events = [e for e in obs.ledger().snapshot()["events"]
                  if e["name"] ==
                  "bench.compare_sweep_config_batch_mismatch"]
        assert events and events[0][
            "metric"] == "utility_megasweep_configs_per_sec"
        # Matching widths on both sides gate exactly like any rate:
        # a >10% drop is a regression.
        reg2 = bench.compare_to_baseline(records=[
            {"metric": "utility_megasweep_configs_per_sec",
             "value": 200, "unit": "configs/s",
             "plan_source": "default", "kernel_backend": "xla",
             "sweep_config_batch": 16}])
        assert reg2["rates"][0].get("regressed") is True
        assert reg2["regressed"] == [
            "utility_megasweep_configs_per_sec"]

    def test_mesh_topology_mismatch_refuses_gate(self, monkeypatch):
        """A hier-topology rate never gates against a flat baseline:
        the two-stage exchange is a different collective schedule (its
        throughput is a property of the topology, not a regression),
        so only matching topologies compare — the mismatch is
        recorded, counted and named in the verdict line. Records
        predating the knob carry no ``mesh_topology`` field and read
        as \"flat\" on both sides, so historical baselines keep
        gating unchanged."""
        bench = _import_bench(monkeypatch)
        bench.reset_run_state()
        env = bench.env_fingerprint()
        store = obs_store.LedgerStore(obs_store.ledger_dir())
        store.append("m", {"record": {
            "metric": "m", "value": 1000, "unit": "rows/s"}}, env=env)
        bench.reset_run_state()
        reg = bench.compare_to_baseline(records=[
            {"metric": "m", "value": 500, "unit": "rows/s",
             "plan_source": "default", "kernel_backend": "xla",
             "mesh_topology": "hier"}])
        rate = reg["rates"][0]
        assert rate.get("mesh_topology_mismatch") is True
        assert rate["baseline_mesh_topology"] == "flat"
        assert reg["regressed"] == []
        assert reg["mesh_topology_mismatches"] == 1
        assert "mesh-topology mismatch" in \
            bench.compare_verdict_line(reg)
        events = [e for e in obs.ledger().snapshot()["events"]
                  if e["name"] == "bench.compare_mesh_topology_mismatch"]
        assert events and events[0]["metric"] == "m"
        # Absent field on the current record reads "flat" too — the
        # pre-knob record shape still gates (and still regresses).
        reg2 = bench.compare_to_baseline(records=[
            {"metric": "m", "value": 500, "unit": "rows/s",
             "plan_source": "default", "kernel_backend": "xla"}])
        assert reg2["rates"][0].get("regressed") is True
        assert reg2["regressed"] == ["m"]


class TestNoAdHocArtifactWrites:
    """AST-precise twin of ``make noartifacts``: ``json.dump(`` file
    writes are banned outside ``pipelinedp_tpu/obs/`` and
    ``pipelinedp_tpu/plan/`` (the planner's atomically-replaced plan
    file is the second blessed durable artifact) — run artifacts must
    flow through the schema-versioned report/store/plan (bench.py, the
    one artifact emitter, is outside the scanned tree)."""

    def test_json_dump_only_under_obs(self):
        # Delegates to the shared AST engine; `make noartifacts` is
        # the same rule.
        from pipelinedp_tpu import lint
        assert lint.check_tree("noartifacts") == []


class TestFsck:
    """``python -m pipelinedp_tpu.obs.store --fsck``: crash-consistency
    over the ledger tree. The tear test is exhaustive — a writer killed
    at EVERY byte boundary of the ledger file leaves a store fsck
    either repairs or reports, never one that loses a committed entry
    or splits one across reads."""

    def _seed_store(self, d):
        s = obs_store.LedgerStore(str(d))
        s.append("run.report", {"phase_s": {"a": 1.0}}, env={"k": "v"})
        s.append("bench.record", {"metric": "m", "value": 2.0},
                 env={"k": "v"})
        with open(s.path, "rb") as f:
            return s, f.read()

    def test_tear_at_every_byte_boundary(self, tmp_path):
        _, data = self._seed_store(tmp_path / "seed")
        full_lines = data.count(b"\n")
        for cut in range(len(data) + 1):
            d = tmp_path / f"torn-{cut}"
            os.makedirs(str(d))
            with open(str(d / "run_ledger.jsonl"), "wb") as f:
                f.write(data[:cut])
            summary = obs_store.fsck(str(d))
            assert summary["clean"], (cut, summary)
            # Entries fully written before the kill are all readable.
            committed = data[:cut].count(b"\n")
            store = obs_store.LedgerStore(str(d))
            entries = store.entries()
            assert len(entries) >= committed, (cut, len(entries))
            assert len(entries) <= full_lines
            # Idempotent: a second fsck finds nothing left to repair.
            again = obs_store.fsck(str(d))
            assert again["repaired"] == [], (cut, again)
            assert again["clean"]

    def test_torn_tail_repaired_and_appendable(self, tmp_path):
        s, data = self._seed_store(tmp_path)
        with open(s.path, "wb") as f:
            f.write(data[:-3])  # kill mid-final-line
        summary = obs_store.fsck(str(tmp_path))
        assert summary["clean"]
        assert any("torn" in r["action"] for r in summary["repaired"])
        # The store accepts appends and reads normally afterwards.
        s2 = obs_store.LedgerStore(str(tmp_path))
        s2.append("run.report", {"phase_s": {"b": 2.0}}, env={})
        entries = s2.entries()
        assert [e["name"] for e in entries][-1] == "run.report"
        assert s2.skipped_lines == 1  # the torn line, counted not lost

    def test_corrupt_budget_doc_reported_never_rewritten(self, tmp_path):
        from pipelinedp_tpu.serve.budget_ledger import TenantBudgetLedger
        led = TenantBudgetLedger(str(tmp_path / "budgets"))
        led.open_tenant("acme", 4.0, 1e-6)
        path = led.path_for("acme")
        with open(path, "rb") as f:
            doc = f.read()
        torn = doc[:len(doc) // 2]
        with open(path, "wb") as f:
            f.write(torn)
        summary = obs_store.fsck(str(tmp_path))
        assert not summary["clean"]
        assert any("corrupt document" in rec["problem"]
                   for rec in summary["damaged"])
        # Byte-for-byte intact: budget repair is an operator decision.
        with open(path, "rb") as f:
            assert f.read() == torn
        # CLI: rc 2 on damage, and the JSON shape carries the report.
        rc = obs_store.main(["--fsck", "--dir", str(tmp_path), "--json"])
        assert rc == 2

    def test_orphan_tmp_removed(self, tmp_path):
        self._seed_store(tmp_path)
        tmp = tmp_path / "budget-acme.json.tmp"
        tmp.write_text("{half")
        summary = obs_store.fsck(str(tmp_path))
        assert summary["clean"]
        assert any("temp" in r["action"] for r in summary["repaired"])
        assert not tmp.exists()
        # --no-repair mode reports and changes nothing.
        tmp.write_text("{half")
        summary = obs_store.fsck(str(tmp_path), repair=False)
        assert tmp.exists()
        assert any("temp" in r["problem"] for r in summary["tolerated"])

    def test_cli_clean_rc0(self, tmp_path, capsys):
        self._seed_store(tmp_path)
        rc = obs_store.main(["--fsck", "--dir", str(tmp_path)])
        assert rc == 0
        assert "clean" in capsys.readouterr().out
