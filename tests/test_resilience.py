"""Resilience-layer unit tests: injectable clock, deterministic
retry/backoff, device-health probing with flagged CPU degradation, and
the checkpoint store. All fast, CPU-only, tier-1 — injected faults and
the FakeClock keep real sleeps and real device probes out of the loop.
"""

import os
import re

import numpy as np
import pytest

from pipelinedp_tpu.resilience import (CheckpointMismatch, CheckpointStore,
                                       FakeClock, FaultPlan,
                                       RetriesExhausted, RetryPolicy,
                                       StreamCheckpoint, SystemClock,
                                       call_with_retry, injected_faults)
from pipelinedp_tpu.resilience import checkpoint as ckpt_mod
from pipelinedp_tpu.resilience import faults, health


class TestClock:

    def test_fake_clock_records_schedule(self):
        c = FakeClock()
        c.sleep(1.5)
        c.sleep(2.5)
        assert c.sleeps == [1.5, 2.5]
        assert c.monotonic() == 4.0

    def test_system_clock_zero_sleep_is_instant(self):
        c = SystemClock()
        t0 = c.monotonic()
        c.sleep(0.0)
        assert c.monotonic() - t0 < 0.5


class TestRetryPolicy:

    def test_schedule_is_deterministic(self):
        p = RetryPolicy(max_attempts=5, base_delay_s=1.0, multiplier=2.0,
                        max_delay_s=6.0, jitter=0.1, seed=7)
        assert p.delays() == p.delays()
        assert RetryPolicy(max_attempts=5, base_delay_s=1.0,
                           multiplier=2.0, max_delay_s=6.0, jitter=0.1,
                           seed=8).delays() != p.delays()

    def test_schedule_is_exponential_with_bounded_jitter(self):
        p = RetryPolicy(max_attempts=5, base_delay_s=1.0, multiplier=2.0,
                        max_delay_s=100.0, jitter=0.1, seed=0)
        delays = p.delays()
        assert len(delays) == 4
        for k, d in enumerate(delays):
            nominal = 1.0 * 2.0**k
            assert nominal * 0.9 <= d <= nominal * 1.1

    def test_max_delay_caps_the_schedule(self):
        p = RetryPolicy(max_attempts=6, base_delay_s=10.0, multiplier=3.0,
                        max_delay_s=15.0, jitter=0.0, seed=0)
        assert p.delays() == [10.0, 15.0, 15.0, 15.0, 15.0]

    def test_call_with_retry_honors_schedule(self):
        p = RetryPolicy(max_attempts=4, base_delay_s=0.5, seed=3)
        clock = FakeClock()
        calls = [0]

        def flaky():
            calls[0] += 1
            if calls[0] < 3:
                raise RuntimeError("transient")
            return "ok"

        assert call_with_retry(flaky, p, clock) == "ok"
        assert calls[0] == 3
        # Exactly the first two policy delays were slept, in order.
        assert clock.sleeps == p.delays()[:2]

    def test_retries_exhausted_carries_last_error(self):
        p = RetryPolicy(max_attempts=3, base_delay_s=0.1, seed=0)
        clock = FakeClock()

        def always_fails():
            raise ValueError("permanently broken")

        with pytest.raises(RetriesExhausted) as ei:
            call_with_retry(always_fails, p, clock)
        assert ei.value.attempts == 3
        assert isinstance(ei.value.last_error, ValueError)
        assert clock.sleeps == p.delays()  # full schedule honored

    def test_retry_on_filters_exception_types(self):
        with pytest.raises(KeyError):
            call_with_retry(lambda: (_ for _ in ()).throw(KeyError("x")),
                            RetryPolicy(max_attempts=3), FakeClock(),
                            retry_on=(ValueError,))


class TestFaultPlan:

    def test_env_round_trip(self):
        plan = FaultPlan(wedged_init=2, fail_chunks=(3, 5),
                         coordinator_timeouts=1)
        assert faults.plan_from_env(plan.to_env()) == plan

    def test_wedged_counts_per_site(self):
        with injected_faults(FaultPlan(wedged_init=2)):
            assert faults.wedged("device.probe")
            assert faults.wedged("device.probe")
            assert not faults.wedged("device.probe")
            # Sites count independently.
            assert faults.wedged("mesh.init")
        assert not faults.wedged("device.probe")  # cleared

    def test_check_chunk_raises_on_planned_chunks_only(self):
        with injected_faults(FaultPlan(fail_chunks=(2,))):
            faults.check_chunk(0)
            faults.check_chunk(1)
            with pytest.raises(faults.ChunkFailure):
                faults.check_chunk(2)

    def test_coordinator_timeouts_are_bounded(self):
        with injected_faults(FaultPlan(coordinator_timeouts=1)):
            with pytest.raises(faults.CoordinatorTimeout):
                faults.check_coordinator()
            faults.check_coordinator()  # second attempt goes through


class TestDeviceHealth:
    """Degradation paths: injected wedged init, FakeClock (no real
    sleeps), asserted backoff schedule, flagged CPU fallback."""

    def test_wedged_probe_degrades_to_cpu_with_backoff(self):
        policy = RetryPolicy(max_attempts=3, base_delay_s=2.0,
                             multiplier=2.0, max_delay_s=60.0,
                             jitter=0.1, seed=0)
        clock = FakeClock()
        env = {}
        with injected_faults(FaultPlan(wedged_init=99)):
            report = health.ensure_device_or_degrade(
                policy=policy, clock=clock, timeout_s=300.0, env=env)
        assert report.degraded and not report.healthy
        assert report.attempts == 3
        # The backoff schedule was honored exactly — and in zero wall
        # time (the FakeClock recorded, never slept).
        assert clock.sleeps == policy.delays()
        assert report.backoff_s == policy.delays()
        # The fallback is explicit: platform steered to CPU, the
        # degradation marker set (later backends must report it), the
        # failure reason preserved.
        assert env["JAX_PLATFORMS"] == "cpu"
        assert env[health.DEGRADED_ENV] == "1"
        assert "did not return within 300" in report.detail

    def test_recovered_device_lifts_the_degradation_override(self):
        """A healthy probe after a degradation clears the CPU pin and
        the marker — the flags never claim a vacuous CPU 'healthy'."""
        env = {"JAX_PLATFORMS": "cpu", health.DEGRADED_ENV: "1"}
        report = health.ensure_device_or_degrade(
            policy=RetryPolicy(max_attempts=1), clock=FakeClock(),
            timeout_s=120.0, env=env)
        assert report.healthy and not report.degraded
        assert health.DEGRADED_ENV not in env
        assert "JAX_PLATFORMS" not in env

    def test_transient_wedge_recovers_without_degrading(self):
        # First probe wedges, second succeeds (real subprocess probe on
        # the CPU platform): healthy after one backoff, NOT degraded.
        policy = RetryPolicy(max_attempts=3, base_delay_s=1.0, seed=0)
        clock = FakeClock()
        env = {}
        with injected_faults(FaultPlan(wedged_init=1)):
            report = health.ensure_device_or_degrade(
                policy=policy, clock=clock, timeout_s=120.0, env=env)
        assert report.healthy and not report.degraded
        assert report.attempts == 2
        assert clock.sleeps == policy.delays()[:1]
        assert "JAX_PLATFORMS" not in env

    def test_resilient_make_mesh_falls_back_to_cpu_mesh(self):
        policy = RetryPolicy(max_attempts=2, base_delay_s=1.0, seed=0)
        clock = FakeClock()
        with injected_faults(FaultPlan(wedged_init=99)):
            mesh, report = health.resilient_make_mesh(
                n_devices=4, policy=policy, clock=clock)
        assert report.degraded
        assert report.attempts == 2
        assert clock.sleeps == policy.delays()
        # The degraded mesh is a REAL, usable CPU mesh.
        assert mesh.devices.size == 4
        assert all(d.platform == "cpu" for d in mesh.devices.ravel())

    def test_resilient_make_mesh_healthy_path(self):
        mesh, report = health.resilient_make_mesh(n_devices=2)
        assert not report.degraded and report.healthy
        assert report.attempts == 1
        assert mesh.devices.size == 2

    def test_jax_backend_degrades_flagged(self, monkeypatch):
        from pipelinedp_tpu.backends import JaxBackend
        monkeypatch.setenv(faults.ENV_VAR, "")  # isolate from ambient
        # setenv registers the pre-test state, so the degradation the
        # production code writes into os.environ is rolled back at
        # teardown and cannot pollute later tests.
        monkeypatch.setenv("JAX_PLATFORMS",
                           os.environ.get("JAX_PLATFORMS", "cpu"))
        monkeypatch.setenv(health.DEGRADED_ENV, "")
        # Before any degradation: ordinary construction is un-degraded.
        assert JaxBackend(rng_seed=0).degraded is False
        policy = RetryPolicy(max_attempts=2, base_delay_s=1.0, seed=0)
        clock = FakeClock()
        with injected_faults(FaultPlan(wedged_init=99)):
            backend = JaxBackend(health_policy=policy, clock=clock,
                                 probe_timeout_s=60.0)
        assert backend.degraded is True
        assert backend.health.attempts == 2
        assert backend.mesh is None
        assert clock.sleeps == policy.delays()
        # The degradation pinned the PROCESS to CPU: every later backend
        # must report it too, probe or no probe — never silent.
        assert JaxBackend(rng_seed=0).degraded is True


class TestCheckpointStore:

    def test_round_trip(self, tmp_path):
        store = CheckpointStore(str(tmp_path / "run.ckpt"))
        assert store.load() is None
        arrays = {"acc:count": np.arange(8, dtype=np.int64),
                  "val:sum": np.linspace(0, 1, 8),
                  "vec": np.ones((8, 3))}
        store.save(StreamCheckpoint("fp123", 5, arrays))
        got = store.load_for("fp123")
        assert got.next_batch == 5
        assert got.fingerprint == "fp123"
        for k, v in arrays.items():
            np.testing.assert_array_equal(got.arrays[k], v)
        store.clear()
        assert store.load() is None

    def test_fingerprint_mismatch_refuses_to_resume(self, tmp_path):
        store = CheckpointStore(str(tmp_path / "run.ckpt"))
        store.save(StreamCheckpoint("fp_old", 2,
                                    {"acc:count": np.zeros(4, np.int64)}))
        with pytest.raises(CheckpointMismatch, match="refusing to resume"):
            store.load_for("fp_new")

    def test_fingerprint_separates_runs(self):
        fp = ckpt_mod.run_fingerprint
        base = fp("cfg", 100, 4, 7, 16, 1, 12)
        assert base == fp("cfg", 100, 4, 7, 16, 1, 12)
        assert base != fp("cfg", 100, 4, 8, 16, 1, 12)  # seed
        assert base != fp("cfg2", 100, 4, 7, 16, 1, 12)  # config
        assert base != fp("cfg", 101, 4, 7, 16, 1, 12)  # data size

    def test_as_store_accepts_path_or_store(self, tmp_path):
        p = str(tmp_path / "x.ckpt")
        s = ckpt_mod.as_store(p)
        assert isinstance(s, CheckpointStore) and s.path == p
        assert ckpt_mod.as_store(s) is s
        assert ckpt_mod.as_store(None) is None


class _FakeMesh:
    def __init__(self, multi):
        self.is_multi_process = multi


class TestCollectiveFailureToLoss:
    """The third loss-detection channel: a peer dying INSIDE a
    collective surfaces on the survivor as a transport runtime error,
    which must convert to ``MeshParticipantLost`` only when a peer's
    beat file names a provably dead pid — never on message text alone
    (a transient network fault must not shrink the mesh)."""

    GLOO = RuntimeError("FAILED_PRECONDITION: Buffer Definition Event: "
                        "Gloo all-reduce failed: Connection reset by peer")

    def _arm(self, monkeypatch, tmp_path, me=0, n=2):
        monkeypatch.setenv(health.MESH_DIR_ENV, str(tmp_path))
        import jax
        monkeypatch.setattr(jax, "process_index", lambda: me)
        monkeypatch.setattr(jax, "process_count", lambda: n)

    def test_unarmed_or_single_process_returns_none(self, monkeypatch):
        monkeypatch.delenv(health.MESH_DIR_ENV, raising=False)
        assert health.collective_failure_to_loss(
            self.GLOO, _FakeMesh(True)) is None
        monkeypatch.setenv(health.MESH_DIR_ENV, "/nonexistent")
        assert health.collective_failure_to_loss(
            self.GLOO, _FakeMesh(False)) is None

    def test_non_collective_error_returns_none(self, monkeypatch,
                                               tmp_path):
        self._arm(monkeypatch, tmp_path)
        assert health.collective_failure_to_loss(
            RuntimeError("out of memory"), _FakeMesh(True),
            clock=FakeClock()) is None

    def test_dead_peer_confirms_loss(self, monkeypatch, tmp_path):
        self._arm(monkeypatch, tmp_path)
        # A pid that cannot be alive: spawn a no-op child and reap it
        # (not os.fork — jax is multithreaded and warns on fork).
        import subprocess
        import sys as _sys
        child = subprocess.Popen([_sys.executable, "-c", "pass"])
        child.wait()
        pid = child.pid
        ckpt_mod.atomic_write_json(
            str(tmp_path / "mesh-1.json"),
            {"process_id": 1, "pid": pid, "beat": 7})
        loss = health.collective_failure_to_loss(
            self.GLOO, _FakeMesh(True), clock=FakeClock())
        assert isinstance(loss, health.MeshParticipantLost)
        assert loss.process_id == 1 and loss.reason == "collective_failure"
        assert "died mid-collective" in str(loss)

    def test_all_peers_alive_reraises(self, monkeypatch, tmp_path):
        self._arm(monkeypatch, tmp_path)
        ckpt_mod.atomic_write_json(
            str(tmp_path / "mesh-1.json"),
            {"process_id": 1, "pid": os.getpid(), "beat": 7})
        clock = FakeClock()
        assert health.collective_failure_to_loss(
            self.GLOO, _FakeMesh(True), clock=clock) is None
        # It polled the full confirmation window before giving up.
        assert clock.monotonic() >= health._COLLECTIVE_LOSS_CONFIRM_S


class TestNoDirectSleep:
    """Lint-style invariant: no library/bench code path calls
    ``time.sleep`` directly — every wait must route through the
    injectable ``resilience.clock`` so fault tests stay fast and
    deterministic. (``make faultcheck`` runs the same check via grep.)"""

    def test_no_time_sleep_and_no_bare_threads(self):
        # Both halves (direct time.sleep + bare threading.Thread) are
        # one rule in the shared AST engine; `make nosleep` is the
        # same check.
        from pipelinedp_tpu import lint
        assert lint.check_tree("nosleep") == []
