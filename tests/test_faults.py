"""Fault-injection integration tests: kill a streamed run mid-flight,
resume from checkpoint, assert BIT-IDENTICAL outputs — the acceptance
oracle for budget-safe retry (same noise draws, same kept-partition set,
one budget charge). Plus the bench's wedged-device degradation path.

Fast and CPU-only throughout — the end-to-end bench subprocess runs in
smoke mode (~20s). ``make faultcheck`` runs this file plus
``test_resilience.py``.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.backends import JaxBackend
from pipelinedp_tpu.resilience import (CheckpointMismatch, CheckpointStore,
                                       FaultPlan, injected_faults)
from pipelinedp_tpu.resilience.faults import ChunkFailure


@pytest.fixture(autouse=True)
def tiny_chunks(monkeypatch):
    monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "997")


def run_streamed(ds, params, seed=0, eps=5.0, delta=1e-6, public=None,
                 checkpoint=None, mesh=None):
    ds.invalidate_cache()
    acc = pdp.NaiveBudgetAccountant(total_epsilon=eps, total_delta=delta)
    engine = pdp.DPEngine(acc, JaxBackend(rng_seed=seed, mesh=mesh,
                                          checkpoint=checkpoint))
    res = engine.aggregate(ds, params, pdp.DataExtractors(),
                           public_partitions=public)
    acc.compute_budgets()
    got = dict(res)
    assert res.timings.get("stream_batches", 0) > 1, (
        "dataset did not stream — the kill/resume path was not exercised")
    return got, res.timings


def make_ds(seed=1, n=9_000, users=2_000, parts=12):
    rng = np.random.default_rng(seed)
    return pdp.ArrayDataset(privacy_ids=rng.integers(0, users, n),
                            partition_keys=rng.integers(0, parts, n),
                            values=rng.uniform(0.0, 10.0, n)), parts


def assert_bit_identical(got_a, got_b):
    """EXACT equality of every released metric — noisy floats included —
    and of the kept-partition sets: the bit-parity contract."""
    assert set(got_a) == set(got_b), (
        f"kept sets differ: {sorted(set(got_a) ^ set(got_b))}")
    for k in got_a:
        ta, tb = got_a[k], got_b[k]
        assert ta._fields == tb._fields
        for f in ta._fields:
            va, vb = getattr(ta, f), getattr(tb, f)
            np.testing.assert_array_equal(np.asarray(va), np.asarray(vb),
                                          err_msg=f"partition {k}.{f}")


class TestCheckpointResumeBitParity:
    """Kill after chunk k via fault injection, resume, compare against
    the uninterrupted run at MODERATE eps — real noise, real private
    selection, so any key-replay drift shows up as a float mismatch."""

    def test_killed_and_resumed_run_is_bit_identical(self, tmp_path):
        ds, parts = make_ds(seed=1)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM, pdp.Metrics.MEAN,
                     pdp.Metrics.PRIVACY_ID_COUNT],
            max_partitions_contributed=parts,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=10.0)
        # Ground truth: one uninterrupted run, NO checkpointing at all.
        baseline, _ = run_streamed(ds, params, seed=42)

        # Kill at chunk 3 (checkpoints for chunks 0-1 are on disk; chunk
        # 2's fold is still pending — deliberately mid-pipeline).
        store = CheckpointStore(str(tmp_path / "stream.ckpt"))
        with injected_faults(FaultPlan(fail_chunks=(3,))):
            with pytest.raises(ChunkFailure):
                run_streamed(ds, params, seed=42, checkpoint=store)
        assert store.exists(), "no checkpoint survived the kill"

        # Resume: restores the fold prefix, replays the SAME keys.
        resumed, timings = run_streamed(ds, params, seed=42,
                                        checkpoint=store)
        assert timings["stream_resumed_from"] >= 1
        assert_bit_identical(baseline, resumed)
        # Success cleared the checkpoint: the budget cannot be re-spent
        # by accidentally resuming a finished run.
        assert not store.exists()

    def test_resume_with_private_selection_same_kept_set(self, tmp_path):
        """Selection at modest eps — partitions genuinely on the keep
        boundary — must come out IDENTICAL after kill + resume."""
        rng = np.random.default_rng(9)
        n = 8_000
        pid = np.arange(n)
        pk = np.where(np.arange(n) < 7_600,
                      rng.integers(0, 4, n), 4 + np.arange(n) % 120)
        ds = pdp.ArrayDataset(privacy_ids=pid, partition_keys=pk,
                              values=None)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PRIVACY_ID_COUNT],
            max_partitions_contributed=1,
            max_contributions_per_partition=1)
        baseline, _ = run_streamed(ds, params, seed=5, eps=5.0,
                                   delta=1e-5)
        store = CheckpointStore(str(tmp_path / "sel.ckpt"))
        with injected_faults(FaultPlan(fail_chunks=(4,))):
            with pytest.raises(ChunkFailure):
                run_streamed(ds, params, seed=5, eps=5.0, delta=1e-5,
                             checkpoint=store)
        resumed, _ = run_streamed(ds, params, seed=5, eps=5.0,
                                  delta=1e-5, checkpoint=store)
        assert_bit_identical(baseline, resumed)

    def test_resume_with_percentiles_is_bit_identical(self, tmp_path):
        """Percentile configs carry extra checkpoint state (the additive
        device mid-histogram) and a resumed run must disable the pass-B
        device cache (the skipped prefix is not resident) — both paths
        pinned by exact equality against the uninterrupted run."""
        rng = np.random.default_rng(11)
        n = 8_000
        ds = pdp.ArrayDataset(privacy_ids=rng.integers(0, 2_000, n),
                              partition_keys=rng.integers(0, 4, n),
                              values=rng.uniform(0.0, 10.0, n))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50), pdp.Metrics.PERCENTILE(90),
                     pdp.Metrics.COUNT],
            max_partitions_contributed=4,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=10.0)
        public = list(range(4))
        baseline, _ = run_streamed(ds, params, seed=13, public=public)
        store = CheckpointStore(str(tmp_path / "pct.ckpt"))
        with injected_faults(FaultPlan(fail_chunks=(3,))):
            with pytest.raises(ChunkFailure):
                run_streamed(ds, params, seed=13, public=public,
                             checkpoint=store)
        resumed, timings = run_streamed(ds, params, seed=13,
                                        public=public, checkpoint=store)
        assert timings["stream_resumed_from"] >= 1
        # The resumed run must have re-streamed pass B (no partial
        # cache), not silently dropped the skipped prefix.
        assert timings["stream_pass_b"] == "reship"
        assert_bit_identical(baseline, resumed)

    def test_kill_on_first_chunk_resumes_from_scratch(self, tmp_path):
        """A kill before ANY fold completes leaves no checkpoint; the
        'resume' is a clean, still bit-identical, restart."""
        ds, parts = make_ds(seed=3)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            max_partitions_contributed=parts,
            max_contributions_per_partition=50)
        baseline, _ = run_streamed(ds, params, seed=7)
        store = CheckpointStore(str(tmp_path / "first.ckpt"))
        with injected_faults(FaultPlan(fail_chunks=(0,))):
            with pytest.raises(ChunkFailure):
                run_streamed(ds, params, seed=7, checkpoint=store)
        assert not store.exists()
        resumed, timings = run_streamed(ds, params, seed=7,
                                        checkpoint=store)
        assert timings["stream_resumed_from"] == 0
        assert_bit_identical(baseline, resumed)

    def test_checkpoint_requires_fixed_seed(self, tmp_path):
        ds, parts = make_ds(seed=4, n=5_000)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            max_partitions_contributed=parts,
            max_contributions_per_partition=50)
        ds.invalidate_cache()
        acc = pdp.NaiveBudgetAccountant(total_epsilon=5.0,
                                        total_delta=1e-6)
        engine = pdp.DPEngine(acc, JaxBackend(
            rng_seed=None, checkpoint=str(tmp_path / "x.ckpt")))
        res = engine.aggregate(ds, params, pdp.DataExtractors())
        acc.compute_budgets()
        with pytest.raises(ValueError, match="budget is consumed at "
                                             "noise draw"):
            dict(res)

    def test_mismatched_checkpoint_refuses_resume(self, tmp_path):
        """A checkpoint from a DIFFERENT seed must refuse to resume —
        silently restarting would re-draw noise and double-spend."""
        ds, parts = make_ds(seed=6, n=5_000)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            max_partitions_contributed=parts,
            max_contributions_per_partition=50)
        store = CheckpointStore(str(tmp_path / "mismatch.ckpt"))
        with injected_faults(FaultPlan(fail_chunks=(3,))):
            with pytest.raises(ChunkFailure):
                run_streamed(ds, params, seed=1, checkpoint=store)
        assert store.exists()
        ds.invalidate_cache()
        acc = pdp.NaiveBudgetAccountant(total_epsilon=5.0,
                                        total_delta=1e-6)
        engine = pdp.DPEngine(acc, JaxBackend(rng_seed=2,
                                              checkpoint=store))
        res = engine.aggregate(ds, params, pdp.DataExtractors())
        acc.compute_budgets()
        with pytest.raises(CheckpointMismatch):
            dict(res)

    def test_same_shape_different_data_refuses_resume(self, tmp_path):
        """The fingerprint's data component is a CONTENT digest: a
        different dataset with the identical row count / config / seed
        must refuse to resume (splicing two datasets into one release
        would corrupt it silently)."""
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=12,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=10.0)
        ds_a, _ = make_ds(seed=31)
        store = CheckpointStore(str(tmp_path / "data.ckpt"))
        with injected_faults(FaultPlan(fail_chunks=(3,))):
            with pytest.raises(ChunkFailure):
                run_streamed(ds_a, params, seed=4, checkpoint=store)
        assert store.exists()
        ds_b, _ = make_ds(seed=32)  # same shape, different rows
        ds_b.invalidate_cache()
        acc = pdp.NaiveBudgetAccountant(total_epsilon=5.0,
                                        total_delta=1e-6)
        engine = pdp.DPEngine(acc, JaxBackend(rng_seed=4,
                                              checkpoint=store))
        res = engine.aggregate(ds_b, params, pdp.DataExtractors())
        acc.compute_budgets()
        with pytest.raises(CheckpointMismatch):
            dict(res)

    def test_resume_on_mesh_is_bit_identical(self, tmp_path,
                                             monkeypatch):
        """Kill + resume composed with the 8-device CPU mesh: the
        owner-sharded fold restores and replays identically."""
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "500")
        from pipelinedp_tpu.parallel import make_mesh
        ds, parts = make_ds(seed=8, n=14_000)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=parts,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=10.0)
        mesh = make_mesh()
        baseline, _ = run_streamed(ds, params, seed=21, mesh=mesh)
        store = CheckpointStore(str(tmp_path / "mesh.ckpt"))
        with injected_faults(FaultPlan(fail_chunks=(2,))):
            with pytest.raises(ChunkFailure):
                run_streamed(ds, params, seed=21, mesh=mesh,
                             checkpoint=store)
        resumed, timings = run_streamed(ds, params, seed=21, mesh=mesh,
                                        checkpoint=store)
        assert timings["stream_resumed_from"] >= 1
        assert_bit_identical(baseline, resumed)


class TestMegasweepKillResume:
    """Kill the utility-analysis megasweep between config batches via
    ``FaultPlan.fail_sweep_config_chunks``, resume from the ``.sweep``
    sibling checkpoint, and assert the resumed grid is BIT-IDENTICAL to
    an uninterrupted batched run — with zero orphan threads left behind
    (ISSUE-18 acceptance)."""

    GRID = 12
    BATCH = 4  # 12 configs / 4 per batch = 3 sweep chunks

    @staticmethod
    def _run_sweep(checkpoint=None):
        import dataclasses

        from pipelinedp_tpu import analysis, plan as plan_mod
        from pipelinedp_tpu.analysis import data_structures
        rng = np.random.default_rng(31)
        n = 8_000
        ds = pdp.ArrayDataset(
            privacy_ids=rng.integers(0, 600, n),
            partition_keys=rng.integers(0, 40, n),
            values=rng.uniform(0, 10, n))
        multi = data_structures.MultiParameterConfiguration(
            max_partitions_contributed=list(range(1, 13)),
            max_contributions_per_partition=[1, 2] * 6)
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6,
            aggregate_params=pdp.AggregateParams(
                metrics=[pdp.Metrics.COUNT],
                max_partitions_contributed=4,
                max_contributions_per_partition=2),
            multi_param_configuration=multi)
        with plan_mod.seam_override("sweep_config_batch",
                                    TestMegasweepKillResume.BATCH):
            res = analysis.perform_utility_analysis(
                ds, JaxBackend(rng_seed=0, checkpoint=checkpoint),
                options, pdp.DataExtractors())
            out = list(res)[0]
        assert len(out) == TestMegasweepKillResume.GRID
        metrics = [dataclasses.asdict(m.count_metrics) for m in out]
        return metrics, res

    @staticmethod
    def _assert_configs_bit_identical(got, ref):
        for ci, (a, b) in enumerate(zip(got, ref)):
            for field in a:
                np.testing.assert_array_equal(
                    np.asarray(a[field]), np.asarray(b[field]),
                    err_msg=f"cfg{ci}.{field}")

    def test_killed_megasweep_resumes_bit_identical(self, tmp_path):
        import threading

        # Ground truth: one uninterrupted batched run, no checkpoint.
        baseline, _ = self._run_sweep()

        # Kill at config chunk 2: chunks 0-1 (8 configs) are already in
        # the ``.sweep`` sibling checkpoint; chunk 2 never dispatched.
        path = str(tmp_path / "ua.ckpt")
        sweep_store = CheckpointStore(path + ".sweep")
        with injected_faults(FaultPlan(fail_sweep_config_chunks=(2,))):
            with pytest.raises(ChunkFailure):
                self._run_sweep(checkpoint=path)
        assert sweep_store.exists(), (
            "no .sweep checkpoint survived the kill")
        orphans = [t.name for t in threading.enumerate()
                   if t.name.startswith("pdp-") and t.is_alive()]
        assert not orphans, f"killed sweep left orphans: {orphans}"

        # Resume: replays only the remaining chunk, bit-identically.
        resumed, res = self._run_sweep(checkpoint=path)
        assert res._resumed_from_chunk == 2
        self._assert_configs_bit_identical(resumed, baseline)
        # Completion cleared the sweep checkpoint — a finished grid
        # cannot be accidentally resumed.
        assert not sweep_store.exists()
        orphans = [t.name for t in threading.enumerate()
                   if t.name.startswith("pdp-") and t.is_alive()]
        assert not orphans, f"resumed sweep left orphans: {orphans}"

    def test_kill_on_first_config_chunk_resumes_from_scratch(
            self, tmp_path):
        baseline, _ = self._run_sweep()
        path = str(tmp_path / "ua0.ckpt")
        with injected_faults(FaultPlan(fail_sweep_config_chunks=(0,))):
            with pytest.raises(ChunkFailure):
                self._run_sweep(checkpoint=path)
        # Nothing was checkpointed — the resume IS a fresh run.
        assert not CheckpointStore(path + ".sweep").exists()
        resumed, res = self._run_sweep(checkpoint=path)
        assert res._resumed_from_chunk == 0
        self._assert_configs_bit_identical(resumed, baseline)


class TestElasticMeshRecovery:
    """Device loss mid-stream is a RECOVERABLE event: the elastic
    wrapper re-forms the mesh from the survivors, resumes from the
    last checkpoint (adopting the original batch assignment regrouped
    onto the smaller mesh), and releases values bit-identical to a
    clean run at the surviving shape — the `mesh.reshard` event on the
    run record. Single-kill AND double-kill-to-single-device."""

    def _params(self, parts):
        return pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=parts,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=10.0)

    def test_single_device_loss_reforms_and_matches_surviving_shape(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "500")
        from pipelinedp_tpu import obs
        from pipelinedp_tpu.parallel import make_mesh
        from pipelinedp_tpu.resilience.faults import DeviceLost
        ds, parts = make_ds(seed=8, n=14_000)
        params = self._params(parts)
        # Clean run at the SURVIVING shape (8 devices halve to 4).
        baseline, _ = run_streamed(ds, params, seed=21,
                                   mesh=make_mesh(4))

        obs.reset()
        store = CheckpointStore(str(tmp_path / "elastic.ckpt"))
        with injected_faults(FaultPlan(lose_device_chunks=(2,))):
            survived, timings = run_streamed(ds, params, seed=21,
                                             mesh=make_mesh(),
                                             checkpoint=store)
        # The run did NOT wedge and did NOT restart from scratch: it
        # re-formed, resumed from the checkpoint, and finished.
        assert timings["stream_mesh_reshards"] == 1
        hist = timings["stream_reshard_history"]
        assert hist[0]["old_devices"] == 8
        assert hist[0]["new_devices"] == 4
        assert hist[0]["reason"] == "device_lost"
        assert timings["stream_resumed_from"] >= 1
        snap = obs.ledger().snapshot()
        reshard_events = [e for e in snap["events"]
                          if e["name"] == "mesh.reshard"]
        assert len(reshard_events) == 1
        assert reshard_events[0]["old_devices"] == 8
        assert reshard_events[0]["new_devices"] == 4
        assert snap["counters"]["checkpoint.elastic_adoptions"] >= 1
        assert_bit_identical(baseline, survived)
        assert not store.exists()  # success cleared the checkpoint

    def test_double_loss_shrinks_to_single_device(self, tmp_path,
                                                  monkeypatch):
        """4 -> 2 -> 1: two participants lost in one run, two reshard
        records, the final single-device mesh still releases values
        bit-identical to a clean 1-device run."""
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "500")
        from pipelinedp_tpu import obs
        from pipelinedp_tpu.parallel import make_mesh
        ds, parts = make_ds(seed=8, n=9_000)
        params = self._params(parts)
        baseline, _ = run_streamed(ds, params, seed=23,
                                   mesh=make_mesh(1))

        obs.reset()
        store = CheckpointStore(str(tmp_path / "double.ckpt"))
        with injected_faults(FaultPlan(lose_device_chunks=(1, 3))):
            survived, timings = run_streamed(ds, params, seed=23,
                                             mesh=make_mesh(4),
                                             checkpoint=store)
        hist = timings["stream_reshard_history"]
        assert [(h["old_devices"], h["new_devices"]) for h in hist] == [
            (4, 2), (2, 1)]
        assert timings["stream_mesh_reshards"] == 2
        assert_bit_identical(baseline, survived)

    def test_loss_on_last_mesh_reraises(self, monkeypatch):
        """A 1-device mesh has nothing to re-form from: the loss
        propagates instead of looping."""
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "500")
        from pipelinedp_tpu.parallel import make_mesh
        from pipelinedp_tpu.resilience.faults import DeviceLost
        ds, parts = make_ds(seed=8, n=5_000)
        params = self._params(parts)
        with injected_faults(FaultPlan(lose_device_chunks=(1,))):
            with pytest.raises(DeviceLost):
                run_streamed(ds, params, seed=23, mesh=make_mesh(1))

    def test_loss_without_fixed_seed_reraises(self, monkeypatch):
        """No fixed rng_seed means replay cannot be guaranteed — the
        elastic retry must refuse rather than silently re-draw."""
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "500")
        from pipelinedp_tpu.parallel import make_mesh
        from pipelinedp_tpu.resilience.faults import DeviceLost
        ds, parts = make_ds(seed=8, n=5_000)
        params = self._params(parts)
        with injected_faults(FaultPlan(lose_device_chunks=(1,))):
            with pytest.raises(DeviceLost):
                run_streamed(ds, params, seed=None, mesh=make_mesh())

    @pytest.mark.parametrize("accumulator", ["fx", "f32"])
    def test_vector_sum_survives_mid_stream_shrink(self, tmp_path,
                                                   monkeypatch,
                                                   accumulator):
        """ISSUE-17 satellite: a VECTOR_SUM workload shrinks 8 -> 4
        mid-stream and resumes matching a clean run at the surviving
        shape. Under 'fx' the match is BIT-identical (int32 lane psum
        + exact per-chunk lanes->steps fold — the same contract the
        scalar metrics hold); under 'f32' it is only
        float-approximate, because the f32 psum's partial-sum grouping
        changes with the device count — the gap the fx accumulator
        exists to close."""
        monkeypatch.setenv("PIPELINEDP_TPU_STREAM_CHUNK", "500")
        monkeypatch.setenv("PIPELINEDP_TPU_VECTOR_ACCUMULATOR",
                           accumulator)
        from pipelinedp_tpu.parallel import make_mesh
        rng = np.random.default_rng(29)
        n, parts, d = 14_000, 12, 16
        ds = pdp.ArrayDataset(
            privacy_ids=rng.integers(0, 2_000, n),
            partition_keys=rng.integers(0, parts, n),
            values=rng.uniform(-1.0, 1.0, (n, d)))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.VECTOR_SUM],
            max_partitions_contributed=parts,
            max_contributions_per_partition=50,
            vector_size=d, vector_max_norm=4.0,
            vector_norm_kind=pdp.NormKind.L2)
        baseline, _ = run_streamed(ds, params, seed=31,
                                   mesh=make_mesh(4))
        store = CheckpointStore(str(tmp_path / "vec_elastic.ckpt"))
        with injected_faults(FaultPlan(lose_device_chunks=(2,))):
            survived, timings = run_streamed(ds, params, seed=31,
                                             mesh=make_mesh(),
                                             checkpoint=store)
        assert timings["stream_mesh_reshards"] == 1
        hist = timings["stream_reshard_history"]
        assert (hist[0]["old_devices"], hist[0]["new_devices"]) == (8, 4)
        assert timings["stream_resumed_from"] >= 1
        if accumulator == "fx":
            assert_bit_identical(baseline, survived)
        else:
            assert set(baseline) == set(survived)
            for k in baseline:
                np.testing.assert_allclose(
                    np.asarray(survived[k].vector_sum),
                    np.asarray(baseline[k].vector_sum), rtol=1e-6)


class TestBenchDegradation:
    """The BENCH_r05 failure mode, end to end: a wedged device probe
    must yield rc=0 and parseable ``"degraded": true`` JSON, not rc=3 —
    and with the heartbeat monitor on, the wedge (held for real via
    ``wedged_hold``) is cancelled at the stall deadline, the degraded
    artifact embeds the flight-record path + stall diagnosis, and the
    heartbeat file survives the run."""

    def test_wedged_probe_bench_exits_zero_with_degraded_json(
            self, tmp_path):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ledger_dir = str(tmp_path / "ledger")
        env = dict(os.environ)
        env["PIPELINEDP_TPU_FAULTS"] = "wedged_init=99,wedged_hold=1"
        env["PIPELINEDP_TPU_PROBE_BACKOFF"] = "0.01"  # real clock: tiny
        env["PIPELINEDP_TPU_PROBE_TIMEOUT"] = "30"  # the watchdog cuts it
        env["PIPELINEDP_TPU_PROBE_ATTEMPTS"] = "2"
        env["PIPELINEDP_TPU_HEARTBEAT"] = "1"
        env["PIPELINEDP_TPU_HEARTBEAT_S"] = "0.05"
        env["PIPELINEDP_TPU_STALL_S"] = "0.3"
        env["PIPELINEDP_TPU_LEDGER_DIR"] = ledger_dir
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PIPELINEDP_TPU_DEGRADED", None)  # fresh process state
        env.pop("PYTHONPATH", None)
        proc = subprocess.run(
            [sys.executable, "bench.py", "--smoke", "--flagship-only",
             "--stream-rows", "0"],
            cwd=repo, env=env, capture_output=True, text=True,
            timeout=1200)
        assert proc.returncode == 0, proc.stderr[-3000:]
        headline = json.loads(proc.stdout.strip().splitlines()[-1])
        assert headline["degraded"] is True
        assert headline["value"] > 0
        assert "DEVICE UNREACHABLE" in proc.stderr
        # The watchdog, not the 30s probe timeout, ended each attempt.
        diagnosis = headline["degraded_diagnosis"]
        assert diagnosis["probe_attempts"] == 2
        assert "cancelled by the stall watchdog" in diagnosis["detail"]
        assert "flight_record" in diagnosis
        flight = json.load(open(diagnosis["flight_record"],
                                encoding="utf-8"))
        assert flight["stall"]["deadline_s"] == 0.3
        # The live heartbeat streamed next to the durable ledger,
        # namespaced by the bench's run name (resident processes
        # sharing a ledger dir must not clobber each other's beat).
        import glob as _glob
        hb_files = _glob.glob(os.path.join(ledger_dir,
                                           "heartbeat-bench-*.json"))
        assert hb_files, os.listdir(ledger_dir)
        hb = json.load(open(hb_files[0], encoding="utf-8"))
        assert hb["phase"]

    def test_probe_helper_degrades_without_subprocess(self, monkeypatch):
        """The bench probe helper itself (fast, tier-1): wedged probe →
        degraded report, backoff schedule from the env knobs."""
        monkeypatch.setenv("PIPELINEDP_TPU_PROBE_ATTEMPTS", "2")
        monkeypatch.setenv("PIPELINEDP_TPU_PROBE_BACKOFF", "0.0")
        # Roll back the degradation the helper writes into os.environ.
        monkeypatch.setenv("JAX_PLATFORMS",
                           os.environ.get("JAX_PLATFORMS", "cpu"))
        monkeypatch.setenv("PIPELINEDP_TPU_DEGRADED", "")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        monkeypatch.syspath_prepend(repo)
        import bench
        with injected_faults(FaultPlan(wedged_init=99)):
            report = bench._ensure_device_or_degrade()
        assert report.degraded
        assert report.attempts == 2
