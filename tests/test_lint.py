"""The AST invariant checker (pipelinedp_tpu/lint/).

Covers, per the PR-13 acceptance criteria:

* one bad-fixture + one clean-fixture per rule (16 rules x 2) — the
  bad fixture proves the rule FIRES, the clean one proves the blessed
  location/shape passes;
* the registry meta-test: every legacy Makefile grep lint name is
  owned by a rule, the born-AST analyses exist, and every
  registered rule has a fixture pair here;
* the seeded regressions from the issue: a ``time.sleep`` "in"
  ``streaming.py``, an ``atomic_write_json`` inside a
  ``with self._lock:`` body, a raw ``jax.random.normal`` "in"
  ``jax_engine.py`` — all caught through the same engine `make
  lintcheck` runs;
* suppression semantics: reasoned suppressions silence AND are
  counted; reasonless or unknown-rule suppressions are findings;
  docstring mentions are inert;
* the whole-tree zero-unsuppressed-findings acceptance run;
* ``--json`` round-trip through the ``obs/store.py`` envelope so a CI
  gate can diff per-rule finding counts across runs.
"""

import json
import os

import pytest

from pipelinedp_tpu import lint
from pipelinedp_tpu.lint import cli, engine
from pipelinedp_tpu.lint import rules as rules_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LEGACY_MAKE_LINTS = {"nosleep", "nofoldin", "nostager", "noperf",
                     "noartifacts", "nocost", "noknobs", "nopallas",
                     "noserve"}
NEW_ANALYSES = {"rng-purity", "blocking-under-lock", "jit-staticness",
                "fusion-masking", "sketch-confinement",
                "socket-confinement", "collective-confinement"}


def findings_for(rule_id, source, rel):
    """Unsuppressed findings of ONE rule over a virtual file."""
    result = engine.lint_source(source, rel,
                                rules=[rules_mod.get(rule_id)])
    return [f for f in result.findings if f.rule == rule_id]


# ---------------------------------------------------------------------
# fixture pairs: (bad_source, bad_rel), (clean_source, clean_rel)
# ---------------------------------------------------------------------

FIXTURES = {
    "nosleep": {
        # The issue's seeded regression: a time.sleep in streaming.py.
        "bad": ("import time\n\n"
                "def wait():\n"
                "    time.sleep(0.5)\n",
                "pipelinedp_tpu/streaming.py"),
        "clean": ("import time\n\n"
                  "def sleep(clock, s):\n"
                  "    time.sleep(s)\n",
                  "pipelinedp_tpu/resilience/clock.py"),
    },
    "nofoldin": {
        "bad": ("import jax\n\n"
                "def keys(k, idx):\n"
                "    return jax.vmap(\n"
                "        lambda i: jax.random.fold_in(k, i))(idx)\n",
                "pipelinedp_tpu/ops/quantile_tree.py"),
        "clean": ("import jax\n\n"
                  "def keys(k, idx):\n"
                  "    return jax.vmap(\n"
                  "        lambda i: jax.random.fold_in(k, i))(idx)\n",
                  "pipelinedp_tpu/ops/counter_rng.py"),
    },
    "nostager": {
        "bad": ("from pipelinedp_tpu.ingest import BackgroundStager\n\n"
                "def restream(src):\n"
                "    return BackgroundStager(src)\n",
                "pipelinedp_tpu/jax_engine.py"),
        # streaming.py keeps exactly two sites, in the two blessed
        # functions.
        "clean": ("def _stream_impl(src):\n"
                  "    return BackgroundStager(src)\n\n"
                  "def run_sweep(src):\n"
                  "    return BackgroundStager(src)\n",
                  "pipelinedp_tpu/streaming.py"),
    },
    "noperf": {
        "bad": ("import time\n\n"
                "def t():\n"
                "    return time.perf_counter()\n",
                "pipelinedp_tpu/streaming.py"),
        "clean": ("import time\n\n"
                  "def t():\n"
                  "    return time.perf_counter()\n",
                  "pipelinedp_tpu/obs/costs.py"),
    },
    "noartifacts": {
        "bad": ("import json\n\n"
                "def save(report, fh):\n"
                "    json.dump(report, fh)\n",
                "pipelinedp_tpu/jax_engine.py"),
        "clean": ("import json\n\n"
                  "def save(report, fh):\n"
                  "    json.dump(report, fh)\n",
                  "pipelinedp_tpu/obs/report.py"),
    },
    "nocost": {
        "bad": ("def analyze(compiled):\n"
                "    return compiled.cost_analysis()\n",
                "pipelinedp_tpu/streaming.py"),
        "clean": ("def analyze(compiled):\n"
                  "    return compiled.cost_analysis()\n",
                  "pipelinedp_tpu/obs/costs.py"),
    },
    "noknobs": {
        "bad": ("from pipelinedp_tpu import jax_engine as je\n\n"
                "def cap():\n"
                "    return je._SUBHIST_BYTE_CAP\n",
                "pipelinedp_tpu/streaming.py"),
        # The defining module's Store-context assignment IS the seam.
        "clean": ("_Q_CHUNK = 8\n",
                  "pipelinedp_tpu/streaming.py"),
    },
    "nopallas": {
        "bad": ("from jax.experimental import pallas as pl\n",
                "pipelinedp_tpu/streaming.py"),
        "clean": ("from jax.experimental import pallas as pl\n",
                  "pipelinedp_tpu/ops/kernels/hist.py"),
    },
    "noserve": {
        "bad": ("from pipelinedp_tpu.serve import Service\n",
                "pipelinedp_tpu/jax_engine.py"),
        "clean": ("from pipelinedp_tpu.serve.budget_ledger import (\n"
                  "    TenantBudgetLedger)\n\n"
                  "def make(d):\n"
                  "    return TenantBudgetLedger(d)\n",
                  "pipelinedp_tpu/serve/service.py"),
    },
    "rng-purity": {
        # The issue's seeded regression: a raw jax.random.normal in
        # jax_engine.py.
        "bad": ("import jax\n\n"
                "def noise(key, shape):\n"
                "    return jax.random.normal(key, shape)\n",
                "pipelinedp_tpu/jax_engine.py"),
        "clean": ("import jax\n\n"
                  "def noise(key, shape):\n"
                  "    return jax.random.normal(key, shape)\n",
                  "pipelinedp_tpu/ops/noise.py"),
    },
    "blocking-under-lock": {
        # The issue's seeded regression: a durable (fsync'd) write
        # inside a with self._lock: body.
        "bad": ("import threading\n"
                "from pipelinedp_tpu.resilience.checkpoint import (\n"
                "    atomic_write_json)\n\n\n"
                "class Ledger:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n\n"
                "    def write(self, state):\n"
                "        with self._lock:\n"
                "            atomic_write_json('p.json', state)\n",
                "pipelinedp_tpu/serve/budget_ledger.py"),
        "clean": ("import threading\n"
                  "from pipelinedp_tpu.resilience.checkpoint import (\n"
                  "    atomic_write_json)\n\n\n"
                  "class Ledger:\n"
                  "    def __init__(self):\n"
                  "        self._lock = threading.Lock()\n\n"
                  "    def write(self, state):\n"
                  "        with self._lock:\n"
                  "            snap = dict(state)\n"
                  "        atomic_write_json('p.json', snap)\n",
                  "pipelinedp_tpu/serve/budget_ledger.py"),
    },
    "fusion-masking": {
        # A second pad/mask policy growing outside serve/fusion.py:
        # padding request arrays to a bucket shape (or dispatching the
        # batched kernel) anywhere else risks the engine seeing padded
        # rows without their validity mask.
        "bad": ("from pipelinedp_tpu.serve.fusion import (\n"
                "    pad_request_to_bucket)\n"
                "from pipelinedp_tpu import jax_engine as je\n\n"
                "def run_batch(encoded, rows, config, args):\n"
                "    padded = pad_request_to_bucket(encoded, rows,\n"
                "                                   True)\n"
                "    return je.fused_aggregate_batch_kernel(\n"
                "        config, 8, *args)\n",
                "pipelinedp_tpu/streaming.py"),
        # The blessed seam itself never scans (serve/fusion.py is the
        # rule's blessed module); the clean fixture shows the legal
        # shape elsewhere — consuming fusion RESULTS without building
        # padding.
        "clean": ("def summarize(batch_result):\n"
                  "    # mentions pad_request_to_bucket only in prose\n"
                  "    return len(batch_result)\n",
                  "pipelinedp_tpu/serve/service.py"),
    },
    "sketch-confinement": {
        # Raw builtin hash() on a key: process-salted, cannot replay —
        # bucket/candidate derivation must use the seeded stable hash.
        "bad": ("def shard_of(key, n):\n"
                "    return hash(key) % n\n",
                "pipelinedp_tpu/streaming.py"),
        # __hash__ protocol implementations are exempt (in-process
        # dict/set membership, not key bucketing), and calling the
        # blessed stable hash is the legal shape everywhere.
        "clean": ("from pipelinedp_tpu.sketch.hashing import (\n"
                  "    stable_hash_any)\n\n\n"
                  "class Metric:\n"
                  "    def __hash__(self):\n"
                  "        return hash((self.name, self.param))\n\n\n"
                  "def shard_of(key, n):\n"
                  "    return stable_hash_any(key) % n\n",
                  "pipelinedp_tpu/streaming.py"),
    },
    "socket-confinement": {
        # A second wire surface growing outside obs/http.py: any raw
        # socket / http.server / socketserver import elsewhere means
        # an accept-loop lifecycle the serve drain discipline cannot
        # see.
        "bad": ("import socket\n"
                "from http.server import HTTPServer\n\n"
                "def listen(port):\n"
                "    return HTTPServer(('', port), None)\n",
                "pipelinedp_tpu/serve/service.py"),
        # Client-side stdlib stays free (urllib is how tests scrape
        # the endpoint), and prose mentions never trip the AST rule.
        "clean": ("import urllib.request\n\n\n"
                  "def scrape(url):\n"
                  "    # docs may mention http.server freely\n"
                  "    with urllib.request.urlopen(url) as r:\n"
                  "        return r.read()\n",
                  "pipelinedp_tpu/serve/service.py"),
    },
    "collective-confinement": {
        # A raw collective outside parallel/sharded.py: invisible to
        # the mesh_topology knob, the ici/dcn byte meter and the
        # hier-vs-flat parity contract.
        "bad": ("import jax\n\n"
                "def combine(x, axis):\n"
                "    return jax.lax.psum_scatter(\n"
                "        x, axis, scatter_dimension=0, tiled=True)\n",
                "pipelinedp_tpu/streaming.py"),
        # The one blessed seam: sharded.py's exchange helpers own the
        # raw jax.lax calls.
        "clean": ("import jax\n\n"
                  "def combine_shards(x, axis, dim, replicate):\n"
                  "    if replicate:\n"
                  "        return jax.lax.psum(x, axis)\n"
                  "    return jax.lax.psum_scatter(\n"
                  "        x, axis, scatter_dimension=dim, tiled=True)\n",
                  "pipelinedp_tpu/parallel/sharded.py"),
    },
    "jit-staticness": {
        # PR 9's shape-blind knob-read bug class: ambient reads frozen
        # at trace time.
        "bad": ("import os\n"
                "import jax\n\n"
                "@jax.jit\n"
                "def kernel(x):\n"
                "    if os.environ.get('PIPELINEDP_TPU_CAP'):\n"
                "        return x\n"
                "    return x + 1\n",
                "pipelinedp_tpu/jax_engine.py"),
        "clean": ("import os\n"
                  "import jax\n\n"
                  "def host_helper(x):\n"
                  "    return os.environ.get('PIPELINEDP_TPU_CAP', x)\n"
                  "\n\n"
                  "@jax.jit\n"
                  "def kernel(x, cap):\n"
                  "    return x + cap\n",
                  "pipelinedp_tpu/jax_engine.py"),
    },
}


class TestRegistry:

    def test_every_legacy_make_lint_has_an_owner(self):
        owned = set(rules_mod.legacy_targets())
        assert owned == LEGACY_MAKE_LINTS

    def test_registry_is_exactly_the_known_rules(self):
        assert set(rules_mod.rule_ids()) == (
            LEGACY_MAKE_LINTS | NEW_ANALYSES)

    def test_every_rule_has_a_fixture_pair(self):
        assert set(FIXTURES) == set(rules_mod.rule_ids())
        for rid, pair in FIXTURES.items():
            assert {"bad", "clean"} <= set(pair), rid

    def test_rules_carry_their_prose(self):
        for rule in rules_mod.all_rules():
            assert rule.invariant, rule.id
            assert rule.fix_hint, rule.id


class TestRuleFixtures:

    @pytest.mark.parametrize("rule_id", sorted(FIXTURES))
    def test_bad_fixture_fires(self, rule_id):
        src, rel = FIXTURES[rule_id]["bad"]
        found = findings_for(rule_id, src, rel)
        assert found, f"{rule_id}: bad fixture produced no finding"
        for f in found:
            assert f.rule == rule_id and f.path == rel
            assert f.line >= 1 and f.message

    @pytest.mark.parametrize("rule_id", sorted(FIXTURES))
    def test_clean_fixture_passes(self, rule_id):
        src, rel = FIXTURES[rule_id]["clean"]
        assert findings_for(rule_id, src, rel) == [], rule_id


class TestRuleShapes:
    """Rule behaviors beyond the basic fire/pass pair."""

    def test_nostager_streaming_shape_checks(self):
        three = ("def _stream_impl(s):\n"
                 "    return BackgroundStager(s)\n\n"
                 "def run_sweep(s):\n"
                 "    return BackgroundStager(s)\n\n"
                 "def pass_b_tile(s):\n"
                 "    return BackgroundStager(s)\n")
        found = findings_for("nostager", three,
                             "pipelinedp_tpu/streaming.py")
        # Site #3 is doubly wrong: unblessed function AND over count.
        assert len(found) >= 2
        assert any("pass_b_tile" in f.message for f in found)

    def test_noperf_monitor_rejects_any_time_use(self):
        src = "import time\n\nDEADLINE = time.monotonic\n"
        found = findings_for("noperf", src,
                             "pipelinedp_tpu/obs/monitor.py")
        assert found, "monitor.py touching `time` must be a finding"
        # ... while other obs modules may import time freely.
        assert findings_for("noperf", src,
                            "pipelinedp_tpu/obs/store.py") == []

    def test_rng_purity_flags_stdlib_and_numpy_and_from_imports(self):
        src = ("import random\n"
               "import numpy as np\n"
               "from random import sample\n\n"
               "def f():\n"
               "    random.seed()\n"
               "    return np.random.default_rng(0)\n")
        found = findings_for("rng-purity", src,
                             "pipelinedp_tpu/streaming.py")
        msgs = "\n".join(f.message for f in found)
        assert "random.seed" in msgs
        assert "default_rng" in msgs
        assert "from-import" in msgs

    def test_rng_purity_ignores_annotations_and_docstrings(self):
        src = ('"""Mentions jax.random.normal and fold_in freely."""\n'
               "import numpy as np\n"
               "from typing import Optional\n\n\n"
               "def f(rng: Optional[np.random.Generator] = None):\n"
               "    return rng\n")
        assert findings_for("rng-purity", src,
                            "pipelinedp_tpu/streaming.py") == []

    def test_blocking_under_lock_nested_acquisition(self):
        src = ("import threading\n\n\n"
               "class S:\n"
               "    def __init__(self):\n"
               "        self._admit = threading.Lock()\n"
               "        self._books_lock = threading.Lock()\n\n"
               "    def f(self):\n"
               "        with self._admit:\n"
               "            with self._books_lock:\n"
               "                return 1\n")
        found = findings_for("blocking-under-lock", src,
                             "pipelinedp_tpu/serve/service.py")
        # The non-'lock'-named _admit is still recognized (assigned
        # from threading.Lock()), and the nested hold is the finding.
        assert len(found) == 1
        assert "nested lock" in found[0].message

    def test_blocking_under_lock_skips_deferred_bodies(self):
        src = ("import threading\n"
               "_lock = threading.Lock()\n\n\n"
               "def f(q):\n"
               "    with _lock:\n"
               "        return lambda: q.get()\n")
        assert findings_for("blocking-under-lock", src,
                            "pipelinedp_tpu/ingest/ring.py") == []

    def test_blocking_under_lock_queue_waits(self):
        src = ("import threading\n"
               "_lock = threading.Lock()\n\n\n"
               "def f(queue, opts):\n"
               "    with _lock:\n"
               "        item = queue.get()\n"
               "        flag = opts.get('x')\n"
               "    return item, flag\n")
        found = findings_for("blocking-under-lock", src,
                             "pipelinedp_tpu/ingest/ring.py")
        # dict-style .get on a non-queue receiver is NOT a finding.
        assert len(found) == 1
        assert ".get()" in found[0].message

    def test_jit_staticness_assigned_program_and_knob_read(self):
        src = ("from pipelinedp_tpu.obs.costs import instrumented_jit\n"
               "_Q_CHUNK = 8\n\n\n"
               "def _kernel(x):\n"
               "    return x * _Q_CHUNK\n\n\n"
               "program = instrumented_jit(_kernel, phase='pass_b')\n")
        found = findings_for("jit-staticness", src,
                             "pipelinedp_tpu/streaming.py")
        assert len(found) == 1
        assert "_Q_CHUNK" in found[0].message

    def test_nopallas_call_sites_without_import(self):
        # The import ban alone would miss attribute access through an
        # already-imported submodule — the legacy grep's pallas_call/
        # pl. call-site bans must survive the port.
        src = ("import jax\n\n"
               "def k(x):\n"
               "    return jax.experimental.pallas.pallas_call(x)\n")
        found = findings_for("nopallas", src,
                             "pipelinedp_tpu/streaming.py")
        assert len(found) == 1  # one violation, one finding
        src_pl = "def k(pl, x):\n    return pl.program_id(0) + x\n"
        assert findings_for("nopallas", src_pl,
                            "pipelinedp_tpu/streaming.py")

    def test_blocking_under_lock_direct_nested_region_counts_once(self):
        src = ("import threading\n"
               "import os\n"
               "_lock = threading.Lock()\n"
               "_io_lock = threading.Lock()\n\n\n"
               "def f(fd):\n"
               "    with _lock:\n"
               "        with _io_lock:\n"
               "            os.fsync(fd)\n")
        found = findings_for("blocking-under-lock", src,
                             "pipelinedp_tpu/ingest/ring.py")
        by_msg = sorted(f.message for f in found)
        # Exactly one nested-acquisition finding and one fsync finding
        # (from the inner region's own scan) — never duplicates.
        assert len(found) == 2, by_msg
        assert "fsync() inside a held lock body" in by_msg[0]
        assert "nested lock" in by_msg[1]

    def test_jit_staticness_megasweep_config_constants(self):
        """ISSUE-18's batched-sweep contract, as a lint fixture pair:
        config values (bounds, eps-splits, noise tables) must arrive as
        RUNTIME inputs to the jitted sweep kernels — a module-level
        config table read inside the traced body bakes the grid into
        the compiled program, and every new config batch recompiles."""
        bad = ("from pipelinedp_tpu.obs.costs import instrumented_jit\n"
               "from pipelinedp_tpu.plan import knobs as _knobs\n\n\n"
               "def _sweep_kernel(stats, noise_std):\n"
               "    width = _knobs.value('sweep_config_batch')\n"
               "    return stats * width + noise_std\n\n\n"
               "program = instrumented_jit(_sweep_kernel, "
               "phase='sweep')\n")
        found = findings_for("jit-staticness", bad,
                             "pipelinedp_tpu/analysis/jax_sweep.py")
        assert len(found) == 1
        assert "knobs.value" in found[0].message
        # Clean twin: the same kernel with the config axis as data —
        # one compiled program serves every config batch.
        clean = ("from pipelinedp_tpu.obs.costs import "
                 "instrumented_jit\n\n\n"
                 "def _sweep_kernel(stats, bounds_hi, noise_std):\n"
                 "    clipped = stats * bounds_hi\n"
                 "    return clipped + noise_std\n\n\n"
                 "program = instrumented_jit(_sweep_kernel, "
                 "phase='sweep')\n")
        assert findings_for(
            "jit-staticness", clean,
            "pipelinedp_tpu/analysis/jax_sweep.py") == []

    def test_jit_staticness_time_read(self):
        src = ("import time\n"
               "import jax\n\n\n"
               "@jax.jit\n"
               "def kernel(x):\n"
               "    return x + time.time()\n")
        found = findings_for("jit-staticness", src,
                             "pipelinedp_tpu/jax_engine.py")
        assert len(found) == 1 and "time.time" in found[0].message


class TestSuppressions:

    BAD_SLEEP = ("import time\n\n"
                 "def wait():\n"
                 "    time.sleep(0.5)  "
                 "# lint: disable=nosleep(fixture reason)\n")

    def test_reasoned_suppression_silences_and_is_counted(self):
        result = engine.lint_source(
            self.BAD_SLEEP, "pipelinedp_tpu/streaming.py",
            rules=[rules_mod.get("nosleep")])
        assert result.findings == []
        assert len(result.suppressed) == 1
        sup = result.suppressed[0]
        assert sup.rule == "nosleep" and sup.suppressed
        assert sup.reason == "fixture reason"
        assert result.suppressed_counts() == {"nosleep": 1}
        assert all(s.used for s in result.suppressions)

    def test_own_line_suppression_governs_next_code_line(self):
        src = ("import time\n\n"
               "def wait():\n"
               "    # lint: disable=nosleep(own-line fixture reason)\n"
               "    time.sleep(0.5)\n")
        result = engine.lint_source(
            src, "pipelinedp_tpu/streaming.py",
            rules=[rules_mod.get("nosleep")])
        assert result.findings == []
        assert len(result.suppressed) == 1

    def test_reasonless_suppression_does_not_suppress(self):
        src = ("import time\n\n"
               "def wait():\n"
               "    time.sleep(0.5)  # lint: disable=nosleep\n")
        result = engine.lint_source(
            src, "pipelinedp_tpu/streaming.py",
            rules=[rules_mod.get("nosleep")])
        rules_hit = {f.rule for f in result.findings}
        assert engine.SUPPRESSION_RULE in rules_hit  # the bad comment
        assert "nosleep" in rules_hit  # the original finding survives
        assert result.suppressed == []

    def test_unknown_rule_suppression_is_a_finding(self):
        src = "X = 1  # lint: disable=no-such-rule(typo)\n"
        result = engine.lint_source(src, "pipelinedp_tpu/streaming.py")
        assert any(f.rule == engine.SUPPRESSION_RULE and
                   "unknown rule" in f.message
                   for f in result.findings)

    def test_docstring_mention_is_not_a_suppression(self):
        src = ('"""Example: # lint: disable=nosleep(docs)"""\n'
               "import time\n\n"
               "def wait():\n"
               "    time.sleep(0.5)\n")
        result = engine.lint_source(
            src, "pipelinedp_tpu/streaming.py",
            rules=[rules_mod.get("nosleep")])
        assert len(result.findings) == 1  # NOT suppressed
        assert result.suppressions == []

    def test_unused_suppressions_are_reported(self):
        src = "X = 1  # lint: disable=nosleep(nothing here sleeps)\n"
        result = engine.lint_source(
            src, "pipelinedp_tpu/streaming.py",
            rules=[rules_mod.get("nosleep")])
        unused = result.unused_suppressions()
        assert len(unused) == 1 and unused[0].rule == "nosleep"


class TestWholeTree:
    """The acceptance runs `make lintcheck` rides on."""

    def test_tree_has_zero_unsuppressed_findings(self):
        result = engine.run(root=REPO)
        assert result.findings == [], "\n".join(
            f.format() for f in result.findings)
        assert result.files_scanned > 50

    def test_tree_suppressions_all_carry_reasons_and_are_used(self):
        result = engine.run(root=REPO)
        assert result.suppressed, (
            "the rng/lock audit left reasoned suppressions in the "
            "tree; their disappearance means the audit was reverted")
        for sup in result.suppressions:
            assert sup.used and sup.reason

    def test_check_tree_convenience(self):
        assert lint.check_tree("nosleep", "noserve", root=REPO) == []

    def test_cli_exits_zero_on_the_tree(self, capsys):
        assert cli.main([]) == 0
        out = capsys.readouterr().out
        assert "lint: OK" in out

    def test_cli_single_rule_and_unknown_rule(self, capsys):
        assert cli.main(["--rule", "nosleep"]) == 0
        assert cli.main(["--rule", "bogus"]) == 2
        err = capsys.readouterr().err
        assert "unknown lint rule" in err

    def test_cli_list_names_all_rules(self, capsys):
        assert cli.main(["--list"]) == 0
        out = capsys.readouterr().out
        for rid in rules_mod.rule_ids():
            assert rid in out


class TestJsonRoundTrip:
    """--json emits the obs/store.py envelope; a CI gate can append it
    to a run ledger and diff per-rule counts across runs."""

    def test_document_shape_and_json_round_trip(self):
        result = engine.run(root=REPO)
        doc = cli.findings_document(result, ts=123.0)
        assert doc["name"] == cli.RECORD_NAME
        assert doc["schema_version"] == cli.JSON_SCHEMA_VERSION
        back = json.loads(json.dumps(doc))
        assert back == doc
        payload = back["payload"]
        assert payload["ok"] is True
        assert payload["counts"] == {}
        assert set(payload["rules_run"]) == set(rules_mod.rule_ids())
        # Per-rule suppression counts are diffable numbers.
        for rule, n in payload["suppressed_counts"].items():
            assert rule in rules_mod.rule_ids() and n >= 1

    def test_round_trips_through_the_ledger_store(self, tmp_path,
                                                  monkeypatch):
        from pipelinedp_tpu.obs.store import LedgerStore
        result = engine.run(root=REPO)
        doc = cli.findings_document(result, ts=123.0)
        store = LedgerStore(str(tmp_path))
        store.append(doc["name"], doc["payload"])
        entry = store.entries()[-1]
        assert entry["name"] == cli.RECORD_NAME
        assert entry["payload"]["counts"] == doc["payload"]["counts"]
        assert (entry["payload"]["suppressed_counts"] ==
                doc["payload"]["suppressed_counts"])

    def test_cli_out_of_scope_path_is_loud(self, capsys, tmp_path):
        # A requested file no rule scopes over must never read as
        # "checked OK".
        p = tmp_path / "loose.py"
        p.write_text("import time\ntime.sleep(1)\n")
        assert cli.main([str(p)]) == 2
        out = capsys.readouterr().out
        assert "NOT checked" in out and "nothing was checked" in out

    def test_cli_json_output_parses(self, capsys):
        assert cli.main(["--json", "--rule", "nosleep"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["payload"]["ok"] is True
        assert doc["payload"]["rules_run"] == ["nosleep"]


class TestSeededRegressions:
    """The exact regressions the acceptance criteria name, driven
    through the same engine `make lintcheck` runs — proven caught."""

    def test_time_sleep_in_streaming_is_caught(self):
        real = open(os.path.join(REPO, "pipelinedp_tpu",
                                 "streaming.py"),
                    encoding="utf-8").read()
        seeded = real + "\n\ndef _seeded_wait():\n    time.sleep(1)\n"
        found = findings_for("nosleep", seeded,
                             "pipelinedp_tpu/streaming.py")
        assert len(found) == 1

    def test_atomic_write_under_lock_is_caught(self):
        real = open(os.path.join(REPO, "pipelinedp_tpu", "serve",
                                 "budget_ledger.py"),
                    encoding="utf-8").read()
        seeded = real + (
            "\n\ndef _seeded_write(self, state):\n"
            "    with self._lock:\n"
            "        atomic_write_json('x.json', state)\n")
        found = findings_for("blocking-under-lock", seeded,
                             "pipelinedp_tpu/serve/budget_ledger.py")
        assert len(found) == 1

    def test_raw_jax_random_normal_in_engine_is_caught(self):
        real = open(os.path.join(REPO, "pipelinedp_tpu",
                                 "jax_engine.py"),
                    encoding="utf-8").read()
        seeded = real + (
            "\n\ndef _seeded_noise(key, shape):\n"
            "    return jax.random.normal(key, shape)\n")
        found = findings_for("rng-purity", seeded,
                             "pipelinedp_tpu/jax_engine.py")
        assert len(found) == 1


class TestVectorSurfaces:
    """ISSUE-17's new files under the existing rules: the wide-D
    kernel keeps its pallas privileges, the device vector-noise seam
    is a blessed generator module — and NEITHER privilege leaks to
    the other file."""

    def test_nopallas_covers_the_wide_kernel_file(self):
        src = "from jax.experimental import pallas as pl\n"
        # The new kernel file carries the import like every kernels/
        # module ...
        assert findings_for("nopallas", src,
                            "pipelinedp_tpu/ops/kernels/segsum.py") == []
        # ... but the noise seam is NOT a kernel: a pallas import
        # there is a finding.
        assert findings_for("nopallas", src,
                            "pipelinedp_tpu/ops/vector_noise.py")

    def test_rng_purity_blesses_the_vector_noise_seam(self):
        src = ("import jax\n\n"
               "def unit(key, x0, x1):\n"
               "    k = jax.random.fold_in(key, 0x7EC)\n"
               "    return jax.random.normal(k, x0.shape)\n")
        # Blessed: the seam module draws and derives keys freely.
        assert findings_for("rng-purity", src,
                            "pipelinedp_tpu/ops/vector_noise.py") == []
        # The same draws anywhere else stay findings — the blessing
        # is the file, not the pattern.
        assert findings_for("rng-purity", src,
                            "pipelinedp_tpu/jax_engine.py")
        assert findings_for("rng-purity", src,
                            "pipelinedp_tpu/ops/kernels/segsum.py")

    def test_real_vector_noise_module_is_clean(self):
        real = open(os.path.join(REPO, "pipelinedp_tpu", "ops",
                                 "vector_noise.py"),
                    encoding="utf-8").read()
        result = engine.lint_source(real,
                                    "pipelinedp_tpu/ops/vector_noise.py")
        assert [f for f in result.findings] == []

    def test_real_wide_kernel_module_is_clean(self):
        real = open(os.path.join(REPO, "pipelinedp_tpu", "ops",
                                 "kernels", "segsum.py"),
                    encoding="utf-8").read()
        result = engine.lint_source(
            real, "pipelinedp_tpu/ops/kernels/segsum.py")
        assert [f for f in result.findings] == []
