"""Differential tests: the fused JAX plane vs the LocalBackend oracle.

Strategy (SURVEY.md §4/§7): run the same aggregation with huge eps on both
planes — noise vanishes, so the raw bounded aggregates must agree; plus
targeted tests of bounding, selection, public partitions and fallbacks.
Runs on the virtual 8-device CPU mesh configured in conftest.py.
"""

import operator

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.backends import JaxBackend
from pipelinedp_tpu.ops import noise as noise_ops

BIG_EPS = 1e5


def extractors():
    return pdp.DataExtractors(privacy_id_extractor=operator.itemgetter(0),
                              partition_extractor=operator.itemgetter(1),
                              value_extractor=operator.itemgetter(2))


def run(backend, data, params, public_partitions=None, eps=BIG_EPS,
        delta=1e-10, ext=None):
    acc = pdp.NaiveBudgetAccountant(total_epsilon=eps, total_delta=delta)
    engine = pdp.DPEngine(acc, backend)
    result = engine.aggregate(data, params, ext or extractors(),
                              public_partitions=public_partitions)
    acc.compute_budgets()
    return dict(result)


def count_params(**kw):
    base = dict(metrics=[pdp.Metrics.COUNT], max_partitions_contributed=3,
                max_contributions_per_partition=2)
    base.update(kw)
    return pdp.AggregateParams(**base)


class TestWideIdPacking:
    """Ids >= 2^16 ship as 3xuint8 planes over the host link; the pack /
    widen round trip must be exact at every width boundary."""

    @pytest.mark.parametrize("top", [(1 << 16) - 1, 1 << 16, (1 << 16) + 1,
                                     (1 << 24) - 1, 1 << 24])
    def test_roundtrip_at_boundaries(self, top):
        from pipelinedp_tpu import jax_engine as je
        ids = np.array([0, 1, 7, top - 1, top], np.int64)
        enc = je.EncodedData(pid=ids.astype(np.int64),
                             pk=np.arange(len(ids), dtype=np.int32),
                             values=np.zeros(len(ids), np.float32),
                             pk_vocab=list(range(len(ids))),
                             n_rows=len(ids))
        pid, pk, _, valid = je.pad_and_put(enc, None)
        got = np.asarray(pid)[:len(ids)]
        np.testing.assert_array_equal(got, ids)
        assert np.asarray(valid)[:len(ids)].all()

    def test_wide_ids_match_oracle(self):
        # pids and pks both above 2^16: the fused result must equal the
        # LocalBackend oracle partition by partition (caps never bind).
        rng = np.random.default_rng(5)
        n = 4000
        pid = rng.integers(70_000, 120_000, n)
        pk = rng.integers(0, 300, n) + 100_000
        vals = rng.uniform(0, 10, n)
        ds = pdp.ArrayDataset(privacy_ids=pid, partition_keys=pk,
                              values=vals)
        public = sorted(np.unique(pk).tolist())
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=20,
            max_contributions_per_partition=20,
            min_value=0.0, max_value=10.0)
        fused = run(JaxBackend(rng_seed=0), ds, params,
                    public_partitions=public, eps=1e6,
                    ext=pdp.DataExtractors())
        local = run(pdp.LocalBackend(), ds, params,
                    public_partitions=public, eps=1e6,
                    ext=pdp.DataExtractors())
        assert set(fused) == set(local) == set(public)
        for k in public:
            assert round(fused[k].count) == round(local[k].count), k
            assert fused[k].sum == pytest.approx(local[k].sum, abs=0.5), k


class TestFusedEdgeCases:
    """Degenerate shapes through the fused plane."""

    @staticmethod
    def _run(ds, public=None):
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=5,
            max_contributions_per_partition=5,
            min_value=0.0, max_value=10.0)
        return run(JaxBackend(rng_seed=0), ds, params,
                   public_partitions=public, eps=1e12, delta=1e-2,
                   ext=pdp.DataExtractors())

    def test_empty_rejected_like_reference(self):
        ds = pdp.ArrayDataset(privacy_ids=np.array([], np.int64),
                              partition_keys=np.array([], np.int64),
                              values=np.array([], np.float64))
        with pytest.raises(ValueError, match="non-empty"):
            self._run(ds)

    def test_single_row(self):
        got = self._run(
            pdp.ArrayDataset(privacy_ids=np.array([3]),
                             partition_keys=np.array([5]),
                             values=np.array([2.5])), public=[5])
        assert got[5].count == pytest.approx(1.0, abs=1e-3)
        assert got[5].sum == pytest.approx(2.5, abs=1e-3)

    def test_one_pid_one_partition_caps_bind(self):
        # 5000 identical contributions from one user: linf=5 keeps 5.
        got = self._run(
            pdp.ArrayDataset(privacy_ids=np.zeros(5000, np.int64),
                             partition_keys=np.zeros(5000, np.int64),
                             values=np.full(5000, 1.0)), public=[0])
        assert got[0].count == pytest.approx(5.0, abs=1e-2)
        assert got[0].sum == pytest.approx(5.0, abs=1e-2)

    def test_count_without_values_column(self):
        # values=None COUNT: the int32 count column must survive the
        # stacked transfer bit-exactly (on real TPUs, small ints bitcast
        # to float32 are subnormals and get flushed to zero).
        ds = pdp.ArrayDataset(privacy_ids=np.arange(500),
                              partition_keys=np.zeros(500, np.int64),
                              values=None)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1e12,
                                        total_delta=1e-2)
        engine = pdp.DPEngine(acc, JaxBackend(rng_seed=0))
        res = engine.aggregate(ds, params, pdp.DataExtractors(),
                               public_partitions=[0])
        acc.compute_budgets()
        assert dict(res)[0].count == pytest.approx(500, abs=0.01)

    def test_negative_keys_roundtrip(self):
        got = self._run(
            pdp.ArrayDataset(privacy_ids=np.array([-5, -5, 7]),
                             partition_keys=np.array([-9, -9, -9]),
                             values=np.array([1.0, 2.0, 3.0])),
            public=[-9])
        assert set(got) == {-9}
        assert got[-9].count == pytest.approx(3.0, abs=1e-2)
        assert got[-9].sum == pytest.approx(6.0, abs=1e-2)


class TestDifferentialVsLocal:

    def test_count(self):
        noise_ops.seed_host_rng(0)
        data = [(u, pk, 1.0) for u in range(50) for pk in ("a", "b", "c")]
        local = run(pdp.LocalBackend(), data, count_params())
        fused = run(JaxBackend(rng_seed=1), data, count_params())
        assert set(local) == set(fused) == {"a", "b", "c"}
        for k in local:
            assert fused[k].count == pytest.approx(local[k].count,
                                                   abs=0.5)

    def test_sum_mean_variance(self):
        noise_ops.seed_host_rng(0)
        rng = np.random.default_rng(0)
        data = [(u, "p" + str(u % 4), float(v))
                for u, v in enumerate(rng.uniform(0, 10, 400))]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.VARIANCE, pdp.Metrics.MEAN,
                     pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=1,
            max_contributions_per_partition=1, min_value=0.0,
            max_value=10.0)
        local = run(pdp.LocalBackend(), data, params)
        fused = run(JaxBackend(rng_seed=2), data, params)
        assert set(local) == set(fused)
        for k in local:
            assert fused[k].count == pytest.approx(local[k].count, abs=0.5)
            assert fused[k].sum == pytest.approx(local[k].sum, rel=0.01)
            assert fused[k].mean == pytest.approx(local[k].mean, abs=0.05)
            assert fused[k].variance == pytest.approx(local[k].variance,
                                                      abs=0.2)

    def test_sum_per_partition_bounds(self):
        noise_ops.seed_host_rng(0)
        data = [(u, "a", 100.0) for u in range(20)]  # each user sum 100
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.SUM], max_partitions_contributed=1,
            max_contributions_per_partition=5, min_sum_per_partition=0.0,
            max_sum_per_partition=10.0)
        fused = run(JaxBackend(rng_seed=3), data, params)
        # 20 users, each clipped to 10 -> 200.
        assert fused["a"].sum == pytest.approx(200.0, rel=0.01)

    def test_privacy_id_count(self):
        noise_ops.seed_host_rng(0)
        # 30 users, each with 5 rows in partition a.
        data = [(u, "a", 1.0) for u in range(30) for _ in range(5)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PRIVACY_ID_COUNT],
            max_partitions_contributed=1,
            max_contributions_per_partition=1)
        fused = run(JaxBackend(rng_seed=4), data, params)
        assert fused["a"].privacy_id_count == pytest.approx(30, abs=0.5)


class TestFusedBounding:

    def test_linf_caps_rows(self):
        noise_ops.seed_host_rng(0)
        data = [(0, "a", 1.0)] * 100  # one user, 100 rows
        params = count_params(max_partitions_contributed=1,
                              max_contributions_per_partition=7)
        fused = run(JaxBackend(rng_seed=5), data, params,
                    public_partitions=["a"])
        assert fused["a"].count == pytest.approx(7, abs=0.5)

    def test_l0_caps_partitions(self):
        noise_ops.seed_host_rng(0)
        pks = [f"p{i}" for i in range(10)]
        data = [(u, pk, 1.0) for u in range(200) for pk in pks]
        params = count_params(max_partitions_contributed=2,
                              max_contributions_per_partition=1)
        fused = run(JaxBackend(rng_seed=6), data, params,
                    public_partitions=pks)
        total = sum(v.count for v in fused.values())
        # Each user contributes to exactly 2 of 10 partitions.
        assert total == pytest.approx(400, rel=0.1)

    def test_sum_clipping(self):
        noise_ops.seed_host_rng(0)
        data = [(u, "a", 100.0) for u in range(10)]  # clipped to 10 each
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.SUM], max_partitions_contributed=1,
            max_contributions_per_partition=1, min_value=0.0,
            max_value=10.0)
        fused = run(JaxBackend(rng_seed=7), data, params)
        assert fused["a"].sum == pytest.approx(100.0, rel=0.01)


class TestFusedSelection:

    def test_small_partition_dropped(self):
        noise_ops.seed_host_rng(0)
        data = [(u, "big", 1.0) for u in range(1000)] + [(5000, "tiny",
                                                          1.0)]
        params = count_params(max_partitions_contributed=1,
                              max_contributions_per_partition=1)
        fused = run(JaxBackend(rng_seed=8), data, params, eps=1.0,
                    delta=1e-6)
        assert "big" in fused
        assert "tiny" not in fused

    @pytest.mark.parametrize("strategy", [
        pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC,
        pdp.PartitionSelectionStrategy.LAPLACE_THRESHOLDING,
        pdp.PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING,
    ])
    def test_strategies(self, strategy):
        noise_ops.seed_host_rng(0)
        data = [(u, "big", 1.0) for u in range(1000)]
        params = count_params(max_partitions_contributed=1,
                              max_contributions_per_partition=1,
                              partition_selection_strategy=strategy)
        fused = run(JaxBackend(rng_seed=9), data, params, eps=1.0,
                    delta=1e-6)
        assert "big" in fused

    def test_pre_threshold(self):
        noise_ops.seed_host_rng(0)
        data = [(u, "mid", 1.0) for u in range(50)]
        params = count_params(max_partitions_contributed=1,
                              max_contributions_per_partition=1,
                              pre_threshold=100)
        fused = run(JaxBackend(rng_seed=10), data, params, eps=BIG_EPS,
                    delta=1e-6)
        assert fused == {}


class TestFusedPublicPartitions:

    def test_empty_partition_injected(self):
        noise_ops.seed_host_rng(0)
        data = [(u, "a", 1.0) for u in range(40)]
        params = count_params()
        fused = run(JaxBackend(rng_seed=11), data, params,
                    public_partitions=["a", "missing"])
        assert fused["a"].count == pytest.approx(40, abs=0.5)
        assert fused["missing"].count == pytest.approx(0, abs=0.5)

    def test_non_public_dropped(self):
        noise_ops.seed_host_rng(0)
        data = [(u, pk, 1.0) for u in range(40) for pk in ("a", "b")]
        fused = run(JaxBackend(rng_seed=12), data, count_params(),
                    public_partitions=["a"])
        assert set(fused) == {"a"}


class TestFusedVectorSum:

    def test_vector_sum_linf(self):
        noise_ops.seed_host_rng(0)
        data = [(u, "a", [1.0, 2.0]) for u in range(50)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.VECTOR_SUM],
            max_partitions_contributed=1,
            max_contributions_per_partition=1, vector_size=2,
            vector_max_norm=1000.0,
            vector_norm_kind=pdp.NormKind.Linf)
        fused = run(JaxBackend(rng_seed=13), data, params)
        np.testing.assert_allclose(fused["a"].vector_sum, [50.0, 100.0],
                                   atol=1.0)

    def test_vector_sum_l2_clip(self):
        noise_ops.seed_host_rng(0)
        data = [(0, "a", [30.0, 40.0])]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.VECTOR_SUM],
            max_partitions_contributed=1,
            max_contributions_per_partition=1, vector_size=2,
            vector_max_norm=10.0, vector_norm_kind=pdp.NormKind.L2)
        fused = run(JaxBackend(rng_seed=14), data, params,
                    public_partitions=["a"])
        np.testing.assert_allclose(fused["a"].vector_sum, [6.0, 8.0],
                                   atol=0.1)


class TestBoundsAlreadyEnforcedFused:

    def test_no_pid(self):
        noise_ops.seed_host_rng(0)
        data = [("a", 4.0)] * 100
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.SUM], max_partitions_contributed=1,
            max_contributions_per_partition=1, min_value=0.0,
            max_value=10.0, contribution_bounds_already_enforced=True)
        ext = pdp.DataExtractors(partition_extractor=operator.itemgetter(0),
                                 value_extractor=operator.itemgetter(1))
        fused = run(JaxBackend(rng_seed=15), data, params, ext=ext)
        assert fused["a"].sum == pytest.approx(400.0, rel=0.01)


class TestFusedPercentile:

    def _percentile_params(self, ps, **kw):
        base = dict(metrics=[pdp.Metrics.PERCENTILE(p) for p in ps],
                    max_partitions_contributed=1,
                    max_contributions_per_partition=1, min_value=0.0,
                    max_value=100.0)
        base.update(kw)
        return pdp.AggregateParams(**base)

    def test_matches_local_oracle_at_big_eps(self):
        noise_ops.seed_host_rng(0)
        rng = np.random.default_rng(1)
        data = [(u, "ab"[u % 2], float(v))
                for u, v in enumerate(rng.uniform(0, 100, 2000))]
        params = self._percentile_params([50, 90])
        local = run(pdp.LocalBackend(), data, params)
        fused = run(JaxBackend(rng_seed=16), data, params)
        assert set(local) == set(fused)
        # Both walks share a tie quirk: when a rank exactly equals a
        # cumulative integer count, the (negligible) noise decides whether
        # the walk stops at a child's right edge or continues into a
        # zero-count sibling — an RNG-dependent jump of up to one child
        # width, identical in kind on both planes but resolved by
        # different RNGs. Hence tolerance ~ level-2 child width, not leaf.
        for k in local:
            true = np.percentile([v for _, p, v in data if p == k],
                                 [50, 90])
            assert fused[k].percentile_50 == pytest.approx(
                local[k].percentile_50, abs=0.5)
            assert fused[k].percentile_90 == pytest.approx(
                local[k].percentile_90, abs=0.5)
            assert fused[k].percentile_50 == pytest.approx(true[0],
                                                           abs=0.5)
            assert fused[k].percentile_90 == pytest.approx(true[1],
                                                           abs=0.5)

    def test_compound_with_other_metrics_field_order(self):
        noise_ops.seed_host_rng(0)
        data = [(u, "a", float(u % 100)) for u in range(1000)]
        params = self._percentile_params(
            [50], metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM,
                           pdp.Metrics.PERCENTILE(50)])
        local = run(pdp.LocalBackend(), data, params)
        fused = run(JaxBackend(rng_seed=17), data, params)
        assert local["a"]._fields == fused["a"]._fields
        assert fused["a"].count == pytest.approx(local["a"].count, abs=0.5)
        assert fused["a"].percentile_50 == pytest.approx(
            local["a"].percentile_50, abs=0.2)

    def test_degenerate_clip_range_rejected_at_params(self):
        """A zero-width clip range with percentiles fails at params
        construction with the cause named — not as a trace-time
        ZeroDivisionError or a ctor error deep in the pipeline."""
        with pytest.raises(ValueError, match="min_value < max_value"):
            self._percentile_params([50, 90], min_value=5.0,
                                    max_value=5.0)

    def test_tiny_clip_range_falls_back_to_host_path(self):
        """A valid but pathologically tiny range overflows the fused
        leaf constant in f32 — fusability must route it to the host
        path (f64), which still produces in-range percentiles."""
        from pipelinedp_tpu import jax_engine
        params = self._percentile_params([50], min_value=0.0,
                                         max_value=1e-35)
        assert not jax_engine.params_are_fusable(params)
        data = [(u, "a", 0.5e-35) for u in range(200)]
        fused = run(JaxBackend(rng_seed=29), data, params)
        assert 0.0 <= fused["a"].percentile_50 <= 1e-35

    def test_all_equal_values_hit_compaction_fallback(self):
        """Every row carries the same value, so every kept row lands in
        each walk's chosen subtree — the sub-histogram compaction prefix
        overflows and the lax.cond fallback (full-row scatters) must
        produce the same exact counts."""
        noise_ops.seed_host_rng(0)
        data = [(u, "ab"[u % 2], 42.0) for u in range(5000)]
        params = self._percentile_params([50, 90, 99])
        fused = run(JaxBackend(rng_seed=19), data, params)
        for k in ("a", "b"):
            # All mass at 42: every quantile lands within one leaf width
            # of it.
            assert fused[k].percentile_50 == pytest.approx(42.0, abs=0.1)
            assert fused[k].percentile_99 == pytest.approx(42.0, abs=0.1)

    def test_five_percentiles_cross_packed_group(self):
        """Q=5 exercises the second packed block-id word (4 ids per
        int32)."""
        noise_ops.seed_host_rng(0)
        rng = np.random.default_rng(7)
        data = [(u, "a", float(v))
                for u, v in enumerate(rng.uniform(0, 100, 4000))]
        params = self._percentile_params([10, 25, 50, 75, 90])
        fused = run(JaxBackend(rng_seed=20), data, params)
        vals = [v for _, _, v in data]
        for p, name in [(10, "percentile_10"), (25, "percentile_25"),
                        (50, "percentile_50"), (75, "percentile_75"),
                        (90, "percentile_90")]:
            assert getattr(fused["a"], name) == pytest.approx(
                np.percentile(vals, p), abs=0.5)

    def test_monotone_across_quantiles_at_small_eps(self):
        noise_ops.seed_host_rng(0)
        rng = np.random.default_rng(3)
        data = [(u, "a", float(v))
                for u, v in enumerate(rng.uniform(0, 100, 500))]
        params = self._percentile_params([90, 10, 50])
        fused = run(JaxBackend(rng_seed=18), data, params, eps=0.3,
                    delta=1e-6)
        t = fused["a"]
        assert t.percentile_10 <= t.percentile_50 <= t.percentile_90

    def test_deterministic_under_seed(self):
        data = [(u, "a", float(u % 50)) for u in range(300)]
        params = self._percentile_params([25, 75])
        outs = []
        for _ in range(2):
            noise_ops.seed_host_rng(0)
            outs.append(run(JaxBackend(rng_seed=19), data, params, eps=1.0,
                            delta=1e-6)["a"])
        assert outs[0] == outs[1]

    def test_sharded_matches_single_device(self):
        import jax
        from pipelinedp_tpu.parallel import make_mesh
        assert len(jax.devices()) >= 8
        noise_ops.seed_host_rng(0)
        rng = np.random.default_rng(5)
        data = [(u, f"p{u % 3}", float(v))
                for u, v in enumerate(rng.uniform(0, 100, 3000))]
        params = self._percentile_params([50, 99])
        single = run(JaxBackend(rng_seed=20), data, params)
        sharded = run(JaxBackend(mesh=make_mesh(8), rng_seed=20), data,
                      params)
        assert set(single) == set(sharded)
        for k in single:
            assert sharded[k].percentile_50 == pytest.approx(
                single[k].percentile_50, abs=0.5)
            assert sharded[k].percentile_99 == pytest.approx(
                single[k].percentile_99, abs=1.0)


class TestFallbacks:

    def test_noise_actually_added_at_small_eps(self):
        # Two different seeds must give different noisy outputs.
        data = [(u, "a", 1.0) for u in range(2000)]
        params = count_params(max_partitions_contributed=1,
                              max_contributions_per_partition=1)
        outs = []
        for seed in (20, 21):
            noise_ops.seed_host_rng(0)
            fused = run(JaxBackend(rng_seed=seed), data, params, eps=0.5,
                        delta=1e-6)
            outs.append(fused["a"].count)
        assert outs[0] != outs[1]
        # But both near the true count.
        for o in outs:
            assert o == pytest.approx(2000, rel=0.05)


class TestShardedMultiChip:
    """The multi-chip path on the virtual 8-device CPU mesh."""

    def _mesh(self):
        import jax
        from pipelinedp_tpu.parallel import make_mesh
        assert len(jax.devices()) >= 8, (
            "conftest must provide 8 virtual devices")
        return make_mesh(8)

    def test_matches_single_device(self):
        noise_ops.seed_host_rng(0)
        data = [(u, f"p{u % 5}", 3.0) for u in range(500)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=1,
            max_contributions_per_partition=1, min_value=0.0,
            max_value=10.0)
        single = run(JaxBackend(rng_seed=30), data, params)
        sharded = run(JaxBackend(mesh=self._mesh(), rng_seed=30), data,
                      params)
        assert set(single) == set(sharded)
        for k in single:
            assert sharded[k].count == pytest.approx(single[k].count,
                                                     abs=0.5)
            assert sharded[k].sum == pytest.approx(single[k].sum,
                                                   rel=0.01)

    def test_bounding_across_shards(self):
        noise_ops.seed_host_rng(0)
        # Users contribute to 10 partitions, L0=2: bounding must hold
        # globally even though rows are sharded by pid.
        pks = [f"p{i}" for i in range(10)]
        data = [(u, pk, 1.0) for u in range(160) for pk in pks]
        params = count_params(max_partitions_contributed=2,
                              max_contributions_per_partition=1)
        sharded = run(JaxBackend(mesh=self._mesh(), rng_seed=31), data,
                      params, public_partitions=pks)
        total = sum(v.count for v in sharded.values())
        assert total == pytest.approx(320, rel=0.1)

    def test_selection_on_mesh(self):
        noise_ops.seed_host_rng(0)
        data = [(u, "big", 1.0) for u in range(1000)] + [(5000, "tiny",
                                                          1.0)]
        params = count_params(max_partitions_contributed=1,
                              max_contributions_per_partition=1)
        sharded = run(JaxBackend(mesh=self._mesh(), rng_seed=32), data,
                      params, eps=1.0, delta=1e-6)
        assert "big" in sharded
        assert "tiny" not in sharded


class TestEnforcedBoundsSelectionEstimate:

    def test_rows_divided_by_max_rows_per_user(self):
        # Privacy regression (user-count estimate): with
        # contribution_bounds_already_enforced and linf=5, a partition with
        # 5 rows could be ONE user — selection must see ceil(5/5)=1 user
        # and (almost) never keep it, even though 5 users would often pass.
        noise_ops.seed_host_rng(0)
        data = [("solo", 1.0)] * 5
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT], max_partitions_contributed=1,
            max_contributions_per_partition=5,
            contribution_bounds_already_enforced=True)
        ext = pdp.DataExtractors(partition_extractor=operator.itemgetter(0),
                                 value_extractor=operator.itemgetter(1))
        kept = 0
        for seed in range(40):
            fused = run(JaxBackend(rng_seed=100 + seed), data, params,
                        eps=1.0, delta=1e-4, ext=ext)
            kept += "solo" in fused
        # P(keep | 1 user) <= delta = 1e-4: 40 trials should keep ~0.
        assert kept == 0


class TestShardedMultiChipBroad:
    """VERDICT r1 #8: VARIANCE, VECTOR_SUM, per-partition-bound SUM and
    public partitions on the 8-device mesh, each pinned to the
    single-device output."""

    def _mesh(self):
        import jax
        from pipelinedp_tpu.parallel import make_mesh
        assert len(jax.devices()) >= 8
        return make_mesh(8)

    def _both(self, data, params, seed, public=None):
        noise_ops.seed_host_rng(0)
        single = run(JaxBackend(rng_seed=seed), data, params,
                     public_partitions=public)
        noise_ops.seed_host_rng(0)
        sharded = run(JaxBackend(mesh=self._mesh(), rng_seed=seed), data,
                      params, public_partitions=public)
        assert set(single) == set(sharded)
        return single, sharded

    def test_variance_on_mesh(self):
        rng = np.random.default_rng(7)
        data = [(u, f"p{u % 4}", float(v))
                for u, v in enumerate(rng.uniform(0, 10, 2000))]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.VARIANCE, pdp.Metrics.MEAN,
                     pdp.Metrics.COUNT],
            max_partitions_contributed=2,
            max_contributions_per_partition=2,
            min_value=0.0, max_value=10.0)
        single, sharded = self._both(data, params, seed=41)
        for k in single:
            assert sharded[k].count == pytest.approx(single[k].count,
                                                     rel=0.02)
            assert sharded[k].mean == pytest.approx(single[k].mean,
                                                    abs=0.3)
            assert sharded[k].variance == pytest.approx(
                single[k].variance, rel=0.2, abs=0.5)

    def test_vector_sum_on_mesh(self):
        rng = np.random.default_rng(8)
        data = [(u, f"p{u % 3}", rng.uniform(-1, 1, 4))
                for u in range(600)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.VECTOR_SUM],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            vector_size=4, vector_max_norm=5.0,
            vector_norm_kind=pdp.NormKind.L2)
        single, sharded = self._both(data, params, seed=42)
        for k in single:
            np.testing.assert_allclose(sharded[k].vector_sum,
                                       single[k].vector_sum, atol=1.0)

    def test_per_partition_bound_sum_on_mesh(self):
        # Each user's per-partition sum is 30, clipped to 10: the clip
        # happens per (pid, pk) segment and must survive sharding.
        data = [(u, f"p{u % 2}", 10.0) for u in range(200) for _ in range(3)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.SUM], max_partitions_contributed=1,
            max_contributions_per_partition=5,
            min_sum_per_partition=0.0, max_sum_per_partition=10.0)
        single, sharded = self._both(data, params, seed=43)
        for k in single:
            assert single[k].sum == pytest.approx(1000.0, rel=0.02)
            assert sharded[k].sum == pytest.approx(single[k].sum,
                                                   rel=0.02)

    def test_public_partitions_on_mesh(self):
        data = [(u, f"p{u % 3}", 1.0) for u in range(300)]
        public = ["p0", "p1", "p2", "p_empty"]
        params = count_params(max_partitions_contributed=1,
                              max_contributions_per_partition=1)
        single, sharded = self._both(data, params, seed=44, public=public)
        assert sorted(sharded) == sorted(public)
        for k in public:
            assert sharded[k].count == pytest.approx(single[k].count,
                                                     abs=0.5)
        assert sharded["p_empty"].count == pytest.approx(0.0, abs=0.5)

    def test_max_contributions_on_mesh(self):
        # Total-cap bounding on the mesh: per-pid sampling is shard-local
        # (a pid's rows live on one shard), so sharded == single-device
        # up to the independent sample draw; with a non-binding cap both
        # equal the raw aggregates.
        data = [(u, f"p{i}", 2.0) for u in range(60) for i in range(3)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_contributions=5, min_value=0.0, max_value=10.0)
        single, sharded = self._both(data, params, seed=46,
                                     public=[f"p{i}" for i in range(3)])
        for k in single:
            assert sharded[k].count == pytest.approx(single[k].count,
                                                     abs=0.1)
            assert sharded[k].sum == pytest.approx(single[k].sum, abs=0.5)
            assert single[k].count == pytest.approx(60, abs=0.1)

    def test_uneven_shard_load(self):
        # One privacy id owns half the rows: hashing must still place all
        # its rows on one shard and results must match single-device.
        data = ([(0, "hot", 1.0)] * 500 +
                [(u, f"p{u % 4}", 1.0) for u in range(1, 401)])
        params = count_params(max_partitions_contributed=2,
                              max_contributions_per_partition=600)
        single, sharded = self._both(data, params, seed=45)
        for k in single:
            assert sharded[k].count == pytest.approx(single[k].count,
                                                     rel=0.05)


class TestFusedSelectPartitions:
    """select_partitions on the fused plane vs the host graph."""

    def _run(self, backend, data, l0=2, eps=BIG_EPS, delta=1e-2,
             pre_threshold=None):
        acc = pdp.NaiveBudgetAccountant(total_epsilon=eps,
                                        total_delta=delta)
        engine = pdp.DPEngine(acc, backend)
        ex = pdp.DataExtractors(
            privacy_id_extractor=operator.itemgetter(0),
            partition_extractor=operator.itemgetter(1))
        params = pdp.SelectPartitionsParams(
            max_partitions_contributed=l0, pre_threshold=pre_threshold)
        result = engine.select_partitions(data, params, ex)
        acc.compute_budgets()
        return sorted(result)

    def test_matches_local_at_huge_eps(self):
        noise_ops.seed_host_rng(0)
        data = [(u, f"p{u % 4}") for u in range(400)]
        local = self._run(pdp.LocalBackend(), data)
        fused = self._run(JaxBackend(rng_seed=50), data)
        assert local == fused == ["p0", "p1", "p2", "p3"]

    @pytest.mark.parametrize("seed", range(60, 66))
    def test_fuzz_populated_partitions_kept_on_both_planes(self, seed):
        # Random shapes: every partition with >= 40 distinct users must
        # be kept by both planes at huge eps; 1-user partitions must be
        # dropped by both at tiny delta.
        rng = np.random.default_rng(seed)
        n_parts = int(rng.integers(3, 12))
        data = []
        big = set()
        uid = 0
        for p in range(n_parts):
            # The last partition is always a singleton so the must-drop
            # branch below is exercised for every seed.
            users = 1 if p == n_parts - 1 else int(rng.integers(2, 80))
            if users >= 40:
                big.add(f"p{p}")
            for _ in range(users):
                data.append((uid, f"p{p}"))
                uid += 1
        lone = [f"p{n_parts - 1}"]
        noise_ops.seed_host_rng(seed)
        local = set(self._run(pdp.LocalBackend(), data, l0=n_parts,
                              delta=1e-6))
        fused = set(self._run(JaxBackend(rng_seed=seed), data, l0=n_parts,
                              delta=1e-6))
        for k in big:
            assert k in local and k in fused, (seed, k, local, fused)
        for k in lone:
            assert k not in local and k not in fused, (seed, k)

    def test_small_partition_dropped(self):
        data = [(u, "big") for u in range(2000)] + [(9999, "tiny")]
        fused = self._run(JaxBackend(rng_seed=51), data, eps=1.0,
                          delta=1e-6)
        assert "big" in fused and "tiny" not in fused

    def test_l0_bounding_limits_contributions(self):
        # One user in 50 partitions with l0=1: at most 1 partition sees a
        # contribution, so at huge eps at most 1 partition survives a
        # selection that needs >= 1 user.
        data = [(0, f"p{i}") for i in range(50)]
        fused = self._run(JaxBackend(rng_seed=52), data, l0=1)
        assert len(fused) <= 1

    def test_pre_threshold(self):
        data = [(u, "mid") for u in range(30)]
        kept = self._run(JaxBackend(rng_seed=53), data, l0=1,
                         pre_threshold=100)
        assert kept == []  # 30 users < pre_threshold 100

    def test_on_mesh(self):
        from pipelinedp_tpu.parallel import make_mesh
        noise_ops.seed_host_rng(0)
        data = [(u, f"p{u % 3}") for u in range(300)]
        fused = self._run(JaxBackend(mesh=make_mesh(8), rng_seed=54),
                          data)
        assert fused == ["p0", "p1", "p2"]

    def test_duplicate_contributions_counted_once(self):
        # A pid contributing many rows to one partition counts once.
        data = [(0, "a")] * 100 + [(1, "a")] * 100
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                        total_delta=1e-6)
        engine = pdp.DPEngine(acc, JaxBackend(rng_seed=55))
        ex = pdp.DataExtractors(
            privacy_id_extractor=operator.itemgetter(0),
            partition_extractor=operator.itemgetter(1))
        result = engine.select_partitions(
            data, pdp.SelectPartitionsParams(max_partitions_contributed=1),
            ex)
        acc.compute_budgets()
        # 2 distinct users: with delta=1e-6 a 2-user partition is
        # (nearly) never kept; 200 rows must not inflate the count.
        assert list(result) == []


class TestFusedSelectMore:
    """Extra fused select_partitions coverage: columnar input, all
    strategies, report stages."""

    def _select(self, data, l0=4, eps=BIG_EPS, delta=1e-2, strategy=None,
                seed=60, ex=None):
        acc = pdp.NaiveBudgetAccountant(total_epsilon=eps,
                                        total_delta=delta)
        engine = pdp.DPEngine(acc, JaxBackend(rng_seed=seed))
        kw = dict(max_partitions_contributed=l0)
        if strategy is not None:
            kw["partition_selection_strategy"] = strategy
        result = engine.select_partitions(
            data, pdp.SelectPartitionsParams(**kw),
            ex or pdp.DataExtractors(
                privacy_id_extractor=operator.itemgetter(0),
                partition_extractor=operator.itemgetter(1)))
        acc.compute_budgets()
        return sorted(result), engine

    def test_array_dataset_input(self):
        # pid stride 101 is coprime to the 4 partitions: users genuinely
        # span partitions, exercising columnar cross-partition bounding.
        ds = pdp.ArrayDataset(privacy_ids=np.arange(500) % 101,
                              partition_keys=np.arange(500) % 4)
        kept, _ = self._select(ds, ex=pdp.DataExtractors())
        assert kept == [0, 1, 2, 3]

    @pytest.mark.parametrize("strategy", list(
        pdp.PartitionSelectionStrategy))
    def test_all_strategies(self, strategy):
        data = [(u, "only") for u in range(500)]
        kept, engine = self._select(data, l0=1, eps=1.0, delta=1e-6,
                                    strategy=strategy, seed=61)
        assert kept == ["only"]
        # The configured strategy must actually reach the fused plane.
        report = engine.explain_computations_report()[0]
        assert f"using {strategy.value}" in report

    def test_report_stages(self):
        data = [(u, "a") for u in range(10)]
        kept, engine = self._select(data, l0=2, eps=1.0, delta=1e-6,
                                    seed=62)
        report = engine.explain_computations_report()[0]
        assert "Cross-partition contribution bounding" in report
        assert "Private Partition selection" in report
        assert "eps=" in report


class TestPartitionAxisSharding:
    """VERDICT r2 #1: the pk axis is sharded over the mesh — per-device
    accumulator state is O(P/n_dev) (owner blocks via psum_scatter), and
    owner-mode selection reproduces the single-chip decisions
    bit-for-bit."""

    def _mesh(self, n=8):
        import jax
        from pipelinedp_tpu.parallel import make_mesh
        assert len(jax.devices()) >= n
        return make_mesh(n)

    def test_outputs_are_partition_sharded(self):
        # The returned accumulator arrays must be sharded over the mesh:
        # every device holds exactly its P/n_dev owner block, not a
        # replica of the full axis.
        import jax
        from pipelinedp_tpu import jax_engine as je
        from pipelinedp_tpu.parallel import sharded_fused_aggregate

        mesh = self._mesh()
        P = 1 << 12
        rng = np.random.default_rng(0)
        n = 4096
        pid = rng.integers(0, 500, n).astype(np.int32)
        pk = rng.integers(0, P, n).astype(np.int32)
        config = je.FusedConfig.from_params(count_params(), public=False)
        keep_table, thr, s_scale, min_count = je.selection_inputs(
            config, 1.0, 1e-6, None)
        keep, out = sharded_fused_aggregate(
            mesh, config, P, pid, pk, None, np.ones(n, bool),
            np.zeros(0, np.float32), keep_table, thr, s_scale, min_count,
            1.0, jax.random.PRNGKey(0))
        for arr in [keep] + list(out.values()):
            shard_shapes = {s.data.shape for s in arr.addressable_shards}
            assert shard_shapes == {(P // 8,)}, (
                f"expected owner blocks of {P // 8}, got {shard_shapes}")

    def test_selection_bit_parity_with_single_chip(self):
        # Same seed, bounding that never binds => the mesh's selection
        # decisions (drawn globally, sliced per owner) must EQUAL the
        # single-chip ones, and the int count accumulators exactly too.
        noise_ops.seed_host_rng(0)
        rng = np.random.default_rng(3)
        data = [(u, f"p{rng.integers(0, 200)}", 1.0) for u in range(3000)]
        params = count_params(max_partitions_contributed=64,
                              max_contributions_per_partition=8)
        single = run(JaxBackend(rng_seed=77), data, params, eps=1.0,
                     delta=1e-6)
        noise_ops.seed_host_rng(0)
        sharded = run(JaxBackend(mesh=self._mesh(), rng_seed=77), data,
                      params, eps=1.0, delta=1e-6)
        assert set(single) == set(sharded)

    def test_large_partition_axis_on_mesh(self):
        # A pk axis of 2^20 partitions: per-device owner blocks are 2^17
        # — the dense axis never materializes replicated per device
        # (pre-r3 the full 2^20-vector was psum'd to every chip).
        import jax
        from pipelinedp_tpu import jax_engine as je
        from pipelinedp_tpu.parallel import sharded_fused_aggregate

        mesh = self._mesh()
        P = 1 << 20
        rng = np.random.default_rng(1)
        n = 1 << 15
        pid = rng.integers(0, 2000, n).astype(np.int32)
        pk = rng.integers(0, P, n).astype(np.int32)
        config = je.FusedConfig.from_params(
            count_params(max_partitions_contributed=1 << 20,
                         max_contributions_per_partition=8), public=False)
        keep_table, thr, s_scale, min_count = je.selection_inputs(
            config, BIG_EPS, 1e-6, None)
        keep, out = sharded_fused_aggregate(
            mesh, config, P, pid, pk, None, np.ones(n, bool),
            np.zeros(0, np.float32), keep_table, thr, s_scale, min_count,
            1.0, jax.random.PRNGKey(5))
        assert {s.data.shape for s in out["count"].addressable_shards
                } == {(P // 8,)}
        counts = np.asarray(out["count"])
        expected = np.bincount(pk, minlength=P)
        np.testing.assert_array_equal(counts, expected)


class TestFixedPointAccumulation:
    """VERDICT r2 weak #2 / next #4: value partials accumulate as exact
    fixed-point int32 lanes on device (``_fixedpoint_layout``), leaving
    only the per-row quantization error (bound/2^23, independent of
    partition size). A partition of ~10^7 identical values is where a
    monolithic f32 segment_sum provably drifts (f32 addition of 1.0
    saturates outright at 2^24 = 16777216); the fused release must match
    the float64 oracle bit-close."""

    def test_huge_identical_partition_sum(self):
        import jax
        import jax.numpy as jnp

        n = 1 << 23  # 8.4M rows, one partition — past f32 saturation
        vals = jnp.ones(n, jnp.float32) * 1.5
        ids = jnp.zeros(n, jnp.int32)
        # The monolithic f32 segment_sum demonstrably drifts here...
        plain = float(np.asarray(jax.ops.segment_sum(vals, ids, 4))[0])
        assert abs(plain - 1.5 * n) > 1000
        # ...while the fused engine's release is quantization-accurate.
        ds = pdp.ArrayDataset(privacy_ids=np.arange(n) % (1 << 20),
                              partition_keys=np.zeros(n, np.int64),
                              values=np.full(n, 1.5))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.SUM], max_partitions_contributed=8,
            max_contributions_per_partition=8, min_value=0.0,
            max_value=10.0)
        fused = run(JaxBackend(rng_seed=0), ds, params,
                    ext=pdp.DataExtractors())
        assert fused[0].sum == pytest.approx(1.5 * n, rel=1e-6)

    def test_fused_mean_variance_at_scale_matches_oracle(self):
        # End-to-end: one hot partition with 2^21 rows of the same value;
        # huge eps so noise vanishes. The f64 oracle mean is exactly the
        # value and the variance 0 — pre-compensation the fused f32
        # accumulation drifted both.
        n = 1 << 21
        rng = np.random.default_rng(0)
        ds = pdp.ArrayDataset(
            privacy_ids=np.arange(n) % (1 << 20),
            partition_keys=np.zeros(n, np.int64),
            values=np.full(n, 7.25, np.float64))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.MEAN, pdp.Metrics.VARIANCE,
                     pdp.Metrics.SUM],
            max_partitions_contributed=8,
            max_contributions_per_partition=8,
            min_value=0.0, max_value=10.0)
        fused = run(JaxBackend(rng_seed=0), ds, params,
                    ext=pdp.DataExtractors())
        got = fused[0]
        assert got.sum == pytest.approx(7.25 * n, rel=1e-7)
        assert got.mean == pytest.approx(7.25, abs=1e-6)
        assert got.variance == pytest.approx(0.0, abs=1e-4)


class TestAdaptiveLanePlan:
    """The fixed-point lane width adapts to the global row count: small
    datasets ride 2 wide lanes, huge ones 6 narrow lanes; every plan's
    int32 lane accumulators stay exact (n * (2^bits - 1) < 2^31)."""

    @pytest.mark.parametrize("n,bits,lanes", [
        (1 << 10, 12, 2), (1 << 19, 12, 2), (1 << 20, 11, 3),
        (1 << 23, 8, 3), (1 << 24, 7, 4), (1 << 26, 5, 5),
        (1 << 27, 4, 6),
    ])
    def test_plan(self, n, bits, lanes):
        from pipelinedp_tpu import jax_engine as je
        got_bits, got_lanes = je._fx_plan(n)
        assert (got_bits, got_lanes) == (bits, lanes)
        assert n * ((1 << got_bits) - 1) < (1 << 31)

    def test_beyond_capacity_raises(self):
        from pipelinedp_tpu import jax_engine as je
        with pytest.raises(NotImplementedError, match="2\\^27"):
            je._fx_plan(1 << 28)

    def test_no_value_columns_skip_the_plan(self, monkeypatch):
        """COUNT/PRIVACY_ID_COUNT-only pipelines use no fixed-point
        lanes, so the lane-capacity plan (and its row cap) must never
        run for them — counts are exact int32 to 2^31 rows."""
        from pipelinedp_tpu import jax_engine as je

        def boom(n):
            raise AssertionError("_fx_plan must not run for count-only")

        monkeypatch.setattr(je, "_fx_plan", boom)
        ds = pdp.ArrayDataset(privacy_ids=np.arange(100) % 10,
                              partition_keys=np.arange(100) % 5,
                              values=None)
        params = count_params(max_partitions_contributed=2,
                              max_contributions_per_partition=2)
        fused = run(JaxBackend(rng_seed=0), ds, params, eps=1e6,
                    delta=1e-2, ext=pdp.DataExtractors())
        assert len(fused) == 5


class TestCompactFetchFallback:
    """Private selection keeping more partitions than the packed-fetch
    cap (8192) must fall back to the full fetch and still release every
    kept partition."""

    def test_many_kept_partitions(self):
        n_parts = 10_000
        users_per = 3
        pid = np.arange(n_parts * users_per)  # every row its own user
        pk = np.repeat(np.arange(n_parts), users_per)
        ds = pdp.ArrayDataset(privacy_ids=pid, partition_keys=pk,
                              values=None)
        params = count_params(max_partitions_contributed=1,
                              max_contributions_per_partition=1)
        fused = run(JaxBackend(rng_seed=0), ds, params, eps=1e6,
                    delta=1e-2, ext=pdp.DataExtractors())
        # With eps huge every 3-user partition passes selection.
        assert len(fused) == n_parts
        assert fused[0].count == pytest.approx(3, abs=0.3)
        assert fused[n_parts - 1].count == pytest.approx(3, abs=0.3)


class TestLanePlanBoundary:
    """End-to-end coverage of the non-default lane plans: row counts just
    past a plan boundary switch the kernel to narrower lanes, whose
    released sums must still match the exact float64 oracle within the
    quantization bound (n * bound / 2^23)."""

    @pytest.mark.parametrize("n", [(1 << 19) - 8, 525_000])
    def test_sum_across_plan_boundary(self, n):
        from pipelinedp_tpu import jax_engine as je
        bits, lanes = je._fx_plan(n)
        assert (bits, lanes) == ((12, 2) if n < 524_417 else (11, 3))
        rng = np.random.default_rng(n)
        vals = rng.uniform(0.0, 10.0, n)
        ds = pdp.ArrayDataset(privacy_ids=np.arange(n) % (1 << 18),
                              partition_keys=np.zeros(n, np.int64),
                              values=vals)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.SUM], max_partitions_contributed=4,
            max_contributions_per_partition=4, min_value=0.0,
            max_value=10.0)
        fused = run(JaxBackend(rng_seed=0), ds, params, eps=1e12,
                    delta=1e-2, ext=pdp.DataExtractors())
        exact = float(np.sum(vals))
        # Quantization bound: every row rounds on a bound/2^23 grid (the
        # inputs also pass through float32 encode, same error scale).
        bound = n * (10.0 / (1 << 23)) + n * 10.0 * 2**-24 + 1.0
        assert abs(fused[0].sum - exact) < bound
