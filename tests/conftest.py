"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic
(`jax.sharding.Mesh` + shard_map collectives) is exercised without TPU
hardware; the real-TPU path is covered by bench.py and __graft_entry__.py.
This must run before anything imports jax.
"""

import os

# Force, don't setdefault: the ambient environment may export
# JAX_PLATFORMS=axon (the real TPU tunnel), and tests must never depend on
# TPU hardware. jax may already be pre-imported at interpreter startup, so
# the env var alone is too late — backend selection is lazy, and
# jax.config.update still wins as long as no computation has run yet.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
