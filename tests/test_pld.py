"""Tests for the discretized PLD engine against closed-form ground truth.

Mirrors the reference's PLD accountant tests
(``tests/budget_accounting_test.py:198`` onward) but checks our own engine
against analytic formulas instead of the external dp_accounting library.
"""

import math

import numpy as np
import pytest

from pipelinedp_tpu import pld
from pipelinedp_tpu.aggregate_params import MechanismType
from pipelinedp_tpu.budget_accounting import PLDBudgetAccountant


def analytic_gaussian_delta(eps: float, sigma: float, s: float = 1.0):
    """Exact delta(eps) of the Gaussian mechanism (Balle & Wang 2018)."""

    def phi(z):
        return 0.5 * (1 + math.erf(z / math.sqrt(2)))

    return phi(s / (2 * sigma) - eps * sigma / s) - math.exp(eps) * phi(
        -s / (2 * sigma) - eps * sigma / s)


class TestGaussianPLD:

    @pytest.mark.parametrize("sigma,eps", [(1.0, 1.0), (2.0, 0.5),
                                           (0.5, 3.0), (4.0, 0.1)])
    def test_delta_matches_analytic(self, sigma, eps):
        p = pld.gaussian_pld(sigma, sensitivity=1.0, discretization=1e-4)
        expected = analytic_gaussian_delta(eps, sigma)
        got = p.delta_for_epsilon(eps)
        # Pessimistic rounding: got >= expected, but close.
        assert got >= expected - 1e-6
        assert got == pytest.approx(expected, abs=5e-4)

    def test_composition_equals_scaled_sensitivity(self):
        # k-fold composition of Gaussian(sigma, s=1) == single Gaussian with
        # sensitivity sqrt(k) (losses are normal; means/variances add).
        k, sigma, eps = 4, 2.0, 1.0
        single = pld.gaussian_pld(sigma, discretization=1e-4)
        composed = single.self_compose(k)
        expected = analytic_gaussian_delta(eps, sigma, s=math.sqrt(k))
        assert composed.delta_for_epsilon(eps) == pytest.approx(expected,
                                                                abs=2e-3)

    def test_mass_conservation(self):
        p = pld.gaussian_pld(1.0)
        assert p.probs.sum() + p.infinity_mass == pytest.approx(1.0, abs=1e-9)


class TestLaplacePLD:

    def test_pure_dp_above_eps(self):
        # Laplace(b=1, s=1) is 1-DP: delta(eps) == 0 for eps >= 1.
        p = pld.laplace_pld(1.0, sensitivity=1.0)
        assert p.delta_for_epsilon(1.0 + 1e-3) == pytest.approx(0.0, abs=1e-9)

    def test_delta_at_zero_matches_tv_distance(self):
        # delta(0) = TV(Lap(0,b), Lap(s,b)) = 1 - e^(-s/(2b)).
        b, s = 1.0, 1.0
        p = pld.laplace_pld(b, sensitivity=s)
        expected = 1 - math.exp(-s / (2 * b))
        assert p.delta_for_epsilon(0.0) == pytest.approx(expected, abs=5e-4)

    def test_atom_at_max_loss(self):
        # P(L = s/b) = 1/2 (all x <= 0). The topmost bucket must hold ~1/2.
        p = pld.laplace_pld(1.0, sensitivity=1.0)
        assert p.probs[-1] == pytest.approx(0.5, abs=1e-3)

    def test_composition_of_two_laplace(self):
        # delta(eps) of 2 compositions at eps = 2*s/b must be 0 (pure DP
        # composition: eps totals add).
        p = pld.laplace_pld(1.0).self_compose(2)
        assert p.delta_for_epsilon(2.0 + 1e-2) == pytest.approx(0.0,
                                                                abs=1e-9)
        # And strictly positive below the total eps.
        assert p.delta_for_epsilon(1.0) > 1e-4


class TestPureDpPLD:

    def test_delta_profile(self):
        eps0, delta0 = 1.0, 1e-3
        p = pld.pure_dp_pld(eps0, delta0)
        assert p.delta_for_epsilon(eps0) == pytest.approx(delta0, abs=1e-9)
        assert p.delta_for_epsilon(0.0) > delta0


class TestFindMinimumNoiseStd:

    def test_single_gaussian_matches_analytic_calibration(self):
        eps, delta = 1.0, 1e-6
        std = pld.find_minimum_noise_std(
            [(MechanismType.GAUSSIAN, 1.0, 1.0)], eps, delta,
            discretization=1e-3)
        # Check the analytic delta at the found sigma is <= delta and that
        # slightly less noise would violate it.
        assert analytic_gaussian_delta(eps, std) <= delta
        assert analytic_gaussian_delta(eps, std * 0.9) > delta

    def test_single_laplace_close_to_pure_dp_scale(self):
        # One Laplace mechanism, delta tiny: b -> s/eps, std = b*sqrt(2).
        eps, delta = 1.0, 1e-9
        std = pld.find_minimum_noise_std(
            [(MechanismType.LAPLACE, 1.0, 1.0)], eps, delta,
            discretization=1e-3)
        expected = math.sqrt(2.0) / eps
        assert std == pytest.approx(expected, rel=0.05)

    def test_more_mechanisms_need_more_noise(self):
        eps, delta = 1.0, 1e-6
        one = pld.find_minimum_noise_std([(MechanismType.GAUSSIAN, 1.0, 1.0)],
                                         eps, delta, discretization=1e-3)
        four = pld.find_minimum_noise_std(
            [(MechanismType.GAUSSIAN, 1.0, 1.0)] * 4, eps, delta,
            discretization=1e-3)
        assert four > one
        # Advanced composition: roughly sqrt(4)=2x, certainly < 4x (naive).
        assert four < 4 * one
        assert four == pytest.approx(2 * one, rel=0.15)

    def test_weight_scales_noise(self):
        eps, delta = 1.0, 1e-6
        mechs = [(MechanismType.GAUSSIAN, 1.0, 1.0),
                 (MechanismType.GAUSSIAN, 1.0, 3.0)]
        std = pld.find_minimum_noise_std(mechs, eps, delta,
                                         discretization=1e-3)
        assert std > 0  # weighted mechanisms compose; smoke-level check


class TestPLDBudgetAccountant:

    def test_end_to_end_fills_noise_std(self):
        acc = PLDBudgetAccountant(total_epsilon=1.0, total_delta=1e-6,
                                  pld_discretization=1e-3)
        spec_g = acc.request_budget(MechanismType.GAUSSIAN, sensitivity=2.0)
        spec_l = acc.request_budget(MechanismType.LAPLACE, sensitivity=1.0)
        acc.compute_budgets()
        assert acc.minimum_noise_std is not None
        assert spec_g.noise_standard_deviation == pytest.approx(
            2.0 * acc.minimum_noise_std)
        assert spec_l.noise_standard_deviation == pytest.approx(
            acc.minimum_noise_std)

    def test_generic_mechanism_gets_eps_delta(self):
        acc = PLDBudgetAccountant(total_epsilon=1.0, total_delta=1e-6,
                                  pld_discretization=1e-3)
        spec = acc.request_budget(MechanismType.GENERIC)
        acc.compute_budgets()
        assert spec.eps > 0
        assert spec.delta > 0

    def test_zero_delta_uses_laplace_closed_form(self):
        # Reference budget_accounting.py:509-514: delta=0 =>
        # minimum_noise_std = sum(weights)/eps * sqrt(2).
        acc = PLDBudgetAccountant(total_epsilon=2.0, total_delta=0.0)
        spec = acc.request_budget(MechanismType.LAPLACE, weight=1.0)
        acc.request_budget(MechanismType.LAPLACE, weight=3.0)
        acc.compute_budgets()
        assert acc.minimum_noise_std == pytest.approx(4.0 / 2.0 *
                                                      math.sqrt(2.0))
        assert spec.noise_standard_deviation == pytest.approx(
            acc.minimum_noise_std)

    def test_compute_budgets_inside_scope_raises(self):
        acc = PLDBudgetAccountant(total_epsilon=1.0, total_delta=1e-6)
        with pytest.raises(Exception, match="within a budget scope"):
            with acc.scope(weight=1.0):
                acc.request_budget(MechanismType.GAUSSIAN)
                acc.compute_budgets()

    def test_naive_compute_budgets_inside_scope_raises(self):
        from pipelinedp_tpu.budget_accounting import NaiveBudgetAccountant
        acc = NaiveBudgetAccountant(total_epsilon=1.0, total_delta=1e-6)
        with pytest.raises(Exception, match="within a budget scope"):
            with acc.scope(weight=1.0):
                acc.request_budget(MechanismType.LAPLACE)
                acc.compute_budgets()

    def test_less_noise_than_naive_for_many_mechanisms(self):
        # The whole point of PLD accounting: with many mechanisms the
        # required noise grows ~sqrt(k), not k.
        k, eps, delta = 9, 1.0, 1e-6
        acc = PLDBudgetAccountant(total_epsilon=eps, total_delta=delta,
                                  pld_discretization=1e-3)
        specs = [
            acc.request_budget(MechanismType.GAUSSIAN) for _ in range(k)
        ]
        acc.compute_budgets()
        pld_std = specs[0].noise_standard_deviation
        # Naive split: each mechanism gets eps/k -> sigma grows ~linearly.
        naive_single = pld.find_minimum_noise_std(
            [(MechanismType.GAUSSIAN, 1.0, 1.0)], eps / k, delta / k,
            discretization=1e-3)
        assert pld_std < naive_single


class TestPLDWithEngine:
    """The PLD accountant drives DPEngine end-to-end — a capability the
    reference's PLD accountant lacks (reference budget_accounting.py:406
    'not yet compatible with DPEngine'). The granted noise level is
    published as equivalent per-mechanism (eps, delta) whose standard
    calibration round-trips exactly."""

    @pytest.mark.parametrize("kind", ["laplace", "gaussian"])
    def test_engine_end_to_end(self, kind):
        import operator
        import pipelinedp_tpu as pdp
        from pipelinedp_tpu.backends import JaxBackend
        from pipelinedp_tpu.ops import noise as noise_ops

        data = [(u, p, 1.0) for u in range(200) for p in ("a", "b")]
        ex = pdp.DataExtractors(
            privacy_id_extractor=operator.itemgetter(0),
            partition_extractor=operator.itemgetter(1),
            value_extractor=operator.itemgetter(2))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            noise_kind=pdp.NoiseKind(kind),
            max_partitions_contributed=2,
            max_contributions_per_partition=1)
        for backend in (pdp.LocalBackend(), JaxBackend(rng_seed=3)):
            noise_ops.seed_host_rng(0)
            acc = PLDBudgetAccountant(
                total_epsilon=20.0, total_delta=1e-6)
            engine = pdp.DPEngine(acc, backend)
            result = engine.aggregate(data, params, ex)
            acc.compute_budgets()
            out = dict(result)
            assert sorted(out) == ["a", "b"]
            for v in out.values():
                assert v.count == pytest.approx(200, rel=0.15)

    def test_gaussian_equivalent_roundtrip(self):
        from pipelinedp_tpu.ops import noise as noise_ops
        acc = PLDBudgetAccountant(total_epsilon=3.0,
                                                    total_delta=1e-6)
        spec = acc.request_budget(MechanismType.GAUSSIAN)
        acc.compute_budgets()
        granted = spec.noise_standard_deviation
        recomputed = noise_ops.gaussian_sigma(spec.eps, spec.delta, 1.0)
        assert recomputed == pytest.approx(granted, rel=1e-6)

    def test_laplace_equivalent_roundtrip(self):
        acc = PLDBudgetAccountant(total_epsilon=3.0,
                                                    total_delta=1e-6)
        spec = acc.request_budget(MechanismType.LAPLACE)
        acc.compute_budgets()
        # b = sens/eps; std = b*sqrt(2) must equal the granted std.
        import math
        assert (math.sqrt(2.0) / spec.eps == pytest.approx(
            spec.noise_standard_deviation, rel=1e-9))
        assert spec.delta == 0.0

    def test_pld_beats_naive_composition(self):
        # Many Gaussian mechanisms: PLD composition grants less noise per
        # mechanism than the naive equal split.
        from pipelinedp_tpu.ops import noise as noise_ops
        n_mech = 16
        acc = PLDBudgetAccountant(total_epsilon=2.0,
                                                    total_delta=1e-6)
        specs = [acc.request_budget(MechanismType.GAUSSIAN)
                 for _ in range(n_mech)]
        acc.compute_budgets()
        pld_std = specs[0].noise_standard_deviation
        naive_std = noise_ops.gaussian_sigma(2.0 / n_mech,
                                             1e-6 / n_mech, 1.0)
        assert pld_std < naive_std

    @pytest.mark.parametrize("metrics,extra", [
        (["MEAN"], {}),
        (["VARIANCE", "COUNT"], {}),
        (["PERCENTILE(50)", "PERCENTILE(90)"], {}),
    ])
    def test_multi_mechanism_metrics_end_to_end(self, metrics, extra):
        # MEAN/VARIANCE/PERCENTILE split their budget into several internal
        # mechanisms; the accountant composes them via
        # request_budget(internal_splits=k) — every metric now runs under
        # PLD accounting (the reference's PLD accountant runs none,
        # reference budget_accounting.py:406).
        import operator
        import pipelinedp_tpu as pdp
        from pipelinedp_tpu.backends import JaxBackend
        from pipelinedp_tpu.ops import noise as noise_ops

        def parse(name):
            if name.startswith("PERCENTILE"):
                return pdp.Metrics.PERCENTILE(int(name[11:-1]))
            return getattr(pdp.Metrics, name)

        data = [(u, p, float(u % 10)) for u in range(300)
                for p in ("a", "b")]
        ex = pdp.DataExtractors(
            privacy_id_extractor=operator.itemgetter(0),
            partition_extractor=operator.itemgetter(1),
            value_extractor=operator.itemgetter(2))
        params = pdp.AggregateParams(
            metrics=[parse(m) for m in metrics],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=2,
            max_contributions_per_partition=1,
            min_value=0.0, max_value=10.0, **extra)
        for backend in (pdp.LocalBackend(), JaxBackend(rng_seed=3)):
            noise_ops.seed_host_rng(0)
            acc = PLDBudgetAccountant(total_epsilon=30.0,
                                      total_delta=1e-6)
            engine = pdp.DPEngine(acc, backend)
            result = engine.aggregate(data, params, ex)
            acc.compute_budgets()
            out = dict(result)
            assert sorted(out) == ["a", "b"]
            for v in out.values():
                if "MEAN" in metrics:
                    assert v.mean == pytest.approx(4.5, abs=1.5)
                if "VARIANCE" in metrics:
                    assert v.count == pytest.approx(300, rel=0.2)
                if metrics[0].startswith("PERCENTILE"):
                    assert 2.0 <= v.percentile_50 <= 7.0

    def test_vector_sum_under_pld(self):
        import operator
        import pipelinedp_tpu as pdp
        data = [(u, "a", [1.0, 2.0, 3.0]) for u in range(300)]
        ex = pdp.DataExtractors(
            privacy_id_extractor=operator.itemgetter(0),
            partition_extractor=operator.itemgetter(1),
            value_extractor=operator.itemgetter(2))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.VECTOR_SUM],
            noise_kind=pdp.NoiseKind.GAUSSIAN,
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            vector_size=3, vector_max_norm=2000.0,
            vector_norm_kind=pdp.NormKind.L2)
        acc = PLDBudgetAccountant(total_epsilon=30.0, total_delta=1e-4)
        engine = pdp.DPEngine(acc, pdp.LocalBackend())
        result = engine.aggregate(data, params, ex)
        acc.compute_budgets()
        out = dict(result)
        assert np.allclose(out["a"], [300.0, 600.0, 900.0], rtol=0.25)

    @pytest.mark.parametrize("kind", ["laplace", "gaussian"])
    def test_split_composition_certificate(self, kind):
        # The composition that actually runs (the combiner's even split of
        # each published budget, re-calibrated per sub-mechanism) must
        # satisfy the pipeline's total (eps, delta) when convolved — the
        # certificate the internal_splits machinery exists to preserve.
        import math

        from pipelinedp_tpu import pld as pld_lib
        from pipelinedp_tpu.ops import noise as noise_ops

        total_eps, total_delta = 2.0, 1e-6
        acc = PLDBudgetAccountant(total_epsilon=total_eps,
                                  total_delta=total_delta)
        mech = (MechanismType.LAPLACE if kind == "laplace" else
                MechanismType.GAUSSIAN)
        spec_var = acc.request_budget(mech, internal_splits=3)
        spec_sel = acc.request_budget(MechanismType.GENERIC)
        acc.compute_budgets()

        plds = []
        eps_m = spec_var.eps / 3
        delta_m = spec_var.delta / 3
        if kind == "laplace":
            sub = pld_lib.laplace_pld(parameter=1.0 / eps_m,
                                      sensitivity=1.0)
        else:
            sigma = noise_ops.gaussian_sigma(eps_m, delta_m, 1.0)
            sub = pld_lib.gaussian_pld(standard_deviation=sigma,
                                       sensitivity=1.0)
        plds.append(sub.self_compose(3))
        plds.append(pld_lib.pure_dp_pld(spec_sel.eps, spec_sel.delta))
        composed = pld_lib.compose_all(plds)
        # Bisection tolerance (1e-3 relative on the noise std) is the only
        # slack between the searched noise level and the published
        # equivalents.
        assert composed.delta_for_epsilon(total_eps) <= total_delta * 1.05
        # And the published split budget is genuinely cheaper than what a
        # naive accountant would have granted the same pipeline.
        if kind == "gaussian":
            naive_sigma = noise_ops.gaussian_sigma(
                total_eps / 4, total_delta / 4, 1.0)
            granted_sigma = noise_ops.gaussian_sigma(eps_m, delta_m, 1.0)
            assert granted_sigma < naive_sigma * 1.6
