"""Budget accounting tests (modeled on reference tests/budget_accounting_test.py:27)."""

import pytest

from pipelinedp_tpu.aggregate_params import MechanismType
from pipelinedp_tpu.budget_accounting import (MechanismSpec,
                                              NaiveBudgetAccountant,
                                              PLDBudgetAccountant)


class TestMechanismSpec:

    def test_raises_before_compute(self):
        spec = MechanismSpec(MechanismType.LAPLACE)
        with pytest.raises(AssertionError):
            _ = spec.eps
        with pytest.raises(AssertionError):
            _ = spec.delta

    def test_set_then_read(self):
        spec = MechanismSpec(MechanismType.GAUSSIAN)
        spec.set_eps_delta(0.5, 1e-6)
        assert spec.eps == 0.5
        assert spec.delta == 1e-6

    def test_use_delta(self):
        assert not MechanismSpec(MechanismType.LAPLACE).use_delta()
        assert MechanismSpec(MechanismType.GAUSSIAN).use_delta()
        assert MechanismSpec(MechanismType.GENERIC).use_delta()


class TestNaiveBudgetAccountant:

    def test_validation(self):
        with pytest.raises(ValueError):
            NaiveBudgetAccountant(total_epsilon=0, total_delta=1e-7)
        with pytest.raises(ValueError):
            NaiveBudgetAccountant(total_epsilon=1, total_delta=-1e-7)
        with pytest.raises(ValueError):
            NaiveBudgetAccountant(total_epsilon=1, total_delta=1.0)

    def test_single_mechanism_gets_everything(self):
        acc = NaiveBudgetAccountant(total_epsilon=1.0, total_delta=1e-6)
        spec = acc.request_budget(MechanismType.GAUSSIAN)
        acc.compute_budgets()
        assert spec.eps == 1.0
        assert spec.delta == 1e-6

    def test_equal_split(self):
        acc = NaiveBudgetAccountant(total_epsilon=1.0, total_delta=1e-6)
        s1 = acc.request_budget(MechanismType.LAPLACE)
        s2 = acc.request_budget(MechanismType.LAPLACE)
        acc.compute_budgets()
        assert s1.eps == pytest.approx(0.5)
        assert s2.eps == pytest.approx(0.5)

    def test_delta_only_to_delta_users(self):
        # Laplace gets eps share but no delta; Gaussian gets the whole delta
        # (reference budget_accounting.py:384-395).
        acc = NaiveBudgetAccountant(total_epsilon=1.0, total_delta=1e-6)
        lap = acc.request_budget(MechanismType.LAPLACE)
        gau = acc.request_budget(MechanismType.GAUSSIAN)
        acc.compute_budgets()
        assert lap.eps == pytest.approx(0.5)
        assert lap.delta == 0
        assert gau.eps == pytest.approx(0.5)
        assert gau.delta == pytest.approx(1e-6)

    def test_weighted_split(self):
        acc = NaiveBudgetAccountant(total_epsilon=3.0, total_delta=0)
        s1 = acc.request_budget(MechanismType.LAPLACE, weight=1)
        s2 = acc.request_budget(MechanismType.LAPLACE, weight=2)
        acc.compute_budgets()
        assert s1.eps == pytest.approx(1.0)
        assert s2.eps == pytest.approx(2.0)

    def test_gaussian_requires_delta(self):
        acc = NaiveBudgetAccountant(total_epsilon=1.0, total_delta=0)
        with pytest.raises(AssertionError):
            acc.request_budget(MechanismType.GAUSSIAN)

    def test_request_after_compute_raises(self):
        acc = NaiveBudgetAccountant(total_epsilon=1.0, total_delta=0)
        acc.request_budget(MechanismType.LAPLACE)
        acc.compute_budgets()
        with pytest.raises(AssertionError):
            acc.request_budget(MechanismType.LAPLACE)

    def test_scope_normalises_weights(self):
        # Two mechanisms inside a scope of weight 1 plus one outside with
        # weight 1: the scope's two mechanisms together consume half.
        acc = NaiveBudgetAccountant(total_epsilon=4.0, total_delta=0)
        with acc.scope(weight=1):
            s1 = acc.request_budget(MechanismType.LAPLACE)
            s2 = acc.request_budget(MechanismType.LAPLACE)
        with acc.scope(weight=1):
            s3 = acc.request_budget(MechanismType.LAPLACE)
        acc.compute_budgets()
        assert s1.eps == pytest.approx(1.0)
        assert s2.eps == pytest.approx(1.0)
        assert s3.eps == pytest.approx(2.0)

    def test_num_aggregations_contract_enforced(self):
        acc = NaiveBudgetAccountant(total_epsilon=1.0, total_delta=0,
                                    num_aggregations=2)
        with acc.scope(weight=1):
            acc.request_budget(MechanismType.LAPLACE)
        with pytest.raises(ValueError, match="aggregations"):
            acc.compute_budgets()

    def test_aggregation_weights_contract(self):
        acc = NaiveBudgetAccountant(total_epsilon=1.0, total_delta=0,
                                    aggregation_weights=[1, 2])
        with acc.scope(weight=1):
            acc.request_budget(MechanismType.LAPLACE)
        with acc.scope(weight=3):
            acc.request_budget(MechanismType.LAPLACE)
        with pytest.raises(ValueError, match="weight"):
            acc.compute_budgets()

    def test_num_aggregations_and_weights_exclusive(self):
        with pytest.raises(ValueError):
            NaiveBudgetAccountant(total_epsilon=1.0, total_delta=0,
                                  num_aggregations=1,
                                  aggregation_weights=[1])

    def test_budget_for_aggregation_annotation(self):
        # Knowable only when the pipeline shape was declared up front
        # (reference budget_accounting.py:177-201).
        acc = NaiveBudgetAccountant(total_epsilon=2.0, total_delta=2e-6,
                                    aggregation_weights=[1, 3])
        budget = acc._compute_budget_for_aggregation(1)
        assert budget.epsilon == pytest.approx(0.5)
        assert budget.delta == pytest.approx(5e-7)
        acc2 = NaiveBudgetAccountant(total_epsilon=2.0, total_delta=2e-6,
                                     num_aggregations=4)
        budget2 = acc2._compute_budget_for_aggregation(1)
        assert budget2.epsilon == pytest.approx(0.5)
        acc3 = NaiveBudgetAccountant(total_epsilon=2.0, total_delta=2e-6)
        assert acc3._compute_budget_for_aggregation(1) is None


class TestCountAndDoubleCompute:

    def test_count_divides_budget_per_use(self):
        # count=4 declares four uses of one mechanism: each use receives
        # a quarter of the (single-mechanism) budget.
        acc = NaiveBudgetAccountant(total_epsilon=1.0, total_delta=0.0)
        spec = acc.request_budget(MechanismType.LAPLACE, count=4)
        acc.compute_budgets()
        assert spec.eps == pytest.approx(0.25)

    def test_count_composes_with_other_mechanisms(self):
        acc = NaiveBudgetAccountant(total_epsilon=1.0, total_delta=0.0)
        four = acc.request_budget(MechanismType.LAPLACE, count=4)
        one = acc.request_budget(MechanismType.LAPLACE)
        acc.compute_budgets()
        # Weights: 4 uses + 1 use = 5 shares of eps.
        assert four.eps == pytest.approx(0.2)
        assert one.eps == pytest.approx(0.2)

    @pytest.mark.parametrize("make", [
        lambda: NaiveBudgetAccountant(total_epsilon=1.0, total_delta=1e-6),
        lambda: PLDBudgetAccountant(total_epsilon=1.0, total_delta=1e-6),
    ])
    def test_compute_budgets_twice_raises(self, make):
        acc = make()
        acc.request_budget(MechanismType.GAUSSIAN)
        acc.compute_budgets()
        with pytest.raises(Exception, match="twice"):
            acc.compute_budgets()
