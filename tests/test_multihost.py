"""Multi-host (DCN) mesh proof: two ``jax.distributed`` processes form
one global device mesh and run the fused aggregation across the process
boundary (SURVEY §5.8 — the reference scales the same way via Beam/Spark
cluster workers; the TPU answer is one global mesh whose collectives ride
DCN between hosts).

The test spawns two coordinator-connected CPU processes (4 virtual
devices each → an 8-device global mesh) running
``tests/multihost_worker.py``; the worker asserts exact aggregates and
single-device selection bit-parity. Skipped when the gloo CPU
collectives backend is unavailable.
"""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _clean_env() -> dict:
    """Child env: CPU platform, 4 virtual devices, no ambient TPU-plugin
    site hooks (they pin JAX_PLATFORMS before the worker can)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("PYTHONPATH", None)
    for k in list(env):
        if k.startswith(("PALLAS_AXON", "AXON", "TPU_")):
            env.pop(k)
    return env


def test_two_process_global_mesh_fused_aggregation():
    try:
        import jax
        jax.config.update  # noqa: B018 — presence check
    except Exception:  # pragma: no cover
        pytest.skip("jax unavailable")
    worker = os.path.join(os.path.dirname(__file__),
                          "multihost_worker.py")
    port = _free_port()
    n_proc = 2
    env = _clean_env()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(worker)))
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), str(n_proc), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=repo)
        for i in range(n_proc)
    ]
    outs = []
    failed = False
    for p in procs:
        try:
            out, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            failed = True
        outs.append(out)
        failed = failed or p.returncode != 0
    joined = "\n---\n".join(outs)
    if failed and ("gloo" in joined.lower() and
                   "unimplemented" in joined.lower()):
        pytest.skip(f"gloo CPU collectives unavailable: {joined[-400:]}")
    assert not failed, joined[-4000:]
    for i, out in enumerate(outs):
        assert f"proc {i}: OK" in out, joined[-4000:]
