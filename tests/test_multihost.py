"""Multi-host (DCN) mesh proof: two ``jax.distributed`` processes form
one global device mesh and run the fused aggregation across the process
boundary (SURVEY §5.8 — the reference scales the same way via Beam/Spark
cluster workers; the TPU answer is one global mesh whose collectives ride
DCN between hosts).

The tests spawn coordinator-connected CPU processes (4 virtual devices
each → an 8-device global mesh) running ``tests/multihost_worker.py`` or
``tests/multihost_elastic_worker.py``. Coordinator rendezvous is a FILE,
not a parent-picked port: worker 0 allocates a free port immediately
before binding the coordinator and publishes it atomically; the other
workers poll the file. The old parent-side ``_free_port`` left a
multi-second window (child spawn + jax import) in which another process
could steal the port — the known flake this harness no longer needs a
retry allowance for. Skipped when the gloo CPU collectives backend is
unavailable.
"""

import os
import subprocess
import sys

import pytest


def _clean_env(repo: str) -> dict:
    """Child env: CPU platform, 4 virtual devices, no ambient TPU-plugin
    site hooks (they pin JAX_PLATFORMS before the worker can) and no
    ambient ``PIPELINEDP_TPU_*`` state — an inherited fault plan, stream
    chunk size, mesh dir or checkpoint knob would make the workers'
    behavior depend on which test ran before this one. The repo root
    must be on PYTHONPATH explicitly: the worker runs as
    ``python tests/multihost_worker.py``, whose ``sys.path[0]`` is
    ``tests/`` — without this the import fails wherever the package is
    not pip-installed."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = repo
    for k in list(env):
        if k.startswith(("PALLAS_AXON", "AXON", "TPU_",
                         "PIPELINEDP_TPU_")):
            env.pop(k)
    return env


def _run_workers(worker: str, n_proc: int, rendezvous: str, env: dict,
                 repo: str, deadline_s: float = 540.0,
                 extra_env=None):
    """One attempt: spawn the workers and collect them under ONE hard
    wall-clock deadline — a hung worker is killed when the deadline
    expires instead of hanging the suite (each process previously got
    its own full timeout, serially). ``extra_env`` is an optional
    per-worker list of env overrides (fault plans, checkpoint dirs) laid
    over the shared ``env``. Returns (failed, timed_out, outs)."""
    import time

    procs = []
    for i in range(n_proc):
        child_env = dict(env)
        if extra_env is not None and extra_env[i]:
            child_env.update(extra_env[i])
        procs.append(subprocess.Popen(
            [sys.executable, worker, str(i), str(n_proc), rendezvous],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=child_env, cwd=repo))
    t0 = time.monotonic()
    outs = []
    failed = timed_out = False
    for p in procs:
        remaining = deadline_s - (time.monotonic() - t0)
        try:
            out, _ = p.communicate(timeout=max(1.0, remaining))
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            failed = timed_out = True
        outs.append(out)
        failed = failed or p.returncode != 0
    return failed, timed_out, outs


def _require_jax():
    try:
        import jax
        jax.config.update  # noqa: B018 — presence check
    except Exception:  # pragma: no cover
        pytest.skip("jax unavailable")


def _skip_if_no_gloo(joined: str) -> None:
    if "gloo" in joined.lower() and "unimplemented" in joined.lower():
        pytest.skip(f"gloo CPU collectives unavailable: {joined[-400:]}")


def test_two_process_global_mesh_fused_aggregation(tmp_path):
    _require_jax()
    worker = os.path.join(os.path.dirname(__file__),
                          "multihost_worker.py")
    n_proc = 2
    repo = os.path.dirname(os.path.dirname(os.path.abspath(worker)))
    env = _clean_env(repo)
    # LEGACY CPU runtime for this worker: gloo hands out transfer slots
    # per-context in CALL ORDER, so both processes must issue their
    # cross-process collectives in the same sequence. The legacy
    # runtime executes ops in program order; the default thunk runtime
    # runs independent collective thunks CONCURRENTLY (the sweep's
    # all_gathers, the percentile walk's fetches), letting the two
    # processes pair mismatched ops and abort gloo with
    # ``op.preamble.length <= op.nbytes`` — the second historical flake
    # of this suite, distinct from the rendezvous port race. The
    # ELASTIC test below must NOT set this: the legacy runtime turns a
    # peer-death collective failure into a fatal CHECK
    # (``cpu_runtime.cc`` ``__xla_cpu_runtime_AllReduce``) that kills
    # the survivor, while the thunk runtime surfaces it as a catchable
    # XlaRuntimeError the elastic wrapper converts (its gloo exposure
    # is only the linear per-chunk psum stream, so slot order stays
    # deterministic there).
    env["XLA_FLAGS"] += " --xla_cpu_use_thunk_runtime=false"
    failed, _, outs = _run_workers(
        worker, n_proc, str(tmp_path / "rendezvous.json"), env, repo)
    joined = "\n---\n".join(outs)
    if failed:
        _skip_if_no_gloo(joined)
    assert not failed, joined[-4000:]
    for i, out in enumerate(outs):
        assert f"proc {i}: OK" in out, joined[-4000:]
        # The hier-topology leg ran and measured the byte asymmetry:
        # the worker asserts dcn_bytes(hier) < dcn_bytes(flat) across
        # the real process boundary before printing this line.
        assert f"proc {i}: comms dcn_flat=" in out, joined[-4000:]


def test_elastic_reshard_resume_parity_across_process_loss(tmp_path):
    """ISSUE 16 acceptance: kill one of two gloo processes mid-stream.
    The survivor's mesh supervisor detects the death at the next
    collective dispatch (BEFORE enqueueing the collective that would
    wedge on the dead peer), the elastic wrapper re-forms the mesh over
    the surviving process's local devices, resumes from the checkpoint,
    and finishes with rc=0 — releasing values BIT-IDENTICAL to a clean
    run at the surviving shape, with the ``mesh.reshard`` event on the
    run record. The worker asserts all of it; this parent asserts the
    kill actually happened and both processes exited cleanly."""
    _require_jax()
    worker = os.path.join(os.path.dirname(__file__),
                          "multihost_elastic_worker.py")
    n_proc = 2
    repo = os.path.dirname(os.path.dirname(os.path.abspath(worker)))
    env = _clean_env(repo)
    mesh_dir = str(tmp_path / "mesh")
    ckpt_dir = str(tmp_path / "ckpt")
    os.makedirs(ckpt_dir)
    shared = {"PIPELINEDP_TPU_MESH_DIR": mesh_dir,
              "PIPELINEDP_TPU_STREAM_CHUNK": "500",
              # The dead peer is detected by pid-aliveness at the next
              # dispatch; the stall deadline is only the fallback for a
              # wedged-but-alive peer. Keep it below the harness
              # deadline so even that path finishes in bounds.
              "PIPELINEDP_TPU_MESH_STALL_S": "120",
              "PDP_TEST_CKPT_DIR": ckpt_dir}
    per_worker = [
        dict(shared),  # survivor: no faults
        # Victim: dies on its own injected chunk failure mid-stream —
        # from the survivor's side that is indistinguishable from a
        # host loss.
        dict(shared, PIPELINEDP_TPU_FAULTS="fail_chunks=2"),
    ]
    failed, _, outs = _run_workers(
        worker, n_proc, str(tmp_path / "rendezvous.json"), env, repo,
        extra_env=per_worker)
    joined = "\n---\n".join(outs)
    if failed:
        _skip_if_no_gloo(joined)
    assert not failed, joined[-4000:]
    assert "proc 1: dying (injected fault mid-stream)" in outs[1], (
        joined[-4000:])
    assert "proc 0: OK" in outs[0], joined[-4000:]
    # The survivor's own output names the recovery shape transition.
    assert "reshard 8 -> 4" in outs[0], joined[-4000:]
