"""Multi-host (DCN) mesh proof: two ``jax.distributed`` processes form
one global device mesh and run the fused aggregation across the process
boundary (SURVEY §5.8 — the reference scales the same way via Beam/Spark
cluster workers; the TPU answer is one global mesh whose collectives ride
DCN between hosts).

The test spawns two coordinator-connected CPU processes (4 virtual
devices each → an 8-device global mesh) running
``tests/multihost_worker.py``; the worker asserts exact aggregates and
single-device selection bit-parity. Skipped when the gloo CPU
collectives backend is unavailable.
"""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _clean_env(repo: str) -> dict:
    """Child env: CPU platform, 4 virtual devices, no ambient TPU-plugin
    site hooks (they pin JAX_PLATFORMS before the worker can). The repo
    root must be on PYTHONPATH explicitly: the worker runs as
    ``python tests/multihost_worker.py``, whose ``sys.path[0]`` is
    ``tests/`` — without this the import fails wherever the package is
    not pip-installed."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = repo
    for k in list(env):
        if k.startswith(("PALLAS_AXON", "AXON", "TPU_")):
            env.pop(k)
    return env


#: Substrings that mark a coordinator PORT collision (another process
#: grabbed the port between ``_free_port`` and the coordinator's bind) —
#: a retryable environment race, not a product failure.
_PORT_COLLISION_MARKERS = ("address already in use", "address in use",
                           "failed to bind", "bind address")


def _run_workers(worker: str, n_proc: int, port: int, env: dict,
                 repo: str, deadline_s: float = 540.0):
    """One attempt: spawn the workers and collect them under ONE hard
    wall-clock deadline — a hung worker is killed when the deadline
    expires instead of hanging the suite (each process previously got
    its own full timeout, serially). Returns (failed, timed_out, outs)."""
    import time

    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), str(n_proc), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=repo)
        for i in range(n_proc)
    ]
    t0 = time.monotonic()
    outs = []
    failed = timed_out = False
    for p in procs:
        remaining = deadline_s - (time.monotonic() - t0)
        try:
            out, _ = p.communicate(timeout=max(1.0, remaining))
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            failed = timed_out = True
        outs.append(out)
        failed = failed or p.returncode != 0
    return failed, timed_out, outs


def test_two_process_global_mesh_fused_aggregation():
    try:
        import jax
        jax.config.update  # noqa: B018 — presence check
    except Exception:  # pragma: no cover
        pytest.skip("jax unavailable")
    worker = os.path.join(os.path.dirname(__file__),
                          "multihost_worker.py")
    n_proc = 2
    repo = os.path.dirname(os.path.dirname(os.path.abspath(worker)))
    env = _clean_env(repo)
    failed, _, outs = _run_workers(worker, n_proc, _free_port(), env,
                                   repo)
    joined = "\n---\n".join(outs)
    if failed and any(m in joined.lower()
                      for m in _PORT_COLLISION_MARKERS):
        # Coordinator port collision: pick a FRESH port and retry once.
        failed, _, outs = _run_workers(worker, n_proc, _free_port(),
                                       env, repo)
        joined = "\n---\n".join(outs)
    if failed and ("gloo" in joined.lower() and
                   "unimplemented" in joined.lower()):
        pytest.skip(f"gloo CPU collectives unavailable: {joined[-400:]}")
    assert not failed, joined[-4000:]
    for i, out in enumerate(outs):
        assert f"proc {i}: OK" in out, joined[-4000:]
