"""Validation-matrix tests for aggregate_params.

Modeled on the reference's test strategy (tests/aggregate_params_test.py:22 —
parameterized unit tests of __post_init__ validation)."""

import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.aggregate_params import parameters_to_readable_string


def _valid_count_kwargs(**overrides):
    kw = dict(metrics=[pdp.Metrics.COUNT],
              noise_kind=pdp.NoiseKind.LAPLACE,
              max_partitions_contributed=2,
              max_contributions_per_partition=3)
    kw.update(overrides)
    return kw


class TestAggregateParamsValidation:

    def test_valid_count(self):
        pdp.AggregateParams(**_valid_count_kwargs())

    def test_valid_sum_with_value_bounds(self):
        pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                            max_partitions_contributed=1,
                            max_contributions_per_partition=1,
                            min_value=-1.0,
                            max_value=5.0)

    def test_valid_sum_with_partition_sum_bounds(self):
        # Per-partition sum bounds replace value clipping for SUM, but the
        # contribution-bound pair is still required (reference
        # aggregate_params.py:255-270 demands both unconditionally).
        pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                            max_partitions_contributed=1,
                            max_contributions_per_partition=1,
                            min_sum_per_partition=0.0,
                            max_sum_per_partition=10.0)
        with pytest.raises(ValueError, match="both"):
            pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                max_partitions_contributed=1,
                                min_sum_per_partition=0.0,
                                max_sum_per_partition=10.0)

    @pytest.mark.parametrize("field,value", [
        ("max_partitions_contributed", 0),
        ("max_partitions_contributed", -1),
        ("max_partitions_contributed", 1.5),
        ("max_contributions_per_partition", 0),
        ("max_contributions_per_partition", -3),
    ])
    def test_invalid_contribution_bounds(self, field, value):
        with pytest.raises(ValueError):
            pdp.AggregateParams(**_valid_count_kwargs(**{field: value}))

    def test_max_contributions_exclusive_with_pair(self):
        with pytest.raises(ValueError, match="not both"):
            pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                max_contributions=5,
                                max_partitions_contributed=2)

    def test_max_contributions_alone_ok(self):
        pdp.AggregateParams(metrics=[pdp.Metrics.COUNT], max_contributions=5)

    def test_sum_requires_value_bounds(self):
        with pytest.raises(ValueError, match="bounds"):
            pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                max_partitions_contributed=1,
                                max_contributions_per_partition=1)

    def test_min_above_max_rejected(self):
        with pytest.raises(ValueError):
            pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                max_partitions_contributed=1,
                                max_contributions_per_partition=1,
                                min_value=2.0,
                                max_value=1.0)

    def test_value_bounds_must_come_in_pairs(self):
        with pytest.raises(ValueError, match="together"):
            pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                max_partitions_contributed=1,
                                max_contributions_per_partition=1,
                                min_value=0.0)

    def test_both_bound_kinds_rejected(self):
        with pytest.raises(ValueError, match="not both"):
            pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                max_partitions_contributed=1,
                                max_contributions_per_partition=1,
                                min_value=0.0,
                                max_value=1.0,
                                min_sum_per_partition=0.0,
                                max_sum_per_partition=1.0)

    def test_partition_sum_bounds_reject_mean(self):
        with pytest.raises(ValueError):
            pdp.AggregateParams(metrics=[pdp.Metrics.MEAN],
                                max_partitions_contributed=1,
                                max_contributions_per_partition=1,
                                min_sum_per_partition=0.0,
                                max_sum_per_partition=1.0)

    def test_vector_sum_rejects_scalar_metrics(self):
        with pytest.raises(ValueError, match="VECTOR_SUM"):
            pdp.AggregateParams(
                metrics=[pdp.Metrics.VECTOR_SUM, pdp.Metrics.COUNT],
                max_contributions_per_partition=1,
                max_partitions_contributed=1,
                vector_size=4,
                vector_max_norm=1.0)

    def test_vector_sum_needs_size_and_norm(self):
        with pytest.raises(ValueError):
            pdp.AggregateParams(metrics=[pdp.Metrics.VECTOR_SUM],
                                max_partitions_contributed=1)

    def test_vector_sum_valid(self):
        pdp.AggregateParams(metrics=[pdp.Metrics.VECTOR_SUM],
                            max_partitions_contributed=1,
                            max_contributions_per_partition=1,
                            vector_size=8,
                            vector_max_norm=2.0,
                            vector_norm_kind=pdp.NormKind.L2)

    def test_privacy_id_count_with_bounds_already_enforced_rejected(self):
        with pytest.raises(ValueError, match="PRIVACY_ID_COUNT"):
            pdp.AggregateParams(metrics=[pdp.Metrics.PRIVACY_ID_COUNT],
                                max_partitions_contributed=1,
                                max_contributions_per_partition=1,
                                contribution_bounds_already_enforced=True)

    def test_duplicate_metrics_rejected(self):
        with pytest.raises(ValueError, match="duplicate"):
            pdp.AggregateParams(**_valid_count_kwargs(
                metrics=[pdp.Metrics.COUNT, pdp.Metrics.COUNT]))

    def test_budget_weight_positive(self):
        with pytest.raises(ValueError, match="budget_weight"):
            pdp.AggregateParams(**_valid_count_kwargs(budget_weight=0))

    def test_pre_threshold_positive(self):
        with pytest.raises(ValueError, match="pre_threshold"):
            pdp.AggregateParams(**_valid_count_kwargs(pre_threshold=0))

    def test_custom_combiners_exclusive_with_metrics(self):
        with pytest.raises(ValueError, match="custom_combiners"):
            pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                max_partitions_contributed=1,
                                max_contributions_per_partition=1,
                                custom_combiners=[object()])

    def test_percentiles(self):
        p = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50),
                     pdp.Metrics.PERCENTILE(90)],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0.0,
            max_value=1.0)
        assert p.metrics[0].parameter == 50


class TestMetric:

    def test_equality_and_hash(self):
        assert pdp.Metrics.COUNT == pdp.Metrics.COUNT
        assert pdp.Metrics.PERCENTILE(10) == pdp.Metrics.PERCENTILE(10)
        assert pdp.Metrics.PERCENTILE(10) != pdp.Metrics.PERCENTILE(20)
        assert len({pdp.Metrics.COUNT, pdp.Metrics.COUNT}) == 1

    def test_repr(self):
        assert str(pdp.Metrics.PERCENTILE(90)) == "PERCENTILE(90)"
        assert str(pdp.Metrics.SUM) == "SUM"


class TestConvenienceParams:

    def test_count_params_lowering(self):
        cp = pdp.CountParams(noise_kind=pdp.NoiseKind.GAUSSIAN,
                             max_partitions_contributed=4,
                             max_contributions_per_partition=2)
        ap = cp.to_aggregate_params()
        assert ap.metrics == [pdp.Metrics.COUNT]
        assert ap.noise_kind == pdp.NoiseKind.GAUSSIAN
        assert ap.max_partitions_contributed == 4

    def test_privacy_id_count_forces_linf_1(self):
        ap = pdp.PrivacyIdCountParams(
            max_partitions_contributed=3).to_aggregate_params()
        assert ap.max_contributions_per_partition == 1

    def test_sum_params_lowering(self):
        ap = pdp.SumParams(max_partitions_contributed=1,
                           max_contributions_per_partition=2,
                           min_value=0.0,
                           max_value=1.0).to_aggregate_params()
        assert ap.metrics == [pdp.Metrics.SUM]
        assert ap.max_value == 1.0

    def test_mean_variance_params(self):
        m = pdp.MeanParams(max_partitions_contributed=1,
                           max_contributions_per_partition=1,
                           min_value=0.0,
                           max_value=1.0).to_aggregate_params()
        v = pdp.VarianceParams(max_partitions_contributed=1,
                               max_contributions_per_partition=1,
                               min_value=0.0,
                               max_value=1.0).to_aggregate_params()
        assert m.metrics == [pdp.Metrics.MEAN]
        assert v.metrics == [pdp.Metrics.VARIANCE]


class TestSelectPartitionsParams:

    def test_valid(self):
        pdp.SelectPartitionsParams(max_partitions_contributed=2)

    def test_invalid(self):
        with pytest.raises(ValueError):
            pdp.SelectPartitionsParams(max_partitions_contributed=0)


def test_readable_string():
    p = pdp.AggregateParams(**_valid_count_kwargs())
    s = parameters_to_readable_string(p, is_public_partition=False)
    assert "COUNT" in s
    assert "private" in s
