"""DPEngine tests — big-eps determinism, public partitions, partition
selection, bounding, reports (mirrors the reference's
``tests/dp_engine_test.py`` strategy: deterministic DP via huge eps,
mockable selection boundary, E2E on the local backend)."""

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.ops import noise as noise_ops

BIG_EPS = 1e5


def make_engine(eps=BIG_EPS, delta=1e-10, backend=None):
    backend = backend or pdp.LocalBackend()
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=eps,
                                           total_delta=delta)
    return pdp.DPEngine(accountant, backend), accountant


def extractors():
    return pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                              partition_extractor=lambda r: r[1],
                              value_extractor=lambda r: r[2])


def dataset(n_users=50, partitions=("a", "b", "c"), value=5.0):
    return [(u, pk, value) for u in range(n_users) for pk in partitions]


class TestAggregateCount:

    def test_count_big_eps(self):
        noise_ops.seed_host_rng(0)
        engine, acc = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=1)
        result = engine.aggregate(dataset(), params, extractors())
        acc.compute_budgets()
        out = dict(result)
        assert set(out) == {"a", "b", "c"}
        for v in out.values():
            assert v.count == pytest.approx(50, abs=0.5)

    def test_contribution_bounding_caps_counts(self):
        noise_ops.seed_host_rng(0)
        # One user contributes 100 rows to one partition; linf=2 caps it.
        data = [(0, "a", 1.0)] * 100
        engine, acc = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=2)
        result = engine.aggregate(data, params, extractors(),
                                  public_partitions=["a"])
        acc.compute_budgets()
        out = dict(result)
        assert out["a"].count == pytest.approx(2, abs=0.5)

    def test_l0_bounding_drops_partitions(self):
        noise_ops.seed_host_rng(0)
        # Each user contributes to 4 partitions, L0 bound = 2: the total
        # count across partitions must be ~ n_users * 2.
        data = [(u, pk, 1.0) for u in range(100)
                for pk in ("a", "b", "c", "d")]
        engine, acc = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=2,
                                     max_contributions_per_partition=1)
        result = engine.aggregate(data, params, extractors(),
                                  public_partitions=["a", "b", "c", "d"])
        acc.compute_budgets()
        total = sum(v.count for v in dict(result).values())
        assert total == pytest.approx(200, rel=0.15)


class TestAggregateMultiMetric:

    def test_count_sum_mean(self):
        noise_ops.seed_host_rng(1)
        engine, acc = make_engine()
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.MEAN, pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=3,
            max_contributions_per_partition=1, min_value=0.0,
            max_value=10.0)
        result = engine.aggregate(dataset(value=5.0), params, extractors())
        acc.compute_budgets()
        out = dict(result)
        for v in out.values():
            assert v.count == pytest.approx(50, abs=0.5)
            assert v.sum == pytest.approx(250, rel=0.01)
            assert v.mean == pytest.approx(5.0, abs=0.05)

    def test_variance(self):
        noise_ops.seed_host_rng(2)
        data = [(u, "a", 2.0) for u in range(100)] + [
            (u, "a", 8.0) for u in range(100, 200)
        ]
        engine, acc = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.VARIANCE],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     min_value=0.0, max_value=10.0)
        result = engine.aggregate(data, params, extractors())
        acc.compute_budgets()
        out = dict(result)
        assert out["a"].variance == pytest.approx(9.0, abs=0.3)

    def test_percentiles(self):
        noise_ops.seed_host_rng(3)
        rng = np.random.default_rng(0)
        data = [(u, "a", float(v))
                for u, v in enumerate(rng.uniform(0, 100, 2000))]
        engine, acc = make_engine()
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50),
                     pdp.Metrics.PERCENTILE(90)],
            max_partitions_contributed=1,
            max_contributions_per_partition=1, min_value=0.0,
            max_value=100.0)
        result = engine.aggregate(data, params, extractors())
        acc.compute_budgets()
        out = dict(result)
        assert out["a"].percentile_50 == pytest.approx(50, abs=5)
        assert out["a"].percentile_90 == pytest.approx(90, abs=5)


class TestPublicPartitions:

    def test_empty_public_partition_injected(self):
        noise_ops.seed_host_rng(0)
        engine, acc = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=1)
        result = engine.aggregate(dataset(partitions=("a",)), params,
                                  extractors(),
                                  public_partitions=["a", "zz"])
        acc.compute_budgets()
        out = dict(result)
        assert set(out) == {"a", "zz"}
        assert out["zz"].count == pytest.approx(0, abs=0.5)

    def test_non_public_partitions_dropped(self):
        noise_ops.seed_host_rng(0)
        engine, acc = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=1)
        result = engine.aggregate(dataset(), params, extractors(),
                                  public_partitions=["a"])
        acc.compute_budgets()
        assert set(dict(result)) == {"a"}


class TestPrivatePartitionSelection:

    def test_small_partitions_dropped(self):
        noise_ops.seed_host_rng(0)
        # Partition 'big' has 1000 users, 'tiny' has 1: with reasonable
        # eps/delta 'big' survives, 'tiny' is dropped.
        data = [(u, "big", 1.0) for u in range(1000)] + [(2000, "tiny", 1.0)]
        engine, acc = make_engine(eps=1.0, delta=1e-6)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        result = engine.aggregate(data, params, extractors())
        acc.compute_budgets()
        out = dict(result)
        assert "big" in out
        assert "tiny" not in out

    @pytest.mark.parametrize("strategy", [
        pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC,
        pdp.PartitionSelectionStrategy.LAPLACE_THRESHOLDING,
        pdp.PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING,
    ])
    def test_all_strategies_run(self, strategy):
        noise_ops.seed_host_rng(0)
        data = [(u, "big", 1.0) for u in range(1000)]
        engine, acc = make_engine(eps=1.0, delta=1e-6)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     partition_selection_strategy=strategy)
        result = engine.aggregate(data, params, extractors())
        acc.compute_budgets()
        assert "big" in dict(result)

    def test_pre_threshold_blocks(self):
        noise_ops.seed_host_rng(0)
        data = [(u, "mid", 1.0) for u in range(50)]
        engine, acc = make_engine(eps=BIG_EPS, delta=1e-6)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     pre_threshold=100)
        result = engine.aggregate(data, params, extractors())
        acc.compute_budgets()
        assert dict(result) == {}


class TestSelectPartitions:

    def test_select_partitions_basic(self):
        noise_ops.seed_host_rng(0)
        data = [(u, "big") for u in range(1000)] + [(1, "tiny")]
        engine, acc = make_engine(eps=1.0, delta=1e-6)
        params = pdp.SelectPartitionsParams(max_partitions_contributed=1)
        ext = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                 partition_extractor=lambda r: r[1])
        result = engine.select_partitions(data, params, ext)
        acc.compute_budgets()
        got = list(result)
        assert "big" in got
        assert "tiny" not in got


class TestBoundsAlreadyEnforced:

    def test_no_privacy_id_needed(self):
        noise_ops.seed_host_rng(0)
        data = [("a", 4.0)] * 100
        engine, acc = make_engine()
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.SUM], max_partitions_contributed=1,
            max_contributions_per_partition=1, min_value=0.0,
            max_value=10.0, contribution_bounds_already_enforced=True)
        ext = pdp.DataExtractors(partition_extractor=lambda r: r[0],
                                 value_extractor=lambda r: r[1])
        result = engine.aggregate(data, params, ext)
        acc.compute_budgets()
        out = dict(result)
        assert out["a"].sum == pytest.approx(400.0, rel=0.01)


class TestValidation:

    def test_empty_col_rejected(self):
        engine, _ = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        with pytest.raises(ValueError, match="non-empty"):
            engine.aggregate([], params, extractors())

    def test_max_contributions_supported_for_scalar_metrics(self):
        # The reference rejects max_contributions outright; here only the
        # metrics whose bounding structure genuinely needs (l0, linf)
        # stay rejected (see TestMaxContributions for the working paths).
        engine, _ = make_engine()
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.VECTOR_SUM], max_contributions=5,
            vector_size=2, vector_max_norm=1.0,
            vector_norm_kind=pdp.NormKind.L2)
        with pytest.raises(NotImplementedError, match="max_contributions"):
            engine.aggregate([1], params, extractors())

    def test_wrong_types(self):
        engine, _ = make_engine()
        with pytest.raises(ValueError):
            engine.aggregate([1], None, extractors())
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        with pytest.raises(TypeError):
            engine.aggregate([1], params, "not extractors")


class TestExplainComputation:

    def test_report_content(self):
        noise_ops.seed_host_rng(0)
        engine, acc = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=1)
        report = pdp.ExplainComputationReport()
        result = engine.aggregate(dataset(), params, extractors(),
                                  out_explain_computation_report=report)
        acc.compute_budgets()
        list(result)
        text = report.text()
        assert "DPEngine method: aggregate" in text
        assert "COUNT" in text
        assert "Partition selection" in text
        assert "Computed count" in text

    def test_report_before_budget_raises(self):
        report = pdp.ExplainComputationReport()
        with pytest.raises(ValueError):
            report.text()


class TestMultiProcEndToEnd:

    def test_count_on_multiproc(self):
        noise_ops.seed_host_rng(0)
        backend = pdp.MultiProcLocalBackend(n_jobs=2)
        engine, acc = make_engine(backend=backend)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=1)
        result = engine.aggregate(dataset(n_users=30), params,
                                  _module_extractors(),
                                  public_partitions=["a", "b", "c"])
        acc.compute_budgets()
        out = dict(result)
        for v in out.values():
            assert v.count == pytest.approx(30, abs=0.5)


# Module-level extractor functions: picklable for multiprocessing.


def _pid(r):
    return r[0]


def _pk(r):
    return r[1]


def _val(r):
    return r[2]


def _module_extractors():
    return pdp.DataExtractors(privacy_id_extractor=_pid,
                              partition_extractor=_pk,
                              value_extractor=_val)


class TestMaxContributions:
    """Total-cap contribution bounding — a parameter the reference
    declares but rejects in its engine (reference dp_engine.py:395-396);
    implemented here for the scalar metrics."""

    @staticmethod
    def _params(metrics, m, **kw):
        return pdp.AggregateParams(metrics=metrics, max_contributions=m,
                                   **kw)

    def test_nonbinding_matches_plain_aggregates(self):
        noise_ops.seed_host_rng(0)
        engine, acc = make_engine(eps=1e12, delta=1e-2)
        data = dataset(n_users=40)  # 3 rows per user
        params = self._params(
            [pdp.Metrics.COUNT, pdp.Metrics.SUM, pdp.Metrics.MEAN,
             pdp.Metrics.VARIANCE, pdp.Metrics.PRIVACY_ID_COUNT],
            m=10, min_value=0.0, max_value=10.0)
        result = engine.aggregate(data, params, extractors(),
                                  public_partitions=["a", "b", "c"])
        acc.compute_budgets()
        out = dict(result)
        for pk in ("a", "b", "c"):
            assert out[pk].count == pytest.approx(40, abs=0.1)
            assert out[pk].sum == pytest.approx(200.0, abs=0.5)
            assert out[pk].mean == pytest.approx(5.0, abs=0.1)
            assert out[pk].variance == pytest.approx(0.0, abs=0.1)
            assert out[pk].privacy_id_count == pytest.approx(40, abs=0.1)

    def test_binding_cap_limits_total_rows_per_user(self):
        noise_ops.seed_host_rng(0)
        # One user spreads 90 rows over 3 partitions; M=5 keeps 5 total.
        data = [(0, pk, 1.0) for pk in "abc" for _ in range(30)]
        engine, acc = make_engine(eps=1e12, delta=1e-2)
        params = self._params([pdp.Metrics.COUNT], m=5)
        result = engine.aggregate(data, params, extractors(),
                                  public_partitions=["a", "b", "c"])
        acc.compute_budgets()
        total = sum(v.count for v in dict(result).values())
        assert total == pytest.approx(5, abs=0.1)

    def test_gaussian_count_noise_uses_concentration_sensitivity(self):
        # Delta2 must be M (all contributions in one partition), not
        # sqrt(M): check the predictor and the empirical noise agree.
        from pipelinedp_tpu import dp_computations as dpc
        from pipelinedp_tpu.ops import noise as nops
        p = dpc.ScalarNoiseParams(
            eps=1.0, delta=1e-6, min_value=None, max_value=None,
            min_sum_per_partition=None, max_sum_per_partition=None,
            max_partitions_contributed=None,
            max_contributions_per_partition=None,
            noise_kind=pdp.NoiseKind.GAUSSIAN, max_contributions=9)
        expected_sigma = nops.gaussian_sigma(1.0, 1e-6, 9.0)
        assert dpc.compute_dp_count_noise_std(p) == pytest.approx(
            expected_sigma)
        noise_ops.seed_host_rng(0)
        draws = dpc.compute_dp_count(np.zeros(20000), p)
        assert np.std(draws) == pytest.approx(expected_sigma, rel=0.05)

    def test_pid_count_uses_tight_sqrt_m_sensitivity(self):
        # A unit adds at most 1 per partition to the privacy-id count:
        # Delta2 = sqrt(M), not M.
        import math
        from pipelinedp_tpu import dp_computations as dpc
        from pipelinedp_tpu.ops import noise as nops
        p = dpc.ScalarNoiseParams(
            eps=1.0, delta=1e-6, min_value=None, max_value=None,
            min_sum_per_partition=None, max_sum_per_partition=None,
            max_partitions_contributed=None,
            max_contributions_per_partition=None,
            noise_kind=pdp.NoiseKind.GAUSSIAN, max_contributions=9)
        expected_sigma = nops.gaussian_sigma(1.0, 1e-6, math.sqrt(9.0))
        noise_ops.seed_host_rng(0)
        draws = dpc.compute_dp_privacy_id_count(np.zeros(20000), p)
        assert np.std(draws) == pytest.approx(expected_sigma, rel=0.05)

    def test_laplace_sum_scale_is_m_times_bound(self):
        from pipelinedp_tpu import dp_computations as dpc
        p = dpc.ScalarNoiseParams(
            eps=2.0, delta=0.0, min_value=-3.0, max_value=1.0,
            min_sum_per_partition=None, max_sum_per_partition=None,
            max_partitions_contributed=None,
            max_contributions_per_partition=None,
            noise_kind=pdp.NoiseKind.LAPLACE, max_contributions=4)
        # L1 = M * max|bound| = 4 * 3 = 12 -> std = (12/2) * sqrt(2).
        import math
        assert dpc.compute_dp_sum_noise_std(p) == pytest.approx(
            6 * math.sqrt(2))

    def test_private_selection_runs_with_m(self):
        noise_ops.seed_host_rng(0)
        engine, acc = make_engine(eps=1e5, delta=1e-2)
        data = dataset(n_users=60)
        params = self._params([pdp.Metrics.COUNT], m=6)
        result = engine.aggregate(data, params, extractors())
        acc.compute_budgets()
        out = dict(result)
        assert set(out) == {"a", "b", "c"}  # 60 users: surely kept

    def test_percentile_supported_vector_sum_rejected(self):
        # PERCENTILE runs under the total cap since r3
        # (TestMaxContributionsPercentile); VECTOR_SUM stays rejected.
        engine, _ = make_engine()
        with pytest.raises(NotImplementedError, match="max_contributions"):
            engine.aggregate(
                dataset(), self._params(
                    [pdp.Metrics.VECTOR_SUM], m=3, vector_size=2,
                    vector_max_norm=1.0,
                    vector_norm_kind=pdp.NormKind.L2), extractors())

    def test_fused_plane_matches_local(self):
        from pipelinedp_tpu import jax_engine
        from pipelinedp_tpu.backends import JaxBackend
        noise_ops.seed_host_rng(0)
        data = dataset(n_users=30)
        params = self._params([pdp.Metrics.COUNT, pdp.Metrics.SUM,
                               pdp.Metrics.PRIVACY_ID_COUNT], m=10,
                              min_value=0.0, max_value=10.0)
        out = {}
        for name, backend in (("local", pdp.LocalBackend()),
                              ("jax", JaxBackend(rng_seed=0))):
            engine, acc = make_engine(eps=1e12, delta=1e-2,
                                      backend=backend)
            result = engine.aggregate(data, params, extractors(),
                                      public_partitions=["a", "b", "c"])
            if name == "jax":
                assert isinstance(result, jax_engine.LazyFusedResult), (
                    "total-cap mode must run on the fused plane")
            acc.compute_budgets()
            out[name] = {
                k: (round(v.count), round(v.sum, 1),
                    round(v.privacy_id_count))
                for k, v in dict(result).items()
            }
        assert out["local"] == out["jax"]

    def test_fused_binding_cap_uniform_sample(self):
        from pipelinedp_tpu.backends import JaxBackend
        # One user, 90 rows over 3 partitions; M=30 keeps exactly 30
        # rows total, spread uniformly (each partition expects ~10).
        data = [(0, pk, 1.0) for pk in "abc" for _ in range(30)]
        engine, acc = make_engine(eps=1e12, delta=1e-2,
                                  backend=JaxBackend(rng_seed=3))
        params = self._params([pdp.Metrics.COUNT], m=30)
        result = engine.aggregate(data, params, extractors(),
                                  public_partitions=["a", "b", "c"])
        acc.compute_budgets()
        out = {k: v.count for k, v in dict(result).items()}
        assert sum(out.values()) == pytest.approx(30, abs=0.1)
        # Uniform over rows, not over partitions: every partition keeps
        # some rows with overwhelming probability.
        assert all(v > 0.5 for v in out.values()), out

    def test_analysis_rejects_m(self):
        from pipelinedp_tpu import analysis
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6,
            aggregate_params=self._params([pdp.Metrics.COUNT], m=3))
        with pytest.raises(NotImplementedError, match="max_contributions"):
            analysis.perform_utility_analysis(
                dataset(), pdp.LocalBackend(), options, extractors())

    def test_fused_binding_cap_excludes_sampled_away_segments(self):
        from pipelinedp_tpu.backends import JaxBackend
        # 3 users x 10 partitions x 4 rows each, M=6: most (pid, pk)
        # segments are fully sampled away; the privacy-id count must
        # reflect only segments that kept >= 1 row. With M=6 over 40 rows
        # in 10 partitions, a user contributes to <= 6 partitions.
        data = [(u, f"p{i}", 1.0) for u in range(3) for i in range(10)
                for _ in range(4)]
        engine, acc = make_engine(eps=1e12, delta=1e-2,
                                  backend=JaxBackend(rng_seed=7))
        params = self._params(
            [pdp.Metrics.COUNT, pdp.Metrics.PRIVACY_ID_COUNT], m=6)
        result = engine.aggregate(data, params, extractors(),
                                  public_partitions=[f"p{i}"
                                                     for i in range(10)])
        acc.compute_budgets()
        out = dict(result)
        total_rows = sum(v.count for v in out.values())
        total_pids = sum(v.privacy_id_count for v in out.values())
        assert total_rows == pytest.approx(18, abs=0.2)  # 3 users x M
        # Each user appears in at most 6 partitions (and at least 2,
        # since a partition holds at most 4 of their rows).
        assert 6 <= round(total_pids) <= 18, total_pids
        for v in out.values():
            # A partition's pid count never exceeds its kept-rows count.
            assert v.privacy_id_count <= v.count + 0.2, v

    def test_fused_binding_cap_with_private_selection(self):
        from pipelinedp_tpu.backends import JaxBackend
        # Binding cap + private selection: 200 users each with 6 rows in
        # one hot partition (M=2 keeps 2), one lonely user elsewhere.
        data = ([(u, "hot", 1.0) for u in range(200) for _ in range(6)] +
                [(999, "tiny", 1.0)])
        engine, acc = make_engine(eps=1e5, delta=1e-3,
                                  backend=JaxBackend(rng_seed=9))
        params = self._params([pdp.Metrics.COUNT], m=2)
        result = engine.aggregate(data, params, extractors())
        acc.compute_budgets()
        out = dict(result)
        assert "hot" in out and "tiny" not in out
        assert out["hot"].count == pytest.approx(400, abs=1.0)

    def test_custom_combiners_with_m_rejected(self):
        engine, _ = make_engine()

        class CC(pdp.CustomCombiner):
            def create_accumulator(self, values): return 0
            def merge_accumulators(self, a, b): return a + b
            def compute_metrics(self, acc): return acc
            def explain_computation(self): return "cc"
            def request_budget(self, acc): pass

        params = pdp.AggregateParams(metrics=None, max_contributions=3,
                                     custom_combiners=[CC()])
        with pytest.raises(NotImplementedError, match="custom"):
            engine.aggregate(dataset(), params, extractors())


class TestMaxContributionsPercentile:
    """Total-cap bounding now covers PERCENTILE on both planes (the
    reference rejects max_contributions outright; round 2 supported the
    scalar metrics): the tree noises with the concentration-safe (1, M)
    sensitivity pair."""

    def test_percentile_total_cap_parity(self):
        from pipelinedp_tpu.backends import JaxBackend
        from pipelinedp_tpu.ops import noise as noise_ops
        rng = np.random.default_rng(0)
        # Caps never bind (each user has 3 rows, cap 10): both planes
        # must agree with tiny noise.
        data = [(u, "a", float(rng.uniform(0, 100)))
                for u in range(400) for _ in range(3)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50), pdp.Metrics.PERCENTILE(90)],
            max_contributions=10, min_value=0.0, max_value=100.0)
        outs = []
        for backend in (pdp.LocalBackend(), JaxBackend(rng_seed=5)):
            noise_ops.seed_host_rng(0)
            acc = pdp.NaiveBudgetAccountant(total_epsilon=1e5,
                                            total_delta=1e-10)
            engine = pdp.DPEngine(acc, backend)
            res = engine.aggregate(data, params, extractors())
            acc.compute_budgets()
            outs.append(dict(res)["a"])
        local, fused = outs
        assert fused.percentile_50 == pytest.approx(local.percentile_50,
                                                    abs=1.5)
        assert fused.percentile_90 == pytest.approx(local.percentile_90,
                                                    abs=1.5)
        assert local.percentile_50 == pytest.approx(50.0, abs=5.0)

    def test_binding_cap_limits_one_users_influence(self):
        from pipelinedp_tpu.backends import JaxBackend
        from pipelinedp_tpu.ops import noise as noise_ops
        # 500 regular users at low values + one whale with 5000 rows at
        # 100.0 under cap M=2: the whale contributes at most 2 rows, so
        # the median stays near the regular population's.
        data = ([(u, "a", 10.0) for u in range(500)] +
                [(9999, "a", 100.0)] * 5000)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50)], max_contributions=2,
            min_value=0.0, max_value=100.0)
        noise_ops.seed_host_rng(0)
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1e5,
                                        total_delta=1e-10)
        engine = pdp.DPEngine(acc, JaxBackend(rng_seed=7))
        res = engine.aggregate(data, params, extractors())
        acc.compute_budgets()
        assert dict(res)["a"].percentile_50 == pytest.approx(10.0, abs=5.0)
