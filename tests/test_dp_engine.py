"""DPEngine tests — big-eps determinism, public partitions, partition
selection, bounding, reports (mirrors the reference's
``tests/dp_engine_test.py`` strategy: deterministic DP via huge eps,
mockable selection boundary, E2E on the local backend)."""

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.ops import noise as noise_ops

BIG_EPS = 1e5


def make_engine(eps=BIG_EPS, delta=1e-10, backend=None):
    backend = backend or pdp.LocalBackend()
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=eps,
                                           total_delta=delta)
    return pdp.DPEngine(accountant, backend), accountant


def extractors():
    return pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                              partition_extractor=lambda r: r[1],
                              value_extractor=lambda r: r[2])


def dataset(n_users=50, partitions=("a", "b", "c"), value=5.0):
    return [(u, pk, value) for u in range(n_users) for pk in partitions]


class TestAggregateCount:

    def test_count_big_eps(self):
        noise_ops.seed_host_rng(0)
        engine, acc = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=1)
        result = engine.aggregate(dataset(), params, extractors())
        acc.compute_budgets()
        out = dict(result)
        assert set(out) == {"a", "b", "c"}
        for v in out.values():
            assert v.count == pytest.approx(50, abs=0.5)

    def test_contribution_bounding_caps_counts(self):
        noise_ops.seed_host_rng(0)
        # One user contributes 100 rows to one partition; linf=2 caps it.
        data = [(0, "a", 1.0)] * 100
        engine, acc = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=2)
        result = engine.aggregate(data, params, extractors(),
                                  public_partitions=["a"])
        acc.compute_budgets()
        out = dict(result)
        assert out["a"].count == pytest.approx(2, abs=0.5)

    def test_l0_bounding_drops_partitions(self):
        noise_ops.seed_host_rng(0)
        # Each user contributes to 4 partitions, L0 bound = 2: the total
        # count across partitions must be ~ n_users * 2.
        data = [(u, pk, 1.0) for u in range(100)
                for pk in ("a", "b", "c", "d")]
        engine, acc = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=2,
                                     max_contributions_per_partition=1)
        result = engine.aggregate(data, params, extractors(),
                                  public_partitions=["a", "b", "c", "d"])
        acc.compute_budgets()
        total = sum(v.count for v in dict(result).values())
        assert total == pytest.approx(200, rel=0.15)


class TestAggregateMultiMetric:

    def test_count_sum_mean(self):
        noise_ops.seed_host_rng(1)
        engine, acc = make_engine()
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.MEAN, pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=3,
            max_contributions_per_partition=1, min_value=0.0,
            max_value=10.0)
        result = engine.aggregate(dataset(value=5.0), params, extractors())
        acc.compute_budgets()
        out = dict(result)
        for v in out.values():
            assert v.count == pytest.approx(50, abs=0.5)
            assert v.sum == pytest.approx(250, rel=0.01)
            assert v.mean == pytest.approx(5.0, abs=0.05)

    def test_variance(self):
        noise_ops.seed_host_rng(2)
        data = [(u, "a", 2.0) for u in range(100)] + [
            (u, "a", 8.0) for u in range(100, 200)
        ]
        engine, acc = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.VARIANCE],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     min_value=0.0, max_value=10.0)
        result = engine.aggregate(data, params, extractors())
        acc.compute_budgets()
        out = dict(result)
        assert out["a"].variance == pytest.approx(9.0, abs=0.3)

    def test_percentiles(self):
        noise_ops.seed_host_rng(3)
        rng = np.random.default_rng(0)
        data = [(u, "a", float(v))
                for u, v in enumerate(rng.uniform(0, 100, 2000))]
        engine, acc = make_engine()
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50),
                     pdp.Metrics.PERCENTILE(90)],
            max_partitions_contributed=1,
            max_contributions_per_partition=1, min_value=0.0,
            max_value=100.0)
        result = engine.aggregate(data, params, extractors())
        acc.compute_budgets()
        out = dict(result)
        assert out["a"].percentile_50 == pytest.approx(50, abs=5)
        assert out["a"].percentile_90 == pytest.approx(90, abs=5)


class TestPublicPartitions:

    def test_empty_public_partition_injected(self):
        noise_ops.seed_host_rng(0)
        engine, acc = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=1)
        result = engine.aggregate(dataset(partitions=("a",)), params,
                                  extractors(),
                                  public_partitions=["a", "zz"])
        acc.compute_budgets()
        out = dict(result)
        assert set(out) == {"a", "zz"}
        assert out["zz"].count == pytest.approx(0, abs=0.5)

    def test_non_public_partitions_dropped(self):
        noise_ops.seed_host_rng(0)
        engine, acc = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=1)
        result = engine.aggregate(dataset(), params, extractors(),
                                  public_partitions=["a"])
        acc.compute_budgets()
        assert set(dict(result)) == {"a"}


class TestPrivatePartitionSelection:

    def test_small_partitions_dropped(self):
        noise_ops.seed_host_rng(0)
        # Partition 'big' has 1000 users, 'tiny' has 1: with reasonable
        # eps/delta 'big' survives, 'tiny' is dropped.
        data = [(u, "big", 1.0) for u in range(1000)] + [(2000, "tiny", 1.0)]
        engine, acc = make_engine(eps=1.0, delta=1e-6)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        result = engine.aggregate(data, params, extractors())
        acc.compute_budgets()
        out = dict(result)
        assert "big" in out
        assert "tiny" not in out

    @pytest.mark.parametrize("strategy", [
        pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC,
        pdp.PartitionSelectionStrategy.LAPLACE_THRESHOLDING,
        pdp.PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING,
    ])
    def test_all_strategies_run(self, strategy):
        noise_ops.seed_host_rng(0)
        data = [(u, "big", 1.0) for u in range(1000)]
        engine, acc = make_engine(eps=1.0, delta=1e-6)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     partition_selection_strategy=strategy)
        result = engine.aggregate(data, params, extractors())
        acc.compute_budgets()
        assert "big" in dict(result)

    def test_pre_threshold_blocks(self):
        noise_ops.seed_host_rng(0)
        data = [(u, "mid", 1.0) for u in range(50)]
        engine, acc = make_engine(eps=BIG_EPS, delta=1e-6)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     pre_threshold=100)
        result = engine.aggregate(data, params, extractors())
        acc.compute_budgets()
        assert dict(result) == {}


class TestSelectPartitions:

    def test_select_partitions_basic(self):
        noise_ops.seed_host_rng(0)
        data = [(u, "big") for u in range(1000)] + [(1, "tiny")]
        engine, acc = make_engine(eps=1.0, delta=1e-6)
        params = pdp.SelectPartitionsParams(max_partitions_contributed=1)
        ext = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                 partition_extractor=lambda r: r[1])
        result = engine.select_partitions(data, params, ext)
        acc.compute_budgets()
        got = list(result)
        assert "big" in got
        assert "tiny" not in got


class TestBoundsAlreadyEnforced:

    def test_no_privacy_id_needed(self):
        noise_ops.seed_host_rng(0)
        data = [("a", 4.0)] * 100
        engine, acc = make_engine()
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.SUM], max_partitions_contributed=1,
            max_contributions_per_partition=1, min_value=0.0,
            max_value=10.0, contribution_bounds_already_enforced=True)
        ext = pdp.DataExtractors(partition_extractor=lambda r: r[0],
                                 value_extractor=lambda r: r[1])
        result = engine.aggregate(data, params, ext)
        acc.compute_budgets()
        out = dict(result)
        assert out["a"].sum == pytest.approx(400.0, rel=0.01)


class TestValidation:

    def test_empty_col_rejected(self):
        engine, _ = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        with pytest.raises(ValueError, match="non-empty"):
            engine.aggregate([], params, extractors())

    def test_max_contributions_not_supported(self):
        engine, _ = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_contributions=5)
        with pytest.raises(NotImplementedError):
            engine.aggregate([1], params, extractors())

    def test_wrong_types(self):
        engine, _ = make_engine()
        with pytest.raises(ValueError):
            engine.aggregate([1], None, extractors())
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        with pytest.raises(TypeError):
            engine.aggregate([1], params, "not extractors")


class TestExplainComputation:

    def test_report_content(self):
        noise_ops.seed_host_rng(0)
        engine, acc = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=1)
        report = pdp.ExplainComputationReport()
        result = engine.aggregate(dataset(), params, extractors(),
                                  out_explain_computation_report=report)
        acc.compute_budgets()
        list(result)
        text = report.text()
        assert "DPEngine method: aggregate" in text
        assert "COUNT" in text
        assert "Partition selection" in text
        assert "Computed count" in text

    def test_report_before_budget_raises(self):
        report = pdp.ExplainComputationReport()
        with pytest.raises(ValueError):
            report.text()


class TestMultiProcEndToEnd:

    def test_count_on_multiproc(self):
        noise_ops.seed_host_rng(0)
        backend = pdp.MultiProcLocalBackend(n_jobs=2)
        engine, acc = make_engine(backend=backend)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=1)
        result = engine.aggregate(dataset(n_users=30), params,
                                  _module_extractors(),
                                  public_partitions=["a", "b", "c"])
        acc.compute_budgets()
        out = dict(result)
        for v in out.values():
            assert v.count == pytest.approx(30, abs=0.5)


# Module-level extractor functions: picklable for multiprocessing.


def _pid(r):
    return r[0]


def _pk(r):
    return r[1]


def _val(r):
    return r[2]


def _module_extractors():
    return pdp.DataExtractors(privacy_id_extractor=_pid,
                              partition_extractor=_pk,
                              value_extractor=_val)
