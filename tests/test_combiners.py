"""Combiner tests — create/merge/compute per combiner, compound fusion, and
the factory's budget-request pattern (mirrors the reference's
``tests/combiners_test.py:160-628``)."""

import numpy as np
import pytest

from pipelinedp_tpu import budget_accounting, combiners
from pipelinedp_tpu.aggregate_params import (AggregateParams, MechanismType,
                                             Metrics, NoiseKind, NormKind)


def make_params(metrics, **kwargs):
    defaults = dict(max_partitions_contributed=2,
                    max_contributions_per_partition=3, min_value=0.0,
                    max_value=10.0)
    defaults.update(kwargs)
    return AggregateParams(metrics=metrics, **defaults)


def combiner_params(agg_params, eps=1e5, delta=1e-10):
    spec = budget_accounting.MechanismSpec(MechanismType.LAPLACE, _eps=eps,
                                           _delta=delta)
    return combiners.CombinerParams(spec, agg_params)


class TestCountCombiner:

    def test_create_merge_compute(self):
        c = combiners.CountCombiner(
            combiner_params(make_params([Metrics.COUNT])))
        acc = c.create_accumulator([1, 2, 3])
        assert acc == 3
        acc = c.merge_accumulators(acc, c.create_accumulator([4]))
        assert acc == 4
        assert c.compute_metrics(acc)["count"] == pytest.approx(4, abs=0.01)
        assert c.metrics_names() == ["count"]


class TestPrivacyIdCountCombiner:

    def test_zero_or_one_per_create(self):
        c = combiners.PrivacyIdCountCombiner(
            combiner_params(make_params([Metrics.PRIVACY_ID_COUNT])))
        assert c.create_accumulator([1, 2, 3]) == 1
        assert c.create_accumulator([]) == 0
        acc = c.merge_accumulators(1, 1)
        assert c.compute_metrics(acc)["privacy_id_count"] == pytest.approx(
            2, abs=0.01)


class TestSumCombiner:

    def test_per_value_clipping(self):
        c = combiners.SumCombiner(
            combiner_params(make_params([Metrics.SUM])))
        # values clipped to [0, 10]: -5 -> 0, 20 -> 10, 3 -> 3
        acc = c.create_accumulator([-5, 20, 3])
        assert acc == 13.0
        assert c.compute_metrics(acc)["sum"] == pytest.approx(13, abs=0.01)

    def test_per_partition_sum_clipping(self):
        params = make_params([Metrics.SUM], min_value=None, max_value=None,
                             min_sum_per_partition=0.0,
                             max_sum_per_partition=5.0)
        c = combiners.SumCombiner(combiner_params(params))
        assert c.create_accumulator([10, 20]) == 5.0  # sum 30 clipped to 5
        assert c.create_accumulator([-10]) == 0.0


class TestMeanCombiner:

    def test_accumulator_is_count_and_normalized_sum(self):
        c = combiners.MeanCombiner(
            combiner_params(make_params([Metrics.MEAN])), ["mean"])
        count, nsum = c.create_accumulator([0.0, 10.0])  # middle 5
        assert count == 2
        assert nsum == pytest.approx(0.0)  # (0-5) + (10-5)

    def test_compute_metrics_subset(self):
        c = combiners.MeanCombiner(
            combiner_params(make_params([Metrics.MEAN, Metrics.COUNT])),
            ["mean", "count"])
        acc = c.create_accumulator([7.0] * 10)
        out = c.compute_metrics(acc)
        assert set(out) == {"mean", "count"}
        assert out["mean"] == pytest.approx(7.0, abs=0.01)
        assert out["count"] == pytest.approx(10, abs=0.01)

    def test_requires_mean_metric(self):
        with pytest.raises(ValueError):
            combiners.MeanCombiner(
                combiner_params(make_params([Metrics.MEAN])), ["count"])


class TestVarianceCombiner:

    def test_variance_computation(self):
        c = combiners.VarianceCombiner(
            combiner_params(make_params([Metrics.VARIANCE])), ["variance"])
        values = [2.0] * 50 + [8.0] * 50
        acc = c.create_accumulator(values)
        out = c.compute_metrics(acc)
        assert out["variance"] == pytest.approx(9.0, abs=0.1)


class TestQuantileCombiner:

    def test_percentiles(self):
        params = combiner_params(
            make_params([Metrics.PERCENTILE(50), Metrics.PERCENTILE(90)],
                        min_value=0.0, max_value=100.0))
        c = combiners.QuantileCombiner(params, [50, 90])
        rng = np.random.default_rng(0)
        acc = c.create_accumulator(rng.uniform(0, 100, 2000))
        out = c.compute_metrics(acc)
        assert out["percentile_50"] == pytest.approx(50, abs=3)
        assert out["percentile_90"] == pytest.approx(90, abs=3)
        assert c.metrics_names() == ["percentile_50", "percentile_90"]

    def test_merge_serialized(self):
        params = combiner_params(
            make_params([Metrics.PERCENTILE(50)], min_value=0.0,
                        max_value=100.0))
        c = combiners.QuantileCombiner(params, [50])
        acc = c.merge_accumulators(c.create_accumulator([10.0] * 100),
                                   c.create_accumulator([90.0] * 100))
        assert isinstance(acc, bytes)
        out = c.compute_metrics(acc)
        assert 5 < out["percentile_50"] < 95


class TestVectorSumCombiner:

    def test_create_and_noise(self):
        params = combiner_params(
            make_params([Metrics.VECTOR_SUM], min_value=None,
                        max_value=None,
                        vector_size=2, vector_max_norm=100.0,
                        vector_norm_kind=NormKind.Linf))
        c = combiners.VectorSumCombiner(params)
        acc = c.create_accumulator([np.array([1.0, 2.0]),
                                    np.array([3.0, 4.0])])
        np.testing.assert_allclose(acc, [4.0, 6.0])
        out = c.compute_metrics(acc)["vector_sum"]
        np.testing.assert_allclose(out, [4.0, 6.0], atol=0.05)

    def test_shape_mismatch_raises(self):
        params = combiner_params(
            make_params([Metrics.VECTOR_SUM], min_value=None,
                        max_value=None,
                        vector_size=2, vector_max_norm=100.0))
        c = combiners.VectorSumCombiner(params)
        with pytest.raises(TypeError):
            c.create_accumulator([np.array([1.0, 2.0, 3.0])])


class TestCompoundCombiner:

    def _compound(self):
        params = make_params([Metrics.COUNT, Metrics.SUM])
        acc = budget_accounting.NaiveBudgetAccountant(total_epsilon=1e5,
                                                      total_delta=1e-10)
        compound = combiners.create_compound_combiner(params, acc)
        acc.compute_budgets()
        return compound

    def test_row_count_tracks_creates(self):
        compound = self._compound()
        a1 = compound.create_accumulator([1.0])
        a2 = compound.create_accumulator([2.0, 3.0])
        merged = compound.merge_accumulators(a1, a2)
        row_count, children = merged
        assert row_count == 2
        assert len(children) == 2  # count + sum accumulators

    def test_metrics_tuple_output(self):
        compound = self._compound()
        acc = compound.create_accumulator([1.0, 2.0])
        out = compound.compute_metrics(acc)
        assert out.count == pytest.approx(2, abs=0.01)
        assert out.sum == pytest.approx(3.0, abs=0.01)

    def test_metrics_tuple_picklable(self):
        import pickle
        compound = self._compound()
        out = compound.compute_metrics(compound.create_accumulator([1.0]))
        out2 = pickle.loads(pickle.dumps(out))
        assert out2 == out


class TestCompoundFactory:

    def test_variance_folds_mean_count_sum(self):
        params = make_params(
            [Metrics.VARIANCE, Metrics.MEAN, Metrics.COUNT, Metrics.SUM])
        acc = budget_accounting.NaiveBudgetAccountant(1e5, 1e-10)
        compound = combiners.create_compound_combiner(params, acc)
        # All four metrics from ONE VarianceCombiner -> one budget request.
        assert len(compound.combiners) == 1
        assert isinstance(compound.combiners[0],
                          combiners.VarianceCombiner)
        assert len(acc._mechanisms) == 1

    def test_mean_folds_count_sum(self):
        params = make_params([Metrics.MEAN, Metrics.COUNT])
        acc = budget_accounting.NaiveBudgetAccountant(1e5, 1e-10)
        compound = combiners.create_compound_combiner(params, acc)
        assert len(compound.combiners) == 1
        assert isinstance(compound.combiners[0], combiners.MeanCombiner)

    def test_separate_count_sum(self):
        params = make_params([Metrics.COUNT, Metrics.SUM])
        acc = budget_accounting.NaiveBudgetAccountant(1e5, 1e-10)
        compound = combiners.create_compound_combiner(params, acc)
        assert len(compound.combiners) == 2
        assert len(acc._mechanisms) == 2

    def test_percentiles_one_budget(self):
        params = make_params(
            [Metrics.PERCENTILE(50), Metrics.PERCENTILE(90)],
            min_value=0.0, max_value=100.0)
        acc = budget_accounting.NaiveBudgetAccountant(1e5, 1e-10)
        compound = combiners.create_compound_combiner(params, acc)
        assert len(compound.combiners) == 1
        assert len(acc._mechanisms) == 1

    def test_custom_combiners(self):

        class MyCombiner(combiners.CustomCombiner):

            def request_budget(self, accountant):
                self._spec = accountant.request_budget(
                    MechanismType.LAPLACE)

            def create_accumulator(self, values):
                return sum(values)

            def merge_accumulators(self, a, b):
                return a + b

            def compute_metrics(self, acc):
                return acc

            def explain_computation(self):
                return "custom"

        params = AggregateParams(custom_combiners=[MyCombiner()],
                                 max_partitions_contributed=1,
                                 max_contributions_per_partition=1)
        acc = budget_accounting.NaiveBudgetAccountant(1e5, 1e-10)
        compound = combiners.create_compound_combiner_with_custom_combiners(
            params, acc, params.custom_combiners)
        out = compound.compute_metrics(compound.create_accumulator([1, 2]))
        assert out == (3,)
