"""Combiner tests — create/merge/compute per combiner, compound fusion, and
the factory's budget-request pattern (mirrors the reference's
``tests/combiners_test.py:160-628``)."""

import numpy as np
import pytest

from pipelinedp_tpu import budget_accounting, combiners
from pipelinedp_tpu.aggregate_params import (AggregateParams, MechanismType,
                                             Metrics, NoiseKind, NormKind)


def make_params(metrics, **kwargs):
    defaults = dict(max_partitions_contributed=2,
                    max_contributions_per_partition=3, min_value=0.0,
                    max_value=10.0)
    defaults.update(kwargs)
    return AggregateParams(metrics=metrics, **defaults)


def combiner_params(agg_params, eps=1e5, delta=1e-10):
    spec = budget_accounting.MechanismSpec(MechanismType.LAPLACE, _eps=eps,
                                           _delta=delta)
    return combiners.CombinerParams(spec, agg_params)


def make_compound(metrics=(Metrics.COUNT, Metrics.SUM)):
    """COUNT+SUM compound with budgets already computed (huge eps)."""
    params = make_params(list(metrics))
    acc = budget_accounting.NaiveBudgetAccountant(total_epsilon=1e5,
                                                  total_delta=1e-10)
    compound = combiners.create_compound_combiner(params, acc)
    acc.compute_budgets()
    return compound


class TestCountCombiner:

    def test_create_merge_compute(self):
        c = combiners.CountCombiner(
            combiner_params(make_params([Metrics.COUNT])))
        acc = c.create_accumulator([1, 2, 3])
        assert acc == 3
        acc = c.merge_accumulators(acc, c.create_accumulator([4]))
        assert acc == 4
        assert c.compute_metrics(acc)["count"] == pytest.approx(4, abs=0.01)
        assert c.metrics_names() == ["count"]


class TestPrivacyIdCountCombiner:

    def test_zero_or_one_per_create(self):
        c = combiners.PrivacyIdCountCombiner(
            combiner_params(make_params([Metrics.PRIVACY_ID_COUNT])))
        assert c.create_accumulator([1, 2, 3]) == 1
        assert c.create_accumulator([]) == 0
        acc = c.merge_accumulators(1, 1)
        assert c.compute_metrics(acc)["privacy_id_count"] == pytest.approx(
            2, abs=0.01)


class TestSumCombiner:

    def test_per_value_clipping(self):
        c = combiners.SumCombiner(
            combiner_params(make_params([Metrics.SUM])))
        # values clipped to [0, 10]: -5 -> 0, 20 -> 10, 3 -> 3
        acc = c.create_accumulator([-5, 20, 3])
        assert acc == 13.0
        assert c.compute_metrics(acc)["sum"] == pytest.approx(13, abs=0.01)

    def test_per_partition_sum_clipping(self):
        params = make_params([Metrics.SUM], min_value=None, max_value=None,
                             min_sum_per_partition=0.0,
                             max_sum_per_partition=5.0)
        c = combiners.SumCombiner(combiner_params(params))
        assert c.create_accumulator([10, 20]) == 5.0  # sum 30 clipped to 5
        assert c.create_accumulator([-10]) == 0.0


class TestMeanCombiner:

    def test_accumulator_is_count_and_normalized_sum(self):
        c = combiners.MeanCombiner(
            combiner_params(make_params([Metrics.MEAN])), ["mean"])
        count, nsum = c.create_accumulator([0.0, 10.0])  # middle 5
        assert count == 2
        assert nsum == pytest.approx(0.0)  # (0-5) + (10-5)

    def test_compute_metrics_subset(self):
        c = combiners.MeanCombiner(
            combiner_params(make_params([Metrics.MEAN, Metrics.COUNT])),
            ["mean", "count"])
        acc = c.create_accumulator([7.0] * 10)
        out = c.compute_metrics(acc)
        assert set(out) == {"mean", "count"}
        assert out["mean"] == pytest.approx(7.0, abs=0.01)
        assert out["count"] == pytest.approx(10, abs=0.01)

    def test_requires_mean_metric(self):
        with pytest.raises(ValueError):
            combiners.MeanCombiner(
                combiner_params(make_params([Metrics.MEAN])), ["count"])


class TestVarianceCombiner:

    def test_variance_computation(self):
        c = combiners.VarianceCombiner(
            combiner_params(make_params([Metrics.VARIANCE])), ["variance"])
        values = [2.0] * 50 + [8.0] * 50
        acc = c.create_accumulator(values)
        out = c.compute_metrics(acc)
        assert out["variance"] == pytest.approx(9.0, abs=0.1)


class TestQuantileCombiner:

    def test_percentiles(self):
        params = combiner_params(
            make_params([Metrics.PERCENTILE(50), Metrics.PERCENTILE(90)],
                        min_value=0.0, max_value=100.0))
        c = combiners.QuantileCombiner(params, [50, 90])
        rng = np.random.default_rng(0)
        acc = c.create_accumulator(rng.uniform(0, 100, 2000))
        out = c.compute_metrics(acc)
        assert out["percentile_50"] == pytest.approx(50, abs=3)
        assert out["percentile_90"] == pytest.approx(90, abs=3)
        assert c.metrics_names() == ["percentile_50", "percentile_90"]

    def test_merge_serialized(self):
        params = combiner_params(
            make_params([Metrics.PERCENTILE(50)], min_value=0.0,
                        max_value=100.0))
        c = combiners.QuantileCombiner(params, [50])
        acc = c.merge_accumulators(c.create_accumulator([10.0] * 100),
                                   c.create_accumulator([90.0] * 100))
        assert isinstance(acc, bytes)
        out = c.compute_metrics(acc)
        assert 5 < out["percentile_50"] < 95


class TestVectorSumCombiner:

    def test_create_and_noise(self):
        params = combiner_params(
            make_params([Metrics.VECTOR_SUM], min_value=None,
                        max_value=None,
                        vector_size=2, vector_max_norm=100.0,
                        vector_norm_kind=NormKind.Linf))
        c = combiners.VectorSumCombiner(params)
        acc = c.create_accumulator([np.array([1.0, 2.0]),
                                    np.array([3.0, 4.0])])
        np.testing.assert_allclose(acc, [4.0, 6.0])
        out = c.compute_metrics(acc)["vector_sum"]
        np.testing.assert_allclose(out, [4.0, 6.0], atol=0.05)

    def test_shape_mismatch_raises(self):
        params = combiner_params(
            make_params([Metrics.VECTOR_SUM], min_value=None,
                        max_value=None,
                        vector_size=2, vector_max_norm=100.0))
        c = combiners.VectorSumCombiner(params)
        with pytest.raises(TypeError):
            c.create_accumulator([np.array([1.0, 2.0, 3.0])])


class TestCompoundCombiner:

    def _compound(self):
        return make_compound()

    def test_row_count_tracks_creates(self):
        compound = self._compound()
        a1 = compound.create_accumulator([1.0])
        a2 = compound.create_accumulator([2.0, 3.0])
        merged = compound.merge_accumulators(a1, a2)
        row_count, children = merged
        assert row_count == 2
        assert len(children) == 2  # count + sum accumulators

    def test_metrics_tuple_output(self):
        compound = self._compound()
        acc = compound.create_accumulator([1.0, 2.0])
        out = compound.compute_metrics(acc)
        assert out.count == pytest.approx(2, abs=0.01)
        assert out.sum == pytest.approx(3.0, abs=0.01)

    def test_metrics_tuple_picklable(self):
        import pickle
        compound = self._compound()
        out = compound.compute_metrics(compound.create_accumulator([1.0]))
        out2 = pickle.loads(pickle.dumps(out))
        assert out2 == out


class TestCompoundFactory:

    def test_variance_folds_mean_count_sum(self):
        params = make_params(
            [Metrics.VARIANCE, Metrics.MEAN, Metrics.COUNT, Metrics.SUM])
        acc = budget_accounting.NaiveBudgetAccountant(1e5, 1e-10)
        compound = combiners.create_compound_combiner(params, acc)
        # All four metrics from ONE VarianceCombiner -> one budget request.
        assert len(compound.combiners) == 1
        assert isinstance(compound.combiners[0],
                          combiners.VarianceCombiner)
        assert len(acc._mechanisms) == 1

    def test_mean_folds_count_sum(self):
        params = make_params([Metrics.MEAN, Metrics.COUNT])
        acc = budget_accounting.NaiveBudgetAccountant(1e5, 1e-10)
        compound = combiners.create_compound_combiner(params, acc)
        assert len(compound.combiners) == 1
        assert isinstance(compound.combiners[0], combiners.MeanCombiner)

    def test_separate_count_sum(self):
        params = make_params([Metrics.COUNT, Metrics.SUM])
        acc = budget_accounting.NaiveBudgetAccountant(1e5, 1e-10)
        compound = combiners.create_compound_combiner(params, acc)
        assert len(compound.combiners) == 2
        assert len(acc._mechanisms) == 2

    def test_percentiles_one_budget(self):
        params = make_params(
            [Metrics.PERCENTILE(50), Metrics.PERCENTILE(90)],
            min_value=0.0, max_value=100.0)
        acc = budget_accounting.NaiveBudgetAccountant(1e5, 1e-10)
        compound = combiners.create_compound_combiner(params, acc)
        assert len(compound.combiners) == 1
        assert len(acc._mechanisms) == 1

    def test_custom_combiners(self):

        class MyCombiner(combiners.CustomCombiner):

            def request_budget(self, accountant):
                self._spec = accountant.request_budget(
                    MechanismType.LAPLACE)

            def create_accumulator(self, values):
                return sum(values)

            def merge_accumulators(self, a, b):
                return a + b

            def compute_metrics(self, acc):
                return acc

            def explain_computation(self):
                return "custom"

        params = AggregateParams(custom_combiners=[MyCombiner()],
                                 max_partitions_contributed=1,
                                 max_contributions_per_partition=1)
        acc = budget_accounting.NaiveBudgetAccountant(1e5, 1e-10)
        compound = combiners.create_compound_combiner_with_custom_combiners(
            params, acc, params.custom_combiners)
        out = compound.compute_metrics(compound.create_accumulator([1, 2]))
        assert out == (3,)


class TestCombinerMatrix:
    """Parameterized create/merge/compute matrix over every scalar
    combiner — the reference's per-combiner case tables
    (``tests/combiners_test.py:160-628``), at huge eps so computed
    metrics pin to the exact bounded aggregates."""

    @pytest.mark.parametrize("values,expected", [
        ([], 0), ([1], 1), ([1, 2], 2),
        # Linf capping is the BOUNDER's job; the combiner counts its input.
        ([1, 2, 3, 4, 5], 5),
    ])
    def test_count_create(self, values, expected):
        c = combiners.CountCombiner(combiner_params(make_params(
            [Metrics.COUNT])))
        assert c.create_accumulator(values) == expected

    @pytest.mark.parametrize("accs,expected", [
        ([0, 0, 0], 0), ([1, 2, 4], 7), ([3, 3, 3], 9),
    ])
    def test_count_merge_associative(self, accs, expected):
        c = combiners.CountCombiner(combiner_params(make_params(
            [Metrics.COUNT])))
        a, b, d = accs
        left = c.merge_accumulators(c.merge_accumulators(a, b), d)
        right = c.merge_accumulators(a, c.merge_accumulators(b, d))
        assert left == right == expected
        assert c.compute_metrics(expected)["count"] == pytest.approx(
            expected, abs=0.01)

    @pytest.mark.parametrize("values,bounds,expected", [
        ([1.0, 2.0], (0.0, 10.0), 3.0),
        ([-5.0, 20.0], (0.0, 10.0), 10.0),     # clip both ends
        ([-5.0, -7.0], (-6.0, 0.0), -11.0),    # negative bounds
        ([], (0.0, 10.0), 0.0),
    ])
    def test_sum_per_value_clip(self, values, bounds, expected):
        c = combiners.SumCombiner(combiner_params(make_params(
            [Metrics.SUM], min_value=bounds[0], max_value=bounds[1])))
        acc = c.create_accumulator(values)
        assert acc == pytest.approx(expected)
        assert c.compute_metrics(acc)["sum"] == pytest.approx(expected,
                                                             abs=0.01)

    @pytest.mark.parametrize("values,expected_count,expected_mean", [
        ([4.0, 6.0], 2, 5.0),
        ([0.0], 1, 0.0),
        ([10.0, 10.0, 10.0], 3, 10.0),
    ])
    def test_mean_normalized_sum_roundtrip(self, values, expected_count,
                                           expected_mean):
        params = make_params([Metrics.MEAN, Metrics.COUNT],
                             max_contributions_per_partition=5)
        c = combiners.MeanCombiner(combiner_params(params),
                                   ["mean", "count"])
        acc = c.create_accumulator(values)
        out = c.compute_metrics(acc)
        assert out["count"] == pytest.approx(expected_count, abs=0.01)
        assert out["mean"] == pytest.approx(expected_mean, abs=0.01)

    def test_mean_merge_matches_pooled(self):
        params = make_params([Metrics.MEAN])
        c = combiners.MeanCombiner(combiner_params(params), ["mean"])
        a = c.create_accumulator([2.0, 4.0])
        b = c.create_accumulator([6.0])
        merged = c.merge_accumulators(a, b)
        assert c.compute_metrics(merged)["mean"] == pytest.approx(4.0,
                                                                  abs=0.01)

    def test_variance_matches_numpy(self):
        rng = np.random.default_rng(0)
        vals = rng.uniform(0, 10, 50).tolist()
        params = make_params([Metrics.VARIANCE],
                             max_contributions_per_partition=100)
        c = combiners.VarianceCombiner(combiner_params(params),
                                       ["variance"])
        out = c.compute_metrics(c.create_accumulator(vals))
        assert out["variance"] == pytest.approx(np.var(vals), rel=0.02)

    def test_variance_merge_matches_pooled(self):
        params = make_params([Metrics.VARIANCE],
                             max_contributions_per_partition=100)
        c = combiners.VarianceCombiner(combiner_params(params),
                                       ["variance"])
        a = c.create_accumulator([1.0, 2.0, 3.0])
        b = c.create_accumulator([7.0, 8.0])
        out = c.compute_metrics(c.merge_accumulators(a, b))
        assert out["variance"] == pytest.approx(
            np.var([1.0, 2.0, 3.0, 7.0, 8.0]), rel=0.05, abs=0.05)

    def test_privacy_id_count_merge(self):
        c = combiners.PrivacyIdCountCombiner(combiner_params(make_params(
            [Metrics.PRIVACY_ID_COUNT])))
        accs = [c.create_accumulator(v) for v in ([1], [], [2, 3], [4])]
        total = accs[0]
        for a in accs[1:]:
            total = c.merge_accumulators(total, a)
        # Empty creates count 0; non-empty count 1 privacy unit each.
        assert total == 3

    @pytest.mark.parametrize("kind,raw,expected", [
        (NormKind.Linf, [3.0, -4.0], [2.0, -2.0]),
        (NormKind.L2, [3.0, 4.0], [1.2, 1.6]),  # scale to norm 2
    ])
    def test_vector_sum_norm_modes(self, kind, raw, expected):
        params = make_params(
            [Metrics.VECTOR_SUM], min_value=None, max_value=None,
            vector_size=2, vector_max_norm=2.0, vector_norm_kind=kind)
        c = combiners.VectorSumCombiner(combiner_params(params))
        acc = c.create_accumulator([np.array(raw)])
        out = c.compute_metrics(acc)["vector_sum"]
        np.testing.assert_allclose(out, expected, atol=0.05)

    def test_quantile_tree_accumulator_is_mergeable_any_order(self):
        params = make_params([Metrics.PERCENTILE(50)],
                             max_contributions_per_partition=100)
        c = combiners.QuantileCombiner(combiner_params(params), [50])
        chunks = [[1.0, 2.0], [8.0, 9.0], [5.0]]
        accs = [c.create_accumulator(ch) for ch in chunks]
        left = c.merge_accumulators(c.merge_accumulators(accs[0], accs[1]),
                                    accs[2])
        right = c.merge_accumulators(accs[0], c.merge_accumulators(
            accs[1], accs[2]))
        m_l = c.compute_metrics(left)
        m_r = c.compute_metrics(right)
        assert m_l["percentile_50"] == pytest.approx(m_r["percentile_50"],
                                                     abs=0.2)

    def test_compound_merge_merges_children_fieldwise(self):
        compound = make_compound()
        a = compound.create_accumulator([1.0, 2.0])
        b = compound.create_accumulator([3.0])
        row_count, children = compound.merge_accumulators(a, b)
        assert row_count == 2  # two creates -> two privacy-unit rows
        out = compound.compute_metrics((row_count, children))
        assert out.count == pytest.approx(3, abs=0.01)
        assert out.sum == pytest.approx(6.0, abs=0.01)
