"""Tests for the DP primitive kernels (the PyDP replacement layer).

Statistical/calibration tests follow the reference's pattern
(``tests/dp_computations_test.py:32``): closed-form identities for
calibration, moment checks for sampling, and exact-probability checks for
partition selection.
"""

import math

import numpy as np
import pytest

from pipelinedp_tpu.aggregate_params import (NoiseKind,
                                             PartitionSelectionStrategy)
from pipelinedp_tpu.ops import noise, partition_selection, quantile_tree


class TestNoiseCalibration:

    def test_laplace_scale(self):
        assert noise.laplace_scale(2.0, 4.0) == 2.0
        assert noise.laplace_std(1.0, 1.0) == pytest.approx(math.sqrt(2))

    @pytest.mark.parametrize("eps,delta", [(1.0, 1e-6), (0.1, 1e-8),
                                           (5.0, 1e-3)])
    def test_gaussian_sigma_is_tight(self, eps, delta):
        sigma = noise.gaussian_sigma(eps, delta, 1.0)
        assert noise.gaussian_delta(eps, sigma, 1.0) <= delta * 1.0001
        assert noise.gaussian_delta(eps, sigma * 0.95, 1.0) > delta

    def test_gaussian_sigma_scales_with_sensitivity(self):
        s1 = noise.gaussian_sigma(1.0, 1e-6, 1.0)
        s3 = noise.gaussian_sigma(1.0, 1e-6, 3.0)
        assert s3 == pytest.approx(3 * s1, rel=1e-6)

    def test_sensitivity_calculus(self):
        # L1 = l0*linf, L2 = sqrt(l0)*linf (reference dp_computations.py:72,85)
        assert noise.compute_l1_sensitivity(4, 3) == 12
        assert noise.compute_l2_sensitivity(4, 3) == pytest.approx(6.0)

    def test_invalid_args(self):
        with pytest.raises(ValueError):
            noise.laplace_scale(0.0, 1.0)
        with pytest.raises(ValueError):
            noise.gaussian_sigma(1.0, 0.0, 1.0)


class TestSampling:

    def test_np_laplace_moments(self):
        noise.seed_host_rng(0)
        x = noise.np_laplace(2.0, shape=200_000)
        assert np.mean(x) == pytest.approx(0.0, abs=0.05)
        assert np.std(x) == pytest.approx(2.0 * math.sqrt(2), rel=0.02)

    def test_jax_laplace_moments(self):
        import jax
        x = noise.jax_laplace(jax.random.PRNGKey(0), (200_000,), 2.0)
        assert float(np.mean(x)) == pytest.approx(0.0, abs=0.05)
        assert float(np.std(x)) == pytest.approx(2.0 * math.sqrt(2),
                                                 rel=0.02)

    def test_jax_gaussian_moments(self):
        import jax
        x = noise.jax_gaussian(jax.random.PRNGKey(1), (200_000,), 3.0)
        assert float(np.std(x)) == pytest.approx(3.0, rel=0.02)


class TestTruncatedGeometric:

    def test_basic_properties(self):
        s = partition_selection.TruncatedGeometricPartitionStrategy(
            epsilon=1.0, delta=1e-5, max_partitions_contributed=1)
        assert s.probability_of_keep(0) == 0.0
        table = s.keep_table
        # Monotone nondecreasing, bounded by 1, saturates.
        assert np.all(np.diff(table) >= -1e-15)
        assert table[-1] == 1.0
        # DP constraint holds along the whole table.
        eps, delta = 1.0, 1e-5
        pi = table
        assert np.all(pi[1:] <= np.exp(eps) * pi[:-1] + delta + 1e-12)

    def test_single_user_leq_delta(self):
        # P(keep | 1 user) <= delta (the core privacy property).
        s = partition_selection.TruncatedGeometricPartitionStrategy(
            epsilon=1.0, delta=1e-5, max_partitions_contributed=1)
        assert s.probability_of_keep(1) <= 1e-5

    def test_large_count_kept(self):
        s = partition_selection.TruncatedGeometricPartitionStrategy(
            epsilon=1.0, delta=1e-5, max_partitions_contributed=1)
        assert s.probability_of_keep(10_000) == 1.0
        assert s.should_keep(10_000)

    def test_max_partitions_needs_more_users(self):
        s1 = partition_selection.TruncatedGeometricPartitionStrategy(
            1.0, 1e-5, max_partitions_contributed=1)
        s4 = partition_selection.TruncatedGeometricPartitionStrategy(
            1.0, 1e-5, max_partitions_contributed=4)
        n = 30
        assert s4.probability_of_keep(n) <= s1.probability_of_keep(n)

    def test_pre_threshold(self):
        s = partition_selection.TruncatedGeometricPartitionStrategy(
            1.0, 1e-5, 1, pre_threshold=10)
        assert s.probability_of_keep(9) == 0.0
        base = partition_selection.TruncatedGeometricPartitionStrategy(
            1.0, 1e-5, 1)
        assert s.probability_of_keep(15) == pytest.approx(
            base.probability_of_keep(6))

    def test_should_keep_statistics(self):
        noise.seed_host_rng(7)
        s = partition_selection.TruncatedGeometricPartitionStrategy(
            1.0, 0.01, 1)
        n = 6
        p = s.probability_of_keep(n)
        assert 0.05 < p < 0.95  # interesting regime
        keeps = sum(s.should_keep(n) for _ in range(4000)) / 4000
        assert keeps == pytest.approx(p, abs=0.04)


@pytest.mark.parametrize("strategy_cls", [
    partition_selection.LaplaceThresholdingPartitionStrategy,
    partition_selection.GaussianThresholdingPartitionStrategy,
])
class TestThresholding:

    def test_single_user_leq_delta(self, strategy_cls):
        s = strategy_cls(epsilon=1.0, delta=1e-5,
                         max_partitions_contributed=1)
        assert s.probability_of_keep(1) <= 1e-5 * 1.001

    def test_monotone_and_saturating(self, strategy_cls):
        s = strategy_cls(1.0, 1e-5, 1)
        probs = s.probabilities(np.arange(0, 500))
        assert np.all(np.diff(probs) >= -1e-12)
        assert probs[-1] > 0.999

    def test_should_keep_matches_probability(self, strategy_cls):
        noise.seed_host_rng(3)
        s = strategy_cls(1.0, 0.05, 1)
        # pick n near the threshold for an interesting keep probability
        n = int(s.threshold)
        p = s.probability_of_keep(n)
        keeps = sum(s.should_keep(n) for _ in range(4000)) / 4000
        assert keeps == pytest.approx(p, abs=0.04)

    def test_pre_threshold_blocks_small(self, strategy_cls):
        s = strategy_cls(1.0, 1e-5, 1, pre_threshold=100)
        assert s.probability_of_keep(99) == 0.0
        assert not s.should_keep(99)


class TestFactory:

    @pytest.mark.parametrize("strategy,cls", [
        (PartitionSelectionStrategy.TRUNCATED_GEOMETRIC,
         partition_selection.TruncatedGeometricPartitionStrategy),
        (PartitionSelectionStrategy.LAPLACE_THRESHOLDING,
         partition_selection.LaplaceThresholdingPartitionStrategy),
        (PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING,
         partition_selection.GaussianThresholdingPartitionStrategy),
    ])
    def test_creates_right_class(self, strategy, cls):
        s = partition_selection.create_partition_selection_strategy(
            strategy, 1.0, 1e-5, 2)
        assert isinstance(s, cls)


class TestQuantileTree:

    def _build(self, values, lo=0.0, hi=100.0):
        t = quantile_tree.QuantileTree(lo, hi)
        for v in values:
            t.add_entry(v)
        return t

    def test_quantiles_with_huge_eps(self):
        # Big-eps determinism pattern (reference tests use eps=1e5).
        noise.seed_host_rng(0)
        values = np.random.default_rng(0).uniform(0, 100, size=5000)
        t = self._build(values)
        got = t.compute_quantiles(eps=1e5, delta=0.0,
                                  max_partitions_contributed=1,
                                  max_contributions_per_partition=1,
                                  quantiles=[0.1, 0.5, 0.9])
        for g, expected in zip(got, [10, 50, 90]):
            assert g == pytest.approx(expected, abs=2.0)

    def test_merge_is_addition(self):
        t1 = self._build([1, 2, 3])
        t2 = self._build([50, 60])
        t1.merge(t2)
        dense = t1.to_dense()
        both = self._build([1, 2, 3, 50, 60]).to_dense()
        assert np.array_equal(dense, both)

    def test_serialize_roundtrip(self):
        t = self._build([5, 10, 20])
        t2 = quantile_tree.QuantileTree.deserialize(t.serialize())
        assert np.array_equal(t.to_dense(), t2.to_dense())

    def test_merge_from_bytes(self):
        t1 = self._build([1])
        t1.merge(self._build([2]).serialize())
        assert t1.to_dense().sum() == 2 * t1.height

    def test_dense_roundtrip(self):
        t = self._build([7, 42, 99])
        dense = t.to_dense()
        t2 = quantile_tree.QuantileTree.from_dense(dense, 0.0, 100.0)
        assert np.array_equal(dense, t2.to_dense())

    def test_values_clipped_to_bounds(self):
        t = self._build([-50, 150])
        got = t.compute_quantiles(1e5, 0.0, 1, 1, [0.0, 1.0])
        assert got[0] >= 0.0 and got[1] <= 100.0

    def test_monotone_output(self):
        noise.seed_host_rng(5)
        t = self._build(np.random.default_rng(1).uniform(0, 100, 200))
        got = t.compute_quantiles(0.5, 0.0, 1, 1,
                                  [0.1, 0.25, 0.5, 0.75, 0.9])
        assert got == sorted(got)

    def test_gaussian_noise_kind(self):
        noise.seed_host_rng(6)
        t = self._build(np.random.default_rng(2).uniform(0, 100, 5000))
        got = t.compute_quantiles(1e5, 1e-6, 1, 1, [0.5],
                                  noise_kind=NoiseKind.GAUSSIAN)
        assert got[0] == pytest.approx(50, abs=3.0)

    def test_dense_paths_match_sparse(self):
        values = np.array([0.0, 37.5, 99.9])
        paths = quantile_tree.values_to_dense_paths(values, 0.0, 100.0)
        t = self._build(values)
        dense = t.to_dense()
        flat = paths.ravel()
        expected = np.zeros_like(dense)
        np.add.at(expected, flat, 1.0)
        assert np.array_equal(dense, expected)
