"""Shape-bucketed request fusion acceptance suite (serve/fusion.py).

The ISSUE-14 criteria, end to end:

* fused-vs-solo DP outputs bit-identical — released values AND kept
  sets — as PARITY row 35, asserted across a bucket boundary (request
  sizes straddling a pow2 edge, so both pad masks are exercised inside
  one batched program), with per-request budget debits and audit
  records unchanged in count and content;
* the pad-mask contract the buckets stand on: the solo kernel is
  padding-invariant (same request, larger row padding, identical
  bits) now that row tie-breaks are content-keyed
  (``ops.counter_rng.row_bits``);
* kill-mid-batch: every fused request's lease resolves exactly once
  (the killed member's reserve stays spent, its companions commit);
* zero new ``compile.program`` captures on the second same-bucket
  batch (one warm program per bucket, the whole point);
* per-tenant row/rate quotas refuse as structured ``quota`` refusals
  BEFORE any reserve or compute;
* live bucket occupancy lands in the heartbeat's serve section.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import pipelinedp_tpu as pdp
from pipelinedp_tpu import jax_engine as je
from pipelinedp_tpu import obs, serve
from pipelinedp_tpu.dp_engine import DataExtractors
from pipelinedp_tpu.obs import monitor as obs_monitor
from pipelinedp_tpu.resilience import faults
from pipelinedp_tpu.resilience.clock import FakeClock
from pipelinedp_tpu.serve import fusion
from pipelinedp_tpu.serve.budget_ledger import TenantBudgetLedger

BIG_EPS = 1e6


@pytest.fixture(autouse=True)
def fresh_state(monkeypatch, tmp_path):
    monkeypatch.setenv("PIPELINEDP_TPU_LEDGER_DIR",
                       str(tmp_path / "obs_ledger"))
    monkeypatch.delenv(obs_monitor.ENV_VAR, raising=False)
    monkeypatch.delenv("PIPELINEDP_TPU_SERVE_FUSION", raising=False)
    obs.reset()
    yield
    obs_monitor.stop()
    obs.reset()
    orphans = [t.name for t in threading.enumerate()
               if (t.name.startswith("pdp-serve")
                   and t.is_alive())]
    assert not orphans, f"orphan serve threads: {orphans}"


def make_ds(seed, n, users=None, parts=30):
    """Data that EXERCISES contribution bounding: ~20 rows per user
    against (l0=3, linf=2) caps, so the bounding subsamples truncate
    hard (the regime where the padding-invariant tie-breaks are
    load-bearing, not vacuously equal) while partitions still carry
    enough users that private selection KEEPS a real subset — the
    parity assertions below must compare non-empty kept sets."""
    rng = np.random.default_rng(seed)
    users = users or max(n // 20, 10)
    return pdp.ArrayDataset(
        privacy_ids=rng.integers(0, users, n),
        partition_keys=rng.integers(0, parts, n),
        values=rng.uniform(0.0, 10.0, n))


def fusable_params():
    return pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM, pdp.Metrics.MEAN,
                 pdp.Metrics.VARIANCE, pdp.Metrics.PERCENTILE(50)],
        noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=3,
        max_contributions_per_partition=2,
        min_value=0.0, max_value=10.0)


def req(tenant, ds, seed, rid, params=None, eps=4.0):
    return serve.ServeRequest(tenant=tenant,
                              params=params or fusable_params(),
                              dataset=ds, epsilon=eps, delta=1e-8,
                              rng_seed=seed, request_id=rid)


def submit_concurrently(svc, requests):
    """Submit all requests from parallel threads (the concurrent-
    tenant model); returns outcomes in request order — a response,
    a refusal, or the raised exception."""
    outs = [None] * len(requests)

    def one(i):
        try:
            outs[i] = svc.submit(requests[i])
        except BaseException as e:  # noqa: BLE001 — surfaced to the test
            outs[i] = e

    threads = [threading.Thread(target=one, args=(i,))
               for i in range(len(requests))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return outs


def assert_results_bit_identical(a, b, ctx=""):
    ka, kb = dict(a), dict(b)
    assert set(ka) == set(kb), f"{ctx}: kept sets differ"
    for k in ka:
        assert ka[k]._fields == kb[k]._fields, (ctx, k)
        for f in ka[k]._fields:
            va, vb = getattr(ka[k], f), getattr(kb[k], f)
            assert va == vb, (f"{ctx}: partition {k} metric {f}: "
                              f"{va!r} != {vb!r}")


# ---------------------------------------------------------------------
# PARITY row 35: fused vs solo, across a bucket boundary
# ---------------------------------------------------------------------


class TestFusedSoloParity:

    # 7000 and 8000 rows both bucket at the 8192 pow2 edge (two
    # different pad masks inside ONE batched program); 9000 rows
    # crosses the boundary into the 16384 bucket.
    SIZES = (7_000, 8_000, 9_000)

    def _run(self, state_dir, fusion_on):
        tenants = {f"t{i}": (BIG_EPS, 1e-3) for i in range(3)}
        datasets = [make_ds(40 + i, n) for i, n in enumerate(self.SIZES)]
        requests = [req(f"t{i}", datasets[i], seed=70 + i, rid=f"r{i}")
                    for i in range(3)]
        with serve.Service(str(state_dir), tenants=tenants, workers=2,
                           fusion=fusion_on, fuse_window_ms=250,
                           fuse_max_batch=2) as svc:
            outs = submit_concurrently(svc, requests)
            debits = {t: svc.budgets.debits(t) for t in tenants}
        return outs, debits

    def test_fused_vs_solo_bit_identical_across_bucket_boundary(
            self, tmp_path):
        solo, solo_debits = self._run(tmp_path / "solo", False)
        obs.reset()
        fused, fused_debits = self._run(tmp_path / "fused", True)
        counters = obs.ledger().snapshot()["counters"]
        # The two same-bucket requests really fused; the third crossed
        # the boundary and ran alone.
        assert counters.get("serve.fusion_offered") == 3
        assert counters.get("serve.fused_batches") == 1
        assert counters.get("serve.fused_requests") == 2
        for i in range(3):
            assert solo[i].ok, solo[i]
            assert fused[i].ok, fused[i]
            # The comparison must not be vacuous: selection kept a
            # real, PARTIAL subset (empty kept sets would "agree"
            # about nothing; a full keep would never witness a
            # selection divergence).
            n_kept = len(dict(solo[i].results))
            assert 0 < n_kept < 30, (i, n_kept)
            # Released values AND kept sets, bit for bit.
            assert_results_bit_identical(solo[i].results,
                                         fused[i].results,
                                         ctx=f"request {i}")
            # Audit records unchanged in count and content.
            assert solo[i].audit == fused[i].audit, i
            assert solo[i].remaining == fused[i].remaining, i
        # Budget debits unchanged in count and content.
        for t in solo_debits:
            strip = lambda d: {k: (v["epsilon"], v["delta"], v["state"])
                               for k, v in d.items()}
            assert strip(solo_debits[t]) == strip(fused_debits[t]), t

    def test_books_audit_records_match_solo(self, tmp_path):
        """The per-tenant books carry one serve.request entry per
        request in BOTH modes, with identical embedded audit records
        (the fused entry is additionally stamped fused: true)."""
        import json
        import os

        from pipelinedp_tpu.serve.budget_ledger import tenant_slug

        def books_entries(state_dir, tenant):
            path = os.path.join(str(state_dir), "books",
                                tenant_slug(tenant),
                                "run_ledger.jsonl")
            out = []
            with open(path, encoding="utf-8") as fh:
                for line in fh:
                    entry = json.loads(line)
                    if entry.get("name") == "serve.request":
                        out.append(entry["payload"]["serve"])
            return out

        self._run(tmp_path / "solo", False)
        self._run(tmp_path / "fused", True)
        for i in range(2):  # the two requests that fused
            solo_b = books_entries(tmp_path / "solo", f"t{i}")
            fused_b = books_entries(tmp_path / "fused", f"t{i}")
            assert len(solo_b) == len(fused_b) == 1
            assert solo_b[0]["audit"] == fused_b[0]["audit"]
            assert fused_b[0].get("fused") is True
            assert "fused" not in solo_b[0]


# ---------------------------------------------------------------------
# the pad-mask contract: padding invariance of the kernel
# ---------------------------------------------------------------------


class TestPaddingInvariance:

    def test_solo_kernel_bit_identical_under_larger_row_padding(self):
        """The property every pow2 bucket stands on: padding the SAME
        request further changes nothing — masks keep padding out of
        the data plane, and the content-keyed row tie-breaks
        (counter_rng.row_bits) keep it out of the sampling plane. A
        regression here (e.g. a new shape-dependent draw) would break
        PARITY row 35 for every bucket whose edge exceeds the solo
        shape."""
        ds = make_ds(7, 7_000)
        params = fusable_params()
        config = je.FusedConfig.from_params(params, public=False)
        encoded = je.encode(ds, DataExtractors(), None, None)
        P_pad = je._pad_pow2(len(encoded.pk_vocab))
        keep_table, thr, s_scale, min_count = je.selection_inputs(
            config, 1.0, 1e-8, None)
        scales = np.asarray([0.9], np.float32)

        def run(rows_pad):
            pid, pk, values, valid = fusion.pad_request_to_bucket(
                encoded, rows_pad, config.needs_values)
            keep, raw = je.fused_aggregate_kernel(
                config, P_pad, jnp.asarray(pid), jnp.asarray(pk),
                jnp.asarray(values), jnp.asarray(valid),
                jnp.asarray(scales), jnp.asarray(keep_table),
                jnp.float32(thr), jnp.float32(s_scale),
                jnp.float32(min_count), jnp.float32(1.0),
                jax.random.PRNGKey(11), fx_bits=12)
            return (np.asarray(keep),
                    {k: np.asarray(v) for k, v in raw.items()})

        base_keep, base_raw = run(je._pad_rows(encoded.n_rows))
        for rows_pad in (16_384, 32_768):
            keep, raw = run(rows_pad)
            np.testing.assert_array_equal(base_keep, keep)
            assert set(base_raw) == set(raw)
            for k in base_raw:
                np.testing.assert_array_equal(base_raw[k], raw[k],
                                              err_msg=f"{rows_pad}:{k}")

    def test_row_bits_are_length_invariant(self):
        from pipelinedp_tpu.ops import counter_rng
        key = jax.random.PRNGKey(3)
        short = np.asarray(counter_rng.row_bits(key, 1_000))
        long = np.asarray(counter_rng.row_bits(key, 4_096))
        np.testing.assert_array_equal(short, long[:1_000])

    @pytest.mark.parametrize("accumulator", ["fx", "f32"])
    def test_vector_kernel_bit_identical_under_larger_row_padding(
            self, accumulator):
        """ISSUE-17 acceptance: VECTOR_SUM (both accumulators) holds
        the same padding invariance the scalar metrics do — any bucket
        edge >= the request's rows yields identical raw accumulator
        bits, so a vector request can ride any compatible bucket."""
        from pipelinedp_tpu import plan as plan_mod
        D = 32
        rng = np.random.default_rng(23)
        n = 7_000
        users = n // 20
        data = [(int(rng.integers(0, users)), int(rng.integers(0, 30)),
                 rng.uniform(-1.0, 1.0, D)) for _ in range(n)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.VECTOR_SUM],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=3,
            max_contributions_per_partition=2,
            vector_size=D, vector_max_norm=4.0,
            vector_norm_kind=pdp.NormKind.L2)
        with plan_mod.seam_override("vector_accumulator", accumulator):
            config = je.FusedConfig.from_params(params, public=False)
        assert config.vector_accumulator == accumulator
        import operator
        ext = DataExtractors(
            privacy_id_extractor=operator.itemgetter(0),
            partition_extractor=operator.itemgetter(1),
            value_extractor=operator.itemgetter(2))
        encoded = je.encode(data, ext, D, None)
        P_pad = je._pad_pow2(len(encoded.pk_vocab))
        keep_table, thr, s_scale, min_count = je.selection_inputs(
            config, 1.0, 1e-8, None)
        scales = np.asarray([0.9], np.float32)
        fx_bits = je.fused_fx_bits(config, 32_768)

        def run(rows_pad):
            pid, pk, values, valid = fusion.pad_request_to_bucket(
                encoded, rows_pad, config.needs_values)
            keep, raw = je.fused_aggregate_kernel(
                config, P_pad, jnp.asarray(pid), jnp.asarray(pk),
                jnp.asarray(values), jnp.asarray(valid),
                jnp.asarray(scales), jnp.asarray(keep_table),
                jnp.float32(thr), jnp.float32(s_scale),
                jnp.float32(min_count), jnp.float32(1.0),
                jax.random.PRNGKey(11), fx_bits=fx_bits)
            return (np.asarray(keep),
                    {k: np.asarray(v) for k, v in raw.items()})

        base_keep, base_raw = run(je._pad_rows(encoded.n_rows))
        assert "vector_sum" in base_raw
        if accumulator == "fx":
            # The accumulator really is the int32 lane plane, not a
            # float path wearing the knob.
            assert base_raw["vector_sum"].dtype == np.int32
        for rows_pad in (16_384, 32_768):
            keep, raw = run(rows_pad)
            np.testing.assert_array_equal(base_keep, keep)
            assert set(base_raw) == set(raw)
            for k in base_raw:
                np.testing.assert_array_equal(base_raw[k], raw[k],
                                              err_msg=f"{rows_pad}:{k}")


class TestBucketVectorCompatibility:
    """ISSUE-17 satellite: the bucket key carries the vector compile
    shape EXPLICITLY — two requests differing in D, norm kind or
    accumulator can never land in one fused batch."""

    @staticmethod
    def _encoded(d):
        import operator
        rng = np.random.default_rng(d)
        data = [(u, u % 7, rng.uniform(-1, 1, d)) for u in range(200)]
        ext = DataExtractors(
            privacy_id_extractor=operator.itemgetter(0),
            partition_extractor=operator.itemgetter(1),
            value_extractor=operator.itemgetter(2))
        return je.encode(data, ext, d, None)

    @staticmethod
    def _config(d, norm_kind=pdp.NormKind.L2, accumulator="f32"):
        from pipelinedp_tpu import plan as plan_mod
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.VECTOR_SUM],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=3,
            max_contributions_per_partition=2,
            vector_size=d, vector_max_norm=4.0,
            vector_norm_kind=norm_kind)
        with plan_mod.seam_override("vector_accumulator", accumulator):
            return je.FusedConfig.from_params(params, public=False)

    def test_different_d_never_share_a_bucket(self):
        k64 = fusion.bucket_for(self._config(64), self._encoded(64),
                                8192)
        k256 = fusion.bucket_for(self._config(256), self._encoded(256),
                                 8192)
        assert k64 is not None and k256 is not None
        assert k64.vector_size == 64 and k256.vector_size == 256
        assert k64 != k256

    def test_norm_kind_and_accumulator_split_buckets(self):
        enc = self._encoded(64)
        l2 = fusion.bucket_for(self._config(64), enc, 8192)
        linf = fusion.bucket_for(
            self._config(64, norm_kind=pdp.NormKind.Linf), enc, 8192)
        fx = fusion.bucket_for(
            self._config(64, accumulator="fx"), enc, 8192)
        assert l2.vector_norm_kind == "l2"
        assert linf.vector_norm_kind == "linf"
        assert fx.vector_accumulator == "fx"
        assert len({l2, linf, fx}) == 3

    def test_scalar_requests_keep_empty_vector_fields(self):
        ds = make_ds(9, 2_000)
        config = je.FusedConfig.from_params(fusable_params(),
                                            public=False)
        encoded = je.encode(ds, DataExtractors(), None, None)
        key = fusion.bucket_for(config, encoded, 8192)
        assert (key.vector_size, key.vector_norm_kind,
                key.vector_accumulator) == (0, "", "")


# ---------------------------------------------------------------------
# kill-mid-batch: every lease resolves exactly once
# ---------------------------------------------------------------------


class TestKillMidBatch:

    def test_killed_member_keeps_reserve_companions_commit(
            self, tmp_path):
        tenants = {f"t{i}": (BIG_EPS, 1e-3) for i in range(3)}
        datasets = [make_ds(50 + i, 7_000) for i in range(3)]
        requests = [req(f"t{i}", datasets[i], seed=80 + i, rid=f"k{i}")
                    for i in range(3)]
        plan = faults.FaultPlan(fail_serve_requests=(1,))
        with faults.injected_faults(plan):
            with serve.Service(str(tmp_path / "svc"), tenants=tenants,
                               workers=2, fusion=True,
                               fuse_window_ms=250,
                               fuse_max_batch=3) as svc:
                outs = submit_concurrently(svc, requests)
        killed = [i for i, o in enumerate(outs)
                  if isinstance(o, faults.ServeKill)]
        served = [i for i, o in enumerate(outs)
                  if not isinstance(o, BaseException) and o.ok]
        assert len(killed) == 1, outs
        assert sorted(killed + served) == [0, 1, 2]
        # Exactly-once lease resolution, read back from the durable
        # ledger: the killed member's reserve STAYS SPENT (noise may
        # have been drawn), each companion committed exactly once.
        led = TenantBudgetLedger(str(tmp_path / "svc" / "budgets"))
        for i in range(3):
            debits = led.debits(f"t{i}")
            assert list(debits) == [f"k{i}"]
            expected = "reserved" if i in killed else "committed"
            assert debits[f"k{i}"]["state"] == expected, (i, debits)


# ---------------------------------------------------------------------
# one warm program per bucket
# ---------------------------------------------------------------------


class TestWarmBucketPrograms:

    def test_second_same_bucket_batch_captures_zero_new_programs(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("PIPELINEDP_TPU_COSTS", "1")
        tenants = {f"t{i}": (BIG_EPS, 1e-3) for i in range(2)}
        datasets = [make_ds(60 + i, 7_000) for i in range(2)]
        with serve.Service(str(tmp_path / "svc"), tenants=tenants,
                           workers=2, fusion=True, fuse_window_ms=250,
                           fuse_max_batch=2) as svc:
            outs = submit_concurrently(svc, [
                req(f"t{i}", datasets[i], seed=90 + i, rid=f"a{i}")
                for i in range(2)])
            assert all(o.ok for o in outs), outs
            captured = obs.ledger().snapshot()["counters"].get(
                "cost.programs_captured", 0)
            outs = submit_concurrently(svc, [
                req(f"t{i}", datasets[i], seed=95 + i, rid=f"b{i}")
                for i in range(2)])
            assert all(o.ok for o in outs), outs
            after = obs.ledger().snapshot()["counters"]
            assert after.get("cost.programs_captured", 0) == captured, (
                "the second same-bucket batch captured new "
                "compile.program spans — the warm program was not "
                "reused")
            assert after.get("serve.fused_batches") == 2

    def test_single_member_window_runs_solo_program(self, tmp_path):
        """A window that expires with one request takes the solo path
        (bit-identical, already compiled) instead of compiling a B=1
        batched program."""
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"t0": (BIG_EPS, 1e-3)}, workers=2,
                           fusion=True, fuse_window_ms=40,
                           fuse_max_batch=4) as svc:
            out = svc.submit(req("t0", make_ds(3, 6_000), seed=5,
                                 rid="solo1"))
            assert out.ok, out
        counters = obs.ledger().snapshot()["counters"]
        assert counters.get("serve.fusion_offered") == 1
        assert counters.get("serve.fused_batches", 0) == 0

    def test_non_fusable_params_fall_through_to_solo_queue(
            self, tmp_path):
        """Params the fused plane rejects (here: a percentile range
        whose f32 leaf constant overflows) skip the fuser entirely and
        serve through the classic path."""
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50)],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=3,
            max_contributions_per_partition=2,
            min_value=0.0, max_value=1e-36)
        assert not je.params_are_fusable(params)
        ds = make_ds(9, 600, users=50, parts=5)
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"t0": (BIG_EPS, 1e-3)}, workers=2,
                           fusion=True, fuse_window_ms=40,
                           fuse_max_batch=4) as svc:
            out = svc.submit(req("t0", ds, seed=5, rid="np1",
                                 params=params))
            assert out.ok, out
        counters = obs.ledger().snapshot()["counters"]
        assert counters.get("serve.fusion_offered", 0) == 0
        assert counters.get("serve.requests_served") == 1


# ---------------------------------------------------------------------
# quotas (ROADMAP serve item (b))
# ---------------------------------------------------------------------


class TestQuotas:

    def test_row_quota_refuses_before_any_reserve(self, tmp_path):
        ds = make_ds(1, 6_000)
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"t0": (2.0, 1e-6)},
                           max_rows_per_request=1_000) as svc:
            out = svc.submit(req("t0", ds, seed=1, rid="q1"))
            assert not out.ok
            assert out.reason == "quota"
            assert "row quota" in out.detail and "1000" in out.detail
            # Nothing was reserved, nothing ran.
            assert svc.budgets.remaining("t0").epsilon == (
                pytest.approx(2.0))
            assert svc.budgets.debits("t0") == {}
        assert "quota" in serve.REFUSAL_REASONS

    def test_per_tenant_row_quota_overrides_service_default(
            self, tmp_path):
        ds = make_ds(2, 3_000)
        with serve.Service(str(tmp_path / "svc")) as svc:
            svc.register_tenant("tight", BIG_EPS, 1e-3,
                                max_rows_per_request=100)
            svc.register_tenant("loose", BIG_EPS, 1e-3)
            refused = svc.submit(req("tight", ds, seed=1, rid="r1"))
            assert not refused.ok and refused.reason == "quota"
            served = svc.submit(req("loose", ds, seed=1, rid="r2"))
            assert served.ok, served

    def test_rate_quota_windows_on_the_injectable_clock(self, tmp_path):
        clock = FakeClock()
        ds = make_ds(3, 2_000, users=200, parts=5)
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"t0": (BIG_EPS, 1e-3)},
                           max_reqs_per_s=2, clock=clock) as svc:
            assert svc.submit(req("t0", ds, seed=1, rid="h1")).ok
            assert svc.submit(req("t0", ds, seed=2, rid="h2")).ok
            third = svc.submit(req("t0", ds, seed=3, rid="h3"))
            assert not third.ok and third.reason == "quota"
            assert "rate quota" in third.detail
            # The refusal itself must not consume window slots, and
            # the window slides: one second later the tenant is
            # admitted again.
            clock.sleep(1.01)
            assert svc.submit(req("t0", ds, seed=4, rid="h4")).ok


# ---------------------------------------------------------------------
# heartbeat: live bucket occupancy
# ---------------------------------------------------------------------


class TestHeartbeatOccupancy:

    def test_monitor_embeds_fusion_snapshot_in_serve_section(
            self, tmp_path):
        clock = FakeClock()
        mon = obs_monitor.Monitor(
            clock=clock, interval_s=1.0, stall_s=60.0,
            heartbeat_path=str(tmp_path / "hb.json")).start_inline()
        obs_monitor.update_fusion(
            {"window_ms": 8, "max_batch": 8, "queued": 3,
             "buckets": {"abc@r8192p64": {
                 "queued": 3, "rows": 8192, "partitions": 64,
                 "window_remaining_s": 0.004}}})
        hb = mon.poll_once()
        assert hb["serve"]["fusion"]["queued"] == 3
        bucket = hb["serve"]["fusion"]["buckets"]["abc@r8192p64"]
        assert bucket["window_remaining_s"] == 0.004
        obs_monitor.update_fusion(None)
        assert "serve" not in mon.poll_once()

    def test_live_fuser_pushes_bucket_occupancy(self, tmp_path):
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"t0": (BIG_EPS, 1e-3)}, workers=2,
                           fusion=True, fuse_window_ms=700,
                           fuse_max_batch=4) as svc:
            seen = []

            def submit_one():
                seen.append(svc.submit(
                    req("t0", make_ds(4, 6_000), seed=6, rid="hb1")))

            t = threading.Thread(target=submit_one)
            t.start()
            # The request sits in its bucket for up to the 700ms
            # window; the pushed snapshot must show it queued.
            deadline = 200
            snap = None
            while deadline:
                snap = obs_monitor.fusion_snapshot()
                if snap and snap.get("queued") == 1:
                    break
                deadline -= 1
                t.join(timeout=0.005)
            assert snap and snap.get("queued") == 1, snap
            (label, bucket), = snap["buckets"].items()
            assert bucket["rows"] == 8192 and bucket["queued"] == 1
            assert bucket["window_remaining_s"] > 0
            t.join()
            assert seen[0].ok, seen[0]
        # The closed fuser clears its heartbeat registration.
        assert obs_monitor.fusion_snapshot() is None


# ---------------------------------------------------------------------
# bench/compare integration
# ---------------------------------------------------------------------


class TestCompareRefusal:

    def test_compare_refuses_cross_fusion_gating(self, monkeypatch):
        import bench

        class _StubLedger:
            fingerprint = "f" * 16

            def baseline(self, metric):
                if metric == "serve_fused_throughput":
                    return ({"ts": 1.0, "payload": {"record": {
                        "value": 100.0, "fusion": False,
                        "plan_source": "default", "plan_hash": None,
                        "kernel_backend": "xla"}}}, False)
                return (None, False)

        monkeypatch.setattr(bench, "_bench_ledger",
                            lambda: _StubLedger())
        monkeypatch.setattr(bench, "plan_provenance",
                            lambda: {"plan_source": "default",
                                     "plan_hash": None})
        rec = {"metric": "serve_fused_throughput", "value": 10.0,
               "unit": "req/s", "fusion": True,
               "plan_source": "default", "plan_hash": None,
               "kernel_backend": "xla"}
        regressions = bench.compare_to_baseline(records=[rec])
        assert regressions["fusion_mismatches"] == 1
        assert regressions["regressed"] == []  # refused, not gated
        (entry,) = regressions["rates"]
        assert entry["fusion_mismatch"] is True
        assert entry["baseline_fusion"] is False
        line = bench.compare_verdict_line(regressions)
        assert line.startswith("COMPARE: fusion-mode mismatch")
