"""Tests of the native secure-noise library (C++/ctypes): build, CSPRNG
stream quality, snapping mechanism invariants (Mironov 2012), discrete
Laplace exactness, and the opt-in wiring through the host noise path."""

import math

import numpy as np
import pytest

native = pytest.importorskip("pipelinedp_tpu.native")

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable")


class TestCSPRNG:

    def test_deterministic_under_seed(self):
        native.seed(42)
        a = native.uniform(1000)
        native.seed(42)
        b = native.uniform(1000)
        np.testing.assert_array_equal(a, b)
        native.seed(43)
        c = native.uniform(1000)
        assert not np.array_equal(a, c)

    def test_uniform_range_and_moments(self):
        native.seed(0)
        u = native.uniform(200_000)
        assert u.min() > 0.0 and u.max() <= 1.0
        assert u.mean() == pytest.approx(0.5, abs=0.005)
        assert u.var() == pytest.approx(1 / 12, rel=0.02)

    def test_os_seeding_differs(self):
        native.seed_from_os()
        a = native.uniform(64)
        native.seed_from_os()
        b = native.uniform(64)
        assert not np.array_equal(a, b)


class TestSnappingLaplace:

    def test_outputs_are_multiples_of_lambda(self):
        native.seed(1)
        scale = 3.0  # Lambda = 4
        out = native.snapping_laplace(np.zeros(5000), scale)
        lam = 4.0
        np.testing.assert_allclose(out / lam, np.round(out / lam),
                                   atol=1e-12)

    def test_statistics_match_laplace(self):
        native.seed(2)
        scale = 2.0
        out = native.snapping_laplace(np.full(200_000, 10.0), scale)
        noise = out - 10.0
        # Snapping adds <= Lambda/2 rounding, preserving the moments.
        assert noise.mean() == pytest.approx(0.0, abs=0.05)
        assert noise.std() == pytest.approx(scale * math.sqrt(2),
                                            rel=0.02)

    def test_clamping(self):
        native.seed(3)
        with pytest.warns(UserWarning, match="clamp bound"):
            out = native.snapping_laplace(np.array([1e9, -1e9]), 1.0,
                                          bound=100.0)
        assert out[0] == 100.0 and out[1] == -100.0

    def test_value_plus_noise_not_raw_float(self):
        # The release must NOT equal value + ieee-laplace noise bit
        # pattern: its mantissa below Lambda is zero.
        native.seed(4)
        out = native.snapping_laplace(np.full(100, math.pi), 1.0)
        lam = 1.0
        assert np.all(out == np.round(out / lam) * lam)


class TestDiscreteLaplace:

    def test_integer_noise_distribution(self):
        native.seed(5)
        b = 2.0
        out = native.discrete_laplace(np.zeros(200_000, np.int64), b)
        assert out.dtype == np.int64
        q = math.exp(-1.0 / b)
        # Two-sided geometric: Var = 2q/(1-q)^2.
        assert out.mean() == pytest.approx(0.0, abs=0.05)
        assert out.var() == pytest.approx(2 * q / (1 - q)**2, rel=0.03)
        # P(0) = (1-q)/(1+q).
        p0 = (out == 0).mean()
        assert p0 == pytest.approx((1 - q) / (1 + q), abs=0.01)


class TestHostPathWiring:

    def test_secure_laplace_release_is_snapped(self):
        import pipelinedp_tpu as pdp
        from pipelinedp_tpu import dp_computations
        from pipelinedp_tpu.ops import noise as noise_ops

        params = dp_computations.ScalarNoiseParams(
            eps=1.0, delta=0.0, min_value=0.0, max_value=1.0,
            min_sum_per_partition=None, max_sum_per_partition=None,
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            noise_kind=pdp.NoiseKind.LAPLACE)
        noise_ops.set_secure_host_noise(True)
        try:
            native.seed(6)
            # Integer query (count): exact discrete Laplace — the release
            # is an integer, not a float with noise bits.
            out = dp_computations.compute_dp_count(1000, params)
            assert out == int(out)
            assert out == pytest.approx(1000, abs=30)
            # Float query (sum): snapping mechanism — multiples of Lambda.
            native.seed(7)
            sums = dp_computations.compute_dp_sum(
                np.full(50, 123.456), dp_computations.ScalarNoiseParams(
                    eps=1.0, delta=0.0, min_value=0.0, max_value=200.0,
                    min_sum_per_partition=None, max_sum_per_partition=None,
                    max_partitions_contributed=1,
                    max_contributions_per_partition=1,
                    noise_kind=pdp.NoiseKind.LAPLACE))
            lam = 256.0  # scale = 200 -> Lambda = 256
            np.testing.assert_allclose(np.asarray(sums) / lam,
                                       np.round(np.asarray(sums) / lam),
                                       atol=1e-9)
        finally:
            noise_ops.set_secure_host_noise(False)

    def test_clamp_warning_on_oversized_release(self):
        with pytest.warns(UserWarning, match="clamp bound"):
            native.snapping_laplace(np.array([1e20]), 1e-6)

    def test_small_scale_keeps_large_release_range(self):
        # scale 1e-6 must not shrink the clamp below realistic values.
        native.seed(8)
        out = native.snapping_laplace(np.array([2.0e8]), 1e-6)
        assert out[0] == pytest.approx(2.0e8, rel=1e-6)

    def test_disabled_by_default(self):
        from pipelinedp_tpu.ops import noise as noise_ops
        assert not noise_ops.secure_host_noise_enabled()


class TestFactorize:
    """The native hash factorizer must be bit-identical to
    np.unique(return_inverse=True)."""

    @pytest.mark.skipif(not native.encode_available(),
                        reason="native toolchain unavailable")
    @pytest.mark.parametrize("gen", [
        lambda rng: rng.integers(-1000, 1000, 10_000),
        lambda rng: rng.integers(0, 2**62, 10_000),       # wide range
        lambda rng: rng.integers(0, 50, 100_000),         # heavy duplicates
        lambda rng: rng.integers(0, 2**62, 2_000_000),    # big + wide
        lambda rng: np.array([7]),                        # single element
        lambda rng: np.array([5, 5, 5, 5]),               # one unique
    ])
    def test_matches_np_unique(self, gen):
        rng = np.random.default_rng(0)
        arr = gen(rng).astype(np.int64)
        uniq, inv = native.factorize_i64(arr)
        exp_uniq, exp_inv = np.unique(arr, return_inverse=True)
        np.testing.assert_array_equal(uniq, exp_uniq)
        np.testing.assert_array_equal(inv, exp_inv)
        np.testing.assert_array_equal(uniq[inv], arr)

    @pytest.mark.skipif(not native.encode_available(),
                        reason="native toolchain unavailable")
    def test_empty(self):
        uniq, inv = native.factorize_i64(np.array([], np.int64))
        assert uniq.size == 0 and inv.size == 0

    @pytest.mark.skipif(not native.encode_available(),
                        reason="native toolchain unavailable")
    def test_uint64_above_int64_max_rejected(self):
        with pytest.raises(ValueError, match="wrap"):
            native.factorize_i64(np.array([2**63 + 5, 3], np.uint64))

    def test_unique_inverse_helper_matches(self):
        # The engine helper must agree with np.unique regardless of
        # whether the native path engaged.
        from pipelinedp_tpu.jax_engine import _unique_inverse
        rng = np.random.default_rng(1)
        for arr in (rng.integers(0, 2**40, 50_000),
                    rng.integers(-5, 5, 1000).astype(np.int32),
                    np.array([2**63 + 5, 3, 2**63 + 5], np.uint64),
                    rng.random(1000)):  # float: always numpy path
            uniq, inv = _unique_inverse(np.asarray(arr))
            exp_u, exp_i = np.unique(arr, return_inverse=True)
            np.testing.assert_array_equal(uniq, exp_u)
            np.testing.assert_array_equal(inv, exp_i)
            assert inv.dtype == np.int32
