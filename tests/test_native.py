"""Tests of the native secure-noise library (C++/ctypes): build, CSPRNG
stream quality, snapping mechanism invariants (Mironov 2012), discrete
Laplace exactness, and the opt-in wiring through the host noise path."""

import math

import numpy as np
import pytest

native = pytest.importorskip("pipelinedp_tpu.native")

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable")


class TestCSPRNG:

    def test_deterministic_under_seed(self):
        native.seed(42)
        a = native.uniform(1000)
        native.seed(42)
        b = native.uniform(1000)
        np.testing.assert_array_equal(a, b)
        native.seed(43)
        c = native.uniform(1000)
        assert not np.array_equal(a, c)

    def test_uniform_range_and_moments(self):
        native.seed(0)
        u = native.uniform(200_000)
        assert u.min() > 0.0 and u.max() <= 1.0
        assert u.mean() == pytest.approx(0.5, abs=0.005)
        assert u.var() == pytest.approx(1 / 12, rel=0.02)

    def test_os_seeding_differs(self):
        native.seed_from_os()
        a = native.uniform(64)
        native.seed_from_os()
        b = native.uniform(64)
        assert not np.array_equal(a, b)


class TestSnappingLaplace:

    def test_outputs_are_multiples_of_lambda(self):
        native.seed(1)
        scale = 3.0  # Lambda = 4
        out = native.snapping_laplace(np.zeros(5000), scale)
        lam = 4.0
        np.testing.assert_allclose(out / lam, np.round(out / lam),
                                   atol=1e-12)

    def test_statistics_match_laplace(self):
        native.seed(2)
        scale = 2.0
        out = native.snapping_laplace(np.full(200_000, 10.0), scale)
        noise = out - 10.0
        # Snapping adds <= Lambda/2 rounding, preserving the moments.
        assert noise.mean() == pytest.approx(0.0, abs=0.05)
        assert noise.std() == pytest.approx(scale * math.sqrt(2),
                                            rel=0.02)

    def test_clamping(self):
        native.seed(3)
        with pytest.warns(UserWarning, match="clamp bound"):
            out = native.snapping_laplace(np.array([1e9, -1e9]), 1.0,
                                          bound=100.0)
        assert out[0] == 100.0 and out[1] == -100.0

    def test_value_plus_noise_not_raw_float(self):
        # The release must NOT equal value + ieee-laplace noise bit
        # pattern: its mantissa below Lambda is zero.
        native.seed(4)
        out = native.snapping_laplace(np.full(100, math.pi), 1.0)
        lam = 1.0
        assert np.all(out == np.round(out / lam) * lam)


class TestDiscreteLaplace:

    def test_integer_noise_distribution(self):
        native.seed(5)
        b = 2.0
        out = native.discrete_laplace(np.zeros(200_000, np.int64), b)
        assert out.dtype == np.int64
        q = math.exp(-1.0 / b)
        # Two-sided geometric: Var = 2q/(1-q)^2.
        assert out.mean() == pytest.approx(0.0, abs=0.05)
        assert out.var() == pytest.approx(2 * q / (1 - q)**2, rel=0.03)
        # P(0) = (1-q)/(1+q).
        p0 = (out == 0).mean()
        assert p0 == pytest.approx((1 - q) / (1 + q), abs=0.01)


class TestDiscreteGaussian:

    def test_integer_noise_distribution(self):
        native.seed(9)
        sigma = 7.5
        out = native.discrete_gaussian(np.zeros(200_000, np.int64), sigma)
        assert out.dtype == np.int64
        # For sigma >> 1 the discrete Gaussian's moments match the
        # continuous one's to O(exp(-2 pi^2 sigma^2)) — far below the
        # sampling error here.
        assert out.mean() == pytest.approx(0.0, abs=0.08)
        assert out.std() == pytest.approx(sigma, rel=0.02)
        # P(0) ~ 1 / (sqrt(2 pi) sigma).
        p0 = (out == 0).mean()
        assert p0 == pytest.approx(1.0 / (math.sqrt(2 * math.pi) * sigma),
                                   abs=0.005)

    def test_small_sigma(self):
        native.seed(10)
        out = native.discrete_gaussian(np.zeros(100_000, np.int64), 0.3)
        # Heavily concentrated at 0; variance matches the theta-function
        # sum, computed directly.
        ks = np.arange(-20, 21)
        w = np.exp(-(ks**2) / (2 * 0.3**2))
        var = float((w * ks**2).sum() / w.sum())
        assert out.var() == pytest.approx(var, rel=0.05)

    def test_sigma_bounds(self):
        with pytest.raises(ValueError):
            native.discrete_gaussian(np.array([0]), 0.0)
        with pytest.raises(ValueError):
            native.discrete_gaussian(np.array([0]), 2.0**41)


class TestSecureGaussian:

    def test_outputs_on_granularity_grid(self):
        native.seed(11)
        sigma = 2.0
        out = native.secure_gaussian(np.full(5000, math.pi), sigma)
        g = 2.0 * 2.0**-40  # lambda_for(2.0) = 2 -> g = 2 * 2^-40
        np.testing.assert_allclose(out / g, np.round(out / g), atol=1e-6)

    def test_statistics_match_gaussian(self):
        native.seed(12)
        sigma = 3.25
        out = native.secure_gaussian(np.full(100_000, 10.0), sigma)
        noise = out - 10.0
        assert noise.mean() == pytest.approx(0.0, abs=0.05)
        assert noise.std() == pytest.approx(sigma, rel=0.02)
        # Normality probe: fourth standardized moment (kurtosis) = 3.
        z = noise / noise.std()
        assert np.mean(z**4) == pytest.approx(3.0, abs=0.15)

    def test_clamping_and_warning(self):
        native.seed(13)
        with pytest.warns(UserWarning, match="clamp bound"):
            out = native.secure_gaussian(np.array([1e9, -1e9]), 1.0,
                                         bound=50.0)
        # Inputs clamp to +/-50 BEFORE noise; the release stays within
        # the bound and within a few sigma of it.
        assert np.all(np.abs(out) <= 50.0)
        assert out[0] == pytest.approx(50.0, abs=6.0)
        assert out[1] == pytest.approx(-50.0, abs=6.0)


class TestHostPathWiring:

    def test_secure_laplace_release_is_snapped(self):
        import pipelinedp_tpu as pdp
        from pipelinedp_tpu import dp_computations
        from pipelinedp_tpu.ops import noise as noise_ops

        params = dp_computations.ScalarNoiseParams(
            eps=1.0, delta=0.0, min_value=0.0, max_value=1.0,
            min_sum_per_partition=None, max_sum_per_partition=None,
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            noise_kind=pdp.NoiseKind.LAPLACE)
        noise_ops.set_secure_host_noise(True)
        try:
            native.seed(6)
            # Integer query (count): exact discrete Laplace — the release
            # is an integer, not a float with noise bits.
            out = dp_computations.compute_dp_count(1000, params)
            assert out == int(out)
            assert out == pytest.approx(1000, abs=30)
            # Float query (sum): snapping mechanism — multiples of Lambda.
            native.seed(7)
            sums = dp_computations.compute_dp_sum(
                np.full(50, 123.456), dp_computations.ScalarNoiseParams(
                    eps=1.0, delta=0.0, min_value=0.0, max_value=200.0,
                    min_sum_per_partition=None, max_sum_per_partition=None,
                    max_partitions_contributed=1,
                    max_contributions_per_partition=1,
                    noise_kind=pdp.NoiseKind.LAPLACE))
            lam = 256.0  # scale = 200 -> Lambda = 256
            np.testing.assert_allclose(np.asarray(sums) / lam,
                                       np.round(np.asarray(sums) / lam),
                                       atol=1e-9)
        finally:
            noise_ops.set_secure_host_noise(False)

    def test_secure_gaussian_release_is_hardened(self):
        import pipelinedp_tpu as pdp
        from pipelinedp_tpu import dp_computations
        from pipelinedp_tpu.ops import noise as noise_ops

        params = dp_computations.ScalarNoiseParams(
            eps=1.0, delta=1e-6, min_value=0.0, max_value=1.0,
            min_sum_per_partition=None, max_sum_per_partition=None,
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            noise_kind=pdp.NoiseKind.GAUSSIAN)
        noise_ops.set_secure_host_noise(True)
        try:
            native.seed(14)
            # Integer query (count): exact discrete Gaussian — integer
            # release.
            out = dp_computations.compute_dp_count(1000, params)
            assert out == int(out)
            assert out == pytest.approx(1000, abs=60)
            # Float query: granularity-snapped discrete Gaussian.
            native.seed(15)
            sums = np.asarray(dp_computations.compute_dp_sum(
                np.full(50, 123.456), dp_computations.ScalarNoiseParams(
                    eps=1.0, delta=1e-6, min_value=0.0, max_value=200.0,
                    min_sum_per_partition=None, max_sum_per_partition=None,
                    max_partitions_contributed=1,
                    max_contributions_per_partition=1,
                    noise_kind=pdp.NoiseKind.GAUSSIAN)))
            sigma = noise_ops.gaussian_sigma(1.0, 1e-6, 200.0)
            g = 2.0**math.ceil(math.log2(sigma)) * 2.0**-40
            np.testing.assert_allclose(sums / g, np.round(sums / g),
                                       atol=1e-5)
        finally:
            noise_ops.set_secure_host_noise(False)

    @pytest.mark.parametrize("noise_kind", ["LAPLACE", "GAUSSIAN"])
    def test_secure_mode_fused_engine_matches_oracle(self, noise_kind,
                                                     monkeypatch):
        """Secure host noise enabled end to end on the fused plane, both
        noise kinds: at huge eps the hardened release still matches the
        exact aggregates (the snapping/granularity grids shrink with the
        noise scale, so no precision is lost). The engine must run with
        rng_seed=None — a seeded reproducible rng bypasses the hardened
        path by design — so the test also counts the native calls to
        prove the hardened samplers actually released the metrics."""
        import pipelinedp_tpu as pdp
        from pipelinedp_tpu.backends import JaxBackend
        from pipelinedp_tpu.ops import noise as noise_ops

        calls = {"int": 0, "float": 0}
        int_fn = (native.discrete_laplace if noise_kind == "LAPLACE"
                  else native.discrete_gaussian)
        float_fn = (native.snapping_laplace if noise_kind == "LAPLACE"
                    else native.secure_gaussian)

        def count_int(vals_, scale, **kw):
            calls["int"] += 1
            return int_fn(vals_, scale, **kw)

        def count_float(vals_, scale, **kw):
            calls["float"] += 1
            return float_fn(vals_, scale, **kw)

        monkeypatch.setattr(
            native,
            "discrete_laplace" if noise_kind == "LAPLACE"
            else "discrete_gaussian", count_int)
        monkeypatch.setattr(
            native,
            "snapping_laplace" if noise_kind == "LAPLACE"
            else "secure_gaussian", count_float)

        rng = np.random.default_rng(16)
        n = 2000
        vals = rng.uniform(0.0, 10.0, n)
        pk = rng.integers(0, 5, n)
        ds = pdp.ArrayDataset(privacy_ids=np.arange(n),
                              partition_keys=pk, values=vals)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM, pdp.Metrics.MEAN],
            max_partitions_contributed=5,
            max_contributions_per_partition=1,
            min_value=0.0, max_value=10.0,
            noise_kind=getattr(pdp.NoiseKind, noise_kind))
        noise_ops.set_secure_host_noise(True)
        try:
            native.seed(16)
            acc = pdp.NaiveBudgetAccountant(total_epsilon=1e12,
                                            total_delta=1e-2)
            engine = pdp.DPEngine(acc, JaxBackend())
            res = engine.aggregate(ds, params, pdp.DataExtractors(),
                                   public_partitions=list(range(5)))
            acc.compute_budgets()
            got = dict(res)
        finally:
            noise_ops.set_secure_host_noise(False)
        # COUNT releases through the integer sampler, SUM (and MEAN's
        # normalized sum) through the float one.
        assert calls["int"] >= 1 and calls["float"] >= 1
        for p in range(5):
            mask = pk == p
            assert got[p].count == pytest.approx(mask.sum(), rel=1e-3)
            assert got[p].sum == pytest.approx(vals[mask].sum(), rel=1e-3)
            assert got[p].mean == pytest.approx(vals[mask].mean(),
                                                rel=1e-3)

    def test_clamp_warning_on_oversized_release(self):
        with pytest.warns(UserWarning, match="clamp bound"):
            native.snapping_laplace(np.array([1e20]), 1e-6)

    def test_small_scale_keeps_large_release_range(self):
        # scale 1e-6 must not shrink the clamp below realistic values.
        native.seed(8)
        out = native.snapping_laplace(np.array([2.0e8]), 1e-6)
        assert out[0] == pytest.approx(2.0e8, rel=1e-6)

    def test_disabled_by_default(self):
        from pipelinedp_tpu.ops import noise as noise_ops
        assert not noise_ops.secure_host_noise_enabled()


class TestFactorize:
    """The native hash factorizer must be bit-identical to
    np.unique(return_inverse=True)."""

    @pytest.mark.skipif(not native.encode_available(),
                        reason="native toolchain unavailable")
    @pytest.mark.parametrize("gen", [
        lambda rng: rng.integers(-1000, 1000, 10_000),
        lambda rng: rng.integers(0, 2**62, 10_000),       # wide range
        lambda rng: rng.integers(0, 50, 100_000),         # heavy duplicates
        lambda rng: rng.integers(0, 2**62, 2_000_000),    # big + wide
        lambda rng: np.array([7]),                        # single element
        lambda rng: np.array([5, 5, 5, 5]),               # one unique
    ])
    def test_matches_np_unique(self, gen):
        rng = np.random.default_rng(0)
        arr = gen(rng).astype(np.int64)
        uniq, inv = native.factorize_i64(arr)
        exp_uniq, exp_inv = np.unique(arr, return_inverse=True)
        np.testing.assert_array_equal(uniq, exp_uniq)
        np.testing.assert_array_equal(inv, exp_inv)
        np.testing.assert_array_equal(uniq[inv], arr)

    @pytest.mark.skipif(not native.encode_available(),
                        reason="native toolchain unavailable")
    def test_empty(self):
        uniq, inv = native.factorize_i64(np.array([], np.int64))
        assert uniq.size == 0 and inv.size == 0

    @pytest.mark.skipif(not native.encode_available(),
                        reason="native toolchain unavailable")
    def test_uint64_above_int64_max_rejected(self):
        with pytest.raises(ValueError, match="wrap"):
            native.factorize_i64(np.array([2**63 + 5, 3], np.uint64))

    def test_unique_inverse_helper_matches(self):
        # The engine helper must agree with np.unique regardless of
        # whether the native path engaged.
        from pipelinedp_tpu.jax_engine import _unique_inverse
        rng = np.random.default_rng(1)
        for arr in (rng.integers(0, 2**40, 50_000),
                    rng.integers(-5, 5, 1000).astype(np.int32),
                    np.array([2**63 + 5, 3, 2**63 + 5], np.uint64),
                    rng.random(1000)):  # float: always numpy path
            uniq, inv = _unique_inverse(np.asarray(arr))
            exp_u, exp_i = np.unique(arr, return_inverse=True)
            np.testing.assert_array_equal(uniq, exp_u)
            np.testing.assert_array_equal(inv, exp_i)
            assert inv.dtype == np.int32
