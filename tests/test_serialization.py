"""Real-serialization proof for the cluster adapters (VERDICT r2 #7).

Beam/Spark runners pickle the engine's closures to ship them to workers
(reference ``private_beam``/Beam ``CombinePerKey``; SURVEY.md §3.3). The
two-phase budget protocol exists precisely for this: ``MechanismSpec``
objects are mutated in place by ``compute_budgets()`` BEFORE the runner
serializes the graph, so the pickled copies must carry final budgets and
compute identical results on a worker. These tests exercise that pickling
dimension with the stdlib pickler (the structural fakes in
``fake_beam``/``fake_spark`` execute in-process and cannot catch it); the
CI ``cluster-adapters`` job additionally runs the TestRealBeam/Spark
suites on genuine runners.
"""

import operator
import pickle

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import combiners as dp_combiners
from pipelinedp_tpu.ops import noise as noise_ops


def _build_compound(metrics, eps=1e5, delta=1e-2, **kw):
    params = pdp.AggregateParams(
        metrics=metrics, max_partitions_contributed=2,
        max_contributions_per_partition=3, **kw)
    acc = pdp.NaiveBudgetAccountant(total_epsilon=eps, total_delta=delta)
    compound = dp_combiners.create_compound_combiner(params, acc)
    return compound, acc


class TestCombinerPickling:

    def test_compound_combiner_round_trips_after_budgets(self):
        # The worker-side object: a compound combiner whose specs were
        # filled in place before serialization. The unpickled copy must
        # produce the same metrics (huge eps -> noise negligible).
        noise_ops.seed_host_rng(0)
        compound, acc = _build_compound(
            [pdp.Metrics.COUNT, pdp.Metrics.MEAN], min_value=0.0,
            max_value=10.0)
        acc.compute_budgets()
        blob = pickle.dumps(compound)
        worker = pickle.loads(blob)
        accumulator = worker.create_accumulator([1.0, 5.0, 9.0])
        merged = worker.merge_accumulators(
            accumulator, worker.create_accumulator([2.0]))
        local = compound.compute_metrics(
            compound.merge_accumulators(
                compound.create_accumulator([1.0, 5.0, 9.0]),
                compound.create_accumulator([2.0])))
        remote = worker.compute_metrics(merged)
        assert remote._fields == local._fields
        for f in remote._fields:
            assert getattr(remote, f) == pytest.approx(
                getattr(local, f), rel=1e-3, abs=0.5)

    def test_spec_values_survive_pickling(self):
        compound, acc = _build_compound([pdp.Metrics.COUNT])
        acc.compute_budgets()
        worker = pickle.loads(pickle.dumps(compound))
        spec = worker._combiners[0]._params.mechanism_spec
        assert spec.eps == pytest.approx(1e5)

    def test_pickle_before_budgets_still_lazy(self):
        # Serializing BEFORE compute_budgets yields a DISCONNECTED copy:
        # in-place mutation cannot reach it. The copy must loudly refuse
        # to compute rather than silently run with no budget — the
        # behavior the two-phase protocol's ordering contract relies on.
        compound, acc = _build_compound([pdp.Metrics.COUNT])
        worker = pickle.loads(pickle.dumps(compound))
        acc.compute_budgets()
        accumulator = worker.create_accumulator([1.0])
        with pytest.raises(AssertionError, match="compute_budgets"):
            worker.compute_metrics(accumulator)

    def test_quantile_combiner_accumulator_round_trips(self):
        # Quantile accumulators serialize the host tree to bytes
        # (reference combiners.py:420-432).
        noise_ops.seed_host_rng(0)
        compound, acc = _build_compound(
            [pdp.Metrics.PERCENTILE(50)], min_value=0.0, max_value=100.0)
        acc.compute_budgets()
        worker = pickle.loads(pickle.dumps(compound))
        accumulator = worker.create_accumulator([10.0, 50.0, 90.0])
        blob = pickle.dumps(accumulator)  # the shuffled payload
        merged = worker.merge_accumulators(pickle.loads(blob),
                                           worker.create_accumulator([50.0]))
        out = worker.compute_metrics(merged)
        assert 0.0 <= out.percentile_50 <= 100.0

    def test_metrics_tuple_round_trips(self):
        # The output rows Beam re-shuffles downstream (custom __reduce__).
        mt = dp_combiners._create_named_tuple_instance(
            "MetricsTuple", ("count", "sum"), (3.0, 7.5))
        back = pickle.loads(pickle.dumps(mt))
        assert back.count == 3.0 and back.sum == 7.5
        assert back == mt


class TestEngineClosurePickling:

    def test_selection_filter_closure_pickles(self):
        # The private-partition-selection filter ships to workers as a
        # functools.partial over module-level functions (reference
        # dp_engine.py:350-357) — it must survive the stdlib pickler.
        from pipelinedp_tpu import dp_engine as engine_mod

        captured = {}

        class CapturingBackend(pdp.LocalBackend):
            def filter(self, col, fn, stage_name=None):
                captured.setdefault("fns", []).append(fn)
                return super().filter(col, fn, stage_name)

        noise_ops.seed_host_rng(0)
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1e5,
                                        total_delta=1e-2)
        engine = pdp.DPEngine(acc, CapturingBackend())
        data = [(u, "a", 1.0) for u in range(50)]
        ex = pdp.DataExtractors(
            privacy_id_extractor=operator.itemgetter(0),
            partition_extractor=operator.itemgetter(1),
            value_extractor=operator.itemgetter(2))
        result = engine.aggregate(
            data,
            pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                max_partitions_contributed=1,
                                max_contributions_per_partition=1), ex)
        acc.compute_budgets()
        out = dict(result)
        assert "a" in out
        assert captured["fns"], "selection filter was never constructed"
        for fn in captured["fns"]:
            clone = pickle.loads(pickle.dumps(fn))
            row = ("a", next(iter([(50, ())])))  # (pk, accumulator) shape
            # The clone must behave like the original on the same input.
            sample = ("a", (50, ()))
            assert clone(sample) == fn(sample)

    def test_accountant_itself_not_required_on_workers(self):
        # Workers receive specs, never the accountant; a pickled compound
        # must not drag the whole accountant (and its mechanism registry)
        # into the closure.
        compound, acc = _build_compound([pdp.Metrics.COUNT])
        acc.compute_budgets()
        blob = pickle.dumps(compound)
        import pickletools
        ops = {op.name for op, arg, pos in pickletools.genops(blob)}
        # Sanity: it unpickles standalone with the accountant deleted.
        del acc
        worker = pickle.loads(blob)
        assert worker.metrics_names() == ["count"]
