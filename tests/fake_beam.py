"""A lazy, list-backed structural stand-in for the slice of the
apache_beam API that ``pipelinedp_tpu.beam_backend`` and
``pipelinedp_tpu.private_beam`` consume.

Purpose: apache_beam is not installable in every environment, but the
adapter code paths (stage-label uniqueness, closure semantics, the
CoGroupByKey join regime, the fluent transforms) deserve execution, not
just parsing. Registering this module as ``sys.modules['apache_beam']``
before importing the adapters runs them for real against deferred
collections. Like Beam, execution is deferred: transforms compose thunks
and nothing runs until a collection is materialized — which is what lets
the two-phase budget protocol (compute_budgets after graph construction)
work unchanged.

This is a test double, not a Beam reimplementation: only the operations
the adapters use exist, and scheduling/windowing/distribution are out of
scope.
"""

from __future__ import annotations

import functools
import itertools
import random as _random
import sys
import types


class Pipeline:

    def __init__(self):
        self._labels = set()

    # Real beam pipelines run on context exit; the fake is eager, so the
    # context manager is a pass-through.
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def check_label(self, label):
        if label is None:
            return
        if label in self._labels:
            raise RuntimeError(
                f"A transform with label {label!r} already exists in the "
                "pipeline (beam requires unique stage names)")
        self._labels.add(label)

    def apply(self, transform, pvalue):
        return transform.expand(pvalue)

    def __or__(self, rhs):
        return _apply(self, rhs)


class PCollection:

    def __init__(self, pipeline, thunk):
        self.pipeline = pipeline
        self._thunk = thunk
        self._cache = None

    def materialize(self):
        if self._cache is None:
            self._cache = list(self._thunk())
        return self._cache

    def __iter__(self):
        return iter(self.materialize())

    def __or__(self, rhs):
        return _apply(self, rhs)


def _pipeline_of(pvalue):
    if isinstance(pvalue, Pipeline):
        return pvalue
    if isinstance(pvalue, PCollection):
        return pvalue.pipeline
    if isinstance(pvalue, (tuple, list)):
        return _pipeline_of(pvalue[0])
    if isinstance(pvalue, dict):
        return _pipeline_of(next(iter(pvalue.values())))
    raise TypeError(f"no pipeline on {pvalue!r}")


def _apply(pvalue, transform):
    if not isinstance(transform, PTransform):
        raise TypeError(f"cannot apply {transform!r}")
    _pipeline_of(pvalue).check_label(transform.label)
    return transform.expand(pvalue)


class PTransform:
    label = None

    def __init__(self, label=None):
        # Real beam's PTransform accepts an optional label.
        if label is not None:
            self.label = label

    def __rrshift__(self, label):
        # "stage name" >> transform
        self.label = label
        return self

    def __ror__(self, pvalue):
        # tuple-of-pcollections | Flatten(), dict | CoGroupByKey()
        return _apply(pvalue, self)

    def expand(self, pvalue):
        raise NotImplementedError

    # -- helpers for subclasses --
    @staticmethod
    def _derive(pvalue, fn):
        return PCollection(_pipeline_of(pvalue), fn)


class Create(PTransform):

    def __init__(self, iterable):
        self._data = iterable

    def expand(self, pipeline):
        data = self._data
        return PCollection(pipeline, lambda: list(data))


class Map(PTransform):

    def __init__(self, fn):
        self._fn = fn

    def expand(self, col):
        fn = self._fn
        return self._derive(col, lambda: [fn(x) for x in col.materialize()])


class MapTuple(PTransform):

    def __init__(self, fn):
        self._fn = fn

    def expand(self, col):
        fn = self._fn
        return self._derive(col,
                            lambda: [fn(*x) for x in col.materialize()])


class FlatMap(PTransform):

    def __init__(self, fn):
        self._fn = fn

    def expand(self, col):
        fn = self._fn
        return self._derive(
            col,
            lambda: list(itertools.chain.from_iterable(
                fn(x) for x in col.materialize())))


class Filter(PTransform):

    def __init__(self, fn):
        self._fn = fn

    def expand(self, col):
        fn = self._fn
        return self._derive(col,
                            lambda: [x for x in col.materialize() if fn(x)])


def _group(pairs):
    out = {}
    for k, v in pairs:
        out.setdefault(k, []).append(v)
    return out


class GroupByKey(PTransform):

    def expand(self, col):
        return self._derive(
            col, lambda: list(_group(col.materialize()).items()))


class CombinePerKey(PTransform):

    def __init__(self, fn):
        self._fn = fn

    def expand(self, col):
        fn = self._fn
        return self._derive(
            col, lambda: [(k, fn(vs))
                          for k, vs in _group(col.materialize()).items()])


class Keys(PTransform):

    def expand(self, col):
        return self._derive(col,
                            lambda: [k for k, _ in col.materialize()])


class Values(PTransform):

    def expand(self, col):
        return self._derive(col,
                            lambda: [v for _, v in col.materialize()])


class Distinct(PTransform):

    def expand(self, col):
        def thunk():
            seen, out = set(), []
            for x in col.materialize():
                if x not in seen:
                    seen.add(x)
                    out.append(x)
            return out
        return self._derive(col, thunk)


class Flatten(PTransform):

    def expand(self, cols):
        return PCollection(
            _pipeline_of(cols),
            lambda: list(itertools.chain.from_iterable(
                c.materialize() for c in cols)))


class DoFn:

    def process(self, element):
        raise NotImplementedError


class ParDo(PTransform):

    def __init__(self, dofn):
        self._dofn = dofn

    def expand(self, col):
        dofn = self._dofn
        return self._derive(
            col,
            lambda: list(itertools.chain.from_iterable(
                dofn.process(x) for x in col.materialize())))


class CoGroupByKey(PTransform):

    def expand(self, tagged):
        def thunk():
            grouped = {}
            for tag, col in tagged.items():
                for k, v in col.materialize():
                    grouped.setdefault(k, {t: [] for t in tagged})[
                        tag].append(v)
            return list(grouped.items())
        return PCollection(_pipeline_of(tagged), thunk)


class _SampleFixedSizePerKey(PTransform):

    def __init__(self, n):
        self._n = n

    def expand(self, col):
        n = self._n
        return self._derive(
            col, lambda: [(k, _random.sample(vs, min(n, len(vs))))
                          for k, vs in _group(col.materialize()).items()])


class _CountPerElement(PTransform):

    def expand(self, col):
        def thunk():
            out = {}
            for x in col.materialize():
                out[x] = out.get(x, 0) + 1
            return list(out.items())
        return self._derive(col, thunk)


class _ToList(PTransform):

    def expand(self, col):
        return self._derive(col, lambda: [col.materialize()])


def build_fake_beam_module() -> types.ModuleType:
    """An ``apache_beam``-shaped module object for sys.modules."""
    mod = types.ModuleType("apache_beam")
    for name, obj in (("Pipeline", Pipeline), ("PCollection", PCollection),
                      ("PTransform", PTransform), ("Create", Create),
                      ("Map", Map), ("MapTuple", MapTuple),
                      ("FlatMap", FlatMap), ("Filter", Filter),
                      ("GroupByKey", GroupByKey),
                      ("CombinePerKey", CombinePerKey), ("Keys", Keys),
                      ("Values", Values), ("Distinct", Distinct),
                      ("Flatten", Flatten), ("DoFn", DoFn),
                      ("ParDo", ParDo), ("CoGroupByKey", CoGroupByKey)):
        setattr(mod, name, obj)

    combiners = types.ModuleType("apache_beam.combiners")
    sample = types.SimpleNamespace(FixedSizePerKey=_SampleFixedSizePerKey)
    combiners.Sample = sample
    combiners.Count = types.SimpleNamespace(
        PerElement=_CountPerElement)
    combiners.ToList = _ToList
    mod.combiners = combiners

    transforms = types.ModuleType("apache_beam.transforms")
    ptransform = types.ModuleType("apache_beam.transforms.ptransform")
    ptransform.PTransform = PTransform
    transforms.ptransform = ptransform
    mod.transforms = transforms

    # Submodule registration so "from apache_beam.transforms import
    # ptransform" resolves.
    sys.modules.setdefault("apache_beam.combiners", combiners)
    sys.modules.setdefault("apache_beam.transforms", transforms)
    sys.modules.setdefault("apache_beam.transforms.ptransform", ptransform)
    return mod
