"""Worker process for the multi-host (DCN) mesh test.

Launched by ``tests/test_multihost.py`` as one of two
``jax.distributed`` processes, each contributing 4 virtual CPU devices
to an 8-device GLOBAL mesh (the CPU stand-in for a 2-host TPU slice
connected over DCN — SURVEY §5.8). Runs one fused aggregation with the
partition axis owner-sharded across the process boundary and checks:

* exact aggregates at huge eps against the host truth;
* selection bit-parity: the global mesh's kept-partition set equals a
  single LOCAL device run with the same PRNG seed (the power-of-two
  global axis guarantee from ``parallel/sharded.py``).

Not a pytest file — invoked directly with (process_id, n_processes,
rendezvous_file) argv.
"""

import os
import sys


def rendezvous_port(proc_id: int, path: str,
                    timeout_s: float = 180.0) -> int:
    """File-based coordinator rendezvous. Process 0 allocates a free
    port IMMEDIATELY before the coordinator binds it (closing the
    parent-side pick-then-spawn window another process could steal the
    port in) and publishes it atomically; the others poll the file.
    Shared by every multihost worker variant."""
    import json
    import socket
    import tempfile
    import time
    if proc_id == 0:
        s = socket.socket()
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
        s.close()
        d = os.path.dirname(os.path.abspath(path)) or "."
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(json.dumps({"port": port}))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return port
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with open(path, encoding="utf-8") as f:
                return int(json.loads(f.read())["port"])
        except (OSError, ValueError, KeyError):
            time.sleep(0.05)  # not written (or mid-replace) yet
    raise RuntimeError(f"rendezvous file {path} never appeared "
                       f"within {timeout_s:g}s")


def main() -> None:
    proc_id = int(sys.argv[1])
    n_proc = int(sys.argv[2])
    rendezvous = sys.argv[3]

    # Self-deadline: if the parent test process is killed (suite
    # timeout, operator ^C) before its own worker-kill deadline fires,
    # an orphaned worker would spin in a gloo collective forever. The
    # watchdog makes the worker ITS OWN hard deadline.
    import threading
    watchdog = threading.Timer(480.0, lambda: os._exit(3))
    watchdog.daemon = True  # never keeps a FINISHED worker alive
    watchdog.start()

    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    # Synchronous dispatch: collectives from two in-flight executables
    # must never interleave — XLA:CPU gloo ops are keyed per-op only
    # WITHIN an executable, so a cross-executable overlap can pair
    # mismatched ops across the process boundary and abort the worker
    # with a preamble-size mismatch (see test_multihost.py:_clean_env).
    jax.config.update("jax_cpu_enable_async_dispatch", False)
    port = rendezvous_port(proc_id, rendezvous)
    # Bounded-retry init: coordinator handshakes lose races on loaded
    # hosts, and a second attempt (jittered per process id) usually
    # lands. Exhausted retries raise — a hard failure the parent test
    # reports, never a silent hang.
    from pipelinedp_tpu.resilience import (RetryPolicy,
                                           resilient_distributed_initialize)
    resilient_distributed_initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=n_proc, process_id=proc_id,
        policy=RetryPolicy(max_attempts=2, base_delay_s=1.0,
                           multiplier=2.0, max_delay_s=10.0,
                           jitter=0.25, seed=proc_id))
    assert len(jax.devices()) == 4 * n_proc, jax.devices()
    assert len(jax.local_devices()) == 4

    import numpy as np

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu.backends import JaxBackend
    from pipelinedp_tpu.parallel import make_mesh

    mesh = make_mesh()  # all 8 global devices
    assert mesh.devices.size == 4 * n_proc

    rng = np.random.default_rng(0)  # identical data on every process
    n = 20_000
    pid = rng.integers(0, 2_000, n)
    pk = rng.integers(0, 40, n)
    vals = rng.uniform(0.0, 10.0, n)
    # A handful of single-user partitions that selection must drop.
    pk[:30] = 40 + np.arange(30) % 10
    ds = pdp.ArrayDataset(privacy_ids=pid, partition_keys=pk,
                          values=vals)
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
        max_partitions_contributed=50,
        max_contributions_per_partition=50,
        min_value=0.0, max_value=10.0)

    def run(backend):
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1e8,
                                        total_delta=1e-6)
        engine = pdp.DPEngine(acc, backend)
        res = engine.aggregate(ds, params, pdp.DataExtractors())
        acc.compute_budgets()
        return dict(res)

    from pipelinedp_tpu import obs
    from pipelinedp_tpu.parallel import sharded as psh

    obs.reset()
    sharded = run(JaxBackend(mesh=mesh, rng_seed=11))
    # The comms meter records at trace time, so the counters must be
    # read off the FIRST (cold) dispatch of each topology's programs.
    flat_comms = dict(obs.ledger().snapshot()["counters"])
    ds.invalidate_cache()
    local = run(JaxBackend(rng_seed=11))

    # Bit-parity: identical keep decisions across the process boundary.
    assert set(sharded) == set(local), (
        f"keep sets differ: {sorted(set(sharded) ^ set(local))}")
    for k in range(40):
        m = pk == k
        assert abs(sharded[k].count - m.sum()) < 1.0
        assert abs(sharded[k].sum - vals[m].sum()) < 1.0
        assert abs(sharded[k].count - local[k].count) < 1e-6

    # HIER topology leg: the process boundary is a REAL host boundary
    # here (process_index grouping, nothing simulated), so the two-axis
    # mesh interleaves devices across the two processes and the
    # two-stage exchange's DCN stage rides actual gloo collectives.
    # The release must be BIT-IDENTICAL to the flat run — float for
    # float, same kept set — while the estimated cross-host bytes drop.
    os.environ["PIPELINEDP_TPU_MESH_TOPOLOGY"] = "hier"
    try:
        hier_mesh = make_mesh()
    finally:
        del os.environ["PIPELINEDP_TPU_MESH_TOPOLOGY"]
    topo = psh.topology_of(hier_mesh)
    assert topo.hierarchical and not topo.simulated, topo
    assert (topo.n_hosts, topo.per_host) == (n_proc, 4), topo
    obs.reset()
    ds.invalidate_cache()
    hier = run(JaxBackend(mesh=hier_mesh, rng_seed=11))
    hier_comms = dict(obs.ledger().snapshot()["counters"])
    assert set(hier) == set(sharded), (
        f"hier kept set differs: {sorted(set(hier) ^ set(sharded))}")
    for k in sharded:
        assert sharded[k] == hier[k], (k, sharded[k], hier[k])
    flat_dcn = flat_comms.get("comms.dcn_bytes", 0)
    hier_dcn = hier_comms.get("comms.dcn_bytes", 0)
    hier_ici = hier_comms.get("comms.ici_bytes", 0)
    assert flat_dcn > 0, flat_comms
    assert hier_dcn > 0 and hier_ici > 0, hier_comms
    assert hier_dcn < flat_dcn, (hier_dcn, flat_dcn)
    print(f"proc {proc_id}: comms dcn_flat={flat_dcn} "
          f"dcn_hier={hier_dcn} ici_hier={hier_ici}", flush=True)

    # STREAMING over the cross-process mesh: force tiny per-device
    # chunks so the same dataset streams through >= 3 sharded chunks
    # (replicated-psum exchange — every process folds its own copy).
    # PERCENTILE is included deliberately: its two-pass walk host-
    # fetches the top-walk state and the pass-B subtree histograms,
    # the exact fetch class that breaks on non-addressable shards —
    # this run proves those fetches across the process boundary too.
    os.environ["PIPELINEDP_TPU_STREAM_CHUNK"] = "500"
    try:
        ds.invalidate_cache()
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1e8,
                                        total_delta=1e-6)
        engine = pdp.DPEngine(acc, JaxBackend(mesh=mesh, rng_seed=11))
        stream_params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM,
                     pdp.Metrics.PERCENTILE(50)],
            max_partitions_contributed=50,
            max_contributions_per_partition=50,
            min_value=0.0, max_value=10.0)
        res = engine.aggregate(ds, stream_params, pdp.DataExtractors(),
                               public_partitions=list(range(40)))
        acc.compute_budgets()
        streamed = dict(res)
        n_batches = res.timings.get("stream_batches", 0)
        assert n_batches >= 3, (
            f"dataset did not stream over the 2-process mesh "
            f"({n_batches} batches)")
        for k in range(40):
            m = pk == k
            assert abs(streamed[k].count - m.sum()) < 1.0
            assert abs(streamed[k].sum - vals[m].sum()) < 1.0
            true_med = float(np.percentile(vals[m], 50))
            assert abs(streamed[k].percentile_50 - true_med) < 0.5, (
                k, streamed[k].percentile_50, true_med)
    finally:
        del os.environ["PIPELINEDP_TPU_STREAM_CHUNK"]

    # The analysis sweep over the cross-process mesh: config axis split
    # across processes, outputs all_gathered so each process packs its
    # own copy; must match the single-device sweep.
    from pipelinedp_tpu import analysis
    multi = analysis.MultiParameterConfiguration(
        max_partitions_contributed=list(range(1, 9)),
        max_contributions_per_partition=[2] * 8)
    options = analysis.UtilityAnalysisOptions(
        epsilon=1.0, delta=1e-6,
        aggregate_params=pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT], max_partitions_contributed=4,
            max_contributions_per_partition=2),
        multi_param_configuration=multi)
    ds.invalidate_cache()
    sweep_mesh = list(analysis.perform_utility_analysis(
        ds, JaxBackend(mesh=mesh, rng_seed=11), options,
        pdp.DataExtractors()))[0]
    ds.invalidate_cache()
    sweep_one = list(analysis.perform_utility_analysis(
        ds, JaxBackend(rng_seed=11), options, pdp.DataExtractors()))[0]
    assert len(sweep_mesh) == len(sweep_one) == 8
    for a, b in zip(sweep_one, sweep_mesh):
        av = a.count_metrics.error_expected
        bv = b.count_metrics.error_expected
        assert abs(av - bv) <= 1e-4 * max(1.0, abs(av)), (av, bv)

    print(f"proc {proc_id}: OK ({len(sharded)} partitions kept, "
          f"streamed {n_batches} chunks, 8-config sweep, "
          f"mesh={mesh.shape})", flush=True)


if __name__ == "__main__":
    main()
