"""Metrics-plane + wire-surface acceptance suite (`make metricscheck`).

The ISSUE-19 acceptance criteria, end to end:

* request-scoped trace contexts stay isolated across concurrent
  tenants (contextvars never bleed between submitter threads), and
  spans/events record true parentage under nesting;
* one fused batch of >= 2 tenants' requests reconstructs as >= 2
  complete per-request span trees (admission through books commit) via
  BOTH the live ``/trace/<id>`` endpoint and the durable
  ``store --summarize --trace-id`` CLI twin, with Chrome-trace flow
  events connecting each request's arc;
* fixed-bucket histograms honor the inclusive-``le`` boundary contract
  exactly, and ``/metrics`` serves per-tenant budget gauges + phase
  latency histograms through a LIVE scrape;
* the endpoint is off by default (zero new threads), survives a
  ServeKill episode, and drains with ``Service.close`` (no orphan
  ``pdp-obs-http`` accept loop);
* context stamping on/off leaves DP outputs bit-identical (PARITY
  row 42);
* the heartbeat grows a per-tenant budget section fed by the durable
  budget ledger.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import obs, serve
from pipelinedp_tpu.obs import http as obs_http
from pipelinedp_tpu.obs import metrics as obs_metrics
from pipelinedp_tpu.obs import monitor as obs_monitor
from pipelinedp_tpu.obs import report as obs_report
from pipelinedp_tpu.obs import store as obs_store
from pipelinedp_tpu.obs import trace_context


@pytest.fixture(autouse=True)
def fresh_state(monkeypatch, tmp_path):
    """Fresh obs state, isolated ledger dir, endpoint + heartbeat off
    unless a test arms them — and a zero-orphan-thread assertion
    (pdp-serve workers AND the pdp-obs-http accept loop)."""
    monkeypatch.setenv("PIPELINEDP_TPU_LEDGER_DIR",
                       str(tmp_path / "obs_ledger"))
    monkeypatch.delenv(obs_http.ENV_VAR, raising=False)
    monkeypatch.delenv(obs_monitor.ENV_VAR, raising=False)
    obs.reset()
    yield
    obs_monitor.stop()
    obs.reset()
    orphans = [t.name for t in threading.enumerate()
               if (t.name.startswith("pdp-serve")
                   or t.name == "pdp-obs-http") and t.is_alive()]
    assert not orphans, f"orphan threads: {orphans}"


def make_ds(seed=0, n=3_000, users=800, parts=8):
    rng = np.random.default_rng(seed)
    return pdp.ArrayDataset(privacy_ids=rng.integers(0, users, n),
                            partition_keys=rng.integers(0, parts, n),
                            values=rng.uniform(0.0, 10.0, n))


def count_params(parts=8):
    return pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
        max_partitions_contributed=parts,
        max_contributions_per_partition=20,
        min_value=0.0, max_value=10.0)


def request(tenant, ds, eps=1.0, delta=1e-8, seed=7, rid=None):
    return serve.ServeRequest(tenant=tenant, params=count_params(),
                              dataset=ds, epsilon=eps, delta=delta,
                              rng_seed=seed, request_id=rid)


def http_get(url):
    """(status, parsed-or-text body) without raising on 4xx/5xx."""
    try:
        with urllib.request.urlopen(url) as resp:
            body = resp.read().decode("utf-8")
            code = resp.status
    except urllib.error.HTTPError as e:
        body = e.read().decode("utf-8")
        code = e.code
    try:
        return code, json.loads(body)
    except ValueError:
        return code, body


# ---------------------------------------------------------------------
# request-scoped context propagation
# ---------------------------------------------------------------------


class TestTraceContext:

    def test_concurrent_binds_are_isolated(self):
        """contextvars isolation under a deliberate interleave: every
        thread binds its own context, meets the others at a barrier
        INSIDE the bind, and still reads back only its own ids."""
        n = 8
        barrier = threading.Barrier(n)
        seen = {}

        def work(i):
            with trace_context.bind(tenant=f"t{i}",
                                    request_id=f"r{i}") as ctx:
                barrier.wait(timeout=10)
                cur = trace_context.current()
                attrs = {}
                trace_context.stamp_event_attrs(attrs)
                seen[i] = (cur.trace_id == ctx.trace_id,
                           cur.tenant, attrs["tenant"])

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(seen) == n
        for i, (same, tenant, stamped) in seen.items():
            assert same, f"thread {i} read another thread's context"
            assert tenant == stamped == f"t{i}"
        assert trace_context.current() is None  # nothing leaked out

    def test_restore_none_is_passthrough(self):
        with trace_context.bind(tenant="t") as outer:
            with trace_context.restore(None):
                assert trace_context.current() is outer

    def test_capture_restore_crosses_threads(self):
        """The serve handoff pattern: capture on the submitter thread,
        restore on a worker — trace_id survives, and the worker's exit
        leaves the worker thread context-free."""
        out = {}
        with trace_context.bind(tenant="t", request_id="r") as ctx:
            captured = trace_context.current()

        def worker():
            assert trace_context.current() is None
            with trace_context.restore(captured):
                out["tid"] = trace_context.current().trace_id
            out["after"] = trace_context.current()

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        assert out["tid"] == ctx.trace_id
        assert out["after"] is None

    def test_nested_spans_record_true_parentage(self, monkeypatch):
        """With tracing on, a span opened inside another span's body
        records the enclosing span's id as ``parent_span`` — and both
        carry the bound trace_id."""
        monkeypatch.setenv("PIPELINEDP_TPU_TRACE", "1")
        obs.reset()
        tr = obs.run_tracer()
        with trace_context.bind(tenant="t", request_id="r") as ctx:
            with tr.span("outer", cat="test"):
                with tr.span("inner", cat="test"):
                    pass
        spans = {s.name: s for s in obs.ledger().snapshot()["spans"]}
        outer, inner = spans["outer"], spans["inner"]
        assert outer.args["trace_id"] == ctx.trace_id
        assert inner.args["trace_id"] == ctx.trace_id
        assert inner.args["parent_span"] == outer.args["span_id"]

    def test_spans_unstamped_without_context(self, monkeypatch):
        monkeypatch.setenv("PIPELINEDP_TPU_TRACE", "1")
        obs.reset()
        with obs.run_tracer().span("lonely", cat="test"):
            pass
        (span,) = obs.ledger().snapshot()["spans"]
        assert "trace_id" not in span.args


# ---------------------------------------------------------------------
# histogram exactness + exposition format
# ---------------------------------------------------------------------


class TestHistogram:

    def test_bucket_boundary_inclusive_le(self):
        """Prometheus ``le`` is inclusive: a value EQUAL to a bound
        lands in that bound's bucket; epsilon past it spills to the
        next. This is the boundary-exactness contract."""
        h = obs_metrics.Histogram("t", buckets=(0.1, 1.0, 10.0))
        h.observe(0.1)        # == bound -> le=0.1
        h.observe(0.1000001)  # just past -> le=1.0
        h.observe(1.0)        # == bound -> le=1.0
        h.observe(10.0)       # == last bound -> le=10.0
        h.observe(11.0)       # overflow -> +Inf only
        snap = h.snapshot()
        cum = dict(snap["buckets"])
        assert cum[0.1] == 1
        assert cum[1.0] == 3
        assert cum[10.0] == 4
        assert snap["count"] == 5
        assert snap["sum"] == pytest.approx(22.2000001)

    def test_quantiles_without_sample_retention(self):
        """p50/p99 interpolate inside the owning bucket — and the
        overflow bucket reports the last bound (an honest floor), so
        a wild outlier can never invent a tail value."""
        h = obs_metrics.Histogram("t", buckets=(1.0, 2.0, 4.0))
        for _ in range(100):
            h.observe(1.5)
        assert 1.0 <= h.quantile(0.5) <= 2.0
        h2 = obs_metrics.Histogram("t2", buckets=(1.0, 2.0))
        h2.observe(1e9)
        assert h2.quantile(0.99) == 2.0

    def test_rejects_unsorted_buckets(self):
        with pytest.raises(ValueError):
            obs_metrics.Histogram("bad", buckets=(2.0, 1.0))

    def test_exposition_naming_and_escaping(self):
        obs_metrics.set_gauge("tenant.epsilon_remaining", 4.5,
                              tenant='acme "prod"\nteam')
        obs_metrics.observe("serve.request_seconds", 0.02)
        text = obs_metrics.render_prometheus(
            counters={"serve.requests_served": 3})
        # dots -> underscores, pdp_ prefix, counters get _total.
        assert "pdp_serve_requests_served_total 3" in text
        assert ('pdp_tenant_epsilon_remaining{tenant='
                '"acme \\"prod\\"\\nteam"} 4.5') in text
        # histogram: cumulative buckets, +Inf, sum/count triplet.
        assert 'pdp_serve_request_seconds_bucket{le="+Inf"} 1' in text
        assert "pdp_serve_request_seconds_count 1" in text
        # integral floats print without a trailing .0
        assert 'le="1"' in text and 'le="1.0"' not in text


# ---------------------------------------------------------------------
# the wire surface
# ---------------------------------------------------------------------


class TestEndpointLifecycle:

    def test_off_by_default_zero_threads(self):
        before = sum(1 for t in threading.enumerate() if t.is_alive())
        assert obs_http.endpoint_port() is None
        assert obs_http.maybe_start() is None
        after = sum(1 for t in threading.enumerate() if t.is_alive())
        assert after == before
        assert not any(t.name == "pdp-obs-http"
                       for t in threading.enumerate())

    def test_bad_port_is_off_not_a_crash(self, monkeypatch):
        monkeypatch.setenv(obs_http.ENV_VAR, "not-a-port")
        assert obs_http.endpoint_port() is None
        assert obs_http.maybe_start() is None
        monkeypatch.setenv(obs_http.ENV_VAR, "70000")
        assert obs_http.endpoint_port() is None
        events = [e for e in obs.ledger().snapshot()["events"]
                  if e["name"] == "obs.http_bad_port"]
        assert {e["value"] for e in events} == {"not-a-port", "70000"}

    def test_live_scrape_round_trip(self):
        """A LIVE scrape loop against a running endpoint: gauges and
        histogram observations made between scrapes are visible in the
        next exposition — no restart, no cached render."""
        server = obs_http.IntrospectionServer(0).start()
        try:
            url = f"{server.url}/metrics"
            for i in range(1, 4):
                obs_metrics.set_gauge("tenant.epsilon_remaining",
                                      10.0 - i, tenant="t")
                obs_metrics.observe("serve.request_seconds",
                                    0.01 * i)
                code, text = http_get(url)
                assert code == 200
                assert (f'pdp_tenant_epsilon_remaining{{tenant="t"}} '
                        f"{obs_metrics._fmt(10.0 - i)}") in text
                assert f"pdp_serve_request_seconds_count {i}" in text
            code, doc = http_get(f"{server.url}/healthz")
            assert code == 200 and doc["status"] == "ok"
            code, doc = http_get(f"{server.url}/trace/nope")
            assert code == 404 and "unknown trace_id" in doc["error"]
            code, doc = http_get(f"{server.url}/heartbeat")
            assert code == 200
            code, doc = http_get(f"{server.url}/no-such-route")
            assert code == 404
        finally:
            server.stop()
        assert not any(t.name == "pdp-obs-http"
                       for t in threading.enumerate() if t.is_alive())

    def test_healthz_degraded_is_503(self, monkeypatch):
        server = obs_http.IntrospectionServer(0).start()
        try:
            monkeypatch.setenv("PIPELINEDP_TPU_DEGRADED", "elastic")
            code, doc = http_get(f"{server.url}/healthz")
            assert code == 503 and doc["degraded"] is True
        finally:
            server.stop()

    def test_stop_is_idempotent(self):
        server = obs_http.IntrospectionServer(0).start()
        server.stop()
        server.stop()

    def test_serve_kill_leaves_no_orphan_listener(self, monkeypatch,
                                                  tmp_path):
        """A ServeKill mid-request does not wedge the wire surface:
        the endpoint still answers afterwards, and ``close()`` joins
        the accept loop (the chaos campaign's ``obs_endpoint``
        scenario replays this under the seeded schedule)."""
        from pipelinedp_tpu.resilience import FaultPlan, injected_faults
        from pipelinedp_tpu.resilience import faults
        monkeypatch.setenv(obs_http.ENV_VAR, "0")
        ds = make_ds()
        with injected_faults(FaultPlan(fail_serve_requests=(0,))):
            with serve.Service(str(tmp_path / "svc"),
                               tenants={"t": (10.0, 1e-6)}) as svc:
                assert svc._http is not None
                base = svc._http.url
                with pytest.raises(faults.ServeKill):
                    svc.submit(request("t", ds, rid="req-0"))
                code, _ = http_get(f"{base}/healthz")
                assert code == 200
                ds.invalidate_cache()
                out = svc.submit(request("t", ds, rid="req-1"))
                assert out.ok, out
        assert not any(t.name == "pdp-obs-http"
                       for t in threading.enumerate() if t.is_alive())

    def test_chaos_obs_endpoint_episode(self):
        """The seeded chaos episode for this PR's seam runs green
        in-process (episode 9 of any campaign seed is obs_endpoint —
        appended LAST so earlier episode->scenario pins hold)."""
        from pipelinedp_tpu.resilience import chaos
        spec = chaos.run_episode(5, 9)
        assert spec["scenario"] == "obs_endpoint"


# ---------------------------------------------------------------------
# serve integration: the acceptance shape
# ---------------------------------------------------------------------


class TestServeTraceAcceptance:

    def _walk(self, roots):
        names = []

        def rec(nodes):
            for node in nodes:
                names.append(node["name"])
                rec(node["children"])

        rec(roots)
        return names

    def test_fused_batch_reconstructs_per_request_trees(
            self, monkeypatch, tmp_path):
        """THE acceptance criterion: one fused batch of two tenants'
        requests comes back as two complete per-request causal trees
        (admission -> execution -> books commit) via the live
        ``/trace/<id>`` endpoint AND the durable ``store --summarize
        --trace-id`` twin, with flow events in the Chrome export and
        per-member links on the fused-dispatch span."""
        monkeypatch.setenv("PIPELINEDP_TPU_TRACE", "1")
        monkeypatch.setenv(obs_http.ENV_VAR, "0")
        obs.reset()
        ds = make_ds()
        outs = {}
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"tA": (10.0, 1e-6),
                                    "tB": (10.0, 1e-6)},
                           fusion=True, fuse_window_ms=500,
                           fuse_max_batch=4) as svc:
            base = svc._http.url

            def run(tenant):
                outs[tenant] = svc.submit(request(tenant, ds))

            threads = [threading.Thread(target=run, args=(t,))
                       for t in ("tA", "tB")]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert all(o.ok for o in outs.values()), outs
            trace_ids = {t: o.trace_id for t, o in outs.items()}
            assert len(set(trace_ids.values())) == 2

            # (a) live endpoint: a complete tree per request.
            for tenant, tid in trace_ids.items():
                code, tree = http_get(f"{base}/trace/{tid}")
                assert code == 200
                assert tree["tenant"] == tenant
                names = self._walk(tree["roots"])
                for want in ("serve.admit", "serve.request",
                             "serve.commit"):
                    assert want in names, (tenant, names)

            # (b) the fused dispatch span links every member's trace.
            snap = obs.ledger().snapshot()
            fused = [s for s in snap["spans"]
                     if s.name == "serve.fused_dispatch"]
            assert fused, "burst did not fuse"
            members = fused[0].args["members"].split(",")
            assert set(trace_ids.values()) <= set(members)

            # (c) Chrome export: flow events connect each arc.
            events = obs_report.chrome_trace_events(snap)
            flows = [e for e in events if e.get("cat") == "flow"]
            assert {e["ph"] for e in flows} == {"s", "f"}
            # one deterministic flow id per trace, >= 2 traces' arcs
            assert len({e["id"] for e in flows}) >= 2

        # (d) durable twin, after close: the obs-store run reports
        # carry the span deltas; the CLI reconstructs both chains.
        store_dir = str(tmp_path / "obs_ledger")
        for tenant, tid in trace_ids.items():
            rc = obs_store.main(["--summarize", "--dir", store_dir,
                                 "--trace-id", tid, "--json"])
            assert rc == 0
        # text mode prints the tree (spot-check one tenant).
        rc = obs_store.main(["--summarize", "--dir", store_dir,
                             "--trace-id", trace_ids["tA"]])
        assert rc == 0

    def test_unknown_trace_id_cli_is_rc3(self, tmp_path):
        store = obs_store.LedgerStore(str(tmp_path / "led"))
        store.append("x", {"serve": {"ok": True}}, env={})
        rc = obs_store.main(["--summarize", "--dir",
                             str(tmp_path / "led"),
                             "--trace-id", "feedfacefeedface"])
        assert rc == 3

    def test_trace_context_on_off_bit_identical(self, monkeypatch,
                                                tmp_path):
        """PARITY row 42: context stamping changes only the record —
        the same seeded request through a traced+scraped service and a
        dark one releases bit-identical partitions."""
        results = {}
        for mode in ("off", "on"):
            obs.reset()
            if mode == "on":
                monkeypatch.setenv("PIPELINEDP_TPU_TRACE", "1")
                monkeypatch.setenv(obs_http.ENV_VAR, "0")
            else:
                monkeypatch.delenv("PIPELINEDP_TPU_TRACE",
                                   raising=False)
                monkeypatch.delenv(obs_http.ENV_VAR, raising=False)
            ds = make_ds(seed=3)
            with serve.Service(str(tmp_path / f"svc-{mode}"),
                               tenants={"t": (10.0, 1e-6)}) as svc:
                out = svc.submit(request("t", ds, seed=11))
                assert out.ok, out
                # The context itself is always-on (books entries carry
                # the id either way); only SPAN recording is gated.
                assert out.trace_id
                results[mode] = dict(out.results)
        assert set(results["off"]) == set(results["on"])
        for k in results["off"]:
            assert tuple(results["off"][k]) == tuple(results["on"][k])

    def test_metrics_and_heartbeat_tenants_under_load(
            self, monkeypatch, tmp_path):
        """/metrics serves per-tenant budget gauges + the request
        latency histogram under a multi-tenant workload, and the
        heartbeat document grows the ``tenants`` section fed by the
        durable budget ledger."""
        monkeypatch.setenv(obs_http.ENV_VAR, "0")
        ds = make_ds()
        with serve.Service(str(tmp_path / "svc"),
                           tenants={"tA": (10.0, 1e-6),
                                    "tB": (4.0, 1e-6)}) as svc:
            for tenant in ("tA", "tB"):
                ds.invalidate_cache()
                assert svc.submit(request(tenant, ds)).ok
            code, text = http_get(f"{svc._http.url}/metrics")
            assert code == 200
            assert 'pdp_tenant_epsilon_remaining{tenant="tA"} 9' in text
            assert 'pdp_tenant_epsilon_remaining{tenant="tB"} 3' in text
            assert 'pdp_tenant_reserves_in_flight{tenant="tA"} 0' in text
            assert "pdp_serve_request_seconds_bucket" in text
            assert "pdp_serve_queue_depth" in text
            # the burn-rate gauge exists and is finite
            assert "pdp_tenant_epsilon_burn_per_s" in text
            # heartbeat: the fallback document (monitor off) carries
            # the same per-tenant registry the monitor would embed.
            code, hb = http_get(f"{svc._http.url}/heartbeat")
            assert code == 200
            tenants = hb["tenants"]
            assert tenants["tA"]["epsilon_remaining"] == pytest.approx(
                9.0)
            assert tenants["tB"]["epsilon_remaining"] == pytest.approx(
                3.0)
            assert tenants["tA"]["reserves_in_flight"] == 0

    def test_monitor_heartbeat_document_embeds_tenants(
            self, monkeypatch, tmp_path):
        """The monitor's own heartbeat document (not just the endpoint
        fallback) carries the tenants section once serve pushed it —
        and drops it again when the registry clears."""
        from pipelinedp_tpu.resilience.clock import FakeClock
        mon = obs_monitor.Monitor(
            clock=FakeClock(), stall_s=30.0, interval_s=1.0,
            heartbeat_path=str(tmp_path / "heartbeat.json"),
            run_name="t").start_inline()
        try:
            obs_monitor.update_tenants(
                {"t": {"epsilon_remaining": 2.5,
                       "delta_remaining": 1e-7,
                       "reserves_in_flight": 1,
                       "committed_epsilon": 0.5, "inflight": 0}})
            hb = mon.poll_once()
            assert hb["tenants"]["t"]["epsilon_remaining"] == 2.5
            # the endpoint's /heartbeat serves this same document
            assert mon.last_heartbeat is hb
            obs_monitor.update_tenants(None)
            assert "tenants" not in mon.poll_once()
        finally:
            obs_monitor.update_tenants(None)
            mon.stop()
